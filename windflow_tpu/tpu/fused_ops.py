"""FusedTPUReplica: one XLA program per batch across a chained device
stage.

The reference fuses chain-compatible operators into one thread
(``wf/multipipe.hpp:537-590``); the TPU-native analog fuses their
*programs*. A ``Map_TPU -> Filter_TPU -> Map_TPU`` chain built via
``MultiPipe.chain`` runs as ONE replica whose per-batch work is a single
``jax.jit`` program composed from the sub-operators' kernels
(``ops_tpu.py`` kernel plane):

- a filter's keep mask flows to the next sub-op as a device-side
  ``valid`` mask — no mid-chain compaction, no mid-chain ``int(count)``
  readback; the one compaction + count readback happens at the chain
  exit (or never, for map-only chains);
- stateful sub-ops contribute their grid tables as additional carried
  state: the fused program threads every table through and the
  donation discipline matches the standalone grid scan (tables are
  donated, every commit reassigns them);
- a global ``Reduce_TPU`` terminator folds the masked survivors to one
  tuple inside the same program (``masked_tree_reduce``); a KEYED
  ``Reduce_TPU`` terminator runs its key-sorted segmented scan in the
  same program over the chain's valid mask (the KEYBY shuffle it would
  normally own degenerates to this in-program sort/segment when no
  cross-device re-shard exists — ``topology/stage.py`` legality);
- the whole chain submits ONE host-prep/device-commit pair to the
  replica's ``DeviceDispatchQueue`` — three chained operators cost one
  program launch and one commit per batch instead of three of each
  plus two channel hops.

Cross-operator XLA fusion then eliminates the intermediate HBM
materialization between sub-ops (Snider & Liang, arXiv:2301.13062;
Zheng et al., arXiv:1811.05213): the elementwise map/filter chain
compiles to one fused loop over the batch.

MEGABATCH: when ``WF_MEGABATCH=K`` > 1, the dispatch queue
(``runtime/dispatch.py``) coalesces up to K queued same-signature
commits and runs them through ``_run_megabatch`` — one jitted
``lax.scan`` over the chain program with the grid tables as carry, so K
batches cost ONE host dispatch. Ordering points (EOS / punctuation /
checkpoint / growth drains) always drain as singles, leaving alignment,
exactly-once, and rescale semantics untouched.

Compiled programs are cached per chain signature: the cache key covers
every stateful sub-op's grid shape ``(M, KB)`` (stateless sub-ops pin a
``None`` slot), and the cache itself lives on the chain's HEAD operator
so all replicas of the fused stage share one compilation.

Checkpointing: ``snapshot_state`` records the fused signature plus one
positional entry per sub-op, so PR 3 restores land each grid table back
into the right sub-op; a blob from a differently-fused (or unfused)
topology fails loudly instead of silently dropping state.

``FusedFfatReplica`` (bottom of this module) is the window-terminated
variant: the chain's stateless map/filter prefix composes INTO the
``Ffat_Windows_TPU`` step program via the ``_lift_fn``/``_prefix_mask``
hooks on ``FfatTPUReplica`` — ``source -> map -> Ffat_Windows`` runs as
ONE program per batch.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

import numpy as np

from ..basic import WindFlowError
from ..monitoring.flightrec import instrumented_jit
from ..runtime.dispatch import DeviceDispatchQueue, megabatch_k
from .batch import BatchTPU
from .ffat_tpu import Ffat_Windows_TPU, FfatTPUReplica
from .ops_tpu import (Filter_TPU, Map_TPU, Reduce_TPU, TPUReplicaBase,
                      _compact_order, _grid_scan_core, _KeyedStateScan,
                      cached_compile, masked_tree_reduce,
                      prewarm_zero_fields, reduce_order_and_slots)


class _SubSpec:
    """One sub-operator's contribution to the fused program: a stateless
    kernel, a stateful grid-scan engine, or a terminal reduce."""

    __slots__ = ("op", "kind", "kernel", "engine", "func")

    def __init__(self, op, kind: str, kernel: Optional[Callable],
                 engine: Optional[_KeyedStateScan],
                 func: Optional[Callable] = None) -> None:
        self.op = op
        self.kind = kind  # map | filter | smap | sfilter | reduce | kreduce
        self.kernel = kernel  # stateless composable kernel
        self.engine = engine  # _KeyedStateScan for stateful sub-ops
        self.func = func  # user functor for the grid-scan core


def _build_specs(replica: "FusedTPUReplica", ops) -> List[_SubSpec]:
    specs: List[_SubSpec] = []
    for op in ops:
        if isinstance(op, Reduce_TPU):
            specs.append(_SubSpec(
                op, "reduce" if op.key_extractor is None else "kreduce",
                None, None))
        elif isinstance(op, Map_TPU):
            if op.state_init is not None:
                specs.append(_SubSpec(
                    op, "smap", None,
                    _KeyedStateScan(replica, op.func, op.state_init,
                                    False, op=op), func=op.func))
            else:
                specs.append(_SubSpec(op, "map", op.device_kernel(), None))
        elif isinstance(op, Filter_TPU):
            if op.state_init is not None:
                specs.append(_SubSpec(
                    op, "sfilter", None,
                    _KeyedStateScan(replica, op.pred, op.state_init,
                                    True, op=op), func=op.pred))
            else:
                specs.append(_SubSpec(op, "filter", op.device_kernel(),
                                      None))
        else:
            raise WindFlowError(
                f"{op.name}: operator kind {type(op).__name__} has no "
                "composable device kernel (fusion legality should have "
                "refused this chain)")
    return specs


class FusedTPUReplica(TPUReplicaBase):
    """One replica running a whole chained device stage as one program.

    Protocol-compatible with any ``TPUReplicaBase``: same dispatch-queue
    ordering contract, punctuation/EOS handling, latency-stamp
    propagation (``trace_min/max`` ride the batch through the single
    program) and barrier-alignment drains — the fused node is simply a
    bigger per-batch program."""

    def __init__(self, ops, idx: int) -> None:
        ops = list(ops)
        super().__init__(ops[0], idx)
        self.ops = ops
        self.fused_name = "∘".join(o.name for o in ops)
        # stats/trace attribution: the fused stage is ONE observable
        # operator named map∘filter∘map; prep/commit spans + histograms
        # land on this record
        self.stats.op_name = self.fused_name
        self.stats.fused_ops = len(ops)
        self._span_prep = f"wf:prep:{self.fused_name}"
        # rebuilt so the commit span label carries the fused name
        self.dispatch = DeviceDispatchQueue(stats=self.stats)
        self.specs = _build_specs(self, ops)
        self._engines = [s.engine for s in self.specs
                         if s.engine is not None]
        self._has_filter = any(s.kind in ("filter", "sfilter")
                               for s in self.specs)
        last_kind = self.specs[-1].kind
        self._reduce_combine = (ops[-1].combine
                                if last_kind == "reduce" else None)
        self._kreduce_combine = (ops[-1].combine
                                 if last_kind == "kreduce" else None)
        if any(s.kind in ("reduce", "kreduce") for s in self.specs[:-1]):
            raise WindFlowError(
                f"{self.fused_name}: Reduce_TPU must terminate "
                "the fused chain")
        # compiled fused programs shared across this stage's replicas
        # (the graph build is single-threaded; worker threads only read)
        head = ops[0]
        if not hasattr(head, "_fused_prog_cache"):
            head._fused_prog_cache = {}
            head._fused_prog_lock = threading.Lock()
        self._prog_cache = head._fused_prog_cache
        self._prog_lock = head._fused_prog_lock

    # -- identity ----------------------------------------------------------
    @property
    def fused_signature(self) -> List[str]:
        return [op.name for op in self.ops]

    # -- fused program -----------------------------------------------------
    def _chain_body(self, statics) -> Callable:
        """The UN-jitted chain body ``run(fields, size, hargs, tables)``
        — shared by the per-batch program (``_make``) and the megabatch
        scan program (``_make_scan``), so both trace identical math.
        ``statics`` pins each stateful sub-op's grid shape ``(M, KB)``
        (None for stateless slots) — together with the traced shapes it
        is the full chain signature."""
        import jax
        import jax.numpy as jnp

        specs = self.specs
        has_filter = self._has_filter
        reduce_combine = self._reduce_combine
        kreduce_combine = self._kreduce_combine
        fused_name = self.fused_name

        def run(fields, size, hargs, tables):
            n = next(iter(fields.values())).shape[0]
            valid = jnp.arange(n) < size
            new_tables = []
            ti = 0
            for i, spec in enumerate(specs):
                if spec.kind in ("map", "filter"):
                    fields, valid, _ = spec.kernel(fields, valid, None)
                    if not isinstance(fields, dict):
                        raise WindFlowError(
                            f"{fused_name}: Map_TPU function must return "
                            "a dict of columns")
                elif spec.kind in ("smap", "sfilter"):
                    M, KB = statics[i]
                    core = _grid_scan_core(spec.func,
                                           spec.kind == "sfilter", M, KB)
                    grid_idx, touched, tmask = hargs[i]
                    tbl, dirty = tables[ti]
                    out, t2, d2 = core(fields, valid, grid_idx, touched,
                                       tmask, tbl, dirty)
                    new_tables.append((t2, d2))
                    ti += 1
                    if spec.kind == "sfilter":
                        valid = out
                    else:
                        fields = out
                # reduce/kreduce handled at the exit below (always last)
            if reduce_combine is not None:
                red = masked_tree_reduce(reduce_combine, fields, valid)
                return (red, _compact_order(valid), jnp.sum(valid),
                        tuple(new_tables))
            if kreduce_combine is not None:
                # keyed terminator: host prep sorted the rows by key
                # (reduce_order_and_slots — mask-independent, so it runs
                # over ALL rows); the scan folds each key's VALID rows
                # with the user combine. Validity rides the scan as an
                # Option: an invalid side passes the other through, an
                # invalid tail means no surviving row hit that key and
                # the slot is dropped — exactly the keys the unfused
                # filter stage would have compacted away upstream.
                order, ssorted = hargs[-1]
                f = {c: v[order] for c, v in fields.items()}
                v = valid[order]

                def seg_op(a, b):
                    fa, va, sa = a
                    fb, vb, sb = b
                    same = sa == sb
                    both = va & vb & same
                    merged = kreduce_combine(fa, fb)
                    # fields the combine does not return pass through
                    out = {c: jnp.where(both, merged.get(c, fb[c]),
                                        jnp.where(vb, fb[c],
                                                  jnp.where(same, fa[c],
                                                            fb[c])))
                           for c in fb}
                    return out, vb | (va & same), sb

                scanned, vscan, _ = jax.lax.associative_scan(
                    seg_op, (f, v, ssorted))
                is_last = jnp.concatenate(
                    [ssorted[1:] != ssorted[:-1], jnp.ones((1,), bool)])
                tkeep = is_last & vscan
                torder = _compact_order(tkeep)  # surviving tails first
                tails = {c: a[torder] for c, a in scanned.items()}
                return (tails, ssorted[torder], jnp.sum(tkeep),
                        _compact_order(valid), jnp.sum(valid),
                        tuple(new_tables))
            if has_filter:
                order = _compact_order(valid)  # keepers first, stable
                out = {k: v[order] for k, v in fields.items()}
                return out, order, jnp.sum(valid), tuple(new_tables)
            return fields, tuple(new_tables)

        return run

    def _make(self, statics) -> Callable:
        """Compose the chain into one jitted per-batch program."""
        # grid tables are DONATED exactly like the standalone scan:
        # every commit reassigns the engines' tables from the output.
        # instrumented_jit attributes (re)traces to this replica's
        # Compile_* stats with the chain signature — a fused chain whose
        # batch shapes churn shows up as a retrace storm in the trace
        return instrumented_jit(self._chain_body(statics), self.stats,
                                label=self.fused_name,
                                donate_argnums=(3,))

    def _make_scan(self, statics, k: int) -> Callable:
        """Megabatch program: stack K same-signature batches' columns
        in-trace, ``lax.scan`` the chain body over them with the grid
        tables as carry, and unstack the per-batch outputs in-trace —
        ONE compiled program and ONE host dispatch for K batches. The
        scan body IS ``_chain_body``, so a megabatch commit is
        bit-identical to K sequential single commits (the carry threads
        tables batch-to-batch exactly like sequential donation)."""
        import jax
        import jax.numpy as jnp

        run = self._chain_body(statics)
        tmap = jax.tree_util.tree_map

        def scan_run(fields_t, sizes, hargs_tt, tables):
            # None leaves (stateless sub-op hargs) are empty pytree
            # subtrees: tree_map skips them and the stacked structure
            # mirrors the per-batch one
            xf = tmap(lambda *xs: jnp.stack(xs), *fields_t)
            xh = tmap(lambda *xs: jnp.stack(xs), *hargs_tt)

            def body(tb, x):
                f, sz, h = x
                res = run(f, sz, h, tb)
                return res[-1], res[:-1]

            tables2, outs = jax.lax.scan(body, tables, (xf, sizes, xh))
            per = tuple(tmap(lambda a: a[i], outs) for i in range(k))
            return per, tables2

        return instrumented_jit(scan_run, self.stats,
                                label=f"{self.fused_name}:scan{k}",
                                donate_argnums=(3,))

    # -- compile-stability pre-warm ----------------------------------------
    def prewarm(self, caps) -> Optional[int]:
        """Compile the whole-chain program — and, when ``WF_MEGABATCH``
        enables the scan loop, every power-of-two K-scan variant — once
        per bucket capacity (``PipeGraph.with_prewarm``). Stateless
        chains only: a stateful sub-op's grid shape ``(M, KB)`` and
        table capacity are runtime cardinality — their signatures cannot
        be enumerated at start. A keyed-reduce terminator IS
        enumerable: its order/slot arrays are runtime values, not
        signature."""
        import jax

        if self._engines:
            return None
        sch = self.op.schema
        if sch is None:
            return None
        key = tuple(None for _ in self.specs)
        prog = cached_compile(self._prog_cache, self._prog_lock, key,
                              lambda: self._make(key))
        scan_ks: List[int] = []
        kk = 2
        while kk <= megabatch_k():
            scan_ks.append(kk)
            kk <<= 1
        warmed = 0
        for cap in caps:
            fields = prewarm_zero_fields(sch, cap)
            hargs = tuple(
                ((jax.device_put(np.arange(cap, dtype=np.int32)),
                  jax.device_put(np.zeros(cap, dtype=np.int32)))
                 if s.kind == "kreduce" else None)
                for s in self.specs)
            jax.block_until_ready(prog(fields, 0, hargs, ()))
            warmed += 1
            for k2 in scan_ks:
                sprog = cached_compile(
                    self._prog_cache, self._prog_lock,
                    ("scan", key, cap, k2),
                    lambda: self._make_scan(key, k2))
                jax.block_until_ready(sprog(
                    tuple(fields for _ in range(k2)),
                    np.zeros(k2, dtype=np.int32),
                    tuple(hargs for _ in range(k2)), ()))
                warmed += 1
        return warmed

    # -- batch path --------------------------------------------------------
    def prep_device_batch(self, batch: BatchTPU) -> Optional[Callable]:
        # HOST-PREP: per-stateful-sub-op slot mapping + grid assembly
        # (grid_meta drains the pipeline itself iff a state table must
        # grow); ONE cached-program lookup for the whole chain
        kred_hargs = None
        kextra = None
        if self._kreduce_combine is not None:
            import jax
            # key order over ALL rows (mask-independent: the program
            # applies the chain's valid mask in-trace, so the sort can
            # run before any filter verdict exists)
            order_np, ssorted_np, slot_of_key = reduce_order_and_slots(
                self.ops[-1], batch)
            if not slot_of_key:
                return None
            kred_hargs = (jax.device_put(order_np),
                          jax.device_put(ssorted_np))
            kextra = list(slot_of_key.keys())  # slot order == insertion
        statics: List[Any] = []
        hargs: List[Any] = []
        for spec in self.specs:
            if spec.engine is not None:
                grid_idx, _valid, touched, tmask, M, KB = \
                    spec.engine.grid_meta(batch)
                statics.append((M, KB))
                hargs.append((grid_idx, touched, tmask))
            elif spec.kind == "kreduce":
                statics.append(None)
                hargs.append(kred_hargs)
            else:
                statics.append(None)
                hargs.append(None)
        key = tuple(statics)
        prog = cached_compile(self._prog_cache, self._prog_lock, key,
                              lambda: self._make(key))
        hargs_t = tuple(hargs)
        engines = self._engines

        def commit() -> None:
            # tables (+ dirty bitmaps) read AT COMMIT TIME — earlier
            # queued commits reassign them (donation)
            tables = tuple((e.table, e.dirty) for e in engines)
            res = prog(batch.fields, batch.size, hargs_t, tables)
            self.stats.device_programs_run += 1  # ONE program per batch
            for eng, td in zip(engines, res[-1]):
                eng.table, eng.dirty = td
            self._commit_emit(batch, res[:-1], kextra)

        # megabatch metadata: the dispatch queue groups consecutive
        # commits whose scan_sig matches (same chain, same grid shapes,
        # same capacity bucket => same compiled scan program) and hands
        # the group to scan_runner. Non-fused replicas carry no such
        # attributes and always run as singles.
        commit.scan_sig = (id(self), key, batch.capacity)
        commit.scan_payload = (batch, hargs_t, kextra)
        commit.scan_runner = self._run_megabatch
        return commit

    def _run_megabatch(self, commits: List[Callable]) -> None:
        """Commit K queued same-signature batches through ONE jitted
        ``lax.scan`` over the chain program — host prep already ran per
        batch, so this amortizes the per-program dispatch/commit
        overhead K x. Ordering points (EOS / punctuation / checkpoint /
        growth drains) never reach here: the queue's drain path always
        runs singles (``runtime/dispatch.py``)."""
        import time

        t0 = time.perf_counter()
        k = len(commits)
        payloads = [c.scan_payload for c in commits]
        key = commits[0].scan_sig[1]
        cap = payloads[0][0].capacity
        prog = cached_compile(self._prog_cache, self._prog_lock,
                              ("scan", key, cap, k),
                              lambda: self._make_scan(key, k))
        engines = self._engines
        tables = tuple((e.table, e.dirty) for e in engines)
        fields_t = tuple(p[0].fields for p in payloads)
        sizes = np.asarray([p[0].size for p in payloads], dtype=np.int32)
        hargs_tt = tuple(p[1] for p in payloads)
        per, new_tables = prog(fields_t, sizes, hargs_tt, tables)
        self.stats.device_programs_run += 1  # ONE program for K batches
        # the scan carry threads (table, dirty) batch-to-batch, so a
        # megabatch accumulates dirty bits across all K batches exactly
        # like K sequential commits would
        for eng, td in zip(engines, new_tables):
            eng.table, eng.dirty = td
        for p, parts in zip(payloads, per):
            self._commit_emit(p[0], parts, p[2])
        self.stats.note_megabatch(k, (time.perf_counter() - t0) * 1e6)

    def _commit_emit(self, batch: BatchTPU, parts,
                     kextra=None) -> None:
        """Readback + emit of one batch's program outputs — the ONE
        definition shared by the per-batch commit and the megabatch scan
        loop (their emitted batches must be byte-identical)."""
        if self._kreduce_combine is not None:
            tails, tslots, tcount, rorder, rcount = parts
            m = int(tcount)  # surviving key count (chain-exit readback)
            rn = int(rcount)
            self.stats.inputs_ignored += batch.size - rn
            if m == 0:
                return
            ro = np.asarray(rorder)[:rn]
            batch_ts = int(batch.ts_host[ro].max())
            out_keys = [kextra[s] for s in np.asarray(tslots)[:m]]
            ts2 = np.full(batch.capacity, batch_ts, dtype=np.int64)
            nb = BatchTPU(tails, ts2, m, batch.schema, batch.wm, out_keys)
            nb.stream_tag = batch.stream_tag
            nb.copy_trace_from(batch)
            self._emit_batch(nb)
        elif self._reduce_combine is not None:
            out, order, count = parts
            n_out = int(count)  # the chain's single exit readback
            self.stats.inputs_ignored += batch.size - n_out
            if n_out == 0:
                return
            order_np = np.asarray(order)
            ts = np.array([int(batch.ts_host[order_np[:n_out]].max())],
                          dtype=np.int64)
            nb = BatchTPU(out, ts, 1, batch.schema, batch.wm)
            nb.stream_tag = batch.stream_tag
            nb.copy_trace_from(batch)
            self._emit_batch(nb)
        elif self._has_filter:
            out, order, count = parts
            # emit_compacted's int(count)/np.asarray(order) readbacks
            # run here, depth batches after dispatch
            self.emit_compacted(batch, out, order, count)
        else:
            (out,) = parts
            self._emit_batch(batch.with_fields(out))

    # -- checkpointing -----------------------------------------------------
    def snapshot_state(self) -> dict:
        st = super().snapshot_state()  # drains the dispatch queue
        st["__fused__"] = self.fused_signature
        st["fused_sub_states"] = [
            (spec.engine.snapshot_state() if spec.engine is not None
             else None)
            for spec in self.specs]
        return st

    def restore_state(self, state: dict) -> None:
        sig = state.get("__fused__")
        if sig is None:
            raise WindFlowError(
                f"restore: this graph fuses {self.fused_name!r} into one "
                f"device chain, but the checkpoint blob for "
                f"{self.op.name!r} holds standalone state — the "
                "checkpointed topology was fused differently (match "
                "WF_TPU_FUSION / the chain() calls of the original graph)")
        if list(sig) != self.fused_signature:
            raise WindFlowError(
                "restore: fused-chain mismatch — the checkpoint holds "
                f"{'∘'.join(sig)!r}, this graph builds "
                f"{self.fused_name!r}")
        super().restore_state(state)
        subs = state.get("fused_sub_states")
        if subs is None or len(subs) != len(self.specs):
            raise WindFlowError(
                f"restore: fused chain {self.fused_name!r} expects "
                f"{len(self.specs)} per-sub-op states, checkpoint holds "
                f"{0 if subs is None else len(subs)}")
        # positional restore: entry i belongs to sub-op i
        for spec, sub in zip(self.specs, subs):
            if spec.engine is not None:
                spec.engine.restore_state(sub or {})


class FusedFfatReplica(FfatTPUReplica):
    """A fused device chain TERMINATED by ``Ffat_Windows_TPU``: the
    chain's stateless map/filter prefix composes INTO the window
    replica's own per-batch step program, so ``source -> map -> filter
    -> Ffat_Windows`` runs as ONE composed program per batch — the
    forest rides as donated carried state, compaction + fire readback
    happen once at chain exit (unchanged FFAT commit plane).

    Two composition seams (the ``FfatTPUReplica`` hooks):

    - ``_lift_fn``: the prefix kernels run in front of the user lift
      inside every step/ingest program, so the data plane needs no
      extra program for the prefix maps;
    - ``_prefix_mask``: when the prefix contains filters, the keep mask
      is resolved at PREP time by a small cached mask program (one bool
      readback per batch). It must be: the host control plane's
      liveness quantities (max_leaf / next_fire / CB count) are exact,
      so a row the filter drops may never register a key, advance a
      leaf, or count toward a CB window — otherwise fused and unfused
      topologies would fire different windows. Map-only prefixes skip
      the mask program entirely: ONE program per batch, total.

    Legality (enforced again here after ``topology/stage.py``): the
    prefix is stateless map/filter only — a stateful prefix would run
    twice per batch (mask + compose) and double-advance its grid — and
    the prefix must not rewrite the key field (same PR-4 contract as
    every fused keyed chain: ``_keys_compatible`` checks names only)."""

    def __init__(self, ops, idx: int) -> None:
        ops = list(ops)
        super().__init__(ops[-1], idx)
        self.ops = ops
        self.fused_name = "∘".join(o.name for o in ops)
        self.stats.op_name = self.fused_name
        self.stats.fused_ops = len(ops)
        self._span_prep = f"wf:prep:{self.fused_name}"
        # rebuilt so the commit span label carries the fused name
        self.dispatch = DeviceDispatchQueue(stats=self.stats)
        prefix = ops[:-1]
        for o in prefix:
            if not isinstance(o, (Map_TPU, Filter_TPU)) \
                    or o.state_init is not None:
                raise WindFlowError(
                    f"{self.fused_name}: only stateless map/filter "
                    f"sub-ops may precede a window terminator "
                    f"({o.name} — fusion legality should have refused "
                    "this chain)")
        self._prefix_kernels = [o.device_kernel() for o in prefix]
        self._prefix_filters = any(isinstance(o, Filter_TPU)
                                   for o in prefix)
        self._tag = tuple(o.name for o in prefix)

    # -- identity ----------------------------------------------------------
    @property
    def fused_signature(self) -> List[str]:
        return [op.name for op in self.ops]

    # -- composition seams -------------------------------------------------
    def _chain_tag(self):
        return ("chain",) + self._tag

    def _lift_fn(self) -> Callable:
        import jax.numpy as jnp

        kernels = self._prefix_kernels
        lift = self.op.lift
        if not kernels:
            return lift

        def lifted(fields):
            n = next(iter(fields.values())).shape[0]
            valid = jnp.ones((n,), bool)
            for kern in kernels:
                fields, valid, _ = kern(fields, valid, None)
            # rows the prefix filtered compute garbage through the lift;
            # their segment lanes carry the sentinel (prep scattered the
            # packed composite over surviving rows only), so the scan
            # plane drops them before any leaf is touched
            return lift(fields)

        return lifted

    def _prefix_mask(self, batch: BatchTPU):
        if not self._prefix_filters:
            return None
        prog = cached_compile(self._prog_cache, self.op._prog_lock,
                              ("fmask", batch.capacity, self._tag),
                              self._make_mask)
        # prep-time readback of the keep mask (bools, one D2H): the
        # price of exact host liveness under a fused filter — map-only
        # chains never pay it
        keep = np.asarray(prog(batch.fields, batch.size))
        self.stats.device_programs_run += 1
        return keep[:batch.size]

    def _make_mask(self) -> Callable:
        import jax.numpy as jnp

        kernels = self._prefix_kernels

        def mask(fields, size):
            n = next(iter(fields.values())).shape[0]
            valid = jnp.arange(n) < size
            for kern in kernels:
                fields, valid, _ = kern(fields, valid, None)
            return valid

        return instrumented_jit(mask, self.stats,
                                label=f"{self.fused_name}:mask")

    # -- prewarm -----------------------------------------------------------
    def _prewarm_schema(self):
        # batches arrive with the CHAIN ENTRY's schema (the prefix maps
        # transform columns in-program)
        return self.ops[0].schema

    def prewarm(self, caps) -> Optional[int]:
        warmed = super().prewarm(caps)
        if warmed is None or not self._prefix_filters:
            return warmed
        import jax

        sch = self._prewarm_schema()
        for cap in caps:
            prog = cached_compile(self._prog_cache, self.op._prog_lock,
                                  ("fmask", cap, self._tag),
                                  self._make_mask)
            jax.block_until_ready(prog(prewarm_zero_fields(sch, cap), 0))
            warmed += 1
        return warmed

    # -- checkpointing -----------------------------------------------------
    def snapshot_state(self) -> dict:
        st = super().snapshot_state()  # drains the dispatch queue
        st["__fused__"] = self.fused_signature
        return st

    def restore_state(self, state: dict) -> None:
        sig = state.get("__fused__")
        if sig is None:
            raise WindFlowError(
                f"restore: this graph fuses {self.fused_name!r} into one "
                f"device chain, but the checkpoint blob for "
                f"{self.op.name!r} holds standalone state — the "
                "checkpointed topology was fused differently (match "
                "WF_TPU_FUSION / the chain() calls of the original graph)")
        if list(sig) != self.fused_signature:
            raise WindFlowError(
                "restore: fused-chain mismatch — the checkpoint holds "
                f"{'∘'.join(sig)!r}, this graph builds "
                f"{self.fused_name!r}")
        st = dict(state)
        st.pop("__fused__", None)
        super().restore_state(st)


def make_fused_replica(ops, idx: int):
    """Replica factory for a chained device stage: a window-terminated
    chain composes into the window replica's own step program
    (``FusedFfatReplica``); everything else — including keyed/global
    reduce terminators — runs the generic composed-kernel program
    (``FusedTPUReplica``)."""
    if isinstance(ops[-1], Ffat_Windows_TPU):
        return FusedFfatReplica(ops, idx)
    return FusedTPUReplica(ops, idx)
