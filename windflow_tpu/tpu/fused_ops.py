"""FusedTPUReplica: one XLA program per batch across a chained device
stage.

The reference fuses chain-compatible operators into one thread
(``wf/multipipe.hpp:537-590``); the TPU-native analog fuses their
*programs*. A ``Map_TPU -> Filter_TPU -> Map_TPU`` chain built via
``MultiPipe.chain`` runs as ONE replica whose per-batch work is a single
``jax.jit`` program composed from the sub-operators' kernels
(``ops_tpu.py`` kernel plane):

- a filter's keep mask flows to the next sub-op as a device-side
  ``valid`` mask — no mid-chain compaction, no mid-chain ``int(count)``
  readback; the one compaction + count readback happens at the chain
  exit (or never, for map-only chains);
- stateful sub-ops contribute their grid tables as additional carried
  state: the fused program threads every table through and the
  donation discipline matches the standalone grid scan (tables are
  donated, every commit reassigns them);
- a global ``Reduce_TPU`` terminator folds the masked survivors to one
  tuple inside the same program (``masked_tree_reduce``);
- the whole chain submits ONE host-prep/device-commit pair to the
  replica's ``DeviceDispatchQueue`` — three chained operators cost one
  program launch and one commit per batch instead of three of each
  plus two channel hops.

Cross-operator XLA fusion then eliminates the intermediate HBM
materialization between sub-ops (Snider & Liang, arXiv:2301.13062;
Zheng et al., arXiv:1811.05213): the elementwise map/filter chain
compiles to one fused loop over the batch.

Compiled programs are cached per chain signature: the cache key covers
every stateful sub-op's grid shape ``(M, KB)`` (stateless sub-ops pin a
``None`` slot), and the cache itself lives on the chain's HEAD operator
so all replicas of the fused stage share one compilation.

Checkpointing: ``snapshot_state`` records the fused signature plus one
positional entry per sub-op, so PR 3 restores land each grid table back
into the right sub-op; a blob from a differently-fused (or unfused)
topology fails loudly instead of silently dropping state.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

import numpy as np

from ..basic import WindFlowError
from ..monitoring.flightrec import instrumented_jit
from ..runtime.dispatch import DeviceDispatchQueue
from .batch import BatchTPU
from .ops_tpu import (Filter_TPU, Map_TPU, Reduce_TPU, TPUReplicaBase,
                      _compact_order, _grid_scan_core, _KeyedStateScan,
                      cached_compile, masked_tree_reduce,
                      prewarm_zero_fields)


class _SubSpec:
    """One sub-operator's contribution to the fused program: a stateless
    kernel, a stateful grid-scan engine, or the terminal reduce."""

    __slots__ = ("op", "kind", "kernel", "engine", "func")

    def __init__(self, op, kind: str, kernel: Optional[Callable],
                 engine: Optional[_KeyedStateScan],
                 func: Optional[Callable] = None) -> None:
        self.op = op
        self.kind = kind  # "map" | "filter" | "smap" | "sfilter" | "reduce"
        self.kernel = kernel  # stateless composable kernel
        self.engine = engine  # _KeyedStateScan for stateful sub-ops
        self.func = func  # user functor for the grid-scan core


def _build_specs(replica: "FusedTPUReplica", ops) -> List[_SubSpec]:
    specs: List[_SubSpec] = []
    for op in ops:
        if isinstance(op, Reduce_TPU):
            if op.key_extractor is not None:
                raise WindFlowError(
                    f"{op.name}: keyed Reduce_TPU cannot join a fused "
                    "device chain (it owns a KEYBY shuffle stage)")
            specs.append(_SubSpec(op, "reduce", None, None))
        elif isinstance(op, Map_TPU):
            if op.state_init is not None:
                specs.append(_SubSpec(
                    op, "smap", None,
                    _KeyedStateScan(replica, op.func, op.state_init,
                                    False, op=op), func=op.func))
            else:
                specs.append(_SubSpec(op, "map", op.device_kernel(), None))
        elif isinstance(op, Filter_TPU):
            if op.state_init is not None:
                specs.append(_SubSpec(
                    op, "sfilter", None,
                    _KeyedStateScan(replica, op.pred, op.state_init,
                                    True, op=op), func=op.pred))
            else:
                specs.append(_SubSpec(op, "filter", op.device_kernel(),
                                      None))
        else:
            raise WindFlowError(
                f"{op.name}: operator kind {type(op).__name__} has no "
                "composable device kernel (fusion legality should have "
                "refused this chain)")
    return specs


class FusedTPUReplica(TPUReplicaBase):
    """One replica running a whole chained device stage as one program.

    Protocol-compatible with any ``TPUReplicaBase``: same dispatch-queue
    ordering contract, punctuation/EOS handling, latency-stamp
    propagation (``trace_min/max`` ride the batch through the single
    program) and barrier-alignment drains — the fused node is simply a
    bigger per-batch program."""

    def __init__(self, ops, idx: int) -> None:
        ops = list(ops)
        super().__init__(ops[0], idx)
        self.ops = ops
        self.fused_name = "∘".join(o.name for o in ops)
        # stats/trace attribution: the fused stage is ONE observable
        # operator named map∘filter∘map; prep/commit spans + histograms
        # land on this record
        self.stats.op_name = self.fused_name
        self.stats.fused_ops = len(ops)
        self._span_prep = f"wf:prep:{self.fused_name}"
        # rebuilt so the commit span label carries the fused name
        self.dispatch = DeviceDispatchQueue(stats=self.stats)
        self.specs = _build_specs(self, ops)
        self._engines = [s.engine for s in self.specs
                         if s.engine is not None]
        self._has_filter = any(s.kind in ("filter", "sfilter")
                               for s in self.specs)
        self._reduce_combine = (ops[-1].combine
                                if self.specs[-1].kind == "reduce" else None)
        if any(s.kind == "reduce" for s in self.specs[:-1]):
            raise WindFlowError(
                f"{self.fused_name}: global Reduce_TPU must terminate "
                "the fused chain")
        # compiled fused programs shared across this stage's replicas
        # (the graph build is single-threaded; worker threads only read)
        head = ops[0]
        if not hasattr(head, "_fused_prog_cache"):
            head._fused_prog_cache = {}
            head._fused_prog_lock = threading.Lock()
        self._prog_cache = head._fused_prog_cache
        self._prog_lock = head._fused_prog_lock

    # -- identity ----------------------------------------------------------
    @property
    def fused_signature(self) -> List[str]:
        return [op.name for op in self.ops]

    # -- fused program -----------------------------------------------------
    def _make(self, statics) -> Callable:
        """Compose the chain into one jitted program. ``statics`` pins
        each stateful sub-op's grid shape ``(M, KB)`` (None for
        stateless slots) — together with the traced shapes it is the
        full chain signature."""
        import jax
        import jax.numpy as jnp

        specs = self.specs
        has_filter = self._has_filter
        reduce_combine = self._reduce_combine
        fused_name = self.fused_name

        def run(fields, size, hargs, tables):
            n = next(iter(fields.values())).shape[0]
            valid = jnp.arange(n) < size
            new_tables = []
            ti = 0
            for i, spec in enumerate(specs):
                if spec.kind in ("map", "filter"):
                    fields, valid, _ = spec.kernel(fields, valid, None)
                    if not isinstance(fields, dict):
                        raise WindFlowError(
                            f"{fused_name}: Map_TPU function must return "
                            "a dict of columns")
                elif spec.kind in ("smap", "sfilter"):
                    M, KB = statics[i]
                    core = _grid_scan_core(spec.func,
                                           spec.kind == "sfilter", M, KB)
                    grid_idx, touched, tmask = hargs[i]
                    out, t2 = core(fields, valid, grid_idx, touched,
                                   tmask, tables[ti])
                    new_tables.append(t2)
                    ti += 1
                    if spec.kind == "sfilter":
                        valid = out
                    else:
                        fields = out
                # "reduce" handled at the exit below (always last)
            if reduce_combine is not None:
                red = masked_tree_reduce(reduce_combine, fields, valid)
                return (red, _compact_order(valid), jnp.sum(valid),
                        tuple(new_tables))
            if has_filter:
                order = _compact_order(valid)  # keepers first, stable
                out = {k: v[order] for k, v in fields.items()}
                return out, order, jnp.sum(valid), tuple(new_tables)
            return fields, tuple(new_tables)

        # grid tables are DONATED exactly like the standalone scan:
        # every commit reassigns the engines' tables from the output.
        # instrumented_jit attributes (re)traces to this replica's
        # Compile_* stats with the chain signature — a fused chain whose
        # batch shapes churn shows up as a retrace storm in the trace
        return instrumented_jit(run, self.stats, label=self.fused_name,
                                donate_argnums=(3,))

    # -- compile-stability pre-warm ----------------------------------------
    def prewarm(self, caps) -> Optional[int]:
        """Compile the whole-chain program once per bucket capacity
        (``PipeGraph.with_prewarm``). Stateless chains only: a stateful
        sub-op's grid shape ``(M, KB)`` and table capacity are runtime
        cardinality — their signatures cannot be enumerated at start."""
        import jax

        if self._engines:
            return None
        sch = self.op.schema
        if sch is None:
            return None
        key = tuple(None for _ in self.specs)
        prog = cached_compile(self._prog_cache, self._prog_lock, key,
                              lambda: self._make(key))
        hargs = tuple(None for _ in self.specs)
        for cap in caps:
            jax.block_until_ready(
                prog(prewarm_zero_fields(sch, cap), 0, hargs, ()))
        return len(caps)

    # -- batch path --------------------------------------------------------
    def prep_device_batch(self, batch: BatchTPU) -> Optional[Callable]:
        # HOST-PREP: per-stateful-sub-op slot mapping + grid assembly
        # (grid_meta drains the pipeline itself iff a state table must
        # grow); ONE cached-program lookup for the whole chain
        statics: List[Any] = []
        hargs: List[Any] = []
        for spec in self.specs:
            if spec.engine is not None:
                grid_idx, _valid, touched, tmask, M, KB = \
                    spec.engine.grid_meta(batch)
                statics.append((M, KB))
                hargs.append((grid_idx, touched, tmask))
            else:
                statics.append(None)
                hargs.append(None)
        key = tuple(statics)
        prog = cached_compile(self._prog_cache, self._prog_lock, key,
                              lambda: self._make(key))
        hargs_t = tuple(hargs)
        engines = self._engines

        def commit() -> None:
            # tables read AT COMMIT TIME — earlier queued commits
            # reassign them (donation)
            tables = tuple(e.table for e in engines)
            res = prog(batch.fields, batch.size, hargs_t, tables)
            self.stats.device_programs_run += 1  # ONE program per batch
            new_tables = res[-1]
            for eng, t2 in zip(engines, new_tables):
                eng.table = t2
            if self._reduce_combine is not None:
                out, order, count, _ = res
                n_out = int(count)  # the chain's single exit readback
                self.stats.inputs_ignored += batch.size - n_out
                if n_out == 0:
                    return
                order_np = np.asarray(order)
                ts = np.array([int(batch.ts_host[order_np[:n_out]].max())],
                              dtype=np.int64)
                nb = BatchTPU(out, ts, 1, batch.schema, batch.wm)
                nb.stream_tag = batch.stream_tag
                nb.copy_trace_from(batch)
                self._emit_batch(nb)
            elif self._has_filter:
                out, order, count, _ = res
                # emit_compacted's int(count)/np.asarray(order) readbacks
                # run here, depth batches after dispatch
                self.emit_compacted(batch, out, order, count)
            else:
                out, _ = res
                self._emit_batch(batch.with_fields(out))

        return commit

    # -- checkpointing -----------------------------------------------------
    def snapshot_state(self) -> dict:
        st = super().snapshot_state()  # drains the dispatch queue
        st["__fused__"] = self.fused_signature
        st["fused_sub_states"] = [
            (spec.engine.snapshot_state() if spec.engine is not None
             else None)
            for spec in self.specs]
        return st

    def restore_state(self, state: dict) -> None:
        sig = state.get("__fused__")
        if sig is None:
            raise WindFlowError(
                f"restore: this graph fuses {self.fused_name!r} into one "
                f"device chain, but the checkpoint blob for "
                f"{self.op.name!r} holds standalone state — the "
                "checkpointed topology was fused differently (match "
                "WF_TPU_FUSION / the chain() calls of the original graph)")
        if list(sig) != self.fused_signature:
            raise WindFlowError(
                "restore: fused-chain mismatch — the checkpoint holds "
                f"{'∘'.join(sig)!r}, this graph builds "
                f"{self.fused_name!r}")
        super().restore_state(state)
        subs = state.get("fused_sub_states")
        if subs is None or len(subs) != len(self.specs):
            raise WindFlowError(
                f"restore: fused chain {self.fused_name!r} expects "
                f"{len(self.specs)} per-sub-op states, checkpoint holds "
                f"{0 if subs is None else len(subs)}")
        # positional restore: entry i belongs to sub-op i
        for spec, sub in zip(self.specs, subs):
            if spec.engine is not None:
                spec.engine.restore_state(sub or {})
