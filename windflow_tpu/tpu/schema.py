"""Tuple schemas: the bridge between row-Python payloads and columnar
device batches.

The reference runs arbitrary C++ structs through CUDA kernels; the TPU
plane instead requires a declared (or inferred) mapping tuple -> columns of
fixed dtypes, because XLA programs are compiled per shape/dtype. This is
the "functor surface" decision called out in SURVEY.md §7 step 3a: device
operators are JAX functions over a dict of arrays (struct-of-arrays), and
the schema handles row<->column conversion at the device boundary.

Numeric Python types map to TPU-friendly dtypes: int -> int32,
float -> float32, bool -> bool_. Timestamps stay host-side as int64 numpy
(microseconds can exceed int32; device code that needs event time rebases
to a batch-local int32 offset, see ffat_tpu).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..basic import WindFlowError

_DTYPE_MAP = {
    int: np.int32,
    float: np.float32,
    bool: np.bool_,
}


class TupleSchema:
    """Ordered field name -> numpy dtype, plus a row constructor."""

    def __init__(self, fields: Dict[str, Any],
                 constructor: Optional[Callable] = None) -> None:
        self.fields: Dict[str, np.dtype] = {
            name: np.dtype(dt) for name, dt in fields.items()}
        self.constructor = constructor  # None => rows come back as dicts
        self._names = list(self.fields)
        self._native_ok: Optional[bool] = None  # encode path memo

    # ------------------------------------------------------------------
    @staticmethod
    def infer(payload: Any) -> "TupleSchema":
        """Infer from a sample tuple: dataclass instances or dicts with
        numeric scalar fields."""
        if dataclasses.is_dataclass(payload):
            flds = {}
            for f in dataclasses.fields(payload):
                v = getattr(payload, f.name)
                dt = _DTYPE_MAP.get(type(v))
                if dt is None:
                    dt = np.asarray(v).dtype
                flds[f.name] = dt
            return TupleSchema(flds, type(payload))
        if isinstance(payload, dict):
            flds = {}
            for k, v in payload.items():
                dt = _DTYPE_MAP.get(type(v))
                if dt is None:
                    dt = np.asarray(v).dtype
                flds[k] = dt
            return TupleSchema(flds, None)
        raise WindFlowError(
            f"cannot infer a device schema from {type(payload).__name__}; "
            "use dataclass/dict tuples or pass an explicit TupleSchema")

    # ------------------------------------------------------------------
    def to_columns(self, rows: Sequence[Tuple[Any, int]], capacity: int,
                   pool=None) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
        """Rows [(payload, ts)] -> padded columnar arrays + int64 ts.
        With ``pool`` (an ``ArrayPool``) buffers come from its free lists;
        the caller owns returning them once the H2D transfer commits."""
        if pool is not None:
            cols = {name: pool.acquire(dt, capacity)
                    for name, dt in self.fields.items()}
        else:
            cols = {name: np.zeros(capacity, dtype=dt)
                    for name, dt in self.fields.items()}
        # ts stays out of the pool: it becomes the batch's ts_host metadata
        # and lives as long as the batch itself, not just the transfer
        ts = np.zeros(capacity, dtype=np.int64)
        n = len(rows)
        if n and self._try_native(rows, cols, ts, n):
            return cols, ts
        # access mode follows the PAYLOADS (an explicit dict schema may be
        # used with dataclass tuples and vice versa)
        by_item = bool(rows) and isinstance(rows[0][0], dict)
        for i, (p, t) in enumerate(rows):
            ts[i] = t
            if by_item:
                for name in self._names:
                    cols[name][i] = p[name]
            else:
                for name in self._names:
                    cols[name][i] = getattr(p, name)
        return cols, ts

    def _try_native(self, rows, cols, ts, n) -> bool:
        """One C pass per column instead of a Python loop per row*field
        (windflow_tpu.native staging encoders). The first failure disables
        the path for this schema — retrying a doomed C pass per batch would
        double staging cost forever."""
        if self._native_ok is False:
            return False
        from ..native import ENCODABLE_DTYPES, encode_column, native_available
        if self._native_ok is None:
            if not native_available() or any(
                    str(dt) not in ENCODABLE_DTYPES
                    for dt in self.fields.values()):
                self._native_ok = False
                return False
        payloads = [r[0] for r in rows]
        try:
            for name in self._names:
                encode_column(payloads, name, cols[name][:n])
            ts[:n] = [r[1] for r in rows]
            self._native_ok = True
            return True
        except Exception:
            self._native_ok = False
            return False

    def from_columns(self, cols: Dict[str, np.ndarray], ts: np.ndarray,
                     n: int) -> List[Tuple[Any, int]]:
        """Columnar arrays -> rows [(payload, ts)] for the CPU plane.
        One ``tolist()`` C pass per column (2.4x the per-element ``.item``
        loop this replaces) — the D2H exit is a hot boundary."""
        names = self._names
        ctor = self.constructor
        ts_list = np.asarray(ts[:n], dtype=np.int64).tolist()
        if len(ts_list) != n:
            raise WindFlowError(f"from_columns: ts holds {len(ts_list)} "
                                f"rows, batch claims {n}")
        if not names:  # ts-only tuples: zip(*[]) would silently drop rows
            return [({}, t) for t in ts_list]
        lists = []
        for name in names:
            col = np.asarray(cols[name])[:n].tolist()
            if len(col) != n:  # zip would TRUNCATE silently
                raise WindFlowError(
                    f"from_columns: column {name!r} holds {len(col)} rows, "
                    f"batch claims {n}")
            lists.append(col)
        if ctor is not None:
            # kwargs: an explicit schema's field order may not match the
            # constructor's positional order
            return [(ctor(**dict(zip(names, vals))), t)
                    for vals, t in zip(zip(*lists), ts_list)]
        return [(dict(zip(names, vals)), t)
                for vals, t in zip(zip(*lists), ts_list)]

    def signature(self) -> Tuple:
        """Hashable key for the compile cache."""
        return tuple((name, str(dt)) for name, dt in self.fields.items())

    def __repr__(self) -> str:  # pragma: no cover
        return f"TupleSchema({self.fields})"


def broadcast_scalar_fields(vals: Any, n_rows: int) -> Any:
    """Broadcast per-tuple CONSTANT lift fields (e.g. a count seed
    ``{"n": 1.0}`` — per-row semantics in the reference's lift functor,
    ``wf/ffat_windows.hpp``) to the batch column shape. Shared by the
    single-chip FFAT step and the sharded-forest step so the lift-shape
    rule cannot diverge between them."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda a: (jnp.broadcast_to(jnp.asarray(a), (n_rows,))
                   if jnp.ndim(a) == 0 else a), vals)
