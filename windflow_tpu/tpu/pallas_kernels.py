"""Pallas TPU kernels for the FFAT forest hot path.

The forest level rebuild is the per-batch fixed cost of the flagship
operator: for every key row, internal node ``i`` at each level is
``combine(node[2i], node[2i+1])`` with validity (an invalid child passes
the other through). The XLA lowering materializes every level's
``at[...].set`` back to HBM; this kernel instead loads a block of key
rows into VMEM ONCE, rebuilds all ``log2(F)`` levels with in-register
``jnp`` ops, and writes the finished rows back — one HBM round-trip per
block instead of one per level (reference counterpart:
``wf/flatfat_gpu.hpp:338-395``, per-level ``Update_TreeLevel_Kernel``
launches).

Gated by ``WF_PALLAS=1`` (used automatically only on TPU backends; the
interpreter validates the kernel on CPU in tests). The user ``combine``
is inlined into the kernel body — any jax-traceable combine works.
"""

from __future__ import annotations

import os
from typing import Callable, Dict


def pallas_enabled() -> bool:
    return os.environ.get("WF_PALLAS", "0") == "1"


def make_forest_rebuild(combine: Callable, field_names, F: int,
                        k_block: int = 8, interpret: bool = False):
    """Returns ``rebuild(trees: dict, tvalid) -> (trees, tvalid)`` where
    trees values and tvalid are (K_cap, 2F) arrays whose leaf half
    ``[F:2F)`` is current; internal nodes ``[1:F)`` are recomputed."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    names = list(field_names)
    NNODES = 2 * F

    def kernel(*refs):
        n = len(names)
        in_vals = [refs[i][...] for i in range(n)]        # (KB, 2F) each
        in_valid = refs[n][...]                           # (KB, 2F) bool
        out_vals = refs[n + 1:2 * n + 1]
        out_valid = refs[2 * n + 1]
        # fold upward entirely in VMEM, collecting every level as VALUES;
        # assemble the whole output row with one concatenate + ONE
        # full-row store per ref (narrow lane-slice stores are a Mosaic
        # lowering hazard)
        level = {nm: v[:, F:NNODES] for nm, v in zip(names, in_vals)}
        lvalid = in_valid[:, F:NNODES]
        parts = {nm: [level[nm]] for nm in names}  # leaves first
        vparts = [lvalid]
        width = F
        while width > 1:
            half = width // 2
            pair = {nm: v.reshape(v.shape[0], half, 2)
                    for nm, v in level.items()}
            lc = {nm: p[:, :, 0] for nm, p in pair.items()}
            rc = {nm: p[:, :, 1] for nm, p in pair.items()}
            pv = lvalid.reshape(lvalid.shape[0], half, 2)
            vlc, vrc = pv[:, :, 0], pv[:, :, 1]
            merged = combine(lc, rc)
            level = {nm: jnp.where(vlc & vrc, merged[nm],
                                   jnp.where(vlc, lc[nm], rc[nm]))
                     for nm in names}
            lvalid = vlc | vrc
            for nm in names:
                parts[nm].append(level[nm])
            vparts.append(lvalid)
            width = half
        # row layout: [unused node 0][levels top-down][leaves]
        for i, (nm, ov) in enumerate(zip(names, out_vals)):
            row = jnp.concatenate(
                [in_vals[i][:, 0:1]] + parts[nm][::-1], axis=1)
            ov[...] = row
        out_valid[...] = jnp.concatenate(
            [in_valid[:, 0:1]] + vparts[::-1], axis=1)

    def rebuild(trees: Dict, tvalid):
        K_cap = tvalid.shape[0]
        if K_cap < 8:
            return None  # below the sublane tile; caller uses the XLA path
        kb = min(k_block, K_cap)
        grid = (K_cap // kb,)
        blk = lambda: pl.BlockSpec((kb, NNODES), lambda i: (i, 0))
        in_specs = [blk() for _ in range(len(names) + 1)]
        out_specs = [blk() for _ in range(len(names) + 1)]
        out_shapes = ([jax.ShapeDtypeStruct((K_cap, NNODES),
                                            trees[nm].dtype)
                       for nm in names]
                      + [jax.ShapeDtypeStruct((K_cap, NNODES), jnp.bool_)])
        outs = pl.pallas_call(
            kernel, grid=grid, in_specs=in_specs, out_specs=out_specs,
            out_shape=out_shapes, interpret=interpret,
        )(*[trees[nm] for nm in names], tvalid)
        new_trees = {nm: o for nm, o in zip(names, outs[:len(names)])}
        return new_trees, outs[len(names)]

    return rebuild
