"""BatchTPU: a micro-batch resident in device HBM.

This is the ``batch_tpu_t`` called for by BASELINE.json — the sibling of the
reference's ``Batch_GPU_t`` (``wf/batch_gpu_t.hpp:51-243``): a device buffer
of tuples plus key metadata, with the same message protocol (watermark,
punctuation flag, stream tag) as the CPU batches.

Differences by design (TPU/XLA instead of CUDA):
- storage is columnar (struct-of-arrays) because XLA programs want vector
  lanes, not arrays of structs;
- capacity is a power-of-two bucket with an explicit host-side ``size``
  (pad+mask replaces the reference's variable-size batches — fixed shapes
  avoid re-compiles, SURVEY.md §7 step 3b);
- instead of the reference's per-key linked index chains
  (``start_idxs_gpu``/``map_idxs_gpu``), keyed operators use a dense
  ``key_slots`` int32 column (host dictionary key -> slot id), which is the
  sort/segment-friendly encoding XLA wants;
- there is no per-batch CUDA stream: JAX dispatch is async and XLA orders
  executions on the device queue, which plays the same overlap role
  (``batch_gpu_t.hpp:64`` per-batch stream + double buffering).

``ts`` stays host-side int64 (microsecond timestamps outlive int32); device
code needing event time rebases per batch (see ffat_tpu).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..message import StreamMsg
from .schema import TupleSchema


def key_column_to_list(batch: "BatchTPU", field: str) -> list:
    """D2H of the key column as a host list (one C call, no per-item
    boxing loops)."""
    return np.asarray(batch.fields[field])[:batch.size].tolist()


def key_column_np(batch: "BatchTPU", field: str) -> np.ndarray:
    """D2H of the key column as the RAW numpy array — the vectorized
    twin of ``key_column_to_list`` for consumers that never materialize
    Python keys (the dispatch pipeline's host-prep stage: tolist +
    re-asarray would box every key twice per batch)."""
    return np.asarray(batch.fields[field])[:batch.size]


def bucket_capacity(n: int, minimum: int = 8) -> int:
    c = minimum
    while c < n:
        c <<= 1
    return c


class BatchTPU(StreamMsg):
    __slots__ = ("fields", "ts_host", "size", "capacity", "wm", "is_punct",
                 "stream_tag", "id", "schema", "host_keys", "key_slots",
                 "slot_of_key", "trace_min", "trace_max")

    def __init__(self, fields: Dict[str, Any], ts_host: np.ndarray, size: int,
                 schema: TupleSchema, wm: int = 0,
                 host_keys: Optional[List[Any]] = None,
                 key_slots: Any = None,
                 slot_of_key: Optional[Dict[Any, int]] = None) -> None:
        self.fields = fields  # name -> jax.Array (capacity,)
        self.ts_host = ts_host  # np.int64 (capacity,)
        self.size = size
        self.capacity = len(ts_host)
        self.wm = wm
        self.is_punct = False
        self.stream_tag = 0
        self.id = 0
        self.schema = schema
        # keyed metadata (present on keyby-staged batches):
        self.host_keys = host_keys  # list of python keys, len == size
        self.key_slots = key_slots  # jax int32 (capacity,): dense slot ids
        self.slot_of_key = slot_of_key  # key -> slot id for this batch
        # latency-tracing origin stamps: min/max over traced constituents
        # (0 = none traced; monitoring/tracing.py)
        self.trace_min = 0
        self.trace_max = 0

    # -- protocol ----------------------------------------------------------
    def min_watermark(self) -> int:
        return self.wm

    def __len__(self) -> int:
        return self.size

    def nbytes(self) -> int:
        return sum(int(np.dtype(v.dtype).itemsize) * self.capacity
                   for v in self.fields.values())

    # -- construction ------------------------------------------------------
    @staticmethod
    def stage(rows: Sequence[Tuple[Any, int]], schema: TupleSchema,
              wm: int, keys: Optional[List[Any]] = None,
              capacity: Optional[int] = None,
              recycler=None) -> "BatchTPU":
        """CPU->TPU: columnarize and device_put (async dispatch; the
        reference's pinned staging + async H2D, ``keyby_emitter_gpu.hpp:
        443-505``). With ``recycler`` (an ``InFlightRecycler``) the column
        buffers come from its pool and are returned once the transfer is
        committed — device_put's host read can complete asynchronously
        once the dispatch queue deepens, so premature reuse corrupts
        in-flight batches (the hazard the reference tracks with in-transit
        counters, ``batch_gpu_t.hpp:66``)."""
        import jax

        cap = capacity or bucket_capacity(len(rows))
        pooled = recycler is not None and recycler.enabled
        cols, ts = schema.to_columns(rows, cap,
                                     recycler.pool if pooled else None)
        dev_fields = {name: jax.device_put(col) for name, col in cols.items()}
        if pooled:
            recycler.track(dev_fields.values(), cols.values())
        # per-batch slot ids are computed by the consuming keyed operator
        # (TPUReplicaBase.batch_slots); host_keys is the canonical metadata
        return BatchTPU(dev_fields, ts, len(rows), schema, wm, keys)

    @staticmethod
    def stage_columns(cols: Dict[str, np.ndarray], ts: np.ndarray,
                      schema: TupleSchema, wm: int,
                      keys: Optional[List[Any]] = None,
                      recycler=None) -> "BatchTPU":
        """CPU->TPU from COLUMNS (push_columns fast path): pad each numpy
        column to the capacity bucket and device_put — no per-tuple
        Python at all."""
        import jax

        n = len(ts)
        cap = bucket_capacity(n)
        pooled = recycler is not None and recycler.enabled
        dev_fields = {}
        staged = []
        for name, dt in schema.fields.items():
            src = cols[name]
            # one vectorized copy into a private buffer: the caller may
            # freely reuse its arrays (device_put can defer-read/alias the
            # host buffer, see InFlightRecycler)
            buf = (recycler.pool.acquire(dt, cap) if pooled
                   else np.zeros(cap, dtype=dt))
            buf[:n] = src
            dev_fields[name] = jax.device_put(buf)
            staged.append(buf)
        if pooled:
            recycler.track(dev_fields.values(), staged)
        ts2 = np.zeros(cap, dtype=np.int64)
        ts2[:n] = ts
        return BatchTPU(dev_fields, ts2, n, schema, wm, keys)

    @staticmethod
    def stage_prefilled(cols: Dict[str, np.ndarray], ts: np.ndarray,
                        n: int, schema: TupleSchema, wm: int,
                        keys: Optional[Any] = None,
                        recycler=None) -> "BatchTPU":
        """CPU->TPU from staging buffers ALREADY padded to the capacity
        bucket and filled in place (TPUStageEmitter's block-append path):
        just ``device_put`` — the single host copy per column happened at
        append time. Ownership of ``cols``/``ts`` transfers to the batch:
        the caller must not touch them again (device_put may alias the
        host buffer); with ``recycler`` the field buffers return to its
        pool once the H2D commits."""
        import jax

        dev_fields = {name: jax.device_put(cols[name])
                      for name in schema.fields}
        if recycler is not None and recycler.enabled:
            recycler.track(dev_fields.values(),
                           [cols[name] for name in schema.fields])
        return BatchTPU(dev_fields, ts, n, schema, wm, keys)

    # -- exit to host ------------------------------------------------------
    def prefetch_host(self) -> None:
        """Start async D2H of every column (the reference's
        ``prefetch2CPU``, ``batch_gpu_t_u.hpp:203``). On the tunneled TPU a
        synchronous fetch of a fresh device buffer costs ~70 ms of fixed
        latency regardless of size; issuing the copies early lets them
        overlap each other and subsequent compute, after which
        ``np.asarray`` reads the cached host copy for free."""
        for v in self.fields.values():
            f = getattr(v, "copy_to_host_async", None)
            if f is not None:
                f()

    def to_rows(self) -> List[Tuple[Any, int]]:
        """TPU->CPU (the reference's ``transfer2CPU``,
        ``batch_gpu_t.hpp:154-165``)."""
        host_cols = {name: np.asarray(v) for name, v in self.fields.items()}
        return self.schema.from_columns(host_cols, self.ts_host, self.size)

    def copy_trace_from(self, src: "BatchTPU") -> "BatchTPU":
        """Propagate origin stamps from the batch this one derives from
        (operator outputs, gathers, compactions)."""
        self.trace_min = src.trace_min
        self.trace_max = src.trace_max
        return self

    def with_fields(self, new_fields: Dict[str, Any]) -> "BatchTPU":
        """Same metadata, new device columns (in-place operator output)."""
        b = BatchTPU(new_fields, self.ts_host, self.size, self.schema,
                     self.wm, self.host_keys, self.key_slots,
                     self.slot_of_key)
        b.stream_tag = self.stream_tag
        b.id = self.id
        return b.copy_trace_from(self)

    def copy_for_dest(self) -> "BatchTPU":
        """Broadcast copy: device arrays are immutable, sharing is safe."""
        b = BatchTPU(dict(self.fields), self.ts_host, self.size, self.schema,
                     self.wm, self.host_keys, self.key_slots,
                     self.slot_of_key)
        b.stream_tag = self.stream_tag
        b.id = self.id
        return b.copy_trace_from(self)

    @property
    def num_keys(self) -> int:
        return len(self.slot_of_key) if self.slot_of_key is not None else 0

    def __repr__(self) -> str:  # pragma: no cover
        return (f"<BatchTPU n={self.size}/{self.capacity} wm={self.wm} "
                f"keys={self.num_keys}>")
