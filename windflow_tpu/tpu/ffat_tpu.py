"""Ffat_Windows_TPU: the flagship device operator — sliding-window
lift+combine aggregation over a batched FlatFAT forest in HBM.

Reference: ``wf/ffat_windows_gpu.hpp`` + ``wf/ffat_replica_gpu.hpp`` +
``wf/flatfat_gpu.hpp`` (see SURVEY.md §3.5). The reference's per-batch GPU
flow is: lift kernel -> thrust sort/reduce by (key, pane) -> small D2H of
the unique (key, pane) arrays -> host loop per key pushing panes into a
device ring and firing watermark-complete windows through a per-key FlatFAT
(``Compute_Results_Kernel`` combines O(log B) nodes per window).

TPU-first redesign:
- the control plane runs on HOST METADATA ONLY: keys and timestamps are
  already host-side on ``BatchTPU``, so per-key pane bookkeeping,
  window-fire decisions and eviction lists are numpy — no D2H of data at
  all (the reference pays a D2H of its unique arrays every batch,
  ``ffat_replica_gpu.hpp:945-988``). Segmentation (sort order + run
  detection) is backend-dependent: precomputed with numpy on the CPU
  backend (where the XLA program competes with the host for cores), and
  computed IN-PROGRAM on accelerators (where device work overlaps the
  host control plane);
- the data plane is ONE jitted XLA program per batch:
    lift(columns) -> gather(sort order) -> segmented associative scan with
    the user combine -> gather segment tails -> scatter-combine into the
    leaves of a FlatFAT FOREST (K_cap keys x 2F nodes, one segment tree
    per key slot, circular leaf addressing ``pane mod F``) -> vectorized
    level rebuild (log F fused passes over the whole forest) -> vmapped
    iterative range queries for up to W_cap fired windows (each walks
    <= 2 log F nodes with ordered left/right accumulators, safe for
    non-commutative combines) -> leaf eviction;
- all shapes are static per (cap, K_cap, F, segmentation-mode) bucket;
  key capacity and ring length grow by doubling with a device-side rebuild
  (the reference resizes its pending-pane ring on demand,
  ``ffat_replica_gpu.hpp:219-260``).

Window semantics match the CPU ``Ffat_Windows``: pane = gcd(win, slide)
time units (TB) or one tuple (CB, leaf = per-key arrival index); TB windows
fire when the watermark minus lateness passes their end; empty windows fire
with ``valid=False``; late tuples behind the eviction frontier are counted
as ignored; EOS flushes partial windows.

Output batches carry one row per fired window: the combined value columns,
``wid`` (per-key window id), ``valid`` (False for empty windows), and the
key column when the key is a field name.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..basic import OpType, RoutingMode, WinType, WindFlowError
from .batch import BatchTPU, bucket_capacity
from .ops_tpu import TPUOperatorBase, TPUReplicaBase
from .schema import TupleSchema, broadcast_scalar_fields

class Ffat_Windows_TPU(TPUOperatorBase):
    op_type = OpType.WIN_TPU

    def __init__(self, lift: Callable, combine: Callable, key_extractor,
                 win_len: int, slide_len: int,
                 win_type: WinType = WinType.TB, lateness: int = 0,
                 num_win_per_batch: Optional[int] = None,
                 name: str = "ffat_windows_tpu", parallelism: int = 1,
                 output_batch_size: int = 0,
                 schema: Optional[TupleSchema] = None,
                 key_capacity: int = 16) -> None:
        if key_extractor is None:
            raise WindFlowError(f"{name}: requires a key extractor")
        if win_len <= 0 or slide_len <= 0:
            raise WindFlowError(f"{name}: win/slide must be > 0")
        super().__init__(name, parallelism, RoutingMode.KEYBY, key_extractor,
                         output_batch_size, schema)
        self.lift = lift
        self.combine = combine
        self.win_len = win_len
        self.slide_len = slide_len
        self.win_type = win_type
        self.lateness = lateness
        self.key_capacity = max(1, key_capacity)
        if num_win_per_batch is None:
            # fired windows per step scale with key count (each key slides
            # its own windows): default the fire-batch budget to the key
            # capacity so high-cardinality streams don't drain through
            # many tiny programs (the reference leaves numWinPerBatch
            # manual, builders_gpu.hpp:576)
            num_win_per_batch = max(16, min(8192, self.key_capacity))
        self.num_win_per_batch = max(1, num_win_per_batch)
        self.pane_len = math.gcd(win_len, slide_len)
        # compiled programs shared ACROSS replicas: cache keys carry every
        # shape parameter (cap, K_cap, F, seg mode), so equal-config
        # replicas reuse one compile instead of paying parallelism x
        # (lock: replica worker threads race their first batch)
        import threading
        self._prog_cache: Dict[Any, Any] = {}
        self._prog_lock = threading.Lock()

    @property
    def fusion_role(self) -> Optional[str]:
        """``"window_terminator"``: the window op may END a fused device
        chain (its step program absorbs a stateless map/filter prefix —
        ``fused_ops.FusedFfatReplica``) but can never sit mid-chain: it
        changes the row domain (tuples -> fired windows), so nothing can
        compose after it inside one program."""
        return "window_terminator"

    def build_replicas(self) -> None:
        self.replicas = [FfatTPUReplica(self, i)
                         for i in range(self.parallelism)]


class FfatTPUReplica(TPUReplicaBase):
    def __init__(self, op: Ffat_Windows_TPU, idx: int) -> None:
        super().__init__(op, idx)
        if op.win_type is WinType.CB:
            self.win_units = op.win_len
            self.slide_units = op.slide_len
        else:
            self.win_units = op.win_len // op.pane_len
            self.slide_units = op.slide_len // op.pane_len
        # ring length: window + slack for panes ahead of the watermark
        self.F = 1 << max(3, math.ceil(math.log2(
            self.win_units + max(2 * self.slide_units, 16))))
        # pre-sizing the key table avoids growth recompiles
        # (wf/builders_gpu.hpp has no analog; growth still works past it)
        self.K_cap = 1 << max(2, math.ceil(math.log2(op.key_capacity)))
        # two fire-budget tiers: W_step keeps the full per-batch
        # program's vmapped-query block small, W_cap is the wide budget
        # used by drain iterations and data-less firing so backlogs
        # clear in few programs
        self.W_cap = op.num_win_per_batch
        self.W_step = min(self.W_cap, 64)
        # adaptive two-tier first-iteration fire budget (device mode):
        # an EWMA of fired-windows-per-batch picks W_step (small always-
        # paid query block) or W_cap (high-cardinality streams fire in
        # ONE program per batch); both shapes compile eagerly, see
        # _first_budget. Starts at 0 so low-fire streams begin on the
        # small tier.
        self._fire_ewma = 0.0
        from .keymap import KeySlotMap
        self._keymap = KeySlotMap(on_new=self._on_new_key)
        self.slot_of_key = self._keymap.slot_of_key  # shared dict
        self._out_keys_by_slot: List[Any] = []
        # per-slot host bookkeeping (numpy, grown with K_cap)
        self.next_fire = np.zeros(self.K_cap, dtype=np.int64)
        self.fired = np.zeros(self.K_cap, dtype=np.int64)  # == next gwid
        self.max_leaf = np.full(self.K_cap, -1, dtype=np.int64)
        self.count = np.zeros(self.K_cap, dtype=np.int64)  # CB arrivals
        # integer key values per slot (fast emit path; falls back to the
        # _out_keys_by_slot python list for non-int keys)
        self._keys_np = np.zeros(self.K_cap, dtype=np.int64)
        self._keys_all_int = True
        self._key_dtype = np.dtype(np.int32)
        self._saw_new_key = False
        self._leaf_frontier = 0  # max leaf ever accepted (fast-path guard)
        # device-resident constant program args (avoid re-transferring
        # numpy zeros/dummies every batch on a tunneled device)
        self._zero_fire_cache: Dict[int, Any] = {}
        self._seg_dummy = None
        # deferred-rebuild flag: True while internal tree levels are
        # stale w.r.t. leaves (ingest-only batches ran since the last
        # rebuild); every fire path rebuilds first (see _make_step)
        self._rebuild_dirty = False
        # device-resident per-slot key table (lazy; see _ktable_arg)
        self._ktable_dev = None
        self._ktable_kd = None
        self._ktable_dirty = True
        self.ignored = 0
        # incremental checkpointing (WF_CKPT_DELTA): host-side dirty
        # slot set — ingest and fire mark the rows they touch, and a
        # delta snapshot ships only those rows of the per-slot arrays +
        # forest. Any executed level REBUILD rewrites internal tree
        # rows forest-wide, so it conservatively forces the next
        # snapshot FULL via _dirty_all (ingest-only stretches between
        # fires — the realistic accumulation regime — still delta).
        self._ckpt_dirty: set = set()
        self._dirty_all = False
        self._delta_base = None  # epoch id of the last full snapshot
        self._snaps_since_full = 0
        self._base_nkeys = None  # key count at the last full snapshot
        self._base_geom = None  # (K_cap, F, trees-allocated) at base
        # device forest (lazily shaped once the lift output is known)
        self.trees = None  # dict field -> (K_cap, 2F)
        self.tvalid = None  # (K_cap, 2F) bool
        self._prog_cache = op._prog_cache  # shared across replicas
        self.__host_seg = None  # resolved lazily: backend init is costly
        self.__on_accel = None  # same caching rationale (_on_accelerator)
        self._check_index_plane()

    def _comp_dtype(self):
        """(sentinel M, dtype) of the packed composite — the SINGLE
        definition shared by staging, dummies, and the driver entry
        (the traced and runtime dtypes must stay bit-identical)."""
        M = self.K_cap * self.F
        return M, (np.int16 if M < 2**15 - 1 else np.int32)

    def _check_index_plane(self, k_cap: int = 0, f: int = 0) -> None:
        """Every forest index (host composite sort, device scatter/evict
        flat ids) lives in int32; enforced at init and BEFORE any growth
        commits — in BOTH segmentation modes. ``k_cap``/``f`` check a
        PROSPECTIVE capacity/ring before mutating toward it (growth must
        raise-before-mutate: a caught refusal mid-growth would leave a
        wrapped index plane that no later per-batch guard re-checks)."""
        k = k_cap or self.K_cap
        ff = f or self.F
        if k * 2 * ff >= 2**31 - 1:
            raise WindFlowError(
                f"{self.op.name}: K_cap*2F = {k * 2 * ff} "
                "overflows the int32 index plane; reduce key_capacity or "
                "the window/slide ratio")

    @property
    def _host_seg(self) -> bool:
        if self.__host_seg is None:
            import jax

            from ..basic import env_flag
            if env_flag("WF_FORCE_DEVICE_SEG"):
                # CI lever: exercise the accelerator segmentation path
                # (in-program sort) on the CPU backend across the suite
                self.__host_seg = False
            elif env_flag("WF_FORCE_HOST_SEG"):
                # perf lever: host radix segmentation on an accelerator —
                # TPU sorts are bitonic O(n log^2 n); the host's int16
                # radix argsort overlapped with device compute can win
                self.__host_seg = True
            else:
                self.__host_seg = jax.default_backend() == "cpu"
        return self.__host_seg

    @_host_seg.setter
    def _host_seg(self, v) -> None:
        self.__host_seg = v

    def _on_accelerator(self) -> bool:
        """Backend test for policy decisions (two-tier fire budgets).
        NOT the same as ``not _host_seg``: WF_FORCE_HOST_SEG runs host
        segmentation on an accelerator, where the wide-tier budget
        rationale (dispatches are the cost, wide queries are overlapped
        device work) still applies. WF_FORCE_DEVICE_SEG keeps implying
        accelerator policy so CI exercises the two-tier path on CPU.
        Cached: called per batch on the hot dispatch path."""
        if self.__on_accel is None:
            import jax

            from ..basic import env_flag

            self.__on_accel = (env_flag("WF_FORCE_DEVICE_SEG")
                               or jax.default_backend() != "cpu")
        return self.__on_accel

    # ==================================================================
    # fused-chain seams (overridden by fused_ops.FusedFfatReplica)
    # ==================================================================
    def _lift_fn(self) -> Callable:
        """The lift entry every program traces. The fused-chain replica
        overrides it to compose the chain's stateless map/filter prefix
        IN FRONT of the user lift, so ``source -> map -> filter ->
        Ffat_Windows`` runs as ONE program per batch."""
        return self.op.lift

    def _prefix_mask(self, batch: BatchTPU):
        """Keep mask of a fused prefix filter over ``batch`` (None when
        no prefix filters exist — the base replica never has any). MUST
        be resolved at PREP time: the host control plane's liveness
        quantities (max_leaf/next_fire/count) are exact, so a row the
        prefix drops may never register a key, advance a leaf, or count
        toward a CB window."""
        return None

    def _chain_tag(self):
        """Cache-key discriminator for composed programs (the fused
        replica returns the prefix signature; programs traced through a
        different prefix must never collide)."""
        return None

    # ==================================================================
    # the per-batch device program
    # ==================================================================
    def _query_fns(self):
        """Closures shared by the full step and the fire-only step:
        validity-aware ordered combine + ring window query."""
        import jax
        import jax.numpy as jnp

        combine = self.op.combine
        F = self.F
        NNODES = 2 * F
        LOGQ = NNODES.bit_length()  # enough iterations for the tree walk
        tmap = jax.tree_util.tree_map

        def comb_valid(va, a, vb, b):
            """Ordered combine with validity: an invalid side passes the
            other through (None-as-identity, like the CPU FlatFAT)."""
            both = va & vb
            merged = combine(a, b)
            out = tmap(lambda m, x, y: jnp.where(both, m, jnp.where(va, x, y)),
                       merged, a, b)
            return va | vb, out

        def range_query(tree_row, vrow, lo, length):
            """Ordered combine of physical leaf range [lo, lo+length) of one
            tree row: iterative segment-tree walk, left/right accumulators
            keep combine order (reference prefix/suffix arrays,
            ``wf/flatfat.hpp:85-132``)."""
            zero = tmap(lambda a: jnp.zeros((), a.dtype), tree_row)

            def body(_, st):
                l, r, lv, la, rv, ra = st
                take_l = ((l & 1) == 1) & (l < r)
                il = jnp.clip(l, 0, NNODES - 1)
                node_l = tmap(lambda a: a[il], tree_row)
                lv, la = comb_valid(lv, la, vrow[il] & take_l, node_l)
                l = jnp.where(take_l, l + 1, l)
                take_r = ((r & 1) == 1) & (l < r)
                ir = jnp.clip(r - 1, 0, NNODES - 1)
                node_r = tmap(lambda a: a[ir], tree_row)
                rv, ra = comb_valid(vrow[ir] & take_r, node_r, rv, ra)
                r = jnp.where(take_r, r - 1, r)
                return (l >> 1, r >> 1, lv, la, rv, ra)

            init = (lo + F, lo + length + F,
                    jnp.zeros((), bool), zero, jnp.zeros((), bool), zero)
            st = jax.lax.fori_loop(0, LOGQ, body, init)
            return comb_valid(st[2], st[3], st[4], st[5])

        def window_query(tree_row, vrow, start_phys, length):
            """Logical ring range -> <=2 physical ranges, combined in order."""
            len1 = jnp.minimum(length, F - start_phys)
            v1, r1 = range_query(tree_row, vrow, start_phys, len1)
            v2, r2 = range_query(tree_row, vrow, jnp.zeros_like(start_phys),
                                 length - len1)
            return comb_valid(v1, r1, v2, r2)

        return comb_valid, window_query

    def _rebuild_fn(self):
        """Returns the full-forest internal-level rebuild callable — the
        ONE definition shared by the in-program rebuild and the
        standalone settle program (divergence here would make deferred
        batches aggregate differently from direct ones); routes through
        the optional Pallas fast path when enabled."""
        import jax
        import jax.numpy as jnp

        combine = self.op.combine
        F = self.F
        tmap = jax.tree_util.tree_map
        pallas_rebuild = None
        from .pallas_kernels import make_forest_rebuild, pallas_enabled
        if pallas_enabled() and self.trees is not None and self.K_cap >= 8:
            pallas_rebuild = make_forest_rebuild(
                combine, list(self.trees.keys()), F,
                interpret=jax.default_backend() != "tpu")

        def rebuild_levels(trees, tvalid):
            if pallas_rebuild is not None:
                return pallas_rebuild(trees, tvalid)
            lvl = F >> 1
            while lvl >= 1:
                lc = tmap(lambda t: t[:, 2 * lvl:4 * lvl:2], trees)
                rc = tmap(lambda t: t[:, 2 * lvl + 1:4 * lvl:2], trees)
                vlc = tvalid[:, 2 * lvl:4 * lvl:2]
                vrc = tvalid[:, 2 * lvl + 1:4 * lvl:2]
                merged = combine(lc, rc)
                node = tmap(lambda m, a, b: jnp.where(
                    vlc & vrc, m, jnp.where(vlc, a, b)), merged, lc, rc)
                trees = tmap(lambda t, nd: t.at[:, lvl:2 * lvl].set(nd),
                             trees, node)
                tvalid = tvalid.at[:, lvl:2 * lvl].set(vlc | vrc)
                lvl >>= 1
            return trees, tvalid

        return rebuild_levels

    def _make_step(self, cap: int, donate: bool = True,
                   ingest_only: bool = False):
        """``ingest_only=True`` builds the DEFERRED-REBUILD variant: lift
        + segmented scan + leaf scatter only — no level rebuild, no
        window queries, no eviction. Used for batches the host control
        plane already knows fire NOTHING (chunks empty): leaves stay
        current and the next firing program's full-forest rebuild covers
        every deferred batch at once, so the per-batch rebuild cost —
        independent of batch size, hence the dominant term of the
        low-cardinality small-batch regime — is paid per FIRING batch
        only. Soundness: internal nodes are only ever read by fire
        queries, and every fire path rebuilds first (the full program
        in-program; the dataless path via _ensure_rebuilt)."""
        import jax
        import jax.numpy as jnp

        host_seg = self._host_seg
        use_ktable = self._use_ktable()

        lift = self._lift_fn()
        combine = self.op.combine
        F = self.F
        K_cap = self.K_cap
        NNODES = 2 * F
        OOB = K_cap * NNODES  # scatter target for masked lanes (mode=drop)

        tmap = jax.tree_util.tree_map
        comb_valid, window_query = self._query_fns()
        # shared rebuild body (routes through the WF_PALLAS=1 VMEM
        # kernel when enabled; see _rebuild_fn)
        rebuild_levels = self._rebuild_fn()

        def step(fields, comp, h_order, h_same, h_end,
                 h_flat, trees, tvalid,
                 fire_pack, ktable, evict_pack):
            (fire_slots, fire_starts, fire_lens, fire_wids,
             fire_mask_i) = fire_pack
            fire_mask = fire_mask_i != 0
            evict_slots, evict_leaves, evict_mask_i = evict_pack
            evict_mask = evict_mask_i != 0
            # 1. lift + sort + segmented scan. WHERE the sort happens is
            # backend-dependent: on accelerators it runs in-program (device
            # work overlaps the host control plane); on the CPU backend the
            # host precomputes the order/run metadata with numpy (h_* args;
            # ``comp`` is a dummy then, and vice versa — the cache key
            # includes the mode). In device mode the host ships ONE packed
            # composite array (slot*F+leaf, sentinel K_cap*F for late and
            # padding lanes) in the narrowest int dtype — a third of the
            # transfer volume of separate slot/leaf/live arrays, which
            # matters when the chip sits behind a network tunnel.
            vals = broadcast_scalar_fields(
                lift(fields), next(iter(fields.values())).shape[0])
            if host_seg:
                order = h_order
                same_prev = h_same
                is_end = h_end
                flat_idx = h_flat
            else:
                big = jnp.int32(K_cap * F)  # sentinel: late + padding
                order = jnp.argsort(comp, stable=True)
                sc = comp[order].astype(jnp.int32)
                same_prev = jnp.concatenate(
                    [jnp.zeros((1,), bool), sc[1:] == sc[:-1]])
                is_end = jnp.concatenate(
                    [sc[1:] != sc[:-1], jnp.ones((1,), bool)]) & (sc < big)
                # decode slot/leaf from the sorted composite (F is a power
                # of two, so these lower to shift/mask)
                flat_idx = (sc // F) * NNODES + (F + sc % F)
            svals = tmap(lambda a: a[order], vals)

            def seg_op(a, b):
                fa, sa = a
                fb, same_b = b
                merged = combine(fa, fb)
                out = tmap(lambda m, y: jnp.where(same_b, m, y), merged, fb)
                return out, sa & same_b

            scanned, _ = jax.lax.associative_scan(seg_op, (svals, same_prev))

            # 2. scatter-combine segment tails into forest leaves
            safe_idx = jnp.where(is_end, flat_idx, OOB)
            gather_idx = jnp.where(is_end, flat_idx, 0)
            leaf_valid = tvalid.reshape(-1)[gather_idx] & is_end
            cur_leaves = tmap(lambda t: t.reshape(-1)[gather_idx], trees)
            merged_all = combine(cur_leaves, scanned)
            new_leaves = tmap(lambda m, sv: jnp.where(leaf_valid, m, sv),
                              merged_all, scanned)
            trees = tmap(
                lambda t, nl: t.reshape(-1).at[safe_idx].set(
                    nl, mode="drop").reshape(t.shape),
                trees, new_leaves)
            tvalid = tvalid.reshape(-1).at[safe_idx].set(
                True, mode="drop").reshape(tvalid.shape)

            if ingest_only:
                # deferred rebuild: leaves are current, internal nodes
                # stale until the next firing/rebuild program; dummies
                # keep the output arity (callers never read them — the
                # host knew n_out == 0 before choosing this program)
                dummy = tmap(lambda a: jnp.zeros((1,), a.dtype), vals)
                return (trees, tvalid, dummy, jnp.zeros((1,), bool),
                        jnp.zeros((1,), jnp.int32),
                        jnp.zeros((1,), jnp.int32))

            # 3. rebuild internal levels across the whole forest
            trees, tvalid = rebuild_levels(trees, tvalid)

            # 4. fired-window queries (vmapped over W_cap)
            ftrees = tmap(lambda t: t[fire_slots], trees)
            fvalid = tvalid[fire_slots]
            qv, qr = jax.vmap(window_query)(ftrees, fvalid, fire_starts,
                                            fire_lens)
            qv = qv & fire_mask

            # 5. evict leaves consumed by the fired windows
            eflat = jnp.where(evict_mask,
                              evict_slots * NNODES + (F + evict_leaves), OOB)
            tvalid = tvalid.reshape(-1).at[eflat].set(
                False, mode="drop").reshape(tvalid.shape)

            # 6. output wid/key columns built ON DEVICE: they ride the
            # program's batched argument transfer instead of costing one
            # device_put round trip each at emit time
            wid_out = jnp.asarray(fire_wids)
            if use_ktable:
                key_out = jnp.where(fire_mask, ktable[fire_slots],
                                    jnp.zeros((), ktable.dtype))
            else:
                key_out = jnp.zeros((1,), jnp.int32)
            return trees, tvalid, qr, qv, wid_out, key_out

        # trees/tvalid are DONATED: the leaf scatter and level rebuild
        # update the forest in place in HBM instead of copying the whole
        # forest every step (at 10k keys the forest is tens of MB per
        # field). Every caller reassigns self.trees/self.tvalid from the
        # program outputs — including the warm-up no-op runs.
        # donate=False is for surfaces that re-execute on fixed example
        # args (the driver's __graft_entry__.entry()).
        # instrumented_jit: (re)traces land on Compile_count with the
        # step signature, so the prewarm soak can assert compile-flat
        # streams for window stages exactly like fused map chains
        from ..monitoring.flightrec import instrumented_jit
        return instrumented_jit(
            step, self.stats, label=f"{self.stats.op_name}:step",
            donate_argnums=(6, 7) if donate else ())

    def _make_fire_step(self):
        """Fire-only program: vmapped window queries + leaf eviction, no
        lift/scan/scatter/rebuild. Used for drain iterations after the
        first per-batch step and for data-less firing (punctuation/EOS).

        Soundness of skipping the level rebuild: internal nodes are stale
        only where leaves were evicted after the last rebuild, and those
        panes satisfy p_evicted >= next_fire_at_rebuild. Every queried
        pane satisfies p <= max_leaf < next_fire_at_rebuild + F (the
        _grow_ring span guard enforces this at arrival), so an evicted
        pane's ring slot can only be re-queried at pane p_evicted + F >
        max_leaf — excluded because _pack_fire_arrays clips every query
        to the data extent. The clip is also what keeps the invariant
        robust if F sizing ever changes (regression-tested)."""
        import jax
        import jax.numpy as jnp

        F = self.F
        NNODES = 2 * F
        OOB = self.K_cap * NNODES
        tmap = jax.tree_util.tree_map
        _, window_query = self._query_fns()
        use_ktable = self._use_ktable()

        def fire(trees, tvalid, fire_pack, ktable, evict_pack):
            (fire_slots, fire_starts, fire_lens, fire_wids,
             fire_mask_i) = fire_pack
            fire_mask = fire_mask_i != 0
            evict_slots, evict_leaves, evict_mask_i = evict_pack
            evict_mask = evict_mask_i != 0
            ftrees = tmap(lambda t: t[fire_slots], trees)
            fvalid = tvalid[fire_slots]
            qv, qr = jax.vmap(window_query)(ftrees, fvalid, fire_starts,
                                            fire_lens)
            qv = qv & fire_mask
            eflat = jnp.where(evict_mask,
                              evict_slots * NNODES + (F + evict_leaves), OOB)
            tvalid = tvalid.reshape(-1).at[eflat].set(
                False, mode="drop").reshape(tvalid.shape)
            wid_out = jnp.asarray(fire_wids)
            if use_ktable:
                key_out = jnp.where(fire_mask, ktable[fire_slots],
                                    jnp.zeros((), ktable.dtype))
            else:
                key_out = jnp.zeros((1,), jnp.int32)
            return tvalid, qr, qv, wid_out, key_out

        # tvalid donated (in-place eviction); trees is read-only here
        from ..monitoring.flightrec import instrumented_jit
        return instrumented_jit(fire, self.stats,
                                label=f"{self.stats.op_name}:fire",
                                donate_argnums=(1,))

    def _make_rebuild_step(self):
        """Standalone full-forest level rebuild: settles deferred
        (ingest-only) batches before a DATALESS fire — the fire-only
        program skips the rebuild by design and is only sound over a
        freshly rebuilt forest (see _make_fire_step). Shares the rebuild
        body (and the Pallas fast path) with the full program."""
        from ..monitoring.flightrec import instrumented_jit
        return instrumented_jit(self._rebuild_fn(), self.stats,
                                label=f"{self.stats.op_name}:rebuild",
                                donate_argnums=(0, 1))

    def _ensure_rebuilt(self) -> None:
        """Run the standalone rebuild iff ingest-only batches deferred
        it (idempotent: rebuilding from current leaves is always safe).
        Both the dirty flag and the forest belong to the commit stage,
        so in-flight commits must land before reading either."""
        self.dispatch.drain(forced=True)
        if not self._rebuild_dirty or self.trees is None:
            return
        from .ops_tpu import cached_compile
        prog = cached_compile(self._prog_cache, self.op._prog_lock,
                              ("rebuild", self.K_cap, self.F),
                              self._make_rebuild_step)
        self.trees, self.tvalid = prog(self.trees, self.tvalid)
        self.stats.device_programs_run += 1
        self._rebuild_dirty = False
        self._dirty_all = True  # rebuild rewrote internal rows forest-wide

    # ==================================================================
    # host control plane
    # ==================================================================
    def _on_new_key(self, key, s: int) -> None:
        """KeySlotMap callback: per-slot bookkeeping for a fresh key.
        RAISE-BEFORE-MUTATE: KeySlotMap.slot registers the key only when
        this returns, so a refusal (index-plane overflow on growth) must
        fire before any bookkeeping mutates — a caught-and-retried batch
        would otherwise double-append ``_out_keys_by_slot`` and shift
        every later slot's original-key mapping."""
        if s >= self.K_cap:
            # slots are sequential (s == len(map)), so one doubling
            # always covers s; validate the doubled plane FIRST, and
            # grow BEFORE any bookkeeping mutates (growth itself can
            # fail, e.g. device OOM reallocating the doubled forest)
            self._check_index_plane(self.K_cap * 2)
            self._grow_keys()
        self._saw_new_key = True
        self._out_keys_by_slot.append(key)
        if self._keys_all_int and isinstance(key, int):
            self._keys_np[s] = key
        else:
            self._keys_all_int = False
        self._ktable_dirty = True

    def _slots_of(self, keys, keys_arr: np.ndarray, n: int) -> np.ndarray:
        return self._keymap.slots_of(keys, keys_arr, n)

    def _grow_keys(self) -> None:
        """BUILD-THEN-COMMIT: every fallible step (including the device
        reallocation of the doubled forest) runs into locals first; the
        replica mutates only after all of them succeeded, so a caught
        growth failure leaves fully consistent pre-growth state for the
        retry (which re-enters growth from scratch)."""
        import jax
        import jax.numpy as jnp
        # growth reads the CURRENT forest: deferred commits reassign
        # trees/tvalid (donation), so they must land first
        self.dispatch.drain(forced=True)
        old = self.K_cap
        new_cap = old * 2
        grown = {}
        for name, fill in (("next_fire", 0), ("fired", 0),
                           ("max_leaf", -1), ("count", 0),
                           ("_keys_np", 0)):
            arr = getattr(self, name)
            g = np.full(new_cap, fill, dtype=arr.dtype)
            g[:old] = arr
            grown[name] = g
        new_trees = new_tvalid = None
        if self.trees is not None:
            new_trees = jax.tree_util.tree_map(
                lambda t: jnp.zeros((new_cap,) + t.shape[1:], t.dtype)
                .at[:old].set(t), self.trees)
            new_tvalid = jnp.zeros((new_cap, 2 * self.F), bool
                                   ).at[:old].set(self.tvalid)
        self.K_cap = new_cap
        for name, g in grown.items():
            setattr(self, name, g)
        if new_trees is not None:
            self.trees, self.tvalid = new_trees, new_tvalid
        self._ktable_dirty = True
        self._dirty_all = True  # geometry changed under the delta base

    def _grow_ring(self, needed_span: int) -> None:
        """BUILD-THEN-COMMIT, like ``_grow_keys`` (F and the migrated
        forest commit together, after the fallible allocations)."""
        import jax
        import jax.numpy as jnp
        # same ordering rule as _grow_keys: the migration reads the
        # current forest, so deferred commits must land first
        self.dispatch.drain(forced=True)
        old_F = self.F
        new_F = old_F
        while needed_span >= new_F:
            new_F *= 2
        # prospective check BEFORE mutating F or the forest: a caught
        # refusal after mutation would leave a wrapped index plane that
        # no later per-batch guard re-checks
        self._check_index_plane(f=new_F)
        if self.trees is None:
            self.F = new_F
            return
        old_trees, old_valid = self.trees, self.tvalid
        new_trees = jax.tree_util.tree_map(
            lambda t: jnp.zeros((self.K_cap, 2 * new_F), t.dtype), old_trees)
        new_tvalid = jnp.zeros((self.K_cap, 2 * new_F), bool)
        src_rows, src_cols, dst_cols = [], [], []
        for _, s in self.slot_of_key.items():
            for p in range(int(self.next_fire[s]), int(self.max_leaf[s]) + 1):
                src_rows.append(s)
                src_cols.append(old_F + (p % old_F))
                dst_cols.append(new_F + (p % new_F))
        if src_rows:
            sr, sc, dc = (np.asarray(src_rows), np.asarray(src_cols),
                          np.asarray(dst_cols))
            new_trees = jax.tree_util.tree_map(
                lambda new, old: new.at[sr, dc].set(old[sr, sc]),
                new_trees, old_trees)
            new_tvalid = new_tvalid.at[sr, dc].set(old_valid[sr, sc])
        self.F = new_F
        self.trees, self.tvalid = new_trees, new_tvalid
        # only leaves were carried over: internal levels need a rebuild
        # before any fire-only program may query them
        self._rebuild_dirty = True
        self._dirty_all = True  # geometry changed under the delta base

    def _ensure_forest(self, sample_fields) -> None:
        if self.trees is not None:
            return
        import jax
        import jax.numpy as jnp
        shapes = jax.eval_shape(self._lift_fn(), sample_fields)
        if not isinstance(shapes, dict):
            raise WindFlowError(f"{self.op.name}: lift must return a dict "
                                "of columns")
        self.trees = {name: jnp.zeros((self.K_cap, 2 * self.F), sh.dtype)
                      for name, sh in shapes.items()}
        self.tvalid = jnp.zeros((self.K_cap, 2 * self.F), bool)

    # ------------------------------------------------------------------
    def prep_device_batch(self, batch: BatchTPU):
        """HOST-PREP stage of the dispatch pipeline: everything here runs
        on host metadata only (slot resolution, leaf bookkeeping, window
        fire decisions, fire-pack assembly) and never waits on a device
        result — so it overlaps the deferred device commits of earlier
        batches. Paths that must touch the replica's device forest
        (growth, program warm-up) drain the pipeline first."""
        op = self.op
        n = batch.size
        if n == 0:
            return None
        self._ensure_forest(batch.fields)
        if op.key_field is not None and op.key_field in batch.fields:
            self._key_dtype = np.dtype(batch.fields[op.key_field].dtype)
        # fused prefix filter (FusedFfatReplica): rows it drops must not
        # exist for the control plane AT ALL — no key registration, no
        # max_leaf/next_fire advance, no CB count — exactly the rows the
        # unfused topology's filter stage compacts away before the
        # window operator ever sees them. Resolved here (prep time, one
        # small D2H of the mask) because the liveness quantities are
        # exact: deferring the mask to commit time would let phantom
        # rows fire windows early and mis-index CB leaves.
        keep = self._prefix_mask(batch)
        rowsel = None
        if keep is not None:
            n_kept = int(keep.sum())
            if n_kept < n:
                self.stats.inputs_ignored += n - n_kept
                if n_kept == 0:
                    return None
                rowsel = np.nonzero(keep)[0]
        keys, keys_arr = self.batch_keys_np(batch)
        if rowsel is not None:
            sub_arr = np.asarray(keys_arr)[rowsel]
            keys = (sub_arr if isinstance(keys, np.ndarray)
                    else [keys[i] for i in rowsel])
            keys_arr = sub_arr
            n_rows = len(rowsel)
            ts_rows = batch.ts_host[:n][rowsel]
        else:
            n_rows = n
            ts_rows = batch.ts_host[:n]
        slots = self._slots_of(keys, keys_arr, n_rows)
        from ..checkpoint.delta import env_ckpt_delta
        if env_ckpt_delta() and n_rows:
            # every row this batch touches is dirty vs the delta base
            self._ckpt_dirty.update(np.unique(slots).tolist())
        if op.win_type is WinType.TB:
            leaves = ts_rows // op.pane_len
        else:
            # CB: leaf = per-key arrival index (stable within the batch)
            from .keymap import group_positions
            _, within = group_positions(slots, self.K_cap)
            leaves = self.count[slots] + within
            np.add.at(self.count, slots, 1)
        # align brand-new keys to the first window containing their first
        # leaf: without this, an epoch-scale first timestamp would demand a
        # ring spanning all of absolute time (OOM via _grow_ring).
        # Gated on _saw_new_key when slide <= win: then registration sets
        # next_fire at or below the registering tuple's leaf (w0*slide <=
        # first_leaf - win + slide <= first_leaf), so that tuple is live
        # and max_leaf goes >= 0 in the same batch — a slot can only be
        # "fresh" (max_leaf<0) in its registration batch and steady state
        # skips the 16k-gather entirely. With GAP windows (slide > win)
        # the registering tuple can land in a gap and stay late, so the
        # alignment must re-run every batch (pre-gate behavior; regression
        # test: gap_windows_late_first_key_reanchor).
        if op.win_type is WinType.TB and (
                self._saw_new_key or self.slide_units > self.win_units):
            self._saw_new_key = False
            fresh = self.max_leaf[slots] < 0
            if fresh.any():
                fslots = slots[fresh]
                fleaves = leaves[fresh]
                first_leaf = np.full(self.K_cap, np.iinfo(np.int64).max,
                                     dtype=np.int64)
                np.minimum.at(first_leaf, fslots, fleaves)
                sel = np.unique(fslots)
                new_mask = self.max_leaf[sel] < 0  # still untouched slots
                sel = sel[new_mask]
                w0 = np.maximum(
                    0, (first_leaf[sel] - self.win_units)
                    // self.slide_units + 1)
                self.next_fire[sel] = w0 * self.slide_units
                self.fired[sel] = w0
        nf = self.next_fire[slots]
        live = leaves >= nf
        n_live = int(live.sum())
        n_late = n_rows - n_live
        # unified late accounting: this host-side mask is the SAME
        # late/sentinel classification the packed composite below encodes
        # for the device program — export it instead of discarding it.
        # TB: every dropped row sits behind the fired-window frontier,
        # hence behind the watermark, so late_records ⊇ late_dropped and
        # Late_admitted = records - dropped stays exact
        st = self.stats
        if op.win_type is WinType.TB:
            late_mask = ts_rows < batch.wm
            if n_late:
                late_mask = late_mask | ~live
            n_late_seen = int(late_mask.sum())
            if n_late_seen:
                st.note_late(n_late_seen, n_late,
                             batch.wm - ts_rows[late_mask]
                             if st.hist_lateness is not None else None)
        elif n_late:
            # CB: order-based drops (gap windows / re-registered keys)
            st.note_late(n_late, n_late)
        if n_late:
            self.ignored += n_late
            self.stats.inputs_ignored += n_late
        if n_live:
            if (n_late == 0 and n_rows
                    and int(leaves[0]) >= self._leaf_frontier
                    and bool((leaves[1:] >= leaves[:-1]).all())):
                # monotone event time at or past every previously seen
                # leaf (the common in-order source pattern): the last
                # occurrence per slot carries its max leaf AND cannot
                # undercut an older per-slot max, so a plain fancy
                # assignment (last-write-wins for duplicate indices,
                # np.put semantics) replaces the much slower
                # np.maximum.at buffered scatter
                span = int((leaves - nf).max())
                if span >= self.F:
                    self._grow_ring(span)
                self.max_leaf[slots] = leaves
                self._leaf_frontier = int(leaves[-1])
            else:
                # masked forms avoid boolean fancy-index allocations; the
                # -1 sentinel is a no-op under maximum (max_leaf starts
                # at -1)
                masked_leaves = np.where(live, leaves, -1)
                span = int(np.where(live, leaves - nf, -1).max())
                if span >= self.F:
                    self._grow_ring(span)
                np.maximum.at(self.max_leaf, slots, masked_leaves)
                self._leaf_frontier = max(self._leaf_frontier,
                                          int(masked_leaves.max()))

        cap = batch.capacity
        # packed composite (slot*F + leaf, sentinel M = late/padding) in
        # the narrowest int dtype: ONE array instead of separate
        # slot/leaf/live planes — numpy's argsort takes a radix path for
        # int16 (~12x the int64 comparison sort) on the host-seg branch,
        # and in device mode it is the only 16k-sized program argument
        # (a third of the previous H2D volume; int32 is guaranteed by
        # _check_index_plane at init/growth for BOTH seg modes).
        M, cdt = self._comp_dtype()
        comp_p = np.full(cap, M, dtype=cdt)
        packed = slots * self.F + (leaves & (self.F - 1))  # F is pow-2
        if n_late:
            packed = np.where(live, packed, M)
        if rowsel is None:
            comp_p[:n] = packed
        else:
            # prefix-dropped rows keep the sentinel: the in-program
            # segment plane treats them exactly like late/padding lanes
            comp_p[rowsel] = packed
        if self._host_seg:
            big = cdt(M)
            order_p = np.argsort(comp_p, kind="stable").astype(np.int32)
            sc = comp_p[order_p].astype(np.int32)
            same_p = np.r_[False, sc[1:] == sc[:-1]]
            end_p = np.r_[sc[1:] != sc[:-1], True] & (sc < big)
            flat_p = (sc // self.F) * (2 * self.F) + self.F + sc % self.F
            comp_p = np.zeros(1, dtype=cdt)  # device arg shrinks to dummy
        else:
            order_p = same_p = end_p = flat_p = None

        frontier = (max(0, batch.wm - op.lateness) // op.pane_len
                    if op.win_type is WinType.TB else None)
        return self._prep_step(batch.fields, batch.wm, cap, comp_p,
                               order_p, same_p, end_p, flat_p, frontier)

    # ------------------------------------------------------------------
    def _fireable(self, frontier, partial: bool, budget: int):
        """Fire-eligible windows as per-slot chunk ARRAYS
        (slots, start0, k, wid0, max_leaf), each chunk covering the slot's
        consecutive eligible windows, truncated to ``budget``.

        Fully vectorized: one numpy pass over the live slot table per call
        (C-speed even at 10^5 keys; the reference instead walks its key
        descriptor map in a host loop, ``ffat_replica_gpu.hpp:870-1019``).
        Advances next_fire/fired for the windows taken."""
        ns = len(self.slot_of_key)
        empty = (np.zeros(0, np.int64),) * 5
        if ns == 0:
            return empty
        nf = self.next_fire[:ns]
        ml = self.max_leaf[:ns]
        has_data = ml >= nf
        if partial:
            k = (ml - nf) // self.slide_units + 1
        elif self.op.win_type is WinType.TB:
            if frontier is None:
                return empty
            k_front = ((int(frontier) - self.win_units - nf)
                       // self.slide_units + 1)
            k = np.minimum((ml - nf) // self.slide_units + 1, k_front)
        else:  # CB fires purely by count
            k_cnt = ((self.count[:ns] - self.win_units - nf)
                     // self.slide_units + 1)
            k = np.minimum((ml - nf) // self.slide_units + 1, k_cnt)
        k = np.where(has_data, k, 0)
        slots = np.nonzero(k > 0)[0]
        if slots.size == 0:
            return empty
        k = k[slots]
        # budget: clip the chunk sequence where the cumsum crosses
        before = np.cumsum(k) - k
        k = np.minimum(k, budget - before)
        keep = k > 0
        slots, k = slots[keep], k[keep]
        start0 = self.next_fire[slots].copy()
        wid0 = self.fired[slots].copy()
        self.next_fire[slots] += k * self.slide_units
        self.fired[slots] += k
        if self._ckpt_dirty or self._delta_base is not None:
            # firing advances bookkeeping and evicts ring panes
            self._ckpt_dirty.update(slots.tolist())
        return slots, start0, k, wid0, self.max_leaf[slots].copy()

    @staticmethod
    def _segmented_arange(k: np.ndarray) -> np.ndarray:
        """[0..k0), [0..k1), ... concatenated (standard cumsum trick)."""
        tot = int(k.sum())
        before = np.cumsum(k) - k
        return np.arange(tot, dtype=np.int64) - np.repeat(before, k)

    def _pack_fire_arrays(self, chunks, n_out, W: int):
        """Chunk arrays -> padded fire/evict arrays for the device
        programs (shaped for budget ``W``; jit re-traces per shape). Pure
        numpy (repeat + segmented arange): zero per-window or per-chunk
        Python. Fire metadata is PACKED into one (5, W) int32 array
        (rows: slot, start, len, wid, mask) and evictions into one
        (3, E) (rows: slot, leaf, mask) — fewer program arguments means
        fewer per-call transfer enqueues on a tunneled device."""
        c_slots, c_start0, c_k, c_wid0, c_ml = chunks
        E = max(1, W * self.slide_units)
        f_pack = np.zeros((5, W), dtype=np.int32)
        e_pack = np.zeros((3, E), dtype=np.int32)
        ar = self._segmented_arange(c_k)
        starts = np.repeat(c_start0, c_k) + ar * self.slide_units
        f_pack[0, :n_out] = np.repeat(c_slots, c_k)
        f_pack[1, :n_out] = starts % self.F
        # ALWAYS clip the query to the slot's data extent (max_leaf):
        # panes beyond it hold no current data, and their ring slots may
        # alias panes evicted after the last level rebuild — clipping is
        # what makes the rebuild-free fire-only program sound (every slot
        # inside the clipped range was valid at the last rebuild and is
        # untouched by this drain sequence's evictions; aliases land at
        # pane+F > max_leaf, which is excluded here, and _grow_ring
        # guarantees live spans stay below F)
        f_pack[2, :n_out] = np.minimum(self.win_units,
                                       np.repeat(c_ml, c_k) + 1 - starts)
        f_pack[4, :n_out] = 1  # mask row: rides the SAME transfer as the
        # spec rows (one H2D enqueue per pack instead of pack+mask pairs
        # — per-call enqueues are the fixed cost on a tunneled device)
        f_pack[3, :n_out] = np.repeat(c_wid0, c_k) + ar
        # evicted panes: one contiguous range per chunk
        ne = np.maximum(
            0, np.minimum(c_start0 + c_k * self.slide_units, c_ml + 1)
            - c_start0)
        tot_e = int(ne.sum())
        if tot_e:
            ep = np.repeat(c_start0, ne) + self._segmented_arange(ne)
            e_pack[0, :tot_e] = np.repeat(c_slots, ne)
            e_pack[1, :tot_e] = ep % self.F
            e_pack[2, :tot_e] = 1
        return f_pack, e_pack

    def _use_ktable(self) -> bool:
        """Whether programs gather the output key column from a
        device-resident per-slot key table (int keys with a named key
        field; non-int keys fall back to host construction)."""
        return self._keys_all_int and self.op.key_field is not None

    def _ktable_arg(self):
        """Device key table for the programs' key-column gather; re-staged
        only when a new key registered or the capacity/dtype changed —
        zero steady-state transfer."""
        if not self._use_ktable():
            return np.zeros(1, dtype=np.int32)
        import jax
        kd = self._key_dtype
        if (self._ktable_dev is None or self._ktable_dirty
                or self._ktable_kd != kd):
            self._ktable_dev = jax.device_put(self._keys_np.astype(kd))
            self._ktable_kd = kd
            self._ktable_dirty = False
        return self._ktable_dev

    def _first_budget(self) -> int:
        """Fire budget for the first (full) program of a batch — one of
        exactly TWO tiers (both compiled eagerly, so no mid-stream
        retrace ever): the small W_step block, or W_cap when the recent
        fire rate overflows it. Accelerators only: the wide query block
        is overlapped device work there and saves two host dispatches per
        batch, while on the CPU backend the drain path's fire-only
        program (no lift/sort/rebuild) is much cheaper than widening the
        full program."""
        if not self._on_accelerator() or self._fire_ewma * 1.25 <= self.W_step:
            return self.W_step
        return self.W_cap

    def _zero_fire(self, W: int):
        """Device-resident all-zero fire/evict args for non-firing steps
        (cached per budget: zero steady-state transfer)."""
        z = self._zero_fire_cache.get(W)
        if z is None:
            import jax
            E = max(1, W * self.slide_units)
            z = self._zero_fire_cache[W] = (
                jax.device_put(np.zeros((5, W), dtype=np.int32)),
                jax.device_put(np.zeros((3, E), dtype=np.int32)))
        return z

    def _fire_step(self):
        from .ops_tpu import cached_compile
        return cached_compile(self._prog_cache, self.op._prog_lock,
                              ("fire", self.K_cap, self.F,
                               self._use_ktable(), str(self._key_dtype)),
                              self._make_fire_step)

    def _warm_fire_step(self) -> None:
        """Compile the fire-only program EAGERLY (masked no-op run):
        its first real use is mid-stream on a fire burst, and a ~0.5s
        compile there would land inside the measured/latency-critical
        path instead of startup."""
        if self.trees is None:
            return
        if ("fire", self.K_cap, self.F, self._use_ktable(),
                str(self._key_dtype)) in self._prog_cache:
            return  # already compiled (e.g. a new batch-capacity bucket)
        W = self.W_cap
        E = max(1, W * self.slide_units)
        # all-masked no-op run; tvalid is DONATED, so reassign it
        self.tvalid, *_ = self._fire_step()(
            self.trees, self.tvalid,
            np.zeros((5, W), dtype=np.int32),
            self._ktable_arg(),
            np.zeros((3, E), dtype=np.int32))

    def _warm_programs(self, cap, ckey, ikey, fields,
                       order_p, same_p, end_p, flat_p, ktable) -> None:
        """Compile every program variant of a capacity bucket with no-op
        sentinel runs (masked rows, zero fire args): the full step (both
        fire-budget tiers on accelerators), the ingest-only deferred-
        rebuild step, the fire-only drain step, and the standalone
        rebuild. All runs are semantic no-ops on the forest (sentinel
        rows drop, rebuild is idempotent); trees/tvalid are DONATED, so
        each run reassigns them."""
        from .ops_tpu import cached_compile
        step = cached_compile(self._prog_cache, self.op._prog_lock,
                              ckey, lambda: self._make_step(cap))
        istep = cached_compile(
            self._prog_cache, self.op._prog_lock, ikey,
            lambda: self._make_step(cap, ingest_only=True))
        self._warm_fire_step()
        rkey = ("rebuild", self.K_cap, self.F)
        rb = None if rkey in self._prog_cache else cached_compile(
            self._prog_cache, self.op._prog_lock, rkey,
            self._make_rebuild_step)  # cap-independent: a later capacity
        # bucket must not pay a redundant full-forest rebuild execution
        if self._host_seg:
            # host-segmentation no-op: no segment ends -> scatter drops.
            # dtypes must MATCH the real call site (int32 order/flat,
            # bool same/end) or the warm compiles a shape nobody reuses
            comp_s = np.zeros(1, self._comp_dtype()[1])
            seg = (np.arange(cap, dtype=np.int32), np.zeros(cap, bool),
                   np.zeros(cap, bool),
                   np.zeros(cap, dtype=np.int32))
        else:
            _M, cdt = self._comp_dtype()
            comp_s = np.full(cap, _M, dtype=cdt)  # all-sentinel lanes
            seg = (order_p, same_p, end_p, flat_p)
        tiers = {self.W_step}
        if self._on_accelerator():
            tiers.add(self.W_cap)
        for W in tiers:
            zf, ze = self._zero_fire(W)
            (self.trees, self.tvalid, *_) = step(
                fields, comp_s, *seg, self.trees, self.tvalid,
                zf, ktable, ze)
        zf, ze = self._zero_fire(self.W_step)
        (self.trees, self.tvalid, *_) = istep(
            fields, comp_s, *seg, self.trees, self.tvalid, zf, ktable, ze)
        if rb is not None:
            self.trees, self.tvalid = rb(self.trees, self.tvalid)

    def _prewarm_schema(self):
        """Schema of the batches this replica receives (the fused-chain
        replica receives the CHAIN ENTRY's schema, not the window op's
        declared one)."""
        return self.op.schema

    def prewarm(self, caps) -> Optional[int]:
        """``PipeGraph.with_prewarm`` hook: compile every program
        variant (full step at both fire tiers, ingest-only, fire-only,
        standalone rebuild) per bucket capacity BEFORE the stream
        starts, so ragged streams hopping between capacity buckets never
        pay a mid-stream compile. Needs a declared schema — the forest
        shape comes from ``eval_shape`` of the lift over schema-dtyped
        zeros, and the key dtype from the schema's key column."""
        sch = self._prewarm_schema()
        if sch is None:
            return None
        from .ops_tpu import prewarm_zero_fields
        kf = self.op.key_field
        if kf is not None and kf in sch.fields:
            self._key_dtype = np.dtype(sch.fields[kf])
        warmed = 0
        for cap in caps:
            fields = prewarm_zero_fields(sch, cap)
            self._ensure_forest(fields)
            ckey, ikey = self._step_keys(cap)
            if ckey in self._prog_cache and ikey in self._prog_cache:
                continue
            if self._host_seg:
                seg = (None, None, None, None)  # _warm_programs builds
                # its own host-seg no-op arrays per cap
            else:
                if self._seg_dummy is None:
                    import jax
                    self._seg_dummy = tuple(jax.device_put(a) for a in (
                        np.zeros(1, dtype=np.int32),
                        np.zeros(1, dtype=bool), np.zeros(1, dtype=bool),
                        np.zeros(1, dtype=np.int32)))
                seg = self._seg_dummy
            self._warm_programs(cap, ckey, ikey, fields, *seg,
                                self._ktable_arg())
            warmed += 1
        return warmed

    def _step_keys(self, cap: int):
        """(full-step, ingest-only) program cache keys for one capacity
        bucket — the SINGLE definition shared by the per-batch path and
        ``prewarm`` (a key drift between them would compile a program
        nobody reuses and defeat the compile-flat guarantee). The chain
        tag pins fused-prefix variants to their own cache rows."""
        tag = self._chain_tag()
        ckey = ("step", cap, self.K_cap, self.F, self._host_seg,
                self._use_ktable(), str(self._key_dtype), tag)
        ikey = ("ingest", cap, self.K_cap, self.F, self._host_seg, tag)
        return ckey, ikey

    def _prep_step(self, fields, wm, cap, comp_p,
                   order_p, same_p, end_p, flat_p, frontier):
        """Host half of the per-batch step: program warm-up, the ENTIRE
        fire plan — every drain iteration's chunk arrays and packed
        fire/evict args, computed up front because ``_fireable`` reads
        host metadata only (no control decision ever waits on a device
        result) — and the fire-rate EWMA. Returns the device-commit
        thunk for the dispatch pipeline."""
        if order_p is None:  # device mode: cached 1-elem dummies
            if self._seg_dummy is None:
                import jax
                self._seg_dummy = tuple(jax.device_put(a) for a in (
                    np.zeros(1, dtype=np.int32), np.zeros(1, dtype=bool),
                    np.zeros(1, dtype=bool), np.zeros(1, dtype=np.int32)))
            order_p, same_p, end_p, flat_p = self._seg_dummy
        ktable = self._ktable_arg()
        ckey, ikey = self._step_keys(cap)
        if ckey not in self._prog_cache or ikey not in self._prog_cache:
            # first batch of this capacity bucket: compile EVERY program
            # variant now (full both tiers, ingest-only, fire-only,
            # standalone rebuild) so no later batch — firing or not —
            # pays a mid-stream compile. The warm-up's no-op runs consume
            # the live forest (donation), so in-flight commits land first
            self.dispatch.drain(forced=True)
            self._warm_programs(cap, ckey, ikey, fields, order_p, same_p,
                                end_p, flat_p, ktable)
        plan: List[Any] = []
        first = True
        total_fired = 0
        first_budget = self._first_budget()
        while True:
            budget = first_budget if first else self.W_cap
            chunks = self._fireable(frontier, False, budget)
            n_out = int(chunks[2].sum())
            if not first and not n_out:
                break
            if first and not n_out:
                # nothing fireable: ingest-only program (None sentinel
                # in the plan), rebuild DEFERRED to the next
                # firing/rebuild program
                plan.append(None)
                break
            f_pack, e_pack = self._pack_fire_arrays(chunks, n_out, budget)
            plan.append((first, chunks, n_out, f_pack, e_pack, budget))
            total_fired += n_out
            first = False
            if n_out < budget:
                break
        # fast-rise / slow-decay: a burst switches to the wide tier on
        # the very next batch (both tier shapes are already compiled),
        # while decay back to the small tier is smoothed
        if total_fired > self._fire_ewma:
            self._fire_ewma = float(total_fired)
        else:
            self._fire_ewma += 0.25 * (total_fired - self._fire_ewma)
        seg = (comp_p, order_p, same_p, end_p, flat_p)
        return lambda: self._commit_step(fields, wm, seg, ktable,
                                         ckey, ikey, plan)

    def _commit_step(self, fields, wm, seg, ktable, ckey, ikey,
                     plan) -> None:
        """Device half: runs the planned program sequence in order and
        emits each iteration's windows. Reads ``self.trees``/
        ``self.tvalid`` at COMMIT time — earlier queued commits reassign
        them through donation — and owns the ``_rebuild_dirty`` flag
        updates: they must land in DEVICE order (a later batch's prep
        running before this commit must not see, or clobber, a stale
        flag)."""
        comp_p, order_p, same_p, end_p, flat_p = seg
        for entry in plan:
            if entry is None:
                # ingest-only: leaves current, internal nodes stale until
                # the next firing/rebuild program (the rebuild cost is
                # batch-size-independent — the dominant per-batch term of
                # the low-cardinality small-batch regime). Fire args are
                # unused in this variant but still traced: pin the
                # W_step shape so tier switches never retrace it
                zf, ze = self._zero_fire(self.W_step)
                (self.trees, self.tvalid, *_) = self._prog_cache[ikey](
                    fields, comp_p, order_p, same_p, end_p, flat_p,
                    self.trees, self.tvalid, zf, ktable, ze)
                self._rebuild_dirty = True
                self.stats.device_programs_run += 1
                continue
            is_first, chunks, n_out, f_pack, e_pack, budget = entry
            if is_first:
                # full program: lift + scan + scatter + rebuild + fire
                (self.trees, self.tvalid, qr, qv, wid_dev,
                 key_dev) = self._prog_cache[ckey](
                    fields, comp_p, order_p, same_p,
                    end_p, flat_p, self.trees, self.tvalid,
                    f_pack, ktable, e_pack)
                self._rebuild_dirty = False  # in-program rebuild covers
                # every deferred ingest-only batch (full-forest rebuild)
                self._dirty_all = True  # ... and rewrote internal rows
            else:
                # drain iterations: fire-only program (no rebuild)
                self.tvalid, qr, qv, wid_dev, key_dev = self._fire_step()(
                    self.trees, self.tvalid,
                    f_pack, ktable, e_pack)
            self.stats.device_programs_run += 1
            self._emit_windows(wm, chunks, n_out, qr, qv,
                               wid_dev, key_dev, budget)

    def _emit_windows(self, wm, chunks, n_out, qr, qv,
                      wid_dev, key_dev, W: int) -> None:
        import jax

        op = self.op
        fields = dict(qr)
        fields["valid"] = qv
        fields["wid"] = wid_dev  # built in-program: no device_put here
        c_slots, _st, c_k, _w0, _ml = chunks
        slot_per_win = np.repeat(c_slots, c_k)
        if self._keys_all_int:
            out_keys: Any = self._keys_np[slot_per_win]  # numpy, no boxing
        else:
            # composite/object keys (callable extractors): host metadata
            # only — key_field is always a numeric column, so no key
            # COLUMN is built on this branch (a zero-padded asarray of
            # tuples would be ragged)
            out_keys = [self._out_keys_by_slot[s] for s in slot_per_win]
        if op.key_field is not None:
            if self._use_ktable():
                fields[op.key_field] = key_dev  # gathered in-program
            else:
                # build directly in the key column's dtype (float keys
                # must not round-trip through int64)
                kd = self._key_dtype
                key_col = np.zeros(W, dtype=kd)
                key_col[:n_out] = out_keys
                fields[op.key_field] = jax.device_put(key_col)
        out_schema = TupleSchema(
            {name: np.dtype(v.dtype) for name, v in fields.items()})
        ts = np.full(W, wm, dtype=np.int64)
        out = BatchTPU(fields, ts, n_out, out_schema, wm, out_keys)
        self._emit_batch(out)

    # ------------------------------------------------------------------
    def _fire_dataless(self, frontier, partial: bool) -> None:
        """Watermark/EOS made windows fireable without new data: run ONLY
        the fire-only program (no lift/scan/rebuild at all) — after
        settling any rebuild deferred by ingest-only batches, since the
        fire-only program is sound only over a rebuilt forest."""
        if self.trees is None:
            return
        # ordering: windows of deferred batches must emit before any
        # dataless firing (handle_msg/terminate drain already, but
        # direct drivers — bench, profile scripts — reach here too)
        self.dispatch.drain(forced=True)
        while True:
            chunks = self._fireable(frontier, partial, self.W_cap)
            n_out = int(chunks[2].sum())
            if not n_out:
                return
            self._ensure_rebuilt()
            f_pack, e_pack = self._pack_fire_arrays(
                chunks, n_out, self.W_cap)
            self.tvalid, qr, qv, wid_dev, key_dev = self._fire_step()(
                self.trees, self.tvalid, f_pack,
                self._ktable_arg(), e_pack)
            self.stats.device_programs_run += 1
            self._emit_windows(self.cur_wm, chunks, n_out, qr, qv,
                               wid_dev, key_dev, self.W_cap)
            if n_out < self.W_cap:
                return

    def on_punctuation(self, wm: int) -> None:
        if self.op.win_type is WinType.TB:
            frontier = (max(0, self.cur_wm - self.op.lateness)
                        // self.op.pane_len)
            self._fire_dataless(frontier, partial=False)
        super().on_punctuation(wm)

    def flush_on_termination(self) -> None:
        self._fire_dataless(None, partial=True)

    # ------------------------------------------------------------------
    # checkpointing (windflow_tpu.checkpoint): the replica's whole
    # processing state is the key map, the per-slot host bookkeeping
    # arrays, and the device forest — one device_get per tree field
    # (array-shaped state keeps the snapshot a transfer, not a
    # serializer). Device-side caches (ktable, zero-fire constants) and
    # compiled programs rebuild lazily after restore.
    def snapshot_state(self) -> dict:
        import jax
        from ..checkpoint import delta as ckpt_delta

        st = super().snapshot_state()  # drains the dispatch queue
        ctx = ckpt_delta.snapshot_ctx()
        if (self.trees is not None and not self._dirty_all
                and self._base_geom == (self.K_cap, self.F, True)
                and ckpt_delta.delta_eligible(
                    self._delta_base, self._snaps_since_full, ctx)):
            self._snaps_since_full += 1
            st["ffat"] = self._snapshot_ffat_delta()
            return st
        st["ffat"] = {
            "slot_of_key": dict(self.slot_of_key),
            "out_keys_by_slot": list(self._out_keys_by_slot),
            "K_cap": self.K_cap, "F": self.F,
            "next_fire": self.next_fire.copy(),
            "fired": self.fired.copy(),
            "max_leaf": self.max_leaf.copy(),
            "count": self.count.copy(),
            "keys_np": self._keys_np.copy(),
            "keys_all_int": self._keys_all_int,
            "key_dtype": self._key_dtype,
            "saw_new_key": self._saw_new_key,
            "leaf_frontier": self._leaf_frontier,
            "fire_ewma": self._fire_ewma,
            "rebuild_dirty": self._rebuild_dirty,
            "ignored": self.ignored,
            "trees": (None if self.trees is None
                      else jax.device_get(self.trees)),
            "tvalid": (None if self.tvalid is None
                       else np.asarray(jax.device_get(self.tvalid))),
        }
        if ctx is not None and ckpt_delta.env_ckpt_delta():
            # this full capture is the new delta baseline (capture runs
            # post-drain, so no in-flight commit can race the reset)
            self._delta_base = ctx.ckpt_id
            self._base_geom = (self.K_cap, self.F, self.trees is not None)
            self._base_nkeys = len(self.slot_of_key)
            self._snaps_since_full = 0
            self._ckpt_dirty = set()
            self._dirty_all = False
        return st

    def _snapshot_ffat_delta(self) -> dict:
        """Delta against the last full snapshot: only the dirty slot
        rows of every per-slot array + forest plane, plus the (small)
        replaced bookkeeping fields."""
        import jax
        import jax.numpy as jnp
        from ..checkpoint import delta as ckpt_delta

        sl = np.asarray(sorted(self._ckpt_dirty), dtype=np.int64)
        rows = {
            name: {"slots": sl, "leaves": [getattr(self, attr)[sl].copy()]}
            for name, attr in (("next_fire", "next_fire"),
                               ("fired", "fired"),
                               ("max_leaf", "max_leaf"),
                               ("count", "count"),
                               ("keys_np", "_keys_np"))}
        jsl = jnp.asarray(sl)
        leaves, _ = jax.tree_util.tree_flatten(self.trees)
        rows["trees"] = {"slots": sl, "leaves": [
            np.asarray(jax.device_get(lf[jsl])) for lf in leaves]}
        rows["tvalid"] = {"slots": sl, "leaves": [
            np.asarray(jax.device_get(self.tvalid[jsl]))]}
        repl = {"K_cap": self.K_cap, "F": self.F,
                "keys_all_int": self._keys_all_int,
                "key_dtype": self._key_dtype,
                "saw_new_key": self._saw_new_key,
                "leaf_frontier": self._leaf_frontier,
                "fire_ewma": self._fire_ewma,
                "rebuild_dirty": self._rebuild_dirty,
                "ignored": self.ignored}
        carry = []
        if len(self.slot_of_key) == self._base_nkeys:
            # slots are append-only between rebuilds (a rebuild sets
            # _dirty_all, forcing a full snapshot), so an unchanged key
            # count means an unchanged directory: zero-byte carry
            carry += ["slot_of_key", "out_keys_by_slot"]
        else:
            repl["slot_of_key"] = dict(self.slot_of_key)
            repl["out_keys_by_slot"] = list(self._out_keys_by_slot)
        return ckpt_delta.make_delta(
            self._delta_base, rows=rows, replace=repl,
            carry=carry or None)

    def restore_state(self, state: dict) -> None:
        super().restore_state(state)
        # restored state starts a fresh delta lineage
        self._ckpt_dirty = set()
        self._dirty_all = False
        self._delta_base = None
        self._snaps_since_full = 0
        self._base_geom = None
        self._base_nkeys = None
        d = state.get("ffat")
        if d is None:
            return
        import jax
        import jax.numpy as jnp

        # capacity/ring geometry first: the arrays below are shaped by it
        self.K_cap = d["K_cap"]
        self.F = d["F"]
        self._check_index_plane()
        self.slot_of_key.clear()  # shared alias with the KeySlotMap
        self.slot_of_key.update(d["slot_of_key"])
        self._keymap._lut = None
        self._out_keys_by_slot = list(d["out_keys_by_slot"])
        self.next_fire = d["next_fire"].copy()
        self.fired = d["fired"].copy()
        self.max_leaf = d["max_leaf"].copy()
        self.count = d["count"].copy()
        self._keys_np = d["keys_np"].copy()
        self._keys_all_int = d["keys_all_int"]
        self._key_dtype = d["key_dtype"]
        self._saw_new_key = d["saw_new_key"]
        self._leaf_frontier = d["leaf_frontier"]
        self._fire_ewma = d["fire_ewma"]
        self._rebuild_dirty = d["rebuild_dirty"]
        self.ignored = d["ignored"]
        self.trees = (None if d["trees"] is None else
                      jax.tree_util.tree_map(jnp.asarray, d["trees"]))
        self.tvalid = (None if d["tvalid"] is None
                       else jnp.asarray(d["tvalid"]))
        # device-side caches are stale for the restored geometry
        self._ktable_dev = None
        self._ktable_kd = None
        self._ktable_dirty = True
        self._zero_fire_cache = {}
        self._seg_dummy = None
