"""Device plane: JAX/XLA siblings of the reference's CUDA operators.

Loaded lazily — ``import windflow_tpu`` never imports jax; importing
``windflow_tpu.tpu`` does.
"""

from .schema import TupleSchema
from .batch import BatchTPU
from .ops_tpu import Filter_TPU, Map_TPU, Reduce_TPU
from .ffat_tpu import Ffat_Windows_TPU
from .ffat_mesh import Ffat_Windows_Mesh
from .builders_tpu import (Ffat_Windows_TPU_Builder, Filter_TPU_Builder,
                           Map_TPU_Builder, Reduce_TPU_Builder)

__all__ = [
    "TupleSchema", "BatchTPU",
    "Map_TPU", "Filter_TPU", "Reduce_TPU", "Ffat_Windows_TPU",
    "Ffat_Windows_Mesh",
    "Map_TPU_Builder", "Filter_TPU_Builder", "Reduce_TPU_Builder",
    "Ffat_Windows_TPU_Builder",
]
