"""TPU edge emitters — the device-plane routing (reference
``wf/forward_emitter_gpu.hpp`` / ``wf/keyby_emitter_gpu.hpp`` /
``wf/broadcast_emitter_gpu.hpp``, template cases <inputGPU, outputGPU>).

- TPUStageEmitter  (CPU -> TPU): accumulates rows + keys into columnar
  staging and ships a ``BatchTPU`` per ``output_batch_size`` tuples. JAX
  ``device_put`` dispatch is async, which provides the copy/compute overlap
  the reference gets from double-buffered pinned staging
  (``keyby_emitter_gpu.hpp:443-505``). KEYBY routing hashes on the host and
  keeps one staging buffer per destination; partial batches flush on
  punctuation/EOS (pad+mask instead of variable shapes).
- TPUForward/Broadcast/KeyByEmitter (TPU -> TPU): batches pass by
  reference (device arrays are immutable); a keyed re-shard gathers
  per-destination sub-batches on device from host-computed index vectors
  (the reference rebuilds its key-index maps with device sort/unique,
  ``keyby_emitter_gpu.hpp:518-583`` — here the host key list is the
  canonical metadata, so no device pass is needed).
- TPUExitEmitter   (TPU -> CPU): D2H (``transfer2CPU``) then delegates rows
  to a wrapped CPU emitter (``forward_emitter_gpu.hpp:323-326``).
"""

from __future__ import annotations

import datetime as _dt
import os
import time
from collections import deque
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from ..basic import ExecutionMode, WindFlowError
from ..message import Batch
from ..runtime.emitters import BasicEmitter
from .batch import BatchTPU, bucket_capacity
from .schema import TupleSchema


class TPUStageEmitter(BasicEmitter):
    """CPU->TPU staging. Routing: FORWARD round-robins full batches,
    KEYBY partitions rows by key hash, BROADCAST ships shared batches."""

    _SWEEP_EVERY = 256  # appended rows between staging-age sweeps

    def __init__(self, num_dests: int, output_batch_size: int,
                 schema: Optional[TupleSchema],
                 key_extractor: Optional[Callable],
                 routing: str = "forward",
                 execution_mode: ExecutionMode = ExecutionMode.DEFAULT,
                 key_field: Optional[str] = None,
                 key_fields: Optional[Tuple[str, ...]] = None) -> None:
        super().__init__(num_dests, output_batch_size, execution_mode)
        self.schema = schema
        self.key_extractor = key_extractor
        self.key_field = key_field  # string extractor: vectorized keys
        self.key_fields = key_fields  # composite extractor: stacked columns
        self.routing = routing
        n_bufs = num_dests if routing == "keyby" else 1
        self._rows: List[list] = [[] for _ in range(n_bufs)]
        self._keys: List[list] = [[] for _ in range(n_bufs)]
        self._wms: List[int] = [0] * n_bufs
        # block-native staging (append_columns): per-destination column
        # buffers filled IN PLACE by array-slice copies — the columnar
        # twin of ``_rows``. A buffer holds row-staged OR block-staged
        # data, never both (the append paths ship the other form first,
        # preserving order). Key slices accumulate as parts and are
        # concatenated once per flush.
        self._cbuf: List[Optional[dict]] = [None] * n_bufs
        self._cts: List[Optional[np.ndarray]] = [None] * n_bufs
        self._ckparts: List[list] = [[] for _ in range(n_bufs)]
        self._ccount: List[int] = [0] * n_bufs
        self._ccap = 0  # capacity bucket of the block staging buffers
        # per-buffer min/max origin stamps of traced rows (latency tracing)
        self._trace_lo: List[int] = [0] * n_bufs
        self._trace_hi: List[int] = [0] * n_bufs
        self._rr = 0
        # time-bounded staging (reference: the GPU keyby emitter flushes
        # partial batches rather than parking them, keyby_emitter_gpu.hpp:
        # 740): a partial batch older than this ships even though it is
        # not full, so low-rate streams pay at most ~this much batching
        # delay instead of the full fill time. Only binds when the batch
        # fills SLOWER than the bound — saturated streams are unaffected.
        # Partial batches keep the full capacity bucket: no new compiles.
        # Default 25 ms: the YSB A/B (PERF.md) showed 5 ms multiplies the
        # program count enough to hurt BOTH latency and throughput when
        # host and XLA share cores; 25 ms beat 0 and 5 on each metric.
        try:
            age_ms = float(os.environ.get("WF_MAX_STAGING_MS", "25"))
        except ValueError:
            age_ms = 25.0
        self._stage_age_s = age_ms / 1e3 if age_ms > 0 else None
        self._first_append: List[Optional[float]] = [None] * n_bufs
        self._sweep_every = self._SWEEP_EVERY
        self._sweep_countdown = 1  # first append reads the clock, then adapts
        self._last_sweep = time.monotonic()
        # staging-buffer recycling over async H2D (reference
        # recycling_gpu.hpp per-emitter pools + in-transit counters)
        from ..recycling import ArrayPool, InFlightRecycler
        self.recycler = InFlightRecycler(ArrayPool())
        self._pool_seen = (0, 0)  # (hits, misses) already added to stats

    def _update_pool_stats(self) -> None:
        """Accumulate pool counter DELTAS: several emitters may share one
        StatsRecord (split branches), so assignment would drop data."""
        p = self.recycler.pool
        h0, m0 = self._pool_seen
        self.stats.staging_pool_hits += p.hits - h0
        self.stats.staging_pool_misses += p.misses - m0
        self._pool_seen = (p.hits, p.misses)

    def emit(self, payload: Any, ts: int, wm: int,
             msg_id: Optional[int] = None) -> None:
        if self.schema is None:
            self.schema = TupleSchema.infer(payload)
        key = (self.key_extractor(payload)
               if self.key_extractor is not None else None)
        buf = (_dest_of_key(key, self.num_dests)
               if self.routing == "keyby" else 0)
        if self._ccount[buf]:
            self._ship(buf)  # block-staged partials precede this row
        rows = self._rows[buf]
        if not rows:
            self._wms[buf] = wm
            if self._stage_age_s is not None:
                self._first_append[buf] = time.monotonic()
        elif wm < self._wms[buf]:
            self._wms[buf] = wm
        rows.append((payload, ts))
        if self.trace_ts:  # traced row: fold its stamp into the buffer
            t0 = self.trace_ts
            self.trace_ts = 0
            if self._trace_lo[buf] == 0 or t0 < self._trace_lo[buf]:
                self._trace_lo[buf] = t0
            if t0 > self._trace_hi[buf]:
                self._trace_hi[buf] = t0
        if self.key_extractor is not None:
            self._keys[buf].append(key)
        if len(rows) >= self.output_batch_size:
            self._ship(buf)
        self._sweep_tick(1)
        self._maybe_generate_punctuation(wm)

    def _sweep_tick(self, n_rows: int) -> None:
        """Staging-age sweep bookkeeping, hoisted to once per append CALL
        on the row path and once per BLOCK on the columnar path (the
        countdown decrements by the rows the call staged, so the adaptive
        cadence sees the same row counts as per-row bookkeeping would).

        Sweep EVERY buffer: under keyby routing a shifted key
        distribution must not park another buffer's partial batch
        past the bound (the idle tick never fires on a busy stream).
        AMORTIZED with a rate-ADAPTIVE cadence — a per-row
        monotonic() + O(num_dests) loop is measurable at tens of
        millions of rows/sec, but a fixed row count would let a
        saturated-but-SLOW stream (queue never empty, so no idle
        ticks) overshoot the bound by rows_per_sweep/rate. Each
        sweep re-targets ~[age/8, age/2] between sweeps: fast
        streams settle at the 256-row cap (clock read every ~µs
        of work), slow ones walk down toward per-row checks,
        where the clock read is negligible at their rate."""
        if self._stage_age_s is None:
            return
        self._sweep_countdown -= n_rows
        if self._sweep_countdown > 0:
            return
        now = time.monotonic()
        dt = now - self._last_sweep
        self._last_sweep = now
        if dt > self._stage_age_s / 2:
            self._sweep_every = max(1, self._sweep_every // 8)
        elif dt < self._stage_age_s / 8:
            self._sweep_every = min(self._SWEEP_EVERY,
                                    self._sweep_every * 2)
        self._sweep_countdown = self._sweep_every
        for b in range(len(self._rows)):
            t0 = self._first_append[b]
            if t0 is not None and now - t0 >= self._stage_age_s \
                    and (self._rows[b] or self._ccount[b]):
                self._ship(b)

    def on_idle(self) -> bool:
        """Worker idle tick: ship partial batches older than the staging
        bound (a quiet stream must not park staged rows indefinitely)."""
        if self._stage_age_s is None:
            return False
        now = time.monotonic()
        did = False
        for buf in range(len(self._rows)):
            t0 = self._first_append[buf]
            if t0 is not None and now - t0 >= self._stage_age_s \
                    and (self._rows[buf] or self._ccount[buf]):
                self._ship(buf)
                did = True
        return did

    def _ship(self, buf: int) -> None:
        if self._ccount[buf]:
            self._ship_cbuf(buf)
        rows = self._rows[buf]
        if not rows:
            return
        rec = self.stats.recorder if self.stats is not None else None
        t0 = time.perf_counter_ns() if rec is not None else 0
        keys = self._keys[buf] if self.key_extractor is not None else None
        batch = BatchTPU.stage(rows, self.schema, self._wms[buf], keys,
                               bucket_capacity(self.output_batch_size
                                               if len(rows) <= self.output_batch_size
                                               else len(rows)),
                               recycler=self.recycler)
        n = len(rows)
        self._rows[buf] = []
        self._keys[buf] = []
        if rec is not None:
            # host batch construction IS this plane's host_prep: the
            # rows -> columns encode + pad + device_put
            rec.event("host_prep", (time.perf_counter_ns() - t0) / 1e3, n)
        self._dispatch_batch(buf, batch, n)

    def _ship_cbuf(self, buf: int) -> None:
        """Ship a block-staged buffer: the staging arrays were filled in
        place by ``_append_part`` (already padded to the capacity bucket),
        so the only work left is the key-part concatenation — ONE
        ``np.concatenate`` per flush — and the device_put."""
        n = self._ccount[buf]
        if not n:
            return
        rec = self.stats.recorder if self.stats is not None else None
        t0 = time.perf_counter_ns() if rec is not None else 0
        kparts = self._ckparts[buf]
        keys = None
        if kparts:
            keys = kparts[0] if len(kparts) == 1 else np.concatenate(kparts)
        batch = BatchTPU.stage_prefilled(
            self._cbuf[buf], self._cts[buf], n, self.schema,
            self._wms[buf], keys, self.recycler)
        if rec is not None:
            # block-staged host_prep: buffers already filled in place, so
            # this is the key concat + device_put only
            rec.event("host_prep", (time.perf_counter_ns() - t0) / 1e3, n)
        # ownership of the staging buffers moved to the batch/recycler:
        # a fresh set is allocated at the next append (device_put may
        # alias the host buffer on the CPU backend)
        self._cbuf[buf] = None
        self._cts[buf] = None
        self._ckparts[buf] = []
        self._ccount[buf] = 0
        self._dispatch_batch(buf, batch, n)

    def _dispatch_batch(self, buf: int, batch: BatchTPU, n: int) -> None:
        if self.stats is not None:
            self.stats.outputs_sent += n
            self.stats.device_bytes_h2d += batch.nbytes()
            self._update_pool_stats()
        batch.trace_min = self._trace_lo[buf]
        batch.trace_max = self._trace_hi[buf]
        self._trace_lo[buf] = self._trace_hi[buf] = 0
        self._first_append[buf] = None
        if self.routing == "keyby":
            batch.id = self._next_ids[buf]
            self._next_ids[buf] += 1
            self.ports[buf].send(batch)
        elif self.routing == "broadcast":
            for d in range(self.num_dests):
                out = batch.copy_for_dest() if d > 0 else batch
                out.id = self._next_ids[d]
                self._next_ids[d] += 1
                self.ports[d].send(out)
        else:  # forward round-robin
            batch.id = self._next_ids[self._rr]
            self._next_ids[self._rr] += 1
            self.ports[self._rr].send(batch)
            self._rr = (self._rr + 1) % self.num_dests

    def flush(self) -> None:
        for buf in range(len(self._rows)):
            self._ship(buf)
        # EOS/flush: return every tracked staging buffer to the pool
        self.recycler.drain()

    # -- columnar fast path (push_columns) -----------------------------
    def emit_columns(self, cols, ts_arr, wm: int, trace_rows=None) -> None:
        """Columnar push entry: delegates to the block-native
        ``append_columns`` fast path. KEYBY with an arbitrary callable
        key extractor (no field name to hash vectorized) falls back to
        the generic per-row path — the documented object-key cliff
        (PERF.md)."""
        if self.routing == "keyby" and self.key_field is None \
                and self.key_fields is None:
            return super().emit_columns(cols, ts_arr, wm, trace_rows)
        self.append_columns(cols, ts_arr, wm, trace_rows)

    def append_columns(self, cols, ts_arr, wm: int, trace_rows=None) -> None:
        """Block-native staging: buffer array SLICES instead of per-row
        list appends. Each destination's slice of the block is copied
        once (vectorized) into a staging buffer that is already padded to
        the output capacity bucket; a full buffer ships with no further
        copy — ``device_put`` reads the staging array directly. KEYBY
        routing hashes the key COLUMN once, then argsort/bincount split
        the block into contiguous per-destination slices, so routing cost
        is per-block, not per-row. ``trace_rows`` (int indices) marks the
        traced cohort: a destination's ``trace_lo/hi`` fold the stamp iff
        one of ITS rows is traced."""
        n = len(ts_arr)
        if n == 0:
            return
        if self.schema is None:
            self.schema = TupleSchema(
                {k: np.asarray(v).dtype for k, v in cols.items()})
        t_trace = self.trace_ts
        self.trace_ts = 0
        tmask = None
        if t_trace and trace_rows is not None and len(trace_rows):
            # None tmask + a stamp means "whole block traced" (legacy
            # per-push stamping); an explicit cohort builds the row mask
            tmask = np.zeros(n, dtype=bool)
            tmask[trace_rows] = True
        if self.routing == "keyby":
            kcol, dests = self._block_dests(cols, n)
            if self.num_dests == 1:
                self._append_part(0, {k: np.asarray(v) for k, v in
                                      cols.items()},
                                  ts_arr, np.array(kcol), wm, t_trace,
                                  tmask)
            else:
                # ONE stable sort + one gather per column routes the
                # whole block; per-destination slices are then contiguous
                # views (zero further copies before the staging write)
                order = np.argsort(dests, kind="stable")
                counts = np.bincount(dests, minlength=self.num_dests)
                scols = {k: np.asarray(v)[order] for k, v in cols.items()}
                sts = ts_arr[order]
                skeys = kcol[order]
                stm = tmask[order] if tmask is not None else None
                off = 0
                for d in range(self.num_dests):
                    c = int(counts[d])
                    if c:
                        sl = slice(off, off + c)
                        self._append_part(
                            d, {k: v[sl] for k, v in scols.items()},
                            sts[sl], skeys[sl], wm, t_trace,
                            stm[sl] if stm is not None else None)
                    off += c
        else:
            keys = None
            if self.key_field is not None:
                # copy: the caller may reuse its arrays after push_columns
                keys = np.array(cols[self.key_field])
            elif self.key_fields is not None:
                keys = _stack_key_fields(cols, self.key_fields, n)
            self._append_part(0, cols, ts_arr, keys, wm, t_trace, tmask)
        self._sweep_tick(n)
        # punctuation cadence is per TUPLE (basic.py DEFAULT_WM_AMOUNT),
        # not per columnar push
        self._emit_count += max(0, n - 1)
        self._maybe_generate_punctuation(wm)

    def _block_dests(self, cols, n: int):
        """(key column, destination vector) for a KEYBY block — hashed
        vectorized where the key dtype allows, per-row only for
        object/mixed keys."""
        if self.key_field is not None:
            kcol = np.asarray(cols[self.key_field])
            dests = None
            if _int_keys_hashable_as_identity(kcol, n):
                # hash(n) == n for ints in [0, 2^61-1): the vectorized
                # modulo routes identically to the per-tuple hash of
                # the CPU/TPU keyby emitters
                dests = kcol.astype(np.int64) % self.num_dests
            elif kcol.dtype.kind in "SU":
                dests = _bytes_key_dests(kcol, n, self.num_dests)
        else:
            # composite multi-field key: a structured (void) column
            # carries the key downstream; routing is the vectorized
            # per-field FNV fold over the same structured form
            kcol = _stack_key_fields(cols, self.key_fields, n)
            dests = _vector_key_dests(kcol, n, self.num_dests)
        if dests is None:
            # object keys (mixed types): the per-row Python cliff —
            # documented + bounded in PERF.md
            dests = np.fromiter(
                (_dest_of_key(k, self.num_dests)
                 for k in kcol.tolist()),
                dtype=np.int64, count=n)
        return kcol, dests

    def _append_part(self, buf: int, pcols, pts, pkeys, wm: int,
                     t_trace: int, tmask=None) -> None:
        """Append one destination's slice of a column block to its
        staging buffer, shipping whenever the buffer reaches the output
        batch size. The single host copy per column happens here (caller
        arrays -> staging buffer), so callers may reuse their arrays."""
        if self._rows[buf]:
            self._ship(buf)  # row-staged partials precede this block
        n = len(pts)
        obs = self.output_batch_size
        if obs <= 0:
            # unbatched edge: the block ships as-is (no re-batching);
            # _dispatch_batch transfers the trace stamps, so fold them
            # into the buffer slots it reads
            batch = BatchTPU.stage_columns(pcols, pts, self.schema, wm,
                                           pkeys, self.recycler)
            if t_trace and (tmask is None or tmask.any()):
                self._trace_lo[buf] = self._trace_hi[buf] = t_trace
            self._wms[buf] = wm
            self._dispatch_batch(buf, batch, n)
            return
        names = list(self.schema.fields)
        off = 0
        while off < n:
            cb = self._cbuf[buf]
            if cb is None:
                cb = self._cbuf_alloc(buf)
            cnt = self._ccount[buf]
            if cnt == 0:
                self._wms[buf] = wm
                if self._stage_age_s is not None:
                    self._first_append[buf] = time.monotonic()
            elif wm < self._wms[buf]:
                self._wms[buf] = wm
            take = min(n - off, obs - cnt)
            end = off + take
            for name in names:
                cb[name][cnt:cnt + take] = pcols[name][off:end]
            self._cts[buf][cnt:cnt + take] = pts[off:end]
            if pkeys is not None:
                self._ckparts[buf].append(pkeys[off:end])
            if t_trace and (tmask is None or tmask[off:end].any()):
                if self._trace_lo[buf] == 0 or t_trace < self._trace_lo[buf]:
                    self._trace_lo[buf] = t_trace
                if t_trace > self._trace_hi[buf]:
                    self._trace_hi[buf] = t_trace
            self._ccount[buf] = cnt + take
            off = end
            if cnt + take >= obs:
                self._ship_cbuf(buf)

    def _cbuf_alloc(self, buf: int) -> dict:
        cap = self._ccap
        if cap == 0:
            cap = self._ccap = bucket_capacity(self.output_batch_size)
        pooled = self.recycler.enabled
        pool = self.recycler.pool
        cb = {name: (pool.acquire(dt, cap) if pooled
                     else np.zeros(cap, dtype=dt))
              for name, dt in self.schema.fields.items()}
        self._cbuf[buf] = cb
        # ts is NEVER pooled: it becomes the batch's ts_host metadata and
        # lives as long as the batch itself (see BatchTPU.stage_columns)
        self._cts[buf] = np.zeros(cap, dtype=np.int64)
        return cb


def _async_copy(arr: Any) -> None:
    """Start an async host copy of one device column (no-op for plain
    numpy arrays on the CPU backend)."""
    f = getattr(arr, "copy_to_host_async", None)
    if f is not None:
        f()


def _maybe_prefetch_key(batch: BatchTPU, field: Optional[str]) -> None:
    """Start an async host copy of the key column when the downstream
    keyed device op will have to read it (no host key metadata on the
    batch — e.g. the key was computed ON DEVICE by an upstream Map_TPU).
    Without this, the consumer's key read is a synchronous D2H of a fresh
    buffer (~70 ms fixed on the tunneled TPU)."""
    if field is None or batch.host_keys is not None:
        return
    if field in batch.fields:
        _async_copy(batch.fields[field])


class TPUForwardEmitter(BasicEmitter):
    """TPU->TPU forward: whole batches round-robin. ``prefetch_field``
    (set by the graph wiring) names the consumer's key column for the
    async-prefetch above."""

    prefetch_field: Optional[str] = None

    def emit_device_batch(self, batch: BatchTPU) -> None:
        _maybe_prefetch_key(batch, self.prefetch_field)
        d = getattr(self, "_rr", 0)
        batch.id = self._next_ids[d]
        self._next_ids[d] += 1
        if self.stats is not None:
            self.stats.outputs_sent += batch.size
        self.ports[d].send(batch)
        self._rr = (d + 1) % self.num_dests


class TPUBroadcastEmitter(BasicEmitter):
    """TPU->TPU broadcast: immutable device arrays are shared."""

    prefetch_field: Optional[str] = None

    def emit_device_batch(self, batch: BatchTPU) -> None:
        _maybe_prefetch_key(batch, self.prefetch_field)
        for d in range(self.num_dests):
            out = batch.copy_for_dest() if d > 0 else batch
            out.id = self._next_ids[d]
            self._next_ids[d] += 1
            if self.stats is not None:
                self.stats.outputs_sent += out.size
            self.ports[d].send(out)


class _D2HPipeline:
    """FIFO of device batches with async host copies in flight. On the
    tunneled TPU a synchronous fetch of a fresh device buffer costs ~70 ms
    of FIXED latency (size-independent); overlapping ``depth`` fetches
    amortizes it (8 overlapped fetches measured ~90 ms total vs ~565 ms
    serial — scripts/profile_d2h.py). A queued batch is processed when a
    later batch pushes it out or a drain point (single-row emit,
    punctuation, flush, EOS) forces ordering. Latency-sensitive exits can
    set depth 0 (immediate, synchronous D2H) via the env knobs."""

    def _pipe_init(self, env_var: str, default: int,
                   depth: Optional[int] = None) -> None:
        self.depth = (depth if depth is not None
                      else int(os.environ.get(env_var, str(default))))
        try:
            age_ms = float(os.environ.get("WF_PIPELINE_MAX_AGE_MS", "100"))
        except ValueError:
            age_ms = 100.0
        # wall-clock age bound: on a saturated stream with sparse output
        # (and punctuation disabled outside DEFAULT mode) the idle tick
        # never fires, so _pipe_add itself evicts entries older than this.
        # Depth interplay: the bound only binds at inter-batch intervals
        # > age/depth (25 ms at the defaults), where the ~70 ms async D2H
        # of any entry older than 100 ms has already completed — eviction
        # then is a cheap consume, not a sync-fetch stall
        self._max_age_s = age_ms / 1e3 if age_ms > 0 else None
        self._pending: "deque[Tuple[float, BatchTPU]]" = deque()

    def _pipe_process(self, batch: BatchTPU) -> None:
        raise NotImplementedError

    def _pipe_add(self, batch: BatchTPU) -> None:
        self._pending.append((time.monotonic(), batch))
        stats = getattr(self, "stats", None)
        if stats is not None:
            stats.note_pipe_depth(len(self._pending))
        while len(self._pending) > self.depth:
            self._pipe_process(self._pending.popleft()[1])
        if self._max_age_s is not None:
            horizon = time.monotonic() - self._max_age_s
            while self._pending and self._pending[0][0] < horizon:
                self._pipe_process(self._pending.popleft()[1])

    def _drain(self) -> None:
        while self._pending:
            self._pipe_process(self._pending.popleft()[1])

    def on_idle(self) -> bool:
        """Worker idle tick: deliver queued batches — an idle stream must
        not withhold already-computed results (Worker._process). Returns
        whether anything was drained (drives the worker's idle backoff)."""
        had = bool(self._pending)
        self._drain()
        return had


_HASH_MODULUS = (1 << 61) - 1  # CPython hash(n) == n iff 0 <= n < 2^61-1
_FNV_OFFSET = 0xcbf29ce484222325
_FNV_PRIME = 0x100000001b3
_M64 = 0xFFFFFFFFFFFFFFFF


def _column_hashes(col: np.ndarray, n: int) -> Optional[np.ndarray]:
    """Per-row uint64 hash lanes for a key (or key-element) column, or
    None when the dtype has no vectorized representation (object columns
    take the per-row path). int/uint/bool hash as their two's-complement
    uint64 value, floats as their float64 bit pattern, str/bytes ('U'/'S')
    as zero-skipping FNV-1a over codepoint/byte lanes (invariant to the
    dtype's zero padding — the same key must route identically when two
    batches of one stream infer different fixed widths), and structured
    (void) rows as an ordered FNV fold over their fields. Each case
    matches its scalar twin in ``_scalar_elem_hash`` EXACTLY: a source
    may mix push() and push_columns() on one stream, and a key's tuples
    must all reach the same replica. NOT CPython-hash-compatible, which
    is fine: keyby routing needs a deterministic, balanced key->dest map
    per edge, not a globally blessed hash (the reference's
    ``keyby_emitter.hpp:210-228`` likewise only needs std::hash
    determinism). Cost is O(n * key_width) vectorized numpy passes.
    (Tried and rejected: np.unique + one hash per distinct key — the
    C string sort alone costs more than these passes.)"""
    kind = col.dtype.kind
    if kind in "iub":
        return col[:n].astype(np.uint64)
    if kind == "f":
        # EQUALITY-COMPATIBLE float hash: keys equal under Python/dict
        # equality must route identically (CPython guarantees
        # hash(1) == hash(1.0) and hash(0.0) == hash(-0.0), and the
        # KeySlotMap dict unifies them), so integral floats hash as
        # their int value (which also normalizes -0.0 to 0) and only
        # non-integral values use their float64 bit pattern. |v| >= 2^63
        # stays on the bit pattern (int64-representable bound, matching
        # _scalar_elem_hash; an int key equal to such a float is the one
        # remaining — astronomically rare — split).
        f64 = col[:n].astype(np.float64)
        with np.errstate(invalid="ignore"):
            integral = (f64 == np.floor(f64)) & (np.abs(f64) < 2.0**63)
        iv = np.where(integral, f64, 0).astype(np.int64).astype(np.uint64)
        return np.where(integral, iv, f64.view(np.uint64))
    if kind in "Mm":
        # datetime64/timedelta64: hash the int64 of the SAME unit the
        # value materializes to on the row path (.item(), and the
        # scalar twin's np.datetime64(date)->'D' / (datetime)->'us' /
        # np.timedelta64(timedelta)->'us' conversions) — date-valued
        # units normalize to days, time-valued to microseconds, and
        # units .item() leaves as raw ints (ns and finer; 'Y'/'M'
        # timedeltas) hash raw. Without this, an 'M8[s]' column and its
        # own rows would route one key to two replicas. Values the row
        # path does NOT materialize as date/datetime/timedelta — NaT
        # (.item() -> None), unit-conversion overflow, instants beyond
        # the datetime range (.item() -> raw int in the SOURCE unit) —
        # push the whole batch to the per-row path instead, which
        # hashes the .item()ed tuples consistently with push() rows.
        unit = np.datetime_data(col.dtype)[0]
        # native byte order first (like the 'U'/'S' branch): a '>M8'
        # column would hash byte-swapped on the raw-view path below
        c = col[:n].astype(col.dtype.newbyteorder("="), copy=False)
        if np.isnat(c).any():
            return None
        canon = lo = hi = None
        if kind == "M":
            if unit in ("Y", "M", "W", "D"):
                canon, lo, hi = "M8[D]", -719162, 2932896  # date range
            elif unit in ("h", "m", "s", "ms", "us"):
                canon = "M8[us]"                     # datetime range, us
                lo, hi = -62135596800000000, 253402300799999999
        elif unit in ("W", "D", "h", "m", "s", "ms", "us"):
            canon = "m8[us]"  # every in-int64 us value is a timedelta
        if canon is None:
            return c.view(np.int64).astype(np.uint64)
        c2 = c.astype(canon)
        i64 = c2.view(np.int64)
        ok = c2.astype(c.dtype) == c  # False on conversion overflow
        if lo is not None:
            ok &= (i64 >= lo) & (i64 <= hi)
        if not ok.all():
            return None
        return i64.astype(np.uint64)
    if kind in "SU":
        lane = np.uint32 if kind == "U" else np.uint8
        # normalize to native byte order first: a '>U4' column
        # (frombuffer/parquet) viewed as uint32 lanes would hash
        # byte-swapped codepoints and split a key across replicas
        c = col[:n].astype(col.dtype.newbyteorder("="), copy=False)
        b = np.ascontiguousarray(c).view(lane).reshape(n, -1)
        h = np.full(n, _FNV_OFFSET, np.uint64)
        prime = np.uint64(_FNV_PRIME)
        for j in range(b.shape[1]):
            bj = b[:, j].astype(np.uint64)
            h = np.where(bj != 0, (h ^ bj) * prime, h)
        return h
    if kind == "V" and col.dtype.names:
        h = np.full(n, _FNV_OFFSET, np.uint64)
        prime = np.uint64(_FNV_PRIME)
        for name in col.dtype.names:
            sub = col[name]
            if sub.dtype.kind == "V":
                # nested structs materialize as nested TUPLES on the row
                # path, where _scalar_elem_hash has no fold — route
                # per-row (hash of the .item()ed tuple) on both sides
                return None
            eh = _column_hashes(sub, n)
            if eh is None:
                return None
            h = (h ^ eh) * prime
        return h
    return None


def _vector_key_dests(kcol: np.ndarray, n: int,
                      num_dests: int) -> Optional[np.ndarray]:
    """Hash-free (no per-row Python) keyby destinations for a TOP-LEVEL
    key column; None when the dtype needs the per-row path. Only
    str/bytes and structured (composite) columns qualify: top-level
    int/float keys route via CPython ``hash()`` on the per-row paths
    (identity for the common non-negative case, handled by the caller),
    and a uint64-wrap here would disagree with ``hash()`` for negative
    keys. As composite ELEMENTS ints hash by value on every path, so
    the 'V' fold stays consistent."""
    if kcol.dtype.kind not in "SUV":
        return None
    if n == 0:
        return np.zeros(0, np.int64)
    h = _column_hashes(kcol, n)
    if h is None:
        return None
    return (h % np.uint64(num_dests)).astype(np.int64)


def _bytes_key_dests(kcol: np.ndarray, n: int, num_dests: int) -> np.ndarray:
    """Vectorized routing for fixed-width bytes/str key columns (kept as
    the named entry point for the 'S'/'U' case; see _column_hashes)."""
    d = _vector_key_dests(kcol, n, num_dests)
    assert d is not None  # 'S'/'U' always vectorizes
    return d


def _composite_key_dests(fcols: List[np.ndarray], n: int,
                         num_dests: int) -> Optional[np.ndarray]:
    """Vectorized destinations for a MULTI-FIELD key given separate
    field columns: stacks them into the structured form and delegates to
    ``_vector_key_dests`` so the ordered FNV fold exists in exactly ONE
    place (the 'V' branch of ``_column_hashes`` — keyby correctness
    depends on the folds staying bit-identical). None when a field
    column has no vectorized representation."""
    cols = {f"f{i}": c for i, c in enumerate(fcols)}
    st = _stack_key_fields(cols, list(cols), n)
    return _vector_key_dests(st, n, num_dests)


def _stack_key_fields(cols, key_fields, n: int,
                      where: str = "push_columns (keyby staging edge)"):
    """Structured key column for a composite key: the structured rows
    (.item()) are the same tuples the per-row path extracts, so
    downstream slot maps unify both forms of one key. Raises a
    descriptive WindFlowError (mirroring ``composite_keys_from_device``)
    instead of a bare KeyError when a key field is missing from the
    pushed columns."""
    missing = [f for f in key_fields if f not in cols]
    if missing:
        raise WindFlowError(
            f"{where}: composite key field(s) "
            f"{', '.join(repr(f) for f in missing)} missing from the "
            f"pushed columns (have: {sorted(map(str, cols))}); every "
            "field of a composite key must be present as a column")
    fcols = [np.asarray(cols[f])[:n] for f in key_fields]
    kcol = np.empty(n, np.dtype(
        [(f, c.dtype) for f, c in zip(key_fields, fcols)]))
    for f, c in zip(key_fields, fcols):
        kcol[f] = c
    return kcol


def composite_keys_from_device(batch: BatchTPU, key_fields) -> np.ndarray:
    """Structured key column for a composite-keyed consumer fed WITHOUT
    host key metadata (an unkeyed device edge upstream): D2H the key
    field columns and stack them. The fields must be device columns —
    non-numeric composite members only travel as keyed-staging host
    metadata."""
    from ..basic import WindFlowError
    cols = {}
    for f in key_fields:
        col = batch.fields.get(f)
        if col is None:
            raise WindFlowError(
                f"composite key field {f!r} is not a device column of "
                "this batch; non-numeric composite keys must be keyed at "
                "the staging edge (with_key_by on the operator fed by "
                "the CPU plane), which carries them as host metadata")
        cols[f] = np.asarray(col)
    return _stack_key_fields(cols, key_fields, batch.size)


def _scalar_fnv(lanes) -> int:
    """Scalar twin of the 'S'/'U' branch of ``_column_hashes`` (zero
    lanes skipped): per-row str/bytes keys must route identically to
    their columnar form."""
    h = _FNV_OFFSET
    for v in lanes:
        if v:
            h = ((h ^ v) * _FNV_PRIME) & _M64
    return h


def _scalar_elem_hash(v) -> Optional[int]:
    """Scalar twin of ``_column_hashes`` for one composite-key element;
    None for element types with no columnar representation (the whole
    key then falls back to CPython hash on every path)."""
    if isinstance(v, (np.datetime64, np.timedelta64)):
        # BEFORE the int branch: np.timedelta64 subclasses np.integer
        # (int() on it raises). Normalize units exactly like the
        # kind-'M'/'m' branch of _column_hashes so non-canonical-unit
        # scalars route with their columnar forms.
        unit = np.datetime_data(v.dtype)[0]
        if isinstance(v, np.datetime64):
            if unit in ("Y", "M", "W", "D"):
                v = v.astype("M8[D]")
            elif unit in ("h", "m", "s", "ms", "us"):
                v = v.astype("M8[us]")
        elif unit in ("W", "D", "h", "m", "s", "ms", "us"):
            v = v.astype("m8[us]")
        return int(v.view(np.int64)) & _M64
    if isinstance(v, (bool, np.bool_, int, np.integer)):
        return int(v) & _M64
    if isinstance(v, (float, np.floating)):
        f = float(v)
        # integral floats hash as their int value (dict equality unifies
        # 1 and 1.0, and -0.0 with 0) — the exact twin of the kind-'f'
        # branch in _column_hashes
        if f.is_integer() and abs(f) < 2.0**63:  # False for nan/inf
            return int(f) & _M64
        return int(np.float64(f).view(np.uint64))
    if isinstance(v, str):
        return _scalar_fnv(map(ord, v))
    if isinstance(v, bytes):
        return _scalar_fnv(v)
    if isinstance(v, _dt.date):       # datetime.datetime is a date too
        return int(np.datetime64(v).view(np.int64)) & _M64
    if isinstance(v, _dt.timedelta):
        return int(np.timedelta64(v).view(np.int64)) & _M64
    return None


def _dest_of_key(key, num_dests: int) -> int:
    """Per-row keyby destination, consistent with the vectorized columnar
    routing: FNV over codepoints for str (matching numpy 'U' columns) or
    bytes ('S' columns), an ordered FNV fold over elements for tuples /
    structured rows (matching stacked-column composite keys), CPython
    hash for everything else (ints route as identity either way)."""
    if isinstance(key, str):
        return _scalar_fnv(map(ord, key)) % num_dests
    if isinstance(key, bytes):
        return _scalar_fnv(key) % num_dests
    if isinstance(key, np.void) and key.dtype.names:
        key = key.item()  # structured row -> plain tuple
    if isinstance(key, tuple):
        h = _FNV_OFFSET
        for v in key:
            eh = _scalar_elem_hash(v)
            if eh is None:
                break
            h = ((h ^ eh) * _FNV_PRIME) & _M64
        else:
            return h % num_dests
    return hash(key) % num_dests


def _int_keys_hashable_as_identity(kcol: np.ndarray, n: int) -> bool:
    """True when ``kcol % num_dests`` routes exactly like the per-tuple
    ``hash(key) % num_dests`` of the CPU/TPU keyby emitters (keys must be
    non-negative ints below the Mersenne hash modulus)."""
    if kcol.dtype.kind == "u":
        return n == 0 or int(kcol.max()) < _HASH_MODULUS
    if kcol.dtype.kind == "i":
        return n == 0 or (int(kcol.min()) >= 0
                          and int(kcol.max()) < _HASH_MODULUS)
    return False


def gather_sub_batch(batch: BatchTPU, idx: np.ndarray,
                     host_keys=None) -> BatchTPU:
    """Gather ``idx`` rows of a device batch into a new (smaller) device
    batch without leaving HBM: one XLA gather per column from a
    host-computed index vector. Shared by the keyed re-shard and the
    device-plane splitting emitter."""
    import jax

    cap = bucket_capacity(idx.size)
    gather = np.zeros(cap, dtype=np.int32)
    gather[:idx.size] = idx
    gidx = jax.device_put(gather)
    sub_fields = {k: v[gidx] for k, v in batch.fields.items()}
    ts2 = batch.ts_host[gather]
    if host_keys is None and batch.host_keys is not None:
        hk = batch.host_keys
        host_keys = (hk[idx] if isinstance(hk, np.ndarray)
                     else [hk[j] for j in idx])
    keys2 = host_keys
    sub = BatchTPU(sub_fields, ts2, idx.size, batch.schema, batch.wm, keys2)
    sub.stream_tag = batch.stream_tag
    return sub.copy_trace_from(batch)


class TPUKeyByEmitter(BasicEmitter, _D2HPipeline):
    """TPU->TPU keyed re-shard: per-destination sub-batches gathered on
    device with host-computed index vectors.

    Batches WITHOUT host key metadata (key computed on device upstream)
    need a D2H of the key column before routing; those go through the
    _D2HPipeline FIFO with an async copy in flight. Batches WITH metadata
    route immediately (after draining the FIFO, preserving order)."""

    def __init__(self, key_extractor: Callable, num_dests: int,
                 execution_mode: ExecutionMode = ExecutionMode.DEFAULT,
                 key_field: Optional[str] = None,
                 depth: Optional[int] = None,
                 key_fields: Optional[Tuple[str, ...]] = None) -> None:
        super().__init__(num_dests, 0, execution_mode)
        self.key_extractor = key_extractor
        self.key_field = key_field
        self.key_fields = key_fields
        self._pipe_init("WF_KEYBY_PIPELINE_DEPTH", 2, depth)

    def _keys_of(self, batch: BatchTPU):
        if batch.host_keys is not None:
            return batch.host_keys
        if self.key_field is not None:
            from .batch import key_column_to_list
            return key_column_to_list(batch, self.key_field)
        if self.key_fields:
            return composite_keys_from_device(batch, self.key_fields)
        raise RuntimeError(
            "keyed TPU re-shard needs host key metadata or a field-name "
            "key extractor (with_key_by('field') or a tuple of fields)")

    def emit_device_batch(self, batch: BatchTPU) -> None:
        if self.num_dests == 1:
            self._drain()
            batch.id = self._next_ids[0]
            self._next_ids[0] += 1
            if self.stats is not None:
                self.stats.outputs_sent += batch.size
            self.ports[0].send(batch)
            return
        if batch.host_keys is None and (self.key_field is not None
                                        or self.key_fields):
            for f in ((self.key_field,) if self.key_field is not None
                      else self.key_fields):
                _async_copy(batch.fields.get(f))
            self._pipe_add(batch)
            return
        self._drain()  # keep stream order ahead of an immediate route
        self._pipe_process(batch)

    def flush(self) -> None:
        # BasicEmitter's propagate_punctuation/send_eos_all call flush()
        # first, so draining here covers every ordering point
        self._drain()
        super().flush()

    def _pipe_process(self, batch: BatchTPU) -> None:
        host_keys = self._keys_of(batch)
        dests = None
        if isinstance(host_keys, np.ndarray):
            if _int_keys_hashable_as_identity(host_keys[:batch.size],
                                              batch.size):
                # hash(n) == n for ints in [0, 2^61-1): vectorized routing
                dests = (host_keys[:batch.size].astype(np.int64)
                         % self.num_dests)
            else:
                # str/bytes lanes and structured (composite) rows both
                # vectorize; None falls through to the per-row path
                dests = _vector_key_dests(host_keys, batch.size,
                                          self.num_dests)
        if dests is None:
            dests = np.fromiter(
                (_dest_of_key(k, self.num_dests) for k in host_keys),
                dtype=np.int64, count=batch.size)
        for d in range(self.num_dests):
            idx = np.nonzero(dests == d)[0]
            if idx.size == 0:
                continue
            sub = gather_sub_batch(
                batch, idx,
                host_keys[idx] if isinstance(host_keys, np.ndarray)
                else [host_keys[j] for j in idx])
            sub.id = self._next_ids[d]
            self._next_ids[d] += 1
            if self.stats is not None:
                self.stats.outputs_sent += sub.size
            self.ports[d].send(sub)


class TPUSplittingEmitter(BasicEmitter, _D2HPipeline):
    """Device-plane split (reference ``wf/splitting_emitter_gpu.hpp:48-341``,
    wired at ``wf/multipipe.hpp:698-708``): routes per-branch sub-batches
    after a TPU operator. The reference transfers the whole batch to host
    and re-stages per branch; here the data stays in HBM — only the routing
    decision touches the host, and each branch receives a device gather of
    its rows (same shape as the keyed re-shard).

    ``splitting_logic`` forms:
    - a string field name: the int32/int64 column holds the branch index
      per row (vectorized: one column D2H, no per-tuple Python);
    - a callable payload -> int | iterable[int] | None (reference
      contract): rows are materialized once per batch to evaluate it.
    """

    def __init__(self, splitting_logic, inner_emitters: List[BasicEmitter],
                 execution_mode: ExecutionMode = ExecutionMode.DEFAULT,
                 depth: Optional[int] = None) -> None:
        super().__init__(sum(e.num_dests for e in inner_emitters), 0,
                         execution_mode)
        self.splitting_logic = splitting_logic
        self.inner = inner_emitters
        # the routing decision needs a D2H read; pipeline it (_D2HPipeline)
        self._pipe_init("WF_SPLIT_PIPELINE_DEPTH", 2, depth)

    def set_stats(self, stats) -> None:
        self.stats = stats
        for e in self.inner:
            e.set_stats(stats)

    def _branch_rows(self, batch: BatchTPU) -> List[np.ndarray]:
        """Row indices per branch (host-side routing decision)."""
        n_branches = len(self.inner)
        logic = self.splitting_logic
        if isinstance(logic, str):
            col = np.asarray(batch.fields[logic])[:batch.size]
            if self.stats is not None:
                self.stats.device_bytes_d2h += int(col.nbytes)
            if col.size and (col.min() < 0 or col.max() >= n_branches):
                from ..basic import WindFlowError
                raise WindFlowError(
                    f"split field {logic!r} holds branch index "
                    f"{int(col.min())}..{int(col.max())} outside "
                    f"[0, {n_branches})")
            return [np.nonzero(col == b)[0] for b in range(n_branches)]
        sel: List[list] = [[] for _ in range(n_branches)]
        if self.stats is not None:
            self.stats.device_bytes_d2h += batch.nbytes()
        from ..runtime.emitters import check_branch_index
        for i, (payload, _ts) in enumerate(batch.to_rows()):
            s = logic(payload)
            if s is None:
                continue
            if isinstance(s, int):
                sel[check_branch_index(s, n_branches)].append(i)
            else:
                for b in s:
                    sel[check_branch_index(b, n_branches)].append(i)
        return [np.asarray(ix, dtype=np.int64) for ix in sel]

    def _pipe_process(self, batch: BatchTPU) -> None:
        per_branch = self._branch_rows(batch)
        for b, idx in enumerate(per_branch):
            if idx.size == 0:
                continue
            if idx.size == batch.size:
                # every row selected this branch: no gather needed (device
                # arrays are immutable; copy only the metadata wrapper)
                sub = batch.copy_for_dest()
            else:
                sub = gather_sub_batch(batch, idx)
            self.inner[b].emit_device_batch(sub)

    def emit_device_batch(self, batch: BatchTPU) -> None:
        logic = self.splitting_logic
        if isinstance(logic, str):
            _async_copy(batch.fields[logic])
        else:
            batch.prefetch_host()  # callable logic reads every column
        self._pipe_add(batch)

    def on_idle(self) -> bool:
        # drain our routing FIFO, then the branch emitters' own FIFOs
        # (a TPU->CPU branch nests a TPUExitEmitter the worker can't see)
        did = bool(self._pending)
        self._drain()
        for e in self.inner:
            f = getattr(e, "on_idle", None)
            if f is not None:
                did = bool(f()) or did
        return did

    def propagate_punctuation(self, wm: int) -> None:
        self._drain()
        for e in self.inner:
            e.propagate_punctuation(wm)

    def flush(self) -> None:
        self._drain()
        for e in self.inner:
            e.flush()

    def send_eos_all(self) -> None:
        self._drain()
        for e in self.inner:
            e.send_eos_all()

    def send_barrier_all(self, barrier) -> None:
        self._drain()
        for e in self.inner:
            e.send_barrier_all(barrier)

    def eos_ports(self):
        return [p for e in self.inner for p in e.eos_ports()]

    def emitter_state(self) -> dict:
        return {"inner": [e.emitter_state() for e in self.inner]}

    def restore_emitter_state(self, state: dict) -> None:
        for e, st in zip(self.inner, state.get("inner", [])):
            e.restore_emitter_state(st)


class TPUColumnarExitEmitter(BasicEmitter, _D2HPipeline):
    """TPU -> columnar CPU sink: the exit WITHOUT row boxing (the dual
    of ``push_columns``; the reference exit iterates pinned memory
    without materializing objects, ``wf/batch_gpu_t.hpp:154-179``).
    Whole device batches flow to the sink replica, which converts each
    column once (``np.asarray``) and calls the columnar functor once per
    batch. D2H rides the same async-copy pipeline as the row exit."""

    def __init__(self, num_dests: int,
                 execution_mode: ExecutionMode = ExecutionMode.DEFAULT,
                 depth: Optional[int] = None) -> None:
        super().__init__(num_dests, 0, execution_mode)
        self._pipe_init("WF_EXIT_PIPELINE_DEPTH", 4, depth)
        self._rr = 0

    def emit_device_batch(self, batch: BatchTPU) -> None:
        batch.prefetch_host()
        self._pipe_add(batch)

    def _pipe_process(self, batch: BatchTPU) -> None:
        if self.stats is not None:
            self.stats.device_bytes_d2h += batch.nbytes()
        self._send_batch(self._rr, batch)
        self._rr = (self._rr + 1) % self.num_dests

    def flush(self) -> None:
        # propagate_punctuation/send_eos_all call flush() first, so
        # draining here keeps batches ordered ahead of every marker
        self._drain()
        super().flush()


class TPUExitEmitter(BasicEmitter, _D2HPipeline):
    """TPU->CPU: D2H the batch, then route rows through a wrapped CPU
    emitter (which owns the real ports and batching policy).

    The D2H is PIPELINED (_D2HPipeline): an arriving batch starts async
    host copies of its columns and enters the FIFO; rows materialize only
    when a later batch pushes it out, a punctuation/flush/EOS drains it,
    or the worker's idle tick (WF_IDLE_DRAIN_MS, default 50 ms) fires on
    a quiet stream. Ordering and watermark monotonicity hold; the delay
    bound is the idle tick on a quiet stream, and on a busy stream with
    sparse output batches one watermark-punctuation interval
    (DEFAULT_WM_INTERVAL_USEC) — set WF_EXIT_PIPELINE_DEPTH=0 for
    latency-sensitive exits. The reference
    gets the same overlap from ``prefetch2CPU`` on the batch's CUDA
    stream ahead of the host read (``batch_gpu_t.hpp:154-165``)."""

    def __init__(self, inner: BasicEmitter, depth: Optional[int] = None) -> None:
        super().__init__(inner.num_dests, inner.output_batch_size,
                         inner.execution_mode)
        self.inner = inner
        self._pipe_init("WF_EXIT_PIPELINE_DEPTH", 4, depth)

    def set_ports(self, ports) -> None:
        self.inner.set_ports(ports)
        self.ports = self.inner.ports

    def set_stats(self, stats) -> None:
        self.stats = stats
        self.inner.stats = stats

    def _pipe_process(self, batch: BatchTPU) -> None:
        if self.stats is not None:
            self.stats.device_bytes_d2h += batch.nbytes()
        if batch.trace_min:
            # one traced row re-materializes per traced batch: the inner
            # emitter consumes the stamp on its first emit
            self.inner.trace_ts = batch.trace_min
        for payload, ts in batch.to_rows():
            self.inner.emit(payload, ts, batch.wm)
        self.inner.trace_ts = 0

    def emit_device_batch(self, batch: BatchTPU) -> None:
        batch.prefetch_host()
        self._pipe_add(batch)

    def emit(self, payload: Any, ts: int, wm: int,
             msg_id: Optional[int] = None) -> None:
        self._drain()  # single-row emits must not overtake queued batches
        self.inner.emit(payload, ts, wm, msg_id)

    def propagate_punctuation(self, wm: int) -> None:
        self._drain()  # rows behind the punctuation carry older watermarks
        self.inner.propagate_punctuation(wm)

    def flush(self) -> None:
        self._drain()
        self.inner.flush()

    def send_eos_all(self) -> None:
        self._drain()
        self.inner.send_eos_all()

    def send_barrier_all(self, barrier) -> None:
        self._drain()
        self.inner.send_barrier_all(barrier)

    def eos_ports(self):
        return self.inner.eos_ports()

    def emitter_state(self) -> dict:
        return self.inner.emitter_state()

    def restore_emitter_state(self, state: dict) -> None:
        self.inner.restore_emitter_state(state)
