"""TPU builders — siblings of the reference's ``wf/builders_gpu.hpp``
(Filter_GPU/Map_GPU/Reduce_GPU builders with withName/withParallelism/
withKeyBy/withRebalancing), with ``with_schema`` replacing C++ type
deduction (or inferred from the first tuple at the staging boundary).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..basic import WindFlowError
from ..builders import _RoutableBuilder
from .ops_tpu import Filter_TPU, Map_TPU, Reduce_TPU
from .schema import TupleSchema


class _TPUBuilderMixin:
    def with_schema(self, schema) -> "_TPUBuilderMixin":
        if isinstance(schema, dict):
            schema = TupleSchema(schema)
        self._schema = schema
        return self


class _TieredStateMixin:
    """``with_tiering`` for the keyed-state operators: cap the device
    table at ``hot_capacity`` slots and spill the cold key tail to a
    host sqlite store (``windflow_tpu.state``). Key capacity becomes
    elastic — bounded by host disk, not device memory — while batches
    over the hot set run the unchanged dense path."""

    _tiering = None

    def with_tiering(self, policy: Optional[str] = None,
                     hot_capacity: int = 1024,
                     db_dir: Optional[str] = None):
        """Enable the hot/cold key tiers. ``policy`` picks the eviction
        order ("lru" | "lfu"; default ``WF_TIER_POLICY`` or "lru"),
        ``hot_capacity`` the device-resident slot count — it must exceed
        every batch's distinct-key working set (a single batch touching
        more keys than the hot tier holds raises ``KeyCapacityError``)."""
        from ..state.tiered import TierConfig
        self._tiering = TierConfig(policy=policy, hot_capacity=hot_capacity,
                                   db_dir=db_dir)
        return self

    def _tiering_guard(self, what: str) -> None:
        if self._tiering is not None and self._state_init is None:
            raise WindFlowError(f"{what}: with_tiering requires with_state "
                                "(tiers hold the keyed device state)")


class _MeshBuilderMixin:
    """``with_mesh`` for the keyed device operators: shard the operator's
    keyed-state plane over a ``('key','data')`` device mesh
    (``windflow_tpu.mesh``) instead of a single chip."""

    _mesh_cfg: Optional[dict] = None

    def with_mesh(self, n_devices: Optional[int] = None,
                  mesh_shape: Optional[tuple] = None,
                  local_batch: Optional[int] = None,
                  key_capacity: int = 1024):
        """``build()`` returns the mesh-sharded operator (``Map_Mesh`` /
        ``Filter_Mesh`` / ``Reduce_Mesh``): ONE host replica drives every
        device, the KEYBY shuffle runs in-program as a bucket-by-owner +
        ``lax.all_to_all`` collective, and per-key state is block-sharded
        over the devices. ``mesh_shape=(ka, da)`` forces the
        factorization (results are invariant under reshape); default
        uses every visible device. ARBITRARY int64 keys densify to
        ``key_capacity`` slots via a host KeySlotMap (more distinct keys
        raise loudly). Mesh operators refuse ``rescale()`` — parallelism
        is the mesh shape; to change capacity, checkpoint and restore
        with a different ``with_mesh(mesh_shape=...)``."""
        self._mesh_cfg = {"n_devices": n_devices, "mesh_shape": mesh_shape,
                          "local_batch": local_batch,
                          "key_capacity": key_capacity}
        return self

    def _mesh_guard(self, what: str) -> None:
        if self._parallelism != 1:
            raise WindFlowError(
                f"{what}: with_mesh and with_parallelism are exclusive — "
                "the mesh IS the parallelism (one host replica drives "
                "every chip)")
        if self._output_batch_size:
            raise WindFlowError(
                f"{what}: with_output_batch_size does not apply to the "
                "mesh plane (batches pad to the mesh's global batch)")
        if self._key_extractor is None:
            raise WindFlowError(f"{what}: with_mesh requires with_key_by "
                                "(the mesh shards the KEYED plane)")


class Map_TPU_Builder(_RoutableBuilder, _TPUBuilderMixin, _MeshBuilderMixin,
                      _TieredStateMixin):
    _default_name = "map_tpu"

    def __init__(self, func: Callable) -> None:
        super().__init__(func)
        self._schema: Optional[TupleSchema] = None
        self._state_init: Any = None

    def with_state(self, initial_state: Any) -> "Map_TPU_Builder":
        """Per-key device state: switches the functor to
        ``func(row, state) -> (row, state)`` scanned in arrival order."""
        self._state_init = initial_state
        return self

    def build(self) -> Map_TPU:
        if self._state_init is not None and self._key_extractor is None:
            raise WindFlowError("Map_TPU_Builder: with_state requires "
                                "with_key_by")
        self._tiering_guard("Map_TPU_Builder")
        if self._mesh_cfg is not None:
            from ..mesh.ops_mesh import Map_Mesh
            self._mesh_guard("Map_TPU_Builder")
            return self._finish(Map_Mesh(
                self._func, self._state_init, self._key_extractor,
                self._name if self._name != self._default_name
                else "map_mesh", schema=self._schema,
                tiering=self._tiering, **self._mesh_cfg))
        return self._finish(Map_TPU(self._func, self._name, self._parallelism,
                                    self._routing, self._key_extractor,
                                    self._output_batch_size, self._schema,
                                    self._state_init, self._tiering))


class Filter_TPU_Builder(_RoutableBuilder, _TPUBuilderMixin,
                         _MeshBuilderMixin, _TieredStateMixin):
    _default_name = "filter_tpu"

    def __init__(self, pred: Callable) -> None:
        super().__init__(pred)
        self._schema: Optional[TupleSchema] = None
        self._state_init: Any = None

    def with_state(self, initial_state: Any) -> "Filter_TPU_Builder":
        """Per-key device state: switches the predicate to
        ``pred(row, state) -> (keep, state)``."""
        self._state_init = initial_state
        return self

    def build(self) -> Filter_TPU:
        if self._state_init is not None and self._key_extractor is None:
            raise WindFlowError("Filter_TPU_Builder: with_state requires "
                                "with_key_by")
        self._tiering_guard("Filter_TPU_Builder")
        if self._mesh_cfg is not None:
            from ..mesh.ops_mesh import Filter_Mesh
            self._mesh_guard("Filter_TPU_Builder")
            return self._finish(Filter_Mesh(
                self._func, self._state_init, self._key_extractor,
                self._name if self._name != self._default_name
                else "filter_mesh", schema=self._schema,
                tiering=self._tiering, **self._mesh_cfg))
        return self._finish(Filter_TPU(self._func, self._name,
                                       self._parallelism, self._routing,
                                       self._key_extractor,
                                       self._output_batch_size, self._schema,
                                       self._state_init, self._tiering))


class Reduce_TPU_Builder(_RoutableBuilder, _TPUBuilderMixin,
                         _MeshBuilderMixin):
    _default_name = "reduce_tpu"

    def __init__(self, combine: Callable) -> None:
        super().__init__(combine)
        self._schema: Optional[TupleSchema] = None

    def build(self) -> Reduce_TPU:
        from ..basic import RoutingMode
        if self._routing is RoutingMode.BROADCAST:
            # the op derives its routing from the key extractor (keyed
            # shuffle or forward); silently ignoring withBroadcast would
            # mislead (the reference reduce has no broadcast form either)
            raise WindFlowError("Reduce_TPU_Builder: withBroadcast is not "
                                "supported (use withKeyBy or forward)")
        if self._mesh_cfg is not None:
            from ..mesh.ops_mesh import Reduce_Mesh
            self._mesh_guard("Reduce_TPU_Builder")
            return self._finish(Reduce_Mesh(
                self._func, self._key_extractor,
                self._name if self._name != self._default_name
                else "reduce_mesh", schema=self._schema, **self._mesh_cfg))
        # without withKeyBy this is the GLOBAL per-batch reduce
        return self._finish(Reduce_TPU(self._func, self._key_extractor,
                                       self._name, self._parallelism,
                                       self._output_batch_size, self._schema))


class Ffat_Windows_TPU_Builder(_RoutableBuilder, _TPUBuilderMixin):
    """Sibling of the reference ``Ffat_WindowsGPU_Builder``
    (``wf/builders_gpu.hpp:576`` adds withNumWinPerBatch)."""

    _default_name = "ffat_windows_tpu"

    def __init__(self, lift: Callable, combine: Callable) -> None:
        super().__init__(lift)
        self._combine = combine
        self._schema: Optional[TupleSchema] = None
        self._win_len = 0
        self._slide_len = 0
        self._win_type = None
        self._lateness = 0
        self._nwpb = None  # default: auto-sized from key capacity
        self._key_capacity = 16

    def with_key_capacity(self, n: int):
        """Expected distinct-key count per replica (pre-sizes the device
        forest; avoids growth recompiles on streams with many keys)."""
        self._key_capacity = n
        return self

    def with_cb_windows(self, win_len: int, slide_len: int):
        from ..basic import WinType
        self._win_type = WinType.CB
        self._win_len, self._slide_len = win_len, slide_len
        return self

    def with_tb_windows(self, win_usec: int, slide_usec: int):
        from ..basic import WinType
        self._win_type = WinType.TB
        self._win_len, self._slide_len = win_usec, slide_usec
        return self

    def with_lateness(self, lateness_usec: int):
        self._lateness = lateness_usec
        return self

    def with_num_win_per_batch(self, n: int):
        self._nwpb = n
        return self

    def with_mesh(self, n_devices: Optional[int] = None,
                  mesh_shape: Optional[tuple] = None,
                  local_batch: Optional[int] = None,
                  fire_rounds: int = 4, ring_panes: int = 0,
                  late_policy: str = "keep_open"):
        """Shard the FlatFAT forest over a ('key','data') device mesh:
        ``build()`` returns the multi-chip ``Ffat_Windows_Mesh`` operator
        (keyby via ``lax.all_to_all`` over ICI, on-device fire control)
        instead of the single-chip plane. ``mesh_shape=(ka, da)`` forces
        the factorization; default uses every visible device. TB windows
        only (CB needs a serialized per-key arrival counter — see
        PARITY.md); ARBITRARY int64 keys, densified to
        ``key_capacity`` slots by a host KeySlotMap (more distinct keys
        than the capacity raise). ``late_policy``: "keep_open" (default)
        drops a tuple only when every window containing it already fired
        (less lossy than the reference); "ref_fired" reproduces the
        reference's fired-window bound exactly (drops tuples inside the
        last fired window even when open windows still contain them)."""
        self._mesh_cfg = {"n_devices": n_devices, "mesh_shape": mesh_shape,
                          "local_batch": local_batch,
                          "fire_rounds": fire_rounds,
                          "ring_panes": ring_panes,
                          "late_policy": late_policy}
        return self

    def build(self):
        from .ffat_tpu import Ffat_Windows_TPU
        if self._win_type is None:
            raise WindFlowError("Ffat_Windows_TPU_Builder: call "
                                "with_cb_windows() or with_tb_windows()")
        if self._key_extractor is None:
            raise WindFlowError("Ffat_Windows_TPU_Builder: withKeyBy "
                                "is mandatory")
        if getattr(self, "_mesh_cfg", None) is not None:
            from ..mesh.ffat_mesh import Ffat_Windows_Mesh
            if self._parallelism != 1:
                raise WindFlowError(
                    "Ffat_Windows_TPU_Builder: with_mesh and "
                    "with_parallelism are exclusive — the mesh IS the "
                    "parallelism (one host replica drives every chip)")
            if self._nwpb is not None:
                raise WindFlowError(
                    "Ffat_Windows_TPU_Builder: with_num_win_per_batch does "
                    "not apply to the mesh plane; the per-step fire budget "
                    "is with_mesh(fire_rounds=...)")
            if self._output_batch_size:
                raise WindFlowError(
                    "Ffat_Windows_TPU_Builder: with_output_batch_size does "
                    "not apply to the mesh plane (windows emit as rows "
                    "through the exit edge)")
            return self._finish(Ffat_Windows_Mesh(
                self._func, self._combine, self._key_extractor,
                self._win_len, self._slide_len, self._win_type,
                self._lateness, self._name,
                key_capacity=self._key_capacity,
                schema=self._schema, **self._mesh_cfg))
        return self._finish(Ffat_Windows_TPU(
            self._func, self._combine, self._key_extractor, self._win_len,
            self._slide_len, self._win_type, self._lateness, self._nwpb,
            self._name, self._parallelism, self._output_batch_size,
            self._schema, self._key_capacity))
