"""TPU operators: Map_TPU, Filter_TPU, Reduce_TPU.

Siblings of the reference CUDA operators (``wf/map_gpu.hpp``,
``wf/filter_gpu.hpp``, ``wf/reduce_gpu.hpp``), re-designed for XLA:

- functors are JAX functions over a dict of columns (struct-of-arrays) —
  the whole batch is one compiled program (the reference launches
  grid-stride kernels per batch; XLA fuses the elementwise chain instead);
- ``jax.jit`` is instantiated once per operator; XLA's own cache handles
  one compile per capacity bucket (the reference caches launch configs per
  batch size, ``map_gpu.hpp:251-277``);
- Filter compacts via a cumsum+scatter keepers-first permutation (the
  reference uses ``thrust::copy_if``, ``filter_gpu.hpp:331-335``; no
  sort on either side);
- Reduce groups by key slot (the permutation comes precomputed from the
  HOST key metadata — one sort of the raw keys; no device sort) and runs
  a segmented associative scan with the user's combine, gathering
  segment tails — one result per key per batch, exactly the reference
  semantics (``reduce_gpu.hpp:239-272``: sort_by_key + reduce_by_key).
  The combine must be associative and commutative (``API:78-80``);
- stateful Map/Filter keep per-key state in a device-resident table
  (slots × state pytree) updated by a masked ``lax.scan`` in arrival order —
  replacing the reference's per-key CUDA state objects + cross-replica
  spinlock (``map_gpu.hpp:233-295``, ``basic_gpu.hpp:142-233``) with a
  functional state carry. Keyed TPU operators hold their state per replica
  (keys are partitioned by the keyby shuffle), so no lock exists at all.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..basic import ExecutionMode, OpType, RoutingMode, WindFlowError
from ..monitoring.flightrec import instrumented_jit
from ..monitoring.tracing import device_span
from ..operators.base import BasicOperator, BasicReplica
from ..runtime.dispatch import DeviceDispatchQueue
from .batch import BatchTPU, key_column_np, key_column_to_list
from .schema import TupleSchema


def prewarm_zero_fields(schema: "TupleSchema", cap: int):
    """Zero-valued device columns at one bucket capacity — the dummy
    input the compile-stability pre-warm feeds a program so its
    (shape, dtype) signature traces before any real batch arrives.
    ``device_put`` of schema-dtyped numpy matches the staging emitters'
    transfer path, so the traced signature is byte-for-byte the one the
    stream will present."""
    import jax

    return {name: jax.device_put(np.zeros(cap, dtype=dt))
            for name, dt in schema.fields.items()}


def _compact_order(keep):
    """Stable keepers-first permutation as GATHER indices, via cumsum +
    one scatter — equivalent to ``argsort(~keep, stable)`` but O(n)
    scatter instead of a sort (~11x on CPU, sorts are costly on TPU)."""
    import jax.numpy as jnp

    keep = keep.astype(bool)  # int 0/1 masks: ~keep would be bitwise NOT
    p_keep = jnp.cumsum(keep) - 1
    p_drop = jnp.sum(keep) + jnp.cumsum(~keep) - 1
    pos = jnp.where(keep, p_keep, p_drop).astype(jnp.int32)
    return jnp.zeros(keep.shape[0], jnp.int32).at[pos].set(
        jnp.arange(keep.shape[0], dtype=jnp.int32))


def cached_compile(cache: Dict, lock, key, make):
    """Compile-once lookup shared by every device-program cache
    (double-checked locking: replica worker threads race their first
    batch)."""
    prog = cache.get(key)
    if prog is None:
        with lock:
            prog = cache.get(key)
            if prog is None:
                prog = cache[key] = make()
    return prog


# ---------------------------------------------------------------------------
# composable kernel plane
# ---------------------------------------------------------------------------
# Every device operator's per-batch body is expressed as a kernel of the
# form ``(fields, valid, carry) -> (fields, valid, carry)`` traced inside
# ONE ``jax.jit`` program:
#
# - ``fields``: the batch's column dict;
# - ``valid``: the device-side keep mask (row alive at this point of the
#   chain) — a filter narrows it instead of compacting, so chained
#   operators compose without intermediate HBM materialization or a
#   mid-chain ``int(count)`` readback (compaction + count happen once at
#   the chain exit);
# - ``carry``: the operator's device state (grid tables for stateful
#   ops; None for stateless).
#
# The standalone replicas below and the fused chain replica
# (``tpu/fused_ops.py``) share these kernels, so both paths run the
# same traced math.


def op_batch_keys(op, batch: "BatchTPU"):
    """Per-batch keys for ``op``: host metadata when staged keyed, else
    the device key column named by a string key extractor. Module-level
    so fused sub-ops resolve keys with THEIR OWN key fields, not the
    chain head's."""
    keys = batch.host_keys
    if keys is None:
        field = op.key_field
        if field is not None:
            keys = key_column_to_list(batch, field)
        elif getattr(op, "key_fields", None):
            from .emitters_tpu import composite_keys_from_device
            keys = composite_keys_from_device(batch, op.key_fields)
        else:
            raise WindFlowError(
                f"{op.name}: keyed TPU operator needs keyed staging "
                "(with_key_by on the op) or a field-name key")
    return keys


def op_batch_keys_np(op, batch: "BatchTPU"):
    """``(keys, keys_arr)`` with at most ONE conversion — the host-prep
    stage's hot path (see ``TPUReplicaBase.batch_keys_np``)."""
    keys = batch.host_keys
    if keys is None and op.key_field is not None \
            and op.key_field in batch.fields:
        arr = key_column_np(batch, op.key_field)
        if arr.dtype.kind in "iu":
            return arr, arr
    if keys is None:
        keys = op_batch_keys(op, batch)
    return keys, np.asarray(keys)


def op_batch_slots_np(op, batch: "BatchTPU"):
    """Per-batch dense slot ids (HOST numpy) + slot->key order for
    ``op``'s key fields. Device ops run in DEFAULT mode only, so
    intra-batch output order is free: int keys take a vectorized unique
    (slot order = sorted keys), others keep first-appearance order via
    the Python loop. Module-level so the fused chain resolves slots with
    the TERMINATOR's key fields, not the chain head's."""
    keys = op_batch_keys(op, batch)
    n = batch.size
    keys_arr = np.asarray(keys)
    # ndim guard: tuple-of-int keys become a 2-D int array
    if n and keys_arr.ndim == 1 and keys_arr.dtype.kind in "iu":
        uniq, inv = np.unique(keys_arr[:n], return_inverse=True)
        slots = np.full(batch.capacity, len(uniq), dtype=np.int32)
        slots[:n] = inv
        slot_of_key = {int(k): i for i, k in enumerate(uniq)}
        return slots, slot_of_key
    if n and keys_arr.ndim == 1 and keys_arr.dtype.kind == "V" \
            and keys_arr.dtype.names:
        # structured composite keys: one unique per batch, slot map
        # keyed by plain tuples (shared dedup: keymap.py
        # structured_unique; None = object field, fall to row loop)
        from .keymap import structured_unique
        uu = structured_unique(keys_arr, n)
        if uu is None:
            keys = keys_arr[:n].tolist()
        else:
            uniq, inv = uu
            slots = np.full(batch.capacity, len(uniq), dtype=np.int32)
            slots[:n] = inv
            slot_of_key = {k.item(): i for i, k in enumerate(uniq)}
            return slots, slot_of_key
    slot_of_key: Dict[Any, int] = {}
    slots = np.zeros(batch.capacity, dtype=np.int32)
    for i, k in enumerate(keys):
        slots[i] = slot_of_key.setdefault(k, len(slot_of_key))
    slots[n:] = len(slot_of_key)  # padding segment
    return slots, slot_of_key


def reduce_order_and_slots(op, batch: "BatchTPU"):
    """(order, sorted slot ids, slot->key map) for a keyed reduce over
    ``batch``, with ONE sort: int keys sort directly (group boundaries
    give the sorted slot ids); other keys go through the generic slot
    map + a radix argsort of the small dense ids. Shared by the
    standalone ``ReduceTPUReplica`` and the fused chain's
    ``keyed_terminator`` exit (both must group identically so their
    per-slot outputs — and the slot->key emit order — stay exact
    equals)."""
    from .keymap import stable_group_argsort

    n = batch.size
    cap = batch.capacity
    _, keys_arr = op_batch_keys_np(op, batch)
    if n and keys_arr.ndim == 1 and keys_arr.dtype.kind in "iu":
        order_n = np.argsort(keys_arr[:n], kind="stable")
        sk = keys_arr[:n][order_n]
        new_grp = np.r_[True, sk[1:] != sk[:-1]]
        uniq = sk[new_grp]
        slot_of_key = {int(k): i for i, k in enumerate(uniq)}
        order = np.empty(cap, dtype=np.int32)
        order[:n] = order_n
        order[n:] = np.arange(n, cap)
        ssorted = np.full(cap, len(uniq), dtype=np.int32)
        ssorted[:n] = np.cumsum(new_grp) - 1
        return order, ssorted, slot_of_key
    slots_np, slot_of_key = op_batch_slots_np(op, batch)
    order = stable_group_argsort(
        slots_np, len(slot_of_key) + 1).astype(np.int32)
    return order, slots_np[order], slot_of_key


def _grid_scan_core(func, filter_mode: bool, M: int, KB: int):
    """The keyed grid-scan device core (see ``_KeyedStateScan``): rows
    scatter to a (KB x M) grid of (key slot, per-key position), a
    ``lax.scan`` walks the position axis while ``vmap`` covers the keys,
    and the results gather back to arrival positions. Returns
    ``core(fields, valid, grid_idx, touched, touched_mask, table, dirty)
    -> (out, table2, dirty2)`` where ``out`` is the per-row output
    columns (map mode) or the per-row keep mask ANDed with ``valid``
    (filter mode) and ``dirty2`` is the touched-slot bitmap with this
    grid's slots marked (rides the carry — incremental checkpoints
    gather only dirty rows).
    ``valid`` may be a host bool array (standalone) or a traced
    device mask (fused chains: rows a mid-chain filter dropped skip the
    grid and leave their key's state untouched)."""
    import jax
    import jax.numpy as jnp

    KM = KB * M
    tmap = jax.tree_util.tree_map

    def bwhere(ok, new, old):
        shaped = ok.reshape(ok.shape + (1,) * (new.ndim - ok.ndim))
        return jnp.where(shaped, new, old).astype(old.dtype)

    def core(fields, valid, grid_idx, touched, touched_mask, table, dirty):
        T_cap = next(iter(jax.tree_util.tree_leaves(table))).shape[0]
        tsafe = jnp.where(touched_mask, touched, 0)
        sub = tmap(lambda a: a[tsafe], table)  # (KB, ...)
        safe = jnp.where(valid, grid_idx, KM)
        grids = {f: jnp.zeros((KM,), v.dtype).at[safe].set(
                     v, mode="drop").reshape(KB, M)
                 for f, v in fields.items()}
        gmask = jnp.zeros((KM,), bool).at[safe].set(
            True, mode="drop").reshape(KB, M)
        vfunc = jax.vmap(func)

        def body(tbl, xs):
            col, ok = xs  # col: {f: (KB,)}, ok: (KB,)
            out_col, new_state = vfunc(col, tbl)
            tbl = tmap(lambda o, nw: bwhere(ok, nw, o), tbl, new_state)
            return tbl, out_col

        cols = {f: g.T for f, g in grids.items()}  # (M, KB)
        sub2, outs = jax.lax.scan(body, sub, (cols, gmask.T))
        tscatter = jnp.where(touched_mask, touched, T_cap)
        table2 = tmap(
            lambda a, nw: a.at[tscatter].set(nw, mode="drop"),
            table, sub2)
        # touched-slot bitmap: every slot this grid scattered back to is
        # dirty since the last full snapshot (conservative — marked even
        # when func left the value bit-identical)
        dirty2 = dirty.at[tscatter].set(True, mode="drop")
        # gather outputs back to arrival positions: grid (slot, within)
        slot = grid_idx // M
        within = jnp.where(valid, grid_idx % M, 0)
        row_flat = within * KB + jnp.minimum(slot, KB - 1)
        if filter_mode:
            keep = outs.reshape(-1)[row_flat]  # (cap,)
            return keep.astype(bool) & valid, table2, dirty2
        out_rows = {f: (o.reshape(M * KB, -1)[row_flat].reshape(
                        fields[f].shape)
                        if o.ndim > 2 else o.reshape(-1)[row_flat])
                    for f, o in outs.items()}
        return out_rows, table2, dirty2

    return core


def masked_tree_reduce(combine, fields, valid):
    """Whole-batch fold to one tuple via a masked pairwise tree
    reduction (log2(cap) fused halving passes — associativity is the
    contract). ``valid`` gates which rows participate, so a fused
    chain's filter mask flows straight into the terminal reduce. The
    result is garbage when no row is valid — callers must skip emission
    when the valid count is zero."""
    import jax.numpy as jnp

    n = next(iter(fields.values())).shape[0]
    # Pad up to a power of two so the halving loop never drops an odd
    # tail (upstream ops such as Ffat_Windows_TPU emit batches whose
    # capacity is num_win_per_batch — any user value).
    m = 1 << max(0, n - 1).bit_length()
    if m != n:
        pad = m - n
        fields = {k: jnp.concatenate(
            [v, jnp.zeros((pad,) + v.shape[1:], v.dtype)])
            for k, v in fields.items()}
        valid = jnp.concatenate([valid, jnp.zeros(pad, bool)])
    cur = fields
    vcur = valid
    length = m
    while length > 1:
        half = length // 2
        a = {k: v[:half] for k, v in cur.items()}
        b = {k: v[half:half * 2] for k, v in cur.items()}
        va, vb = vcur[:half], vcur[half:half * 2]
        merged = combine(a, b)
        cur = {k: jnp.where(va & vb, merged.get(k, b[k]),
                            jnp.where(va, a[k], b[k]))
               for k in cur}
        vcur = va | vb
        length = half
    return {k: v[:1] for k, v in cur.items()}


# ---------------------------------------------------------------------------
# shared replica machinery
# ---------------------------------------------------------------------------
class TPUReplicaBase(BasicReplica):
    """Processes whole device batches; never iterates rows.

    Batch processing is SPLIT into a host-prep stage and a device-commit
    stage pipelined through a per-replica ``DeviceDispatchQueue``
    (``WF_DISPATCH_DEPTH``, default 2): ``prep_device_batch`` runs the
    host control plane for batch N+1 while batch N's program dispatch and
    emit readbacks sit deferred in the queue. The queue drains at every
    ordering point (punctuation, EOS/terminate, worker idle tick) and
    whenever host code must touch the replica's device state."""

    def __init__(self, op: BasicOperator, idx: int) -> None:
        super().__init__(op, idx)
        self.dispatch = DeviceDispatchQueue(stats=self.stats)
        # jax.profiler span label for the host-prep stage, so captured
        # device traces line up with the Dispatch_* stats (the commit
        # span lives in the dispatch queue)
        self._span_prep = f"wf:prep:{op.name}"
        # per-record error policy (windflow_tpu.supervision.errors): a
        # whole batch shares one XLA program, so a failing batch is
        # BISECTED until the poison record is isolated at size 1 and the
        # policy applies to that record. None (FAIL default) keeps the
        # pipelined hot path untouched.
        pol = getattr(op, "error_policy", None)
        self._err_policy = pol if pol is not None and not pol.is_fail \
            else None

    def handle_msg(self, ch: int, msg: Any) -> None:
        if msg.is_punct:
            self.stats.punct_received += 1
            self._advance_wm(msg.wm)
            # in-flight batches emit BEFORE the punctuation propagates
            # (watermark monotonicity downstream)
            self.dispatch.drain(forced=True)
            self.on_punctuation(msg.wm)
            return
        if not isinstance(msg, BatchTPU):
            raise WindFlowError(
                f"{self.op.name}: TPU operator received a non-device message "
                f"({type(msg).__name__}); the upstream operator must declare "
                "an output batch size > 0")
        self.stats.start_svc()
        self.stats.inputs_received += msg.size
        self.stats.device_batches_in += 1
        if self.stats.sample_every:  # per batch, not per tuple
            self.stats._svc_rec = True
        self._advance_wm(msg.wm)
        msg.wm = self.cur_wm
        if self._err_policy is not None:
            self._process_batch_guarded(msg)
            self.stats.end_svc(msg.size)
            return
        t0 = time.perf_counter()
        with device_span(self._span_prep):
            commit = self.prep_device_batch(msg)
        prep_us = (time.perf_counter() - t0) * 1e6
        if commit is not None:
            self.dispatch.submit(commit, prep_us)
        else:
            self.stats.note_host_prep(prep_us)  # batch needed no commit
        self.stats.end_svc(msg.size)

    def _process_batch_guarded(self, msg: BatchTPU) -> None:
        """Policy-guarded batch path: commits run SYNCHRONOUSLY (drain
        right after submit) so an error attributes to this exact batch,
        then bisection isolates the offender. Stateless transforms
        bisect safely; a stateful op whose failure left partial device
        state applied keeps that prefix (document-level caveat — the
        FAIL policy is the strict choice for stateful device chains)."""
        try:
            t0 = time.perf_counter()
            with device_span(self._span_prep):
                commit = self.prep_device_batch(msg)
            prep_us = (time.perf_counter() - t0) * 1e6
            if commit is not None:
                self.dispatch.submit(commit, prep_us)
                self.dispatch.drain(forced=True)
            else:
                self.stats.note_host_prep(prep_us)
        except Exception as exc:  # noqa: BLE001 — the policy boundary
            from ..supervision.errors import (apply_record_policy,
                                              batch_row_payload,
                                              split_batch)
            if msg.size <= 1:
                payload = batch_row_payload(msg, 0) if msg.size else {}
                ts = int(msg.ts_host[0]) if msg.size else 0
                apply_record_policy(self, self._err_policy, payload, ts,
                                    exc)
                return
            for half in split_batch(msg):
                self._process_batch_guarded(half)

    def prep_device_batch(self, batch: BatchTPU) -> Optional[Callable]:
        """Host-prep stage: return this batch's device-commit thunk (or
        None when the batch needs no device work). Subclasses that
        separate their host control plane override this; the default
        keeps the whole legacy ``process_device_batch`` as the commit
        stage — still correct (commits run in submission order and drain
        at every ordering point), just without the prep overlap."""
        return lambda: self.process_device_batch(batch)

    def process_device_batch(self, batch: BatchTPU) -> None:
        raise NotImplementedError

    def on_idle(self) -> bool:
        """Worker idle tick: commit in-flight batches on a quiet stream
        (Worker._process; same contract as the emitter FIFOs)."""
        return self.dispatch.on_idle()

    def terminate(self) -> None:
        # EOS: in-flight batches commit before any flush/close logic —
        # regardless of subclass flush_on_termination overrides
        if not self.terminated:
            self.dispatch.drain(forced=True)
        super().terminate()

    def snapshot_state(self) -> dict:
        # the checkpointing worker drains the dispatch queue before
        # snapshotting, but device state must never be captured with
        # commits in flight (donation reassigns it) — drain defensively
        self.dispatch.drain(forced=True)
        return super().snapshot_state()

    def _emit_batch(self, batch: BatchTPU) -> None:
        self.stats.device_batches_out += 1
        rec = self.stats.recorder
        if rec is not None:  # per device batch, not per tuple
            rec.event("emit", 0.0, batch.size)
        self.emitter.emit_device_batch(batch)

    def emit_compacted(self, batch: BatchTPU, out_fields, order, count
                       ) -> None:
        """Emit a compaction result: device columns reordered keep-first,
        host ts/keys reordered to match (shared by the filter paths)."""
        rec = self.stats.recorder
        t0 = time.perf_counter() if rec is not None else 0.0
        # the compaction readbacks: int(count) + the order materialization
        # block on the program result (this is why commits are deferred)
        new_size = int(count)
        order_np = np.asarray(order)
        if rec is not None:
            rec.event("readback", (time.perf_counter() - t0) * 1e6,
                      {"kept": new_size, "of": batch.size})
        self.stats.inputs_ignored += batch.size - new_size
        ts2 = batch.ts_host[order_np]
        keys2 = None
        if batch.host_keys is not None:
            keys_list = list(batch.host_keys)
            keys_arr = keys_list + [None] * (batch.capacity - len(keys_list))
            keys2 = [keys_arr[j] for j in order_np[:new_size]]
        nb = BatchTPU(out_fields, ts2, new_size, batch.schema, batch.wm,
                      keys2)
        nb.stream_tag = batch.stream_tag
        nb.copy_trace_from(batch)
        if new_size > 0:
            self._emit_batch(nb)

    def batch_keys_np(self, batch: BatchTPU):
        """``(keys, keys_arr)`` with at most ONE conversion — the
        host-prep stage's hot path (``key_column_to_list`` followed by
        ``np.asarray`` boxes every key twice per batch). Int key columns
        return the raw array for both forms: every ``KeySlotMap`` path
        that registers keys from an int array goes through ``int()``, so
        slot identity and the ktable fast path's ``isinstance(key, int)``
        checks still see Python ints. Other dtypes keep the list form
        (their consumers iterate Python keys)."""
        return op_batch_keys_np(self.op, batch)

    # per-batch keys: host metadata when staged keyed, else the device key
    # column named by a string key extractor
    def batch_keys(self, batch: BatchTPU):
        return op_batch_keys(self.op, batch)

    def batch_slots_np(self, batch: BatchTPU):
        """See ``op_batch_slots_np`` (module-level: the fused chain
        resolves slots with a sub-op's own key fields)."""
        return op_batch_slots_np(self.op, batch)


class TPUOperatorBase(BasicOperator):
    op_type = OpType.TPU
    is_tpu = True

    def __init__(self, name: str, parallelism: int, input_routing: RoutingMode,
                 key_extractor, output_batch_size: int,
                 schema: Optional[TupleSchema]) -> None:
        import threading
        super().__init__(name, parallelism, input_routing, key_extractor,
                         output_batch_size)
        self.schema = schema  # None => inferred at the staging boundary
        # compiled device programs shared across this op's replicas
        self._scan_prog_cache: Dict[Any, Any] = {}
        self._scan_prog_lock = threading.Lock()

    @property
    def is_chainable(self) -> bool:
        return False

    @property
    def fusion_role(self) -> Optional[str]:
        """Device-chain fusion classification (``topology/stage.py``):
        ``"transform"`` composes mid-chain via its ``device_kernel``;
        ``"terminator"`` may only end a fused chain; None never fuses
        (window/mesh operators own their whole stage)."""
        return None

    def device_kernel(self):
        """The operator's composable ``(fields, valid, carry) ->
        (fields, valid, carry)`` kernel (stateless transforms only;
        stateful ops contribute a grid-scan engine instead)."""
        raise WindFlowError(f"{self.name}: no composable device kernel")

    def configure(self, execution_mode, time_policy) -> None:
        if execution_mode is not ExecutionMode.DEFAULT:
            # reference: GPU operators only in DEFAULT mode (map_gpu.hpp:470-478)
            raise WindFlowError(
                f"{self.name}: TPU operators require DEFAULT execution mode")
        super().configure(execution_mode, time_policy)


# ---------------------------------------------------------------------------
# Map_TPU
# ---------------------------------------------------------------------------
class Map_TPU(TPUOperatorBase):
    """Stateless: ``func(fields) -> fields`` (elementwise over columns).
    Stateful (``state_init`` given): ``func(row, state) -> (row, state)``
    over scalars, scanned in arrival order with per-key state."""

    def __init__(self, func: Callable, name: str = "map_tpu",
                 parallelism: int = 1,
                 input_routing: RoutingMode = RoutingMode.FORWARD,
                 key_extractor=None, output_batch_size: int = 0,
                 schema: Optional[TupleSchema] = None,
                 state_init: Any = None, tiering=None) -> None:
        if state_init is not None and key_extractor is None:
            raise WindFlowError(f"{name}: stateful Map_TPU requires a key "
                                "extractor (KEYBY)")
        if tiering is not None and state_init is None:
            raise WindFlowError(f"{name}: with_tiering requires keyed "
                                "state (with_state)")
        super().__init__(name, parallelism,
                         RoutingMode.KEYBY if state_init is not None
                         else input_routing,
                         key_extractor, output_batch_size, schema)
        self.func = func
        self.state_init = state_init
        self.tiering = tiering

    @property
    def fusion_role(self) -> Optional[str]:
        return "transform"

    def device_kernel(self):
        if self.state_init is not None:
            raise WindFlowError(f"{self.name}: stateful Map_TPU carries a "
                                "grid-scan engine, not a stateless kernel")
        func = self.func

        def kernel(fields, valid, carry):
            return func(fields), valid, carry

        return kernel

    def build_replicas(self) -> None:
        cls = StatefulMapTPUReplica if self.state_init is not None \
            else MapTPUReplica
        self.replicas = [cls(self, i) for i in range(self.parallelism)]


class MapTPUReplica(TPUReplicaBase):
    def __init__(self, op, idx):
        super().__init__(op, idx)
        kernel = op.device_kernel()

        def run(fields):
            out, _, _ = kernel(fields, None, None)
            return out

        self._jitted = instrumented_jit(run, self.stats, label=op.name)

    def process_device_batch(self, batch: BatchTPU) -> None:
        out = self._jitted(batch.fields)
        self.stats.device_programs_run += 1
        if not isinstance(out, dict):
            raise WindFlowError(f"{self.op.name}: Map_TPU function must "
                                "return a dict of columns")
        self._emit_batch(batch.with_fields(out))

    def prewarm(self, caps) -> Optional[int]:
        """Compile-stability pre-warm (``PipeGraph.with_prewarm``): trace
        the program once per bucket capacity on zero dummies — pure
        function, no state, no emit. None when the schema is inferred at
        the staging boundary (nothing to synthesize from yet)."""
        import jax
        sch = self.op.schema
        if sch is None:
            return None
        for cap in caps:
            jax.block_until_ready(
                self._jitted(prewarm_zero_fields(sch, cap)))
        return len(caps)


class _KeyedStateScan:
    """Shared keyed device-state machinery for stateful Map/Filter.

    The reference runs one CUDA worker per distinct key walking its linked
    chain serially (``map_gpu.hpp:80-102``). The TPU shape of that idea: a
    (K_cap x M) GRID scan — rows scatter to (key slot, per-key position),
    the scan walks the per-key POSITION axis (M = max tuples of one key in
    the batch) while ``vmap`` processes all keys in parallel each step.
    Sequential work is the per-key chain depth, not the batch size; state
    lives in a device-resident (K_cap,) table pytree between batches.
    """

    def __init__(self, replica, func, state_init, filter_mode: bool,
                 op=None) -> None:
        from .keymap import KeySlotMap
        self.replica = replica
        self.func = func
        self.state_init = state_init
        self.filter_mode = filter_mode
        self._keymap = KeySlotMap()
        self.slot_of_key = self._keymap.slot_of_key  # shared dict
        self.table_capacity = 64
        # compiled grid-scan programs shared across replicas of the op
        # (keyed by grid shape; the table capacity is read from the table
        # ARGUMENT at trace time, so growth re-traces automatically).
        # ``op`` overrides the owner: a fused chain replica hosts one
        # engine per stateful SUB-operator, each resolving keys and
        # caching against its own op.
        self.op = replica.op if op is None else op
        self._cache = self.op._scan_prog_cache
        self._cache_lock = self.op._scan_prog_lock
        self.table = None  # pytree of (table_capacity, ...) arrays
        # tiered keyed state (windflow_tpu.state): with_tiering caps the
        # device table at hot_capacity and spills the cold tail to a
        # host sqlite store; None = the dense path, byte-identical to
        # before the tier plane existed
        self.tier = None
        cfg = getattr(self.op, "tiering", None)
        if cfg is not None:
            from ..state.tiered import TieredKeyStore
            self.tier = TieredKeyStore(
                f"{self.op.name}_r{replica.idx}_tier", cfg,
                stats=replica.stats)
            self.table_capacity = self.tier.hot_capacity
        # incremental checkpointing (WF_CKPT_DELTA): a device-resident
        # touched-slot bitmap rides the grid-scan carry, so a delta
        # snapshot gathers only the rows dirtied since the last FULL
        # snapshot (the delta base — always a full epoch, chain depth 1)
        self.dirty = None  # (table_capacity,) bool, grown with the table
        self._delta_base = None  # epoch id of the last full snapshot
        self._snaps_since_full = 0
        self._base_capacity = None  # capacity at the last full snapshot
        self._base_nkeys = None  # key count at the last full snapshot

    # -- device program ----------------------------------------------------
    def _make(self, M: int, KB: int):
        """The program works on the BATCH-LOCAL key set: grids are
        (KB x M) where KB = distinct keys in this batch (bucketed), and the
        global state table contributes only its touched rows (gathered in,
        scattered back) — per-batch cost is bounded by the batch, not by
        the stream's total key cardinality. The traced math lives in the
        shared ``_grid_scan_core`` kernel; this wrapper adds the
        standalone exit (compaction for filters) and the jit/donation."""
        import jax
        import jax.numpy as jnp

        core = _grid_scan_core(self.func, self.filter_mode, M, KB)
        filter_mode = self.filter_mode

        def run(fields, grid_idx, valid, touched, touched_mask, table,
                dirty):
            out, table2, dirty2 = core(fields, valid, grid_idx, touched,
                                       touched_mask, table, dirty)
            if filter_mode:
                keep = out
                order = _compact_order(keep)  # keepers first, stable
                outf = {k: v[order] for k, v in fields.items()}
                return outf, order, jnp.sum(keep), table2, dirty2
            return out, table2, dirty2

        # the state table (and its dirty bitmap) are DONATED: the
        # touched-row scatter updates them in place instead of copying
        # the whole table every batch (the same double-buffer discipline
        # as the FFAT forest — every call site reassigns self.table /
        # self.dirty from the program output, so the consumed buffers are
        # never reused)
        return instrumented_jit(run, self.replica.stats,
                                label=self.op.name, donate_argnums=(5, 6))

    # -- host side ---------------------------------------------------------
    def _ensure_table(self, n_keys_needed: int) -> None:
        import jax
        import jax.numpy as jnp

        if self.table is None:
            init = self.state_init
            self.table = jax.tree_util.tree_map(
                lambda v: jnp.full((self.table_capacity,), v,
                                   dtype=jnp.asarray(v).dtype), init)
        self._sync_dirty()
        if self.tier is not None:
            # tiered mode: the device table IS the hot tier, fixed at
            # hot_capacity — keys beyond it spill to the cold store via
            # plan_batch, which guarantees the mapped set always fits
            if n_keys_needed > self.table_capacity:  # pragma: no cover
                from ..basic import KeyCapacityError
                raise KeyCapacityError(
                    self.op.name, self.table_capacity,
                    n_keys_needed - self.table_capacity)
            return
        if n_keys_needed > self.table_capacity:
            # growth reads the CURRENT table: in-flight commits reassign
            # it (donation), so they must land first
            self.replica.dispatch.drain(forced=True)
        while n_keys_needed > self.table_capacity:
            self.table_capacity *= 2
            old = self.table
            fresh = jax.tree_util.tree_map(
                lambda v: jnp.full((self.table_capacity,), v,
                                   dtype=jnp.asarray(v).dtype),
                self.state_init)
            self.table = jax.tree_util.tree_map(
                lambda f, o: f.at[:o.shape[0]].set(o), fresh, old)
        self._sync_dirty()

    def _sync_dirty(self) -> None:
        """Keep the dirty bitmap allocated and shape-matched to the
        table. Growth carries the old bits over — the grown rows hold
        initial state and get marked when first touched (and growth
        changes capacity, which already forces the next snapshot FULL)."""
        import jax.numpy as jnp

        if self.table is None:
            return
        if self.dirty is None:
            self.dirty = jnp.zeros((self.table_capacity,), bool)
        elif int(self.dirty.shape[0]) != self.table_capacity:
            old = self.dirty
            self.dirty = (jnp.zeros((self.table_capacity,), bool)
                          .at[:old.shape[0]].set(old))

    def grid_meta(self, batch: BatchTPU):
        """(grid_idx, valid, touched, touched_mask, M, KB): batch-local
        grid positions, the touched global table rows, and the grid
        bucket sizes. No comparison sort on the hot path: global slots
        come from the KeySlotMap LUT; touched rows + dense local ids come
        from a bincount when the table is batch-sized (falling back to
        np.unique when total keys dwarf the batch — bincount would pay
        O(table) per batch) and the grouping from a radix argsort."""
        from .keymap import group_positions

        n = batch.size
        cap = batch.capacity
        keys, keys_arr = op_batch_keys_np(self.op, batch)
        if self.tier is not None and n:
            from .keymap import distinct_batch_keys
            plan = self.tier.plan_batch(
                self._keymap, distinct_batch_keys(keys, keys_arr, n))
            if plan is not None:
                self._submit_tier_plan(plan)
            self.tier.publish_gauges(len(self.slot_of_key))
        gslots = self._keymap.slots_of(keys, keys_arr, n)
        self._ensure_table(len(self.slot_of_key))
        if self.table_capacity <= 4 * max(1, n):
            # touched rows + dense local ids, O(n + table) via bincount
            cnt = np.bincount(gslots, minlength=self.table_capacity)
            touched_list = np.nonzero(cnt)[0]
            lmap = np.zeros(self.table_capacity, dtype=np.int64)
            lmap[touched_list] = np.arange(len(touched_list))
            lslots = lmap[gslots]
        else:  # high cardinality: O(n log n) beats O(table_capacity)
            touched_list, lslots = np.unique(gslots, return_inverse=True)
        _, within = group_positions(lslots, len(touched_list))
        max_depth = int(within.max()) + 1 if n else 1
        M = 1
        while M < max_depth:
            M <<= 1
        KB = 1
        while KB < max(1, len(touched_list)):
            KB <<= 1
        grid_idx = np.zeros(cap, dtype=np.int32)
        grid_idx[:n] = lslots * M + within
        valid = np.zeros(cap, dtype=bool)
        valid[:n] = True
        touched = np.zeros(KB, dtype=np.int32)
        touched[:len(touched_list)] = touched_list
        touched_mask = np.zeros(KB, dtype=bool)
        touched_mask[:len(touched_list)] = True
        return grid_idx, valid, touched, touched_mask, M, KB

    def program(self, M: int, KB: int):
        return cached_compile(self._cache, self._cache_lock, (M, KB),
                              lambda: self._make(M, KB))

    # -- tiered data movement ----------------------------------------------
    def _submit_tier_plan(self, plan) -> None:
        """Queue one batch's tier maintenance on the replica's dispatch
        queue: ``handle_msg`` submits the batch's own commit AFTER prep
        returns, so this lands behind every in-flight commit and ahead of
        the batch that needs the promoted rows. The movement itself is
        batched — ONE slot-row gather per leaf for the demotes, ONE
        scatter per leaf for the promotes — never per-key transfers."""
        import jax
        import jax.numpy as jnp

        tier = self.tier

        def tier_commit() -> None:
            import jax.numpy as jnp  # local: commit may run on drain
            self._ensure_table(0)  # first batch: allocate the hot tier
            t0 = time.perf_counter()
            leaves, treedef = jax.tree_util.tree_flatten(self.table)
            if len(plan.demote_keys):
                dslots = jnp.asarray(plan.demote_slots)
                cols = [np.asarray(jax.device_get(lf[dslots]))
                        for lf in leaves]
                tier.cold.put_rows(plan.demote_keys, cols)
                tier.note_demote(len(plan.demote_keys))
            if len(plan.promote_keys):
                init_leaves = jax.tree_util.tree_leaves(self.state_init)
                cols, _hits = tier.cold.take_rows(
                    plan.promote_keys, init_leaves,
                    [np.dtype(lf.dtype) for lf in leaves])
                pslots = jnp.asarray(plan.promote_slots)
                leaves = [lf.at[pslots].set(jnp.asarray(col))
                          for lf, col in zip(leaves, cols)]
                self.table = jax.tree_util.tree_unflatten(treedef, leaves)
                if self.dirty is not None:
                    # promoted rows differ from the delta base's hot tier
                    self.dirty = self.dirty.at[pslots].set(True)
                tier.note_promote(len(plan.promote_keys),
                                  (time.perf_counter() - t0) * 1e6)

        self.replica.dispatch.submit(tier_commit, 0.0)

    # -- checkpointing -----------------------------------------------------
    # The whole scan state is (key -> slot dict, capacity, one device
    # pytree): device_get it to host numpy for the blob (DrJAX-style —
    # array state makes snapshots a transfer, not a serializer) and
    # device_put it back on restore. The KeySlotMap LUT refills lazily
    # from the restored dict, and compiled programs re-trace on demand.
    def snapshot_state(self) -> dict:
        import jax
        import jax.numpy as jnp
        from ..checkpoint import delta as ckpt_delta

        ctx = ckpt_delta.snapshot_ctx()
        if (self.table is not None and self.dirty is not None
                and self._base_capacity == self.table_capacity
                and ckpt_delta.delta_eligible(
                    self._delta_base, self._snaps_since_full, ctx)):
            # DELTA: gather only the rows dirtied since the last full
            # snapshot — cost scales with the touched set, not capacity
            self._snaps_since_full += 1
            repl, carry = {}, []
            if (self.tier is None
                    and len(self.slot_of_key) == self._base_nkeys):
                # no key registered since the base: the directory rides
                # as a zero-byte carry, not a re-pickle of every key.
                # Dense slots are append-only, so an unchanged count
                # means an unchanged mapping; under tiering demote /
                # promote swaps remap at constant size, so never carry.
                carry += ["slot_of_key", "table_capacity"]
            else:
                repl["slot_of_key"] = dict(self.slot_of_key)
                repl["table_capacity"] = self.table_capacity
            if self.tier is not None:
                repl["tier"] = self.tier.snapshot_delta(self._delta_base)
            return ckpt_delta.make_delta(
                self._delta_base,
                rows={"table": self._dirty_rows()},
                replace=repl or None, carry=carry or None)
        table = (None if self.table is None
                 else jax.device_get(self.table))
        d = {"slot_of_key": dict(self.slot_of_key),
             "table_capacity": self.table_capacity,
             "table": table}
        if self.tier is not None:
            from ..state.tiered import hot_table_digest
            d["tier"] = self.tier.snapshot(
                hot_digest=hot_table_digest(table))
        if ctx is not None and ckpt_delta.env_ckpt_delta():
            # this full capture is the new delta baseline; the bitmap
            # and the cold store's WAL restart from it (capture runs
            # post-drain, so no in-flight commit can race the reset)
            self._delta_base = ctx.ckpt_id
            self._base_capacity = self.table_capacity
            self._base_nkeys = len(self.slot_of_key)
            self._snaps_since_full = 0
            if self.table is not None:
                self.dirty = jnp.zeros((self.table_capacity,), bool)
            if self.tier is not None:
                self.tier.wal_reset()
        return d

    def _dirty_rows(self) -> dict:
        """Host copies of just the dirty slot rows, one gathered column
        per table leaf (tree_flatten order — matches delta._apply_rows)."""
        import jax

        dirty_np = np.asarray(jax.device_get(self.dirty)).astype(bool)
        slots = np.nonzero(dirty_np)[0].astype(np.int64)
        leaves, _ = jax.tree_util.tree_flatten(self.table)
        rows = [np.asarray(jax.device_get(lf[slots])) for lf in leaves]
        return {"slots": slots, "leaves": rows}

    def restore_state(self, state: dict) -> None:
        import jax

        # restored state starts a fresh delta lineage: the next capture
        # is FULL and re-establishes base/bitmap/WAL
        self.dirty = None
        self._delta_base = None
        self._snaps_since_full = 0
        self._base_capacity = None
        self._base_nkeys = None
        tier_blob = state.get("tier")
        if tier_blob is not None and self.tier is None:
            raise WindFlowError(
                f"{self.op.name}: checkpoint holds a TIERED key store "
                "(hot + cold) but this graph was built without "
                "with_tiering(); cold-tier keys cannot be restored into "
                "a dense table — rebuild the graph with tiering enabled")
        self.slot_of_key.clear()  # shared alias with the KeySlotMap
        self.slot_of_key.update(state.get("slot_of_key", {}))
        self._keymap._lut = None
        table = state.get("table")
        if self.tier is not None:
            if tier_blob is not None:
                from ..state.tiered import hot_table_digest
                self.tier.restore(tier_blob,
                                  hot_digest=hot_table_digest(table))
                self.table_capacity = self.tier.hot_capacity
                self.table = (None if table is None else
                              jax.tree_util.tree_map(jax.device_put,
                                                     table))
            else:
                # dense (pre-tiering) blob into a tiered engine: every
                # checkpointed key becomes hot — dense slot ids are
                # contiguous from 0 so they are valid hot slots iff the
                # key count fits (adopt_dense refuses otherwise)
                self._adopt_dense_blob(table)
            return
        self.table_capacity = state.get("table_capacity",
                                        self.table_capacity)
        self.table = (None if table is None
                      else jax.tree_util.tree_map(jax.device_put, table))

    def _adopt_dense_blob(self, table) -> None:
        import jax
        import jax.numpy as jnp

        self.tier.adopt_dense(self.slot_of_key)
        cap = self.tier.hot_capacity
        self.table_capacity = cap
        if table is None:
            self.table = None
            return
        # refit the dense table to the hot tier's shape: occupied rows
        # carry over (all slots < key count <= cap), padding rows start
        # from the initial state
        self.table = jax.tree_util.tree_map(
            lambda v, a: jnp.full((cap,), v, dtype=np.asarray(a).dtype)
                            .at[:min(cap, len(a))]
                            .set(jnp.asarray(np.asarray(a)[:cap])),
            self.state_init, table)


class StatefulMapTPUReplica(TPUReplicaBase):
    """Per-key device state via the grid scan (see _KeyedStateScan)."""

    def __init__(self, op, idx):
        super().__init__(op, idx)
        self.engine = _KeyedStateScan(self, op.func, op.state_init, False)

    def prep_device_batch(self, batch: BatchTPU) -> Optional[Callable]:
        # host prep: slot mapping + grid assembly (grid_meta drains the
        # pipeline itself iff the state table must grow); the commit
        # reads self.engine.table AT COMMIT TIME — earlier queued commits
        # reassign it (donation)
        grid_idx, valid, touched, tmask, M, KB = self.engine.grid_meta(batch)
        prog = self.engine.program(M, KB)

        def commit() -> None:
            outs, table2, dirty2 = prog(batch.fields, grid_idx, valid,
                                        touched, tmask, self.engine.table,
                                        self.engine.dirty)
            self.stats.device_programs_run += 1
            self.engine.table = table2
            self.engine.dirty = dirty2
            self._emit_batch(batch.with_fields(outs))

        return commit

    def snapshot_state(self) -> dict:
        st = super().snapshot_state()
        st["scan"] = self.engine.snapshot_state()
        return st

    def restore_state(self, state: dict) -> None:
        super().restore_state(state)
        if "scan" in state:
            self.engine.restore_state(state["scan"])


class StatefulFilterTPUReplica(TPUReplicaBase):
    """Keyed-state predicate + compaction in one program (the reference's
    stateful Filter_GPU, ``filter_gpu.hpp:331-335``)."""

    def __init__(self, op, idx):
        super().__init__(op, idx)
        self.engine = _KeyedStateScan(self, op.pred, op.state_init, True)

    def prep_device_batch(self, batch: BatchTPU) -> Optional[Callable]:
        grid_idx, valid, touched, tmask, M, KB = self.engine.grid_meta(batch)
        prog = self.engine.program(M, KB)

        def commit() -> None:
            out, order, count, table2, dirty2 = prog(
                batch.fields, grid_idx, valid, touched, tmask,
                self.engine.table, self.engine.dirty)
            self.stats.device_programs_run += 1
            self.engine.table = table2
            self.engine.dirty = dirty2
            # emit_compacted's int(count)/np.asarray(order) readbacks run
            # here, depth batches after dispatch — no fresh-result stall
            self.emit_compacted(batch, out, order, count)

        return commit

    def snapshot_state(self) -> dict:
        st = super().snapshot_state()
        st["scan"] = self.engine.snapshot_state()
        return st

    def restore_state(self, state: dict) -> None:
        super().restore_state(state)
        if "scan" in state:
            self.engine.restore_state(state["scan"])


# ---------------------------------------------------------------------------
# Filter_TPU
# ---------------------------------------------------------------------------
class Filter_TPU(TPUOperatorBase):
    """Stateless: ``pred(fields) -> bool column``; the batch compacts.
    Stateful (``state_init`` given): ``pred(row, state) -> (keep, state)``
    over scalars with per-key device state (grid scan)."""

    def __init__(self, pred: Callable, name: str = "filter_tpu",
                 parallelism: int = 1,
                 input_routing: RoutingMode = RoutingMode.FORWARD,
                 key_extractor=None, output_batch_size: int = 0,
                 schema: Optional[TupleSchema] = None,
                 state_init: Any = None, tiering=None) -> None:
        if state_init is not None and key_extractor is None:
            raise WindFlowError(f"{name}: stateful Filter_TPU requires a "
                                "key extractor (KEYBY)")
        if tiering is not None and state_init is None:
            raise WindFlowError(f"{name}: with_tiering requires keyed "
                                "state (with_state)")
        super().__init__(name, parallelism,
                         RoutingMode.KEYBY if state_init is not None
                         else input_routing,
                         key_extractor, output_batch_size, schema)
        self.pred = pred
        self.state_init = state_init
        self.tiering = tiering

    @property
    def fusion_role(self) -> Optional[str]:
        return "transform"

    def device_kernel(self):
        if self.state_init is not None:
            raise WindFlowError(f"{self.name}: stateful Filter_TPU carries "
                                "a grid-scan engine, not a stateless kernel")
        pred = self.pred

        def kernel(fields, valid, carry):
            # narrow the keep mask instead of compacting: chained
            # operators see the batch at full capacity and the single
            # chain-exit compaction settles the survivors
            return fields, valid & pred(fields).astype(bool), carry

        return kernel

    def build_replicas(self) -> None:
        cls = (StatefulFilterTPUReplica if self.state_init is not None
               else FilterTPUReplica)
        self.replicas = [cls(self, i) for i in range(self.parallelism)]


class FilterTPUReplica(TPUReplicaBase):
    def __init__(self, op, idx):
        super().__init__(op, idx)
        import jax.numpy as jnp

        kernel = op.device_kernel()

        def run(fields, size):
            n = next(iter(fields.values())).shape[0]
            fields2, keep, _ = kernel(fields, jnp.arange(n) < size, None)
            order = _compact_order(keep)  # keepers first, stable
            out = {k: v[order] for k, v in fields2.items()}
            return out, order, jnp.sum(keep)

        self._jitted = instrumented_jit(run, self.stats, label=op.name)

    def process_device_batch(self, batch: BatchTPU) -> None:
        out, order, count = self._jitted(batch.fields, batch.size)
        self.stats.device_programs_run += 1
        self.emit_compacted(batch, out, order, count)

    def prewarm(self, caps) -> Optional[int]:
        """See ``MapTPUReplica.prewarm`` (``size`` traces as a weak
        scalar, so one warm call per capacity covers every real size)."""
        import jax
        sch = self.op.schema
        if sch is None:
            return None
        for cap in caps:
            jax.block_until_ready(
                self._jitted(prewarm_zero_fields(sch, cap), 0))
        return len(caps)

    # empty batches are dropped entirely (the reference shrinks to zero and
    # forwards; dropping is equivalent because watermarks flow via puncts)


# ---------------------------------------------------------------------------
# Reduce_TPU
# ---------------------------------------------------------------------------
class Reduce_TPU(TPUOperatorBase):
    """Per-batch combine (``combine(fields_a, fields_b) -> fields``,
    associative+commutative, ``API:78-80``). Keyed (key extractor given):
    one output per distinct key per batch (reference ``reduce_by_key``,
    ``reduce_gpu.hpp:245-251``). Global (no key): the whole batch folds to
    ONE output tuple (reference ``thrust::reduce``,
    ``reduce_gpu.hpp:269-272``)."""

    def __init__(self, combine: Callable, key_extractor=None,
                 name: str = "reduce_tpu", parallelism: int = 1,
                 output_batch_size: int = 0,
                 schema: Optional[TupleSchema] = None) -> None:
        routing = (RoutingMode.KEYBY if key_extractor is not None
                   else RoutingMode.FORWARD)
        super().__init__(name, parallelism, routing, key_extractor,
                         output_batch_size, schema)
        self.combine = combine

    @property
    def fusion_role(self) -> Optional[str]:
        # both variants change cardinality, so both may only END a fused
        # chain. The keyed reduce's KEYBY shuffle degenerates to an
        # in-program sort/segment when no cross-device re-shard exists
        # (single replica, or a key-compatible keyed entry) — the
        # legality check in topology/stage.py gates exactly that
        return ("terminator" if self.key_extractor is None
                else "keyed_terminator")

    def build_replicas(self) -> None:
        cls = (ReduceTPUReplica if self.key_extractor is not None
               else GlobalReduceTPUReplica)
        self.replicas = [cls(self, i) for i in range(self.parallelism)]


class GlobalReduceTPUReplica(TPUReplicaBase):
    """Whole-batch fold to one tuple via ``masked_tree_reduce`` (shared
    with the fused-chain exit, which feeds it the chain's keep mask)."""

    def __init__(self, op, idx):
        super().__init__(op, idx)
        import jax.numpy as jnp

        combine = op.combine

        def run(fields, size):
            n = next(iter(fields.values())).shape[0]
            return masked_tree_reduce(combine, fields, jnp.arange(n) < size)

        self._jitted = instrumented_jit(run, self.stats, label=op.name)

    def prewarm(self, caps) -> Optional[int]:
        """See ``MapTPUReplica.prewarm``."""
        import jax
        sch = self.op.schema
        if sch is None:
            return None
        for cap in caps:
            jax.block_until_ready(
                self._jitted(prewarm_zero_fields(sch, cap), 0))
        return len(caps)

    def process_device_batch(self, batch: BatchTPU) -> None:
        if batch.size == 0:
            return
        out = self._jitted(batch.fields, batch.size)
        self.stats.device_programs_run += 1
        ts = np.array([int(batch.ts_host[:batch.size].max())],
                      dtype=np.int64)
        nb = BatchTPU(out, ts, 1, batch.schema, batch.wm)
        nb.stream_tag = batch.stream_tag
        nb.copy_trace_from(batch)
        self._emit_batch(nb)


class ReduceTPUReplica(TPUReplicaBase):
    def __init__(self, op, idx):
        super().__init__(op, idx)
        import jax
        import jax.numpy as jnp

        combine = op.combine

        def run(fields, order, s):
            # order/s precomputed on HOST from the key metadata (already
            # touched for slot mapping; radix argsort of small ids) — no
            # device sort at all
            f = {k: v[order] for k, v in fields.items()}

            def seg_op(a, b):
                fa, sa = a
                fb, sb = b
                same = sa == sb
                merged = combine(fa, fb)
                # fields the combine does not return pass through unchanged
                out = {k: jnp.where(same, merged.get(k, fb[k]), fb[k])
                       for k in fb}
                return out, sb

            scanned, _ = jax.lax.associative_scan(seg_op, (f, s))
            n = s.shape[0]
            is_last = jnp.concatenate(
                [s[1:] != s[:-1], jnp.ones((1,), dtype=bool)])
            idx = jnp.nonzero(is_last, size=n, fill_value=n - 1)[0]
            return {k: v[idx] for k, v in scanned.items()}

        self._jitted = instrumented_jit(run, self.stats, label=op.name)

    def prewarm(self, caps) -> Optional[int]:
        """See ``MapTPUReplica.prewarm`` — the keyed reduce's program
        signature is (fields, order, slots) at one capacity; the
        order/slot VALUES are runtime data, not signature."""
        import jax
        sch = self.op.schema
        if sch is None:
            return None
        for cap in caps:
            order = jax.device_put(np.arange(cap, dtype=np.int32))
            slots = jax.device_put(np.zeros(cap, dtype=np.int32))
            jax.block_until_ready(
                self._jitted(prewarm_zero_fields(sch, cap), order, slots))
        return len(caps)

    def _order_and_slots(self, batch: BatchTPU):
        """See ``reduce_order_and_slots`` (module-level: shared with the
        fused chain's keyed-terminator exit)."""
        return reduce_order_and_slots(self.op, batch)

    def prep_device_batch(self, batch: BatchTPU) -> Optional[Callable]:
        import jax

        # host prep: ONE key sort + slot metadata; the program call and
        # the output-batch assembly are the deferred commit stage
        order_np, ssorted, slot_of_key = self._order_and_slots(batch)
        n_out = len(slot_of_key)
        if n_out == 0:
            return None
        order_dev = jax.device_put(order_np)
        ssorted_dev = jax.device_put(ssorted)
        out_keys = list(slot_of_key.keys())  # insertion order == slot order
        batch_ts = int(batch.ts_host[:batch.size].max()) if batch.size else 0

        def commit() -> None:
            out_fields = self._jitted(batch.fields, order_dev, ssorted_dev)
            self.stats.device_programs_run += 1
            ts2 = np.full(batch.capacity, batch_ts, dtype=np.int64)
            nb = BatchTPU(out_fields, ts2, n_out, batch.schema, batch.wm,
                          out_keys)
            nb.stream_tag = batch.stream_tag
            nb.copy_trace_from(batch)
            self._emit_batch(nb)

        return commit
