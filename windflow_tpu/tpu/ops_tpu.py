"""TPU operators: Map_TPU, Filter_TPU, Reduce_TPU.

Siblings of the reference CUDA operators (``wf/map_gpu.hpp``,
``wf/filter_gpu.hpp``, ``wf/reduce_gpu.hpp``), re-designed for XLA:

- functors are JAX functions over a dict of columns (struct-of-arrays) —
  the whole batch is one compiled program (the reference launches
  grid-stride kernels per batch; XLA fuses the elementwise chain instead);
- ``jax.jit`` is instantiated once per operator; XLA's own cache handles
  one compile per capacity bucket (the reference caches launch configs per
  batch size, ``map_gpu.hpp:251-277``);
- Filter compacts via a stable sort on the keep-mask (the reference uses
  ``thrust::copy_if``, ``filter_gpu.hpp:331-335``);
- Reduce sorts by key slot and runs a segmented associative scan with the
  user's combine, gathering segment tails — one result per key per batch,
  exactly the reference semantics (``reduce_gpu.hpp:239-272``:
  sort_by_key + reduce_by_key). The combine must be associative and
  commutative (``API:78-80``);
- stateful Map/Filter keep per-key state in a device-resident table
  (slots × state pytree) updated by a masked ``lax.scan`` in arrival order —
  replacing the reference's per-key CUDA state objects + cross-replica
  spinlock (``map_gpu.hpp:233-295``, ``basic_gpu.hpp:142-233``) with a
  functional state carry. Keyed TPU operators hold their state per replica
  (keys are partitioned by the keyby shuffle), so no lock exists at all.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..basic import ExecutionMode, OpType, RoutingMode, WindFlowError
from ..operators.base import BasicOperator, BasicReplica
from .batch import BatchTPU, key_column_to_list
from .schema import TupleSchema


# ---------------------------------------------------------------------------
# shared replica machinery
# ---------------------------------------------------------------------------
class TPUReplicaBase(BasicReplica):
    """Processes whole device batches; never iterates rows."""

    def handle_msg(self, ch: int, msg: Any) -> None:
        if msg.is_punct:
            self.stats.punct_received += 1
            self._advance_wm(msg.wm)
            self.on_punctuation(msg.wm)
            return
        if not isinstance(msg, BatchTPU):
            raise WindFlowError(
                f"{self.op.name}: TPU operator received a non-device message "
                f"({type(msg).__name__}); the upstream operator must declare "
                "an output batch size > 0")
        self.stats.start_svc()
        self.stats.inputs_received += msg.size
        self.stats.device_batches_in += 1
        self._advance_wm(msg.wm)
        msg.wm = self.cur_wm
        self.process_device_batch(msg)
        self.stats.end_svc(msg.size)

    def process_device_batch(self, batch: BatchTPU) -> None:
        raise NotImplementedError

    def _emit_batch(self, batch: BatchTPU) -> None:
        self.stats.device_batches_out += 1
        self.emitter.emit_device_batch(batch)

    # per-batch keys: host metadata when staged keyed, else the device key
    # column named by a string key extractor
    def batch_keys(self, batch: BatchTPU):
        keys = batch.host_keys
        if keys is None:
            field = self.op.key_field
            if field is None:
                raise WindFlowError(
                    f"{self.op.name}: keyed TPU operator needs keyed staging "
                    "(with_key_by on the op) or a string field-name key")
            keys = key_column_to_list(batch, field)
        return keys

    def batch_slots(self, batch: BatchTPU):
        import jax
        keys = self.batch_keys(batch)
        slot_of_key: Dict[Any, int] = {}
        slots = np.zeros(batch.capacity, dtype=np.int32)
        for i, k in enumerate(keys):
            slots[i] = slot_of_key.setdefault(k, len(slot_of_key))
        slots[batch.size:] = len(slot_of_key)  # padding segment
        return jax.device_put(slots), slot_of_key


class TPUOperatorBase(BasicOperator):
    op_type = OpType.TPU
    is_tpu = True

    def __init__(self, name: str, parallelism: int, input_routing: RoutingMode,
                 key_extractor, output_batch_size: int,
                 schema: Optional[TupleSchema]) -> None:
        super().__init__(name, parallelism, input_routing, key_extractor,
                         output_batch_size)
        self.schema = schema  # None => inferred at the staging boundary

    @property
    def is_chainable(self) -> bool:
        return False

    def configure(self, execution_mode, time_policy) -> None:
        if execution_mode is not ExecutionMode.DEFAULT:
            # reference: GPU operators only in DEFAULT mode (map_gpu.hpp:470-478)
            raise WindFlowError(
                f"{self.name}: TPU operators require DEFAULT execution mode")
        super().configure(execution_mode, time_policy)


# ---------------------------------------------------------------------------
# Map_TPU
# ---------------------------------------------------------------------------
class Map_TPU(TPUOperatorBase):
    """Stateless: ``func(fields) -> fields`` (elementwise over columns).
    Stateful (``state_init`` given): ``func(row, state) -> (row, state)``
    over scalars, scanned in arrival order with per-key state."""

    def __init__(self, func: Callable, name: str = "map_tpu",
                 parallelism: int = 1,
                 input_routing: RoutingMode = RoutingMode.FORWARD,
                 key_extractor=None, output_batch_size: int = 0,
                 schema: Optional[TupleSchema] = None,
                 state_init: Any = None) -> None:
        if state_init is not None and key_extractor is None:
            raise WindFlowError(f"{name}: stateful Map_TPU requires a key "
                                "extractor (KEYBY)")
        super().__init__(name, parallelism,
                         RoutingMode.KEYBY if state_init is not None
                         else input_routing,
                         key_extractor, output_batch_size, schema)
        self.func = func
        self.state_init = state_init

    def build_replicas(self) -> None:
        cls = StatefulMapTPUReplica if self.state_init is not None \
            else MapTPUReplica
        self.replicas = [cls(self, i) for i in range(self.parallelism)]


class MapTPUReplica(TPUReplicaBase):
    def __init__(self, op, idx):
        super().__init__(op, idx)
        import jax
        self._jitted = jax.jit(op.func)

    def process_device_batch(self, batch: BatchTPU) -> None:
        out = self._jitted(batch.fields)
        self.stats.device_programs_run += 1
        if not isinstance(out, dict):
            raise WindFlowError(f"{self.op.name}: Map_TPU function must "
                                "return a dict of columns")
        self._emit_batch(batch.with_fields(out))


class StatefulMapTPUReplica(TPUReplicaBase):
    """Device-resident keyed state table + masked scan in arrival order."""

    def __init__(self, op, idx):
        super().__init__(op, idx)
        import jax
        import jax.numpy as jnp

        self.slot_of_key: Dict[Any, int] = {}
        self.table_capacity = 64
        self.table = None  # pytree of (table_capacity,)-arrays

        func = op.func

        def run(fields, ts_unused, slots, size, table):
            valid = jnp.arange(next(iter(fields.values())).shape[0]) < size

            def body(tbl, x):
                row, slot, ok = x
                state = jax.tree_util.tree_map(lambda a: a[slot], tbl)
                new_row, new_state = func(row, state)
                tbl = jax.tree_util.tree_map(
                    lambda a, v: a.at[slot].set(
                        jnp.where(ok, v, a[slot]).astype(a.dtype)),
                    tbl, new_state)
                out = {k: jnp.where(ok, new_row[k], row[k]) for k in row}
                return tbl, out

            table2, outs = jax.lax.scan(body, table, (fields, slots, valid))
            return table2, outs

        self._jitted = jax.jit(run)

    def _ensure_table(self, n_keys_needed: int, sample_batch: BatchTPU):
        import jax
        import jax.numpy as jnp

        if self.table is None:
            init = self.op.state_init
            self.table = jax.tree_util.tree_map(
                lambda v: jnp.full((self.table_capacity,), v,
                                   dtype=jnp.asarray(v).dtype), init)
        while n_keys_needed > self.table_capacity:
            self.table_capacity *= 2
            init = self.op.state_init
            old = self.table
            fresh = jax.tree_util.tree_map(
                lambda v: jnp.full((self.table_capacity,), v,
                                   dtype=jnp.asarray(v).dtype), init)
            self.table = jax.tree_util.tree_map(
                lambda f, o: f.at[:o.shape[0]].set(o), fresh, old)

    def process_device_batch(self, batch: BatchTPU) -> None:
        import jax

        slots = np.zeros(batch.capacity, dtype=np.int32)
        for i, k in enumerate(self.batch_keys(batch)):
            s = self.slot_of_key.get(k)
            if s is None:
                s = self.slot_of_key[k] = len(self.slot_of_key)
            slots[i] = s
        self._ensure_table(len(self.slot_of_key), batch)
        table2, outs = self._jitted(batch.fields, None,
                                    jax.device_put(slots), batch.size,
                                    self.table)
        self.stats.device_programs_run += 1
        self.table = table2
        self._emit_batch(batch.with_fields(outs))


# ---------------------------------------------------------------------------
# Filter_TPU
# ---------------------------------------------------------------------------
class Filter_TPU(TPUOperatorBase):
    """``pred(fields) -> bool column``; batch compacts in place."""

    def __init__(self, pred: Callable, name: str = "filter_tpu",
                 parallelism: int = 1,
                 input_routing: RoutingMode = RoutingMode.FORWARD,
                 key_extractor=None, output_batch_size: int = 0,
                 schema: Optional[TupleSchema] = None) -> None:
        super().__init__(name, parallelism, input_routing, key_extractor,
                         output_batch_size, schema)
        self.pred = pred

    def build_replicas(self) -> None:
        self.replicas = [FilterTPUReplica(self, i)
                         for i in range(self.parallelism)]


class FilterTPUReplica(TPUReplicaBase):
    def __init__(self, op, idx):
        super().__init__(op, idx)
        import jax
        import jax.numpy as jnp

        pred = op.pred

        def run(fields, size):
            n = next(iter(fields.values())).shape[0]
            keep = pred(fields) & (jnp.arange(n) < size)
            order = jnp.argsort(~keep, stable=True)  # keepers first, in order
            out = {k: v[order] for k, v in fields.items()}
            return out, order, jnp.sum(keep)

        self._jitted = jax.jit(run)

    def process_device_batch(self, batch: BatchTPU) -> None:
        out, order, count = self._jitted(batch.fields, batch.size)
        self.stats.device_programs_run += 1
        new_size = int(count)
        order_np = np.asarray(order)
        dropped = batch.size - new_size
        self.stats.inputs_ignored += dropped
        ts2 = batch.ts_host[order_np]
        keys2 = None
        if batch.host_keys is not None:
            keys_arr = list(batch.host_keys) + \
                [None] * (batch.capacity - len(batch.host_keys))
            keys2 = [keys_arr[j] for j in order_np[:new_size]]
        nb = BatchTPU(out, ts2, new_size, batch.schema, batch.wm, keys2)
        nb.stream_tag = batch.stream_tag
        if new_size > 0:
            self._emit_batch(nb)

    # empty batches are dropped entirely (the reference shrinks to zero and
    # forwards; dropping is equivalent because watermarks flow via puncts)


# ---------------------------------------------------------------------------
# Reduce_TPU
# ---------------------------------------------------------------------------
class Reduce_TPU(TPUOperatorBase):
    """Per-batch keyed combine: one output tuple per distinct key per batch
    (``combine(fields_a, fields_b) -> fields``, associative+commutative).
    With ``key_extractor=None``... not allowed: KEYBY is mandatory like the
    reference's keyed variant; a global per-batch reduce is the keyed case
    with a constant key."""

    def __init__(self, combine: Callable, key_extractor,
                 name: str = "reduce_tpu", parallelism: int = 1,
                 output_batch_size: int = 0,
                 schema: Optional[TupleSchema] = None) -> None:
        if key_extractor is None:
            raise WindFlowError(f"{name}: Reduce_TPU requires a key extractor")
        super().__init__(name, parallelism, RoutingMode.KEYBY, key_extractor,
                         output_batch_size, schema)
        self.combine = combine

    def build_replicas(self) -> None:
        self.replicas = [ReduceTPUReplica(self, i)
                         for i in range(self.parallelism)]


class ReduceTPUReplica(TPUReplicaBase):
    def __init__(self, op, idx):
        super().__init__(op, idx)
        import jax
        import jax.numpy as jnp

        combine = op.combine

        def run(fields, slots):
            order = jnp.argsort(slots, stable=True)
            f = {k: v[order] for k, v in fields.items()}
            s = slots[order]

            def seg_op(a, b):
                fa, sa = a
                fb, sb = b
                same = sa == sb
                merged = combine(fa, fb)
                # fields the combine does not return pass through unchanged
                out = {k: jnp.where(same, merged.get(k, fb[k]), fb[k])
                       for k in fb}
                return out, sb

            scanned, _ = jax.lax.associative_scan(seg_op, (f, s))
            n = s.shape[0]
            is_last = jnp.concatenate(
                [s[1:] != s[:-1], jnp.ones((1,), dtype=bool)])
            idx = jnp.nonzero(is_last, size=n, fill_value=n - 1)[0]
            return {k: v[idx] for k, v in scanned.items()}

        self._jitted = jax.jit(run)

    def process_device_batch(self, batch: BatchTPU) -> None:
        import jax
        slots_dev, slot_of_key = self.batch_slots(batch)
        out_fields = self._jitted(batch.fields, slots_dev)
        self.stats.device_programs_run += 1
        n_out = len(slot_of_key)
        if n_out == 0:
            return
        out_keys = list(slot_of_key.keys())  # insertion order == slot order
        batch_ts = int(batch.ts_host[:batch.size].max()) if batch.size else 0
        ts2 = np.full(batch.capacity, batch_ts, dtype=np.int64)
        nb = BatchTPU(out_fields, ts2, n_out, batch.schema, batch.wm,
                      out_keys)
        nb.stream_tag = batch.stream_tag
        self._emit_batch(nb)
