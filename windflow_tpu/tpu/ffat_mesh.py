"""Compatibility shim: ``Ffat_Windows_Mesh`` moved to
``windflow_tpu.mesh.ffat_mesh`` when the mesh execution plane became a
first-class subsystem (``windflow_tpu/mesh/``). Import from there."""

from ..mesh.ffat_mesh import Ffat_Windows_Mesh, FfatMeshReplica

__all__ = ["Ffat_Windows_Mesh", "FfatMeshReplica"]
