"""Key -> dense slot mapping shared by keyed device operators.

Every keyed device operator (FFAT forest, stateful map/filter scans,
keyed reduce metadata) needs the same hot operation: map a batch of keys
to dense slot ids, creating slots for unseen keys. The generic path is a
dict; the hot path for small non-negative int keys is a direct numpy
lookup table — O(n) with no per-tuple Python and no sort (the reference
keeps per-batch key maps rebuilt with device sort/unique kernels,
``keyby_emitter_gpu.hpp:518-583``; here keys are host metadata)."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np


def structured_unique(keys_arr: np.ndarray, n: int):
    """``(uniq, inverse)`` for a structured (composite-key) column, or
    None when a field numpy cannot sort (object dtype) — callers then
    walk the rows as ``.tolist()`` tuples. The SINGLE definition of the
    structured dedup used by every slot-mapping path: slot identity must
    never diverge between them for the same stream."""
    try:
        return np.unique(keys_arr[:n], return_inverse=True)
    except TypeError:
        return None


def distinct_batch_keys(keys, keys_arr: np.ndarray, n: int):
    """The batch's DISTINCT keys in the same canonical hashed form each
    ``slots_of`` path registers them (python ints for int columns, tuples
    for structured rows) — the tiered store plans promotions against
    these before the vectorized slot resolution runs, so every form
    mismatch would split one stream key into two slots."""
    if not n:
        return []
    if keys_arr.ndim == 1:
        if keys_arr.dtype.kind in "iu":
            return [int(k) for k in np.unique(keys_arr[:n])]
        if keys_arr.dtype.kind == "V" and keys_arr.dtype.names:
            uu = structured_unique(keys_arr, n)
            if uu is not None:
                return [u.item() for u in uu[0]]
            return list(dict.fromkeys(keys_arr[:n].tolist()))
    it = iter(keys)
    return list(dict.fromkeys(next(it) for _ in range(n)))


class KeySlotMap:
    LUT_MAX = 1 << 22  # 16 MiB int32 ceiling for the direct table

    def __init__(self, on_new: Optional[Callable[[Any, int], None]] = None
                 ) -> None:
        self.slot_of_key: Dict[Any, int] = {}
        self._on_new = on_new  # called as on_new(key, slot) for each new key
        self._lut = None

    def __len__(self) -> int:
        return len(self.slot_of_key)

    def slot(self, key) -> int:
        s = self.slot_of_key.get(key)
        if s is None:
            s = len(self.slot_of_key)
            if self._on_new is not None:
                # on_new may refuse the key (capacity); it must run BEFORE
                # registration so a raise leaves no stale entry that a
                # caught-and-retried batch would silently reuse with an
                # out-of-range slot
                self._on_new(key, s)
            self.slot_of_key[key] = s
        return s

    # -- tiered-store slot reuse (windflow_tpu.state.tiered) ---------------
    # The tiered key store recycles slots of demoted keys, so slot ids are
    # assigned by the TIER plan, not by insertion order; these two keep the
    # dict and the int LUT consistent under out-of-order assignment.
    def assign(self, key, slot: int) -> None:
        """Register ``key`` at an explicit ``slot`` (tier promote)."""
        self.slot_of_key[key] = slot
        lut = self._lut
        if lut is not None and isinstance(key, (int, np.integer)) \
                and 0 <= key < len(lut):
            lut[key] = slot

    def evict(self, key) -> None:
        """Forget ``key`` (tier demote); its slot is the caller's to
        recycle. The LUT entry must clear too — a stale hit would route
        the key to a slot now owned by someone else."""
        self.slot_of_key.pop(key, None)
        lut = self._lut
        if lut is not None and isinstance(key, (int, np.integer)) \
                and 0 <= key < len(lut):
            lut[key] = -1

    def slots_of(self, keys, keys_arr: np.ndarray, n: int) -> np.ndarray:
        """Vectorized mapping of a whole batch; int result of length n
        (int32 on the LUT fast path — valid for indexing and promoted by
        numpy in mixed arithmetic; avoids a 16k-copy per batch). The int
        fast paths require a 1-D int array — tuple-of-int keys become a
        2-D array and must take the generic per-key path."""
        if keys_arr.ndim != 1:
            return np.fromiter((self.slot(k) for k in keys),
                               dtype=np.int64, count=n)
        if keys_arr.dtype.kind in "iu" and n:
            kmin = int(keys_arr.min())
            kmax = int(keys_arr.max())
            if 0 <= kmin and kmax < self.LUT_MAX:
                lut = self._lut
                if lut is None or kmax >= len(lut):
                    size = min(self.LUT_MAX,
                               1 << max(10, (kmax + 1).bit_length()))
                    new = np.full(size, -1, dtype=np.int32)
                    if lut is not None:
                        new[:len(lut)] = lut
                    lut = self._lut = new
                slots = lut[keys_arr]
                miss = slots < 0
                if miss.any():
                    for k in np.unique(keys_arr[miss]):
                        lut[k] = self.slot(int(k))
                    slots = lut[keys_arr]
                return slots
        if keys_arr.dtype.kind in "iu":
            uniq, inverse = np.unique(keys_arr, return_inverse=True)
            slot_map = np.fromiter((self.slot(int(k)) for k in uniq),
                                   dtype=np.int64, count=len(uniq))
            return slot_map[inverse]
        if keys_arr.dtype.kind == "V" and keys_arr.dtype.names:
            # structured (composite-key) columns: O(n log n) C sort +
            # one Python slot() per DISTINCT key. Registered as plain
            # tuples (np.void rows are unhashable and must equal the
            # tuples the per-row path extracts for the same key).
            uu = structured_unique(keys_arr, n)
            if uu is None:  # an object field: per-row over tuples
                return np.fromiter(
                    (self.slot(k) for k in keys_arr[:n].tolist()),
                    dtype=np.int64, count=n)
            uniq, inverse = uu
            slot_map = np.fromiter((self.slot(u.item()) for u in uniq),
                                   dtype=np.int64, count=len(uniq))
            return slot_map[inverse]
        return np.fromiter((self.slot(k) for k in keys),
                           dtype=np.int64, count=n)


def stable_group_argsort(vals: np.ndarray, n_groups: int) -> np.ndarray:
    """Stable argsort of small non-negative group ids. numpy's stable
    sort takes a RADIX path for <=16-bit ints only (~12x the comparison
    sort; int32/int64 both fall back to timsort, measured), so the cast
    pays off exactly when the ids fit int16."""
    if n_groups < 2**15 - 1:
        return np.argsort(vals.astype(np.int16), kind="stable")
    return np.argsort(vals, kind="stable")


def group_positions(slots: np.ndarray, n_groups: int):
    """(order, within): stable group-sort order of ``slots`` and each
    element's arrival rank WITHIN its group (the run-length grouping idiom
    shared by the grid scan and CB leaf numbering)."""
    n = len(slots)
    order = stable_group_argsort(slots, n_groups)
    ss = slots[order]
    seg_start = np.r_[True, ss[1:] != ss[:-1]] if n else np.zeros(0, bool)
    first_of = np.nonzero(seg_start)[0]
    grp = np.cumsum(seg_start) - 1
    within = np.empty(n, dtype=np.int64)
    within[order] = np.arange(n) - first_of[grp]
    return order, within
