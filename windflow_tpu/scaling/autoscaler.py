"""Autoscaler: the observe -> decide -> act policy loop.

Consumes exactly the signals the observability plane already exports per
replica (PR 2/PR 5) and acts through ``RescaleController``:

- ``Queue_blocked_put_usec``: producer time blocked on an operator's full
  input queue — the operator IS the bottleneck (backpressure);
- ``Worker_idle_ticks`` + ``Queue_blocked_get_usec``: the operator is
  starved — a scale-down candidate;
- sink-side ``Latency_e2e_p99_usec``: the user-facing symptom that
  confirms a scale-up (p99 degrading while something backpressures).

Decisions are rate-based deltas between 1 Hz-ish snapshots, debounced by
HYSTERESIS consecutive windows, and separated by a COOLDOWN after every
action (a rescale resets counters and perturbs the pipeline; deciding
again off that transient would oscillate). Scale-up multiplies
parallelism by FACTOR (bounded by MAX_PAR) — a surge needs a step
response; scale-down retreats one replica at a time — draining capacity
is the risky direction.

Every decision (acted or vetoed) is recorded: ``Autoscaler_*`` stats,
``windflow_rescale_*`` /metrics families, and ``rescale:decision`` spans
in the flight-recorder timeline.

Env knobs (builder twin: ``PipeGraph.with_autoscaler(policy)``)::

    WF_AUTOSCALE=1              enable with defaults at start()
    WF_AUTOSCALE_INTERVAL=1.0   snapshot period, seconds
    WF_AUTOSCALE_COOLDOWN=5.0   seconds after an action before deciding
    WF_AUTOSCALE_MAX_PAR=8      upper parallelism bound
    WF_AUTOSCALE_MIN_PAR=1      lower parallelism bound
    WF_AUTOSCALE_UP_MS=50       blocked-put ms per wall second to scale up
    WF_AUTOSCALE_DOWN_MS=900    blocked-get ms/s per replica to scale down
    WF_AUTOSCALE_HYSTERESIS=3   consecutive windows before acting
    WF_AUTOSCALE_FACTOR=2.0     scale-up multiplier
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default  # malformed knob must not take down the graph


class AutoscalePolicy:
    """Pure decision logic over per-operator signal windows; unit-testable
    without a running graph (feed ``observe`` synthetic rate dicts)."""

    def __init__(self,
                 interval_s: Optional[float] = None,
                 cooldown_s: Optional[float] = None,
                 min_parallelism: Optional[int] = None,
                 max_parallelism: Optional[int] = None,
                 up_blocked_put_ms: Optional[float] = None,
                 down_blocked_get_ms: Optional[float] = None,
                 hysteresis: Optional[int] = None,
                 factor: Optional[float] = None) -> None:
        self.interval_s = interval_s if interval_s is not None \
            else _env_f("WF_AUTOSCALE_INTERVAL", 1.0)
        self.cooldown_s = cooldown_s if cooldown_s is not None \
            else _env_f("WF_AUTOSCALE_COOLDOWN", 5.0)
        self.min_parallelism = int(min_parallelism
                                   if min_parallelism is not None
                                   else _env_f("WF_AUTOSCALE_MIN_PAR", 1))
        self.max_parallelism = int(max_parallelism
                                   if max_parallelism is not None
                                   else _env_f("WF_AUTOSCALE_MAX_PAR", 8))
        self.up_blocked_put_ms = up_blocked_put_ms \
            if up_blocked_put_ms is not None \
            else _env_f("WF_AUTOSCALE_UP_MS", 50.0)
        self.down_blocked_get_ms = down_blocked_get_ms \
            if down_blocked_get_ms is not None \
            else _env_f("WF_AUTOSCALE_DOWN_MS", 900.0)
        self.hysteresis = int(hysteresis if hysteresis is not None
                              else _env_f("WF_AUTOSCALE_HYSTERESIS", 3))
        self.factor = factor if factor is not None \
            else _env_f("WF_AUTOSCALE_FACTOR", 2.0)
        self._up_streak: Dict[str, int] = {}
        self._down_streak: Dict[str, int] = {}
        self._last_action_t = 0.0

    def note_action(self, now: float) -> None:
        self._last_action_t = now
        self._up_streak.clear()
        self._down_streak.clear()

    def observe(self, rates: Dict[str, Dict[str, float]], now: float,
                shed_active: bool = False
                ) -> Optional[Tuple[str, int, str]]:
        """One decision step. ``rates`` maps eligible operator name ->
        ``{"parallelism", "blocked_put_ms_per_s", "blocked_get_ms_per_s",
        "tuples_per_s"}`` (rates already normalized per wall second).
        ``shed_active``: the overload governor is shedding (or inside
        its cooldown) — scale-DOWN is vetoed, because a post-surge lull
        under admission control reads as starvation while the dropped
        load is exactly what the current capacity absorbs; draining a
        replica then re-adding it on the next breach flaps. Returns
        ``(op, new_parallelism, reason)`` or None."""
        if now - self._last_action_t < self.cooldown_s:
            return None
        # scale UP the worst backpressured operator first: congestion
        # upstream masks everything downstream of it
        worst, worst_rate = None, 0.0
        for name, m in rates.items():
            r = m.get("blocked_put_ms_per_s", 0.0)
            if r >= self.up_blocked_put_ms:
                self._up_streak[name] = self._up_streak.get(name, 0) + 1
                if r > worst_rate:
                    worst, worst_rate = name, r
            else:
                self._up_streak[name] = 0
        if worst is not None \
                and self._up_streak[worst] >= self.hysteresis:
            par = int(rates[worst]["parallelism"])
            new = min(self.max_parallelism,
                      max(par + 1, int(par * self.factor + 0.5)))
            if new > par:
                return (worst, new,
                        f"backpressure {worst_rate:.0f}ms/s blocked-put "
                        f">= {self.up_blocked_put_ms:.0f}ms/s "
                        f"for {self._up_streak[worst]} windows")
        # scale DOWN a starved operator (never while anything is
        # backpressured — draining capacity under load oscillates — and
        # never while the overload governor sheds or cools down)
        if shed_active:
            self._down_streak.clear()
            return None
        if worst is None:
            for name, m in sorted(rates.items()):
                par = int(m["parallelism"])
                starved = (m.get("blocked_get_ms_per_s", 0.0)
                           >= self.down_blocked_get_ms * max(1, par - 1)
                           and m.get("blocked_put_ms_per_s", 0.0) <= 1.0)
                if starved and par > self.min_parallelism:
                    self._down_streak[name] = \
                        self._down_streak.get(name, 0) + 1
                    if self._down_streak[name] >= self.hysteresis:
                        return (name, par - 1,
                                f"idle {m['blocked_get_ms_per_s']:.0f}"
                                "ms/s blocked-get for "
                                f"{self._down_streak[name]} windows")
                else:
                    self._down_streak[name] = 0
        return None


class Autoscaler(threading.Thread):
    """Policy thread: snapshots ``graph.get_stats()`` every interval,
    derives per-operator rates for the RESCALABLE operators, and acts on
    the policy's decision through ``graph.rescale``."""

    def __init__(self, graph, policy: Optional[AutoscalePolicy] = None
                 ) -> None:
        super().__init__(name=f"autoscaler:{graph.name}", daemon=True)
        self.graph = graph
        self.policy = policy or AutoscalePolicy()
        self.decisions: List[Dict[str, Any]] = []  # acted decisions
        self.errors = 0
        self.last_error: Optional[str] = None
        self._stop_evt = threading.Event()
        self._prev: Optional[Dict[str, Dict[str, float]]] = None
        self._prev_t = 0.0

    def stop(self) -> None:
        self._stop_evt.set()

    # -- signal extraction -----------------------------------------------
    def _eligible_ops(self) -> Dict[str, Any]:
        from .repartition import repartition_refusal
        out = {}
        for s in self.graph._stages:
            if any(repartition_refusal(op) is not None for op in s.ops):
                continue
            out[s.first_op.name] = s
        return out

    def _totals(self) -> Dict[str, Dict[str, float]]:
        st = self.graph.get_stats()
        eligible = self._eligible_ops()
        out: Dict[str, Dict[str, float]] = {}
        for op in st.get("Operators", []):
            name = op.get("name")
            if name not in eligible:
                continue
            reps = op.get("replicas", [])
            out[name] = {
                "parallelism": op.get("parallelism", 1),
                "blocked_put_usec": sum(r.get("Queue_blocked_put_usec", 0)
                                        for r in reps),
                "blocked_get_usec": sum(r.get("Queue_blocked_get_usec", 0)
                                        for r in reps),
                "inputs": sum(r.get("Inputs_received", 0) for r in reps),
            }
        return out

    def _rates(self, cur: Dict[str, Dict[str, float]], now: float
               ) -> Dict[str, Dict[str, float]]:
        prev, prev_t = self._prev, self._prev_t
        self._prev, self._prev_t = cur, now
        if prev is None or now <= prev_t:
            return {}
        dt = now - prev_t
        rates = {}
        for name, m in cur.items():
            p = prev.get(name)
            if p is None or p["parallelism"] != m["parallelism"]:
                continue  # fresh op or mid-rescale counter reset: skip
            rates[name] = {
                "parallelism": m["parallelism"],
                "blocked_put_ms_per_s":
                    max(0.0, m["blocked_put_usec"] - p["blocked_put_usec"])
                    / dt / 1e3,
                "blocked_get_ms_per_s":
                    max(0.0, m["blocked_get_usec"] - p["blocked_get_usec"])
                    / dt / 1e3 / max(1, int(m["parallelism"])),
                "tuples_per_s":
                    max(0.0, m["inputs"] - p["inputs"]) / dt,
            }
        return rates

    # -- loop --------------------------------------------------------------
    def run(self) -> None:
        while not self._stop_evt.wait(self.policy.interval_s):
            try:
                self._tick()
            except Exception as e:  # a bad tick must not kill the loop
                self.errors += 1
                self.last_error = f"{type(e).__name__}: {e}"

    def _tick(self) -> None:
        g = self.graph
        if g._ended:
            return
        now = time.monotonic()
        rates = self._rates(self._totals(), now)
        gov = getattr(g, "_overload_governor", None)
        shed_active = gov is not None and gov.blocks_scale_down(now)
        decision = self.policy.observe(rates, now, shed_active=shed_active)
        if decision is None:
            return
        op, new_par, reason = decision
        ctrl = g._rescale_controller()
        ctrl._span("rescale:decision", 0.0,
                   {"op": op, "to": new_par, "reason": reason})
        report = g.rescale(op, new_par)
        self.policy.note_action(time.monotonic())
        self.decisions.append({
            "t_unix": time.time(), "op": op,
            "from": report.get("old_parallelism"), "to": new_par,
            "reason": reason, "pause_s": report.get("pause_s"),
        })
        del self.decisions[:-64]

    # -- introspection -----------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        return {
            "Autoscaler_decisions": len(self.decisions),
            "Autoscaler_errors": self.errors,
            "Autoscaler_last_error": self.last_error,
            "Autoscaler_history": list(self.decisions),
        }
