"""Elastic rescaling: checkpoint-driven live repartitioning of keyed
state plus the autoscaler control loop that closes observe -> decide ->
act over the observability plane.

- ``repartition``: StateRepartitioner — split/merge per-replica keyed
  checkpoint blobs N -> M by the KEYBY routing function;
- ``controller``: RescaleController — quiesce at an aligned barrier,
  rebuild the runtime plane at the new parallelism, restore the
  repartitioned blobs, resume (no source-zero replay);
- ``autoscaler``: AutoscalePolicy / Autoscaler — scale the bottleneck
  operator up and idle operators down under hysteresis + cooldown.

Entry points live on ``PipeGraph``: ``rescale(op, parallelism)`` and
``with_autoscaler(policy)`` (env twin ``WF_AUTOSCALE=1``).
"""

from .autoscaler import Autoscaler, AutoscalePolicy
from .controller import RescaleController, RescaleReport
from .repartition import (repartition_refusal, split_collector_states,
                          split_operator_states)

__all__ = ["Autoscaler", "AutoscalePolicy", "RescaleController",
           "RescaleReport", "repartition_refusal",
           "split_operator_states", "split_collector_states"]
