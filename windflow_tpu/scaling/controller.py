"""RescaleController: live repartitioning of a running PipeGraph.

``rescale(op_name, parallelism)`` closes the loop the checkpoint plane
opened: it quiesces the graph exactly at an aligned barrier, rebuilds the
runtime plane (replica lists, channels, emitter routing tables, fused
device chains, dispatch queues) with the target stage at the new
parallelism, restores every replica from the just-committed checkpoint —
with the rescaled operator's keyed blobs split/merged by the KEYBY
routing function (``repartition.py``) — and resumes. Sources continue
from their barrier positions: no source-zero replay, and results are
identical to an uninterrupted run for keyed operators.

Mechanics of the quiesce: the rescale epoch is triggered with
``hold=True``; every worker parks inside ``checkpoint_now`` immediately
after acking it. At that instant each worker has flushed all pre-barrier
output and forwarded the barrier, and — because every producer parks
before emitting anything post-barrier — the channels are globally empty
of data once the last ack lands. The controller then releases the old
workers with the ``abandon`` directive (they unwind without an EOS
cascade), rebuilds, restores, and starts fresh workers. An abort at any
point before the abandon releases the workers with ``resume`` and the
graph continues unharmed on the old topology.

Downtime is measured and reported per event: ``checkpoint_s`` (trigger ->
commit, processing continues), ``pause_s`` (all-parked -> resumed, the
true stop-the-world window) and ``total_s`` (trigger -> resumed).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..basic import RoutingMode, WindFlowError
from .repartition import (merge_emitter_states, remap_neighbor_collector,
                          repartition_refusal, split_collector_states,
                          split_operator_states, stretch_emitter_state)

_O2O = -1  # channel-layout sentinel: a one-to-one edge (own replica idx)


# ---------------------------------------------------------------------------
# channel layout (mirrors PipeGraph._wire_edge)
# ---------------------------------------------------------------------------
def _edge_one2one(producer, branch, consumer,
                  par_of: Callable[[Any], int]) -> bool:
    first = consumer.first_op
    p_tpu = getattr(producer.last_op, "is_tpu", False)
    c_tpu = getattr(first, "is_tpu", False)
    return (first.input_routing is RoutingMode.FORWARD
            and branch is None
            and not (c_tpu and not p_tpu)
            and par_of(producer) == par_of(consumer))


def _input_layout(consumer, par_of: Callable[[Any], int]
                  ) -> List[Tuple[int, int]]:
    """One consumer replica's input-channel order as ``(edge_idx, pi)``
    entries (``pi == _O2O`` for a one-to-one edge, which contributes the
    replica's own index). Mirrors the port-registration order of
    ``PipeGraph._wire_edge``."""
    out: List[Tuple[int, int]] = []
    for e_i, edge in enumerate(consumer.upstreams):
        if _edge_one2one(edge.stage, edge.branch, consumer, par_of):
            out.append((e_i, _O2O))
        else:
            out.extend((e_i, pi) for pi in range(par_of(edge.stage)))
    return out


# ---------------------------------------------------------------------------
# checkpoint-state transformation
# ---------------------------------------------------------------------------
def repartition_checkpoint_states(graph, states: Dict[Tuple[str, int], dict],
                                  stage, new_n: int
                                  ) -> Dict[Tuple[str, int], dict]:
    """Transform a committed checkpoint's full state map for a rebuild
    with ``stage`` at ``new_n`` replicas: split/merge the rescaled ops'
    keyed blobs, re-index neighbor collector channels, and re-synthesize
    routing counters on every emitter whose destination count changes."""
    old_n = stage.parallelism

    def par_old(s) -> int:
        return s.parallelism

    def par_new(s) -> int:
        return new_n if s is stage else s.parallelism

    out = dict(states)

    # --- the rescaled stage's own blobs --------------------------------
    first_name = stage.first_op.name
    for op in stage.ops:
        if getattr(op, "_fused_hidden", False):
            continue  # fused sub-op: state rides the head op's blob
        olds: List[dict] = []
        for i in range(old_n):
            st = out.pop((op.name, i), None)
            if st is None:
                raise WindFlowError(
                    f"rescale: checkpoint is missing the blob for "
                    f"{op.name!r} replica {i} — cannot repartition")
            olds.append(dict(st))
        emitters = [st.pop("__emitter__", None) for st in olds]
        colls = [st.pop("__collector__", None) for st in olds]
        news = split_operator_states(op, olds, new_n)
        if op.name == first_name and any(colls):
            key_fn = stage.first_op.key_extractor
            if key_fn is None:
                # FORWARD-routed consumer: any replica may process any
                # tuple — park the whole backlog on replica 0
                key_fn = (lambda p: 0)
                dest0: Callable[[Any], int] = (lambda k: 0)
                split_cs = split_collector_states(colls, new_n, key_fn,
                                                 dest0, op.name)
            else:
                from .repartition import dest_fn_for
                split_cs = split_collector_states(
                    colls, new_n, key_fn, dest_fn_for(op, new_n), op.name)
            # channel layout of the rescaled stage itself can shift too
            # (a FORWARD edge into it flips one-to-one <-> shuffle)
            old_in = _input_layout(stage, par_old)
            new_in = _input_layout(stage, par_new)
            changed = {e for e in range(len(stage.upstreams))
                       if _edge_one2one(stage.upstreams[e].stage,
                                        stage.upstreams[e].branch, stage,
                                        par_old)
                       != _edge_one2one(stage.upstreams[e].stage,
                                        stage.upstreams[e].branch, stage,
                                        par_new)}
            if old_in != new_in:
                split_cs = [None if c is None else
                            remap_neighbor_collector(c, old_in, new_in,
                                                     changed)
                            for c in split_cs]
            for j, c in enumerate(split_cs):
                if c:
                    news[j]["__collector__"] = c
        # new outgoing emitters: dest count at the NEW parallelism
        n_dests = _emitter_dest_count(graph, stage, par_new)
        for j in range(new_n):
            news[j]["__emitter__"] = merge_emitter_states(emitters, n_dests)
            out[(op.name, j)] = news[j]

    # --- neighbors ------------------------------------------------------
    for t in graph._stages:
        if t is stage:
            continue
        # downstream consumer of the rescaled stage: its input-channel
        # numbering shifted — re-index collector state (buffered
        # pre-barrier messages included)
        feeds_from = any(e.stage is stage for e in t.upstreams)
        old_in = _input_layout(t, par_old)
        new_in = _input_layout(t, par_new)
        if feeds_from and old_in != new_in:
            changed = {e_i for e_i, e in enumerate(t.upstreams)
                       if e.stage is stage
                       or _edge_one2one(e.stage, e.branch, t, par_old)
                       != _edge_one2one(e.stage, e.branch, t, par_new)}
            fo = t.first_op
            for i in range(t.parallelism):
                st = out.get((fo.name, i))
                if st is None:
                    continue
                cs = st.get("__collector__")
                if cs:
                    st = dict(st)
                    st["__collector__"] = remap_neighbor_collector(
                        cs, old_in, new_in, changed)
                    out[(fo.name, i)] = st
        # upstream producer into the rescaled stage: its emitter's
        # destination count changes — re-synthesize routing counters
        for b, target in _branch_targets(t):
            if target is not stage:
                continue
            o2o_new = _edge_one2one(t, b, stage, par_new)
            n_dests = 1 if o2o_new else new_n
            lo = t.last_op
            for i in range(t.parallelism):
                st = out.get((lo.name, i))
                if st is None:
                    continue
                st = dict(st)
                em = st.get("__emitter__") or {}
                if b is None:
                    st["__emitter__"] = stretch_emitter_state(em, n_dests)
                else:
                    inner = list(em.get("inner", []))
                    while len(inner) <= b:
                        inner.append({})
                    inner[b] = stretch_emitter_state(inner[b], n_dests)
                    st["__emitter__"] = {"inner": inner}
                out[(lo.name, i)] = st
    return out


def _branch_targets(producer) -> List[Tuple[Optional[int], Any]]:
    """(branch, consumer stage) pairs for a producer stage — branch None
    for the plain downstream edge."""
    if producer.is_split:
        return list(enumerate(producer.split_branches))
    return [(None, producer.downstream)]


def _emitter_dest_count(graph, stage, par_of) -> int:
    """Destination count of the rescaled stage's outgoing emitter under
    the ``par_of`` parallelism view (0 for sinks)."""
    down = stage.downstream
    if down is None:
        return 0
    if _edge_one2one(stage, None, down, par_of):
        return 1
    return par_of(down)


# ---------------------------------------------------------------------------
# the controller
# ---------------------------------------------------------------------------
class RescaleReport(dict):
    """Per-event timing/accounting (a dict for painless JSON export)."""

    @property
    def changed(self) -> bool:
        return bool(self.get("changed"))


class RescaleController:
    """One per PipeGraph; ``PipeGraph.rescale`` delegates here. Rescales
    are serialized by the graph's rescale lock — concurrent callers (the
    autoscaler thread and a manual call) queue up."""

    def __init__(self, graph) -> None:
        import threading
        self.graph = graph
        self.lock = threading.Lock()
        self.events = 0
        self.failures = 0
        self.history: List[Dict[str, Any]] = []  # bounded, newest last
        self.last: Optional[RescaleReport] = None
        self._rec = None  # lazy flight-recorder ring ("rescale" track)

    # -- flight recorder -------------------------------------------------
    def _recorder(self):
        if self._rec is None:
            g = self.graph
            events = g._stage_flightrec_events_max()
            if events > 0:
                from ..monitoring.flightrec import FlightRecorder
                self._rec = FlightRecorder(
                    events, pid_label="rescale",
                    tid_label=f"{g.name}/rescale-controller")
                g._recorders.append(self._rec)
        return self._rec

    def _span(self, name: str, dur_us: float, arg: Any = None) -> None:
        rec = self._recorder()
        if rec is not None:
            try:
                rec.event(name, dur_us, arg)
            except Exception:
                pass  # telemetry must never fail a rescale

    # -- the live rescale ------------------------------------------------
    def rescale(self, op_name: str, parallelism: int,
                timeout_s: Optional[float] = None) -> RescaleReport:
        g = self.graph
        if parallelism < 1:
            raise WindFlowError(
                f"rescale({op_name!r}): parallelism must be >= 1")
        if not g._started or g._ended:
            raise WindFlowError(
                "rescale requires a RUNNING graph (between start() and "
                "wait_end() returning)")
        if g._coordinator is None:
            raise WindFlowError(
                "rescale needs aligned checkpointing: call "
                "with_checkpointing() (or set WF_CKPT_INTERVAL) before "
                "start()")
        stage = next((s for s in g._stages
                      if any(op.name == op_name for op in s.ops)), None)
        if stage is None:
            raise WindFlowError(
                f"rescale: no operator named {op_name!r} in this graph")
        # legality FIRST — before any barrier is triggered
        for op in stage.ops:
            refusal = repartition_refusal(op)
            if refusal is not None:
                raise WindFlowError(
                    f"rescale: operator {op.name!r} is not "
                    f"repartitionable — {refusal}")
        # every plain source must be replayable: the rescale restores ALL
        # sources from their barrier positions, and a functor without a
        # cursor would silently replay from zero (duplicating its whole
        # prefix). Kafka sources carry offsets in their replica state.
        from ..operators.source import Source as _PlainSource
        for s in g._stages:
            if s.is_source and isinstance(s.first_op, _PlainSource) \
                    and getattr(s.first_op.func, "snapshot_position",
                                None) is None:
                raise WindFlowError(
                    f"rescale: source {s.first_op.name!r} is not "
                    "replayable (no snapshot_position()/restore() on the "
                    "functor) — a live rescale would replay its whole "
                    "stream from zero; add the replayable-source protocol "
                    "(the same one checkpoint restore uses)")
        with self.lock:
            return self._rescale_locked(stage, op_name, parallelism,
                                        timeout_s)

    def _rescale_locked(self, stage, op_name: str, new_n: int,
                        timeout_s: Optional[float]) -> RescaleReport:
        g = self.graph
        coord = g._coordinator
        old_n = stage.parallelism
        report = RescaleReport(
            op=op_name, stage=stage.describe(), old_parallelism=old_n,
            new_parallelism=new_n, changed=False, t_unix=time.time())
        if new_n == old_n:
            report["reason"] = "no-op: already at requested parallelism"
            self.last = report
            return report
        timeout = timeout_s if timeout_s is not None else \
            (coord.epoch_timeout_s or 60.0)
        t0 = time.monotonic()
        self._span("rescale:trigger", 0.0,
                   {"op": op_name, "from": old_n, "to": new_n})
        cid = coord.trigger(force=True, hold=True)
        try:
            coord.wait_committed(cid, timeout)
            t_commit = time.monotonic()
            if not coord.wait_all_parked(cid, timeout):
                raise WindFlowError(
                    f"rescale: checkpoint {cid} committed but workers "
                    f"did not all quiesce within {timeout:.0f}s "
                    f"(parked: {sorted(coord.parked)})")
            t_parked = time.monotonic()
            self._span("rescale:quiesce",
                       (t_parked - t0) * 1e6, {"ckpt_id": cid})
            # transform the checkpoint BEFORE the old plane is torn down:
            # any repartition error here aborts with the graph unharmed
            ckpt_dir = coord.store.checkpoint_dir(cid)
            manifest = coord.store.load_manifest(ckpt_dir)
            states = coord.store.load_states(ckpt_dir, manifest)
            states = repartition_checkpoint_states(g, states, stage, new_n)
        except BaseException:
            self.failures += 1
            coord.release_hold("resume")
            raise
        # ---- point of no return: tear down the old runtime plane ------
        t_re0 = time.monotonic()
        coord.abort_pending()
        coord.release_hold("abandon")
        old_workers = list(g._workers)
        for w in old_workers:
            w.join(timeout=max(timeout, 10.0))
        stuck = [w.name for w in old_workers if w.is_alive()]
        if stuck:
            raise WindFlowError(
                f"rescale: old workers failed to unwind: {stuck}")
        g._note_retired_replicas(stage, new_n)
        for op in stage.ops:
            op.parallelism = new_n
        g._rebuild_runtime()
        self._span("rescale:rebuild", (time.monotonic() - t_re0) * 1e6,
                   {"threads": len(g._workers)})
        t_rs0 = time.monotonic()
        g._restore_states(states)
        self._span("rescale:restore", (time.monotonic() - t_rs0) * 1e6,
                   {"ckpt_id": cid})
        coord.expected_acks = len(g._workers)
        coord.worker_names = [w.name for w in g._workers]
        for w in g._workers:
            w.start()
        t_resume = time.monotonic()
        self._span("rescale:resume", 0.0,
                   {"op": op_name, "parallelism": new_n})
        report.update(
            changed=True, ckpt_id=cid,
            checkpoint_s=round(t_commit - t0, 6),
            pause_s=round(t_resume - t_parked, 6),
            total_s=round(t_resume - t0, 6))
        self.events += 1
        self.last = report
        self.history.append(dict(report))
        del self.history[:-64]
        return report

    # -- introspection ---------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        last = self.last or {}
        return {
            "Rescale_events": self.events,
            "Rescale_failures": self.failures,
            "Rescale_last_op": last.get("op"),
            "Rescale_last_from": last.get("old_parallelism"),
            "Rescale_last_to": last.get("new_parallelism"),
            "Rescale_last_checkpoint_s": last.get("checkpoint_s", 0.0),
            "Rescale_last_pause_s": last.get("pause_s", 0.0),
            "Rescale_last_total_s": last.get("total_s", 0.0),
            "Rescale_history": list(self.history),
        }
