"""StateRepartitioner: split/merge checkpointed keyed state N -> M.

A committed checkpoint (windflow_tpu.checkpoint) already serializes every
replica's keyed state into per-replica blobs. Rescaling an operator from N
to M replicas is then exactly the redistribution problem of
"Memory-efficient array redistribution through portable collective
communication" (arXiv:2112.01075): re-bucket every key's state by the SAME
routing function the KEYBY emitters use, so that after restore each new
replica owns precisely the keys the emitters will route to it. Host-dict
states (Reduce key_state, WindowEngine key_map, FlatFAT forests, interval
-join archives) re-bucket per key; array-shaped device states (grid-scan
tables, FFAT TPU forests) re-bucket by slot-row gather along the key axis
(the DrJAX-style array-native keyed plane, arXiv:2403.07128 — state moves
as array transfers, never through a per-tuple serializer).

Routing consistency is the correctness contract: CPU KEYBY routes
``hash(key) % M``; the device plane routes via ``_dest_of_key`` (identity
for non-negative ints, FNV for str/bytes/composite — consistent with the
vectorized columnar paths). Both agree for int keys. Because ``hash`` of
str/bytes is randomized per process (PYTHONHASHSEED), CPU-plane
repartitioning of such keys is only valid within one process — which live
rescale always is; cross-process restore keeps the checkpoint's original
parallelism.

Non-repartitionable state fails LOUDLY (``WindFlowError``), never
silently dropped: global (unkeyed) reduce accumulators, BROADCAST-
distributed window operators (window ids are arithmetic over the replica
count), DP-mode interval joins (round-robin storage is bound to the old
replica set), sqlite-backed persistent operators (the DB image belongs to
one replica), and sources (replay cursors are not keyed state).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from ..basic import OpType, RoutingMode, WindFlowError

# blob keys that need no repartitioning (merged, not split)
_BENIGN_KEYS = {"cur_wm", "shipped", "__emitter__", "__collector__"}


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------
def dest_fn_for(op, new_n: int) -> Callable[[Any], int]:
    """The destination function of the KEYBY emitters that feed ``op`` at
    parallelism ``new_n`` — repartitioned state MUST land where the
    emitters will route the keys."""
    if getattr(op, "is_tpu", False):
        from ..tpu.emitters_tpu import _dest_of_key
        return lambda k: _dest_of_key(k, new_n)
    return lambda k: hash(k) % new_n


# ---------------------------------------------------------------------------
# legality
# ---------------------------------------------------------------------------
def repartition_refusal(op) -> Optional[str]:
    """Why ``op``'s state cannot be repartitioned across a different
    replica count — None when rescaling is legal. Mirrors the chain
    legality diagnostics: the reason string is what the loud error
    carries."""
    if op.op_type == OpType.SOURCE:
        return ("source replicas are independent generators; their replay "
                "cursors are positions, not keyed state")
    if getattr(op, "is_mesh", False):
        return ("mesh-sharded operators parallelize over the device mesh, "
                "not the replica count — one host replica drives every "
                "chip; to change capacity, checkpoint and restore with a "
                "different with_mesh(mesh_shape=...) (sharded restore "
                "relayouts the key axis across the new factorization)")
    if getattr(op, "exactly_once", False):
        return ("exactly-once sinks own per-replica transaction logs "
                "(staged epoch segments / transactional producer ids); "
                "changing the replica count would orphan staged epochs "
                "and break the commit fencing")
    mod = type(op).__module__
    if ".persistent." in mod:
        return ("persistent (sqlite-backed) state is a per-replica DB "
                "image bound to one replica; keyed rows cannot be split "
                "out of it")
    if ".kafka" in mod:
        return ("Kafka connectors own partition assignments managed by "
                "the group protocol, not by WindFlow routing")
    if op.input_routing is RoutingMode.BROADCAST:
        return ("BROADCAST-distributed operators assign work by replica "
                "arithmetic (global window ids mod parallelism); their "
                "state is bound to the replica count, not to keys")
    if getattr(op, "join_mode", None) is not None:
        from ..basic import JoinMode
        if op.join_mode is JoinMode.DP:
            return ("DP-mode interval join stores a round-robin share of "
                    "a replica-count-dependent shared sequence")
    # keyed state without KEYBY routing = global accumulator (e.g. the
    # global Reduce_TPU): one stream-wide value has no keyed partition
    if getattr(op, "fusion_role", None) == "terminator" \
            and op.key_extractor is None:
        return ("global (unkeyed) reduce folds one stream-wide "
                "accumulator; there is no keyed partition to split")
    if op.op_type in (OpType.WIN, OpType.WIN_TPU) \
            and op.input_routing is not RoutingMode.KEYBY:
        return (f"{op.input_routing.name}-routed window operators "
                "distribute windows, not keys, across replicas")
    return None


# ---------------------------------------------------------------------------
# generic splitters
# ---------------------------------------------------------------------------
def _split_keyed_dict(olds: List[Dict[Any, Any]], new_n: int,
                      dest: Callable[[Any], int]) -> List[Dict[Any, Any]]:
    outs: List[Dict[Any, Any]] = [{} for _ in range(new_n)]
    for d in olds:
        for k, v in d.items():
            outs[dest(k)][k] = v
    return outs


def _merged_wm(states: List[dict]) -> int:
    return max((st.get("cur_wm", 0) for st in states), default=0)


def _split_scan(scans: List[Optional[dict]], new_n: int,
                dest: Callable[[Any], int], op_name: str) -> List[dict]:
    """Grid-scan keyed state tables: ``{"slot_of_key", "table_capacity",
    "table"}`` with table a pytree of host arrays whose axis 0 is the
    slot. Re-bucket keys, then gather each new replica's rows.

    Tiered blobs (a ``"tier"`` sub-dict per source) split across BOTH
    tiers: cold rows re-bucket by the same dest function, and a
    destination whose re-bucketed hot set overflows its (unchanged)
    ``hot_capacity`` spills its coldest keys — ranked by the
    checkpointed eviction order — into its own cold tier."""
    import numpy as np

    tiers = [st.get("tier") if st else None for st in scans]
    tiered = any(t is not None for t in tiers)
    proto_tier = next((t for t in tiers if t is not None), None)
    rank: Dict[Tuple[int, Any], int] = {}
    cold_per_dest: List[list] = [[] for _ in range(new_n)]
    if tiered:
        from ..state.tiered import cold_items_from_image
        for si, t in enumerate(tiers):
            if not t:
                continue
            for pos, k in enumerate(t.get("order", [])):
                rank[(si, k)] = pos  # higher = hotter (evicted later)
            for key, row in cold_items_from_image(t["cold_image"]):
                cold_per_dest[dest(key)].append((key, row))

    # (key, source index, source slot) in deterministic order
    per_dest: List[List[Tuple[Any, int, int]]] = [[] for _ in range(new_n)]
    for si, st in enumerate(scans):
        if not st:
            continue
        for key, slot in st["slot_of_key"].items():
            per_dest[dest(key)].append((key, si, slot))
    outs = []
    for j in range(new_n):
        sel = per_dest[j]
        spill: List[Tuple[Any, int, int]] = []
        if tiered:
            cap = int(proto_tier["hot_capacity"])
            # coldest-first; the kept tail is the destination's hot set
            sel = sorted(sel, key=lambda e: rank.get((e[1], e[0]), -1))
            n_spill = max(0, len(sel) - cap)
            spill, sel = sel[:n_spill], sel[n_spill:]
        else:
            cap = 64
            while cap < len(sel):
                cap *= 2
        slot_of_key = {key: i for i, (key, _, _) in enumerate(sel)}
        table = None
        src = next((st for st in scans if st and st.get("table") is not None),
                   None)
        if src is not None:
            import jax

            leaves, treedef = jax.tree_util.tree_flatten(src["table"])
            src_leaves = []
            for st in scans:
                src_leaves.append(
                    None if not st or st.get("table") is None
                    else jax.tree_util.tree_leaves(st["table"]))

            def _src_row(li, si, slot):
                if src_leaves[si] is None:
                    raise WindFlowError(
                        f"repartition: {op_name!r} replica {si} "
                        "registered keys but checkpointed no state "
                        "table")
                return np.asarray(src_leaves[si][li])[slot]

            new_leaves = []
            for li, proto in enumerate(leaves):
                proto = np.asarray(proto)
                out = np.zeros((cap,) + proto.shape[1:], dtype=proto.dtype)
                for i, (_, si, slot) in enumerate(sel):
                    out[i] = _src_row(li, si, slot)
                new_leaves.append(out)
            table = jax.tree_util.tree_unflatten(treedef, new_leaves)
            for key, si, slot in spill:  # overflow hot rows -> dest cold
                cold_per_dest[j].append((key, tuple(
                    _src_row(li, si, slot) for li in range(len(leaves)))))
        elif spill:
            raise WindFlowError(
                f"repartition: {op_name!r} holds tiered keys but "
                "checkpointed no state table to spill rows from")
        blob = {"slot_of_key": slot_of_key, "table_capacity": cap,
                "table": table}
        if tiered:
            from ..state.tiered import build_tier_blob, hot_table_digest
            blob["tier"] = build_tier_blob(
                proto_tier["policy"], cap,
                free_slots=range(cap - 1, len(sel) - 1, -1),
                order=[key for key, _, _ in sel],  # coldest-first kept
                cold_items=cold_per_dest[j],
                hot_digest=hot_table_digest(table))
        outs.append(blob)
    return outs


def _split_ffat_tpu(ffats: List[dict], new_n: int,
                    dest: Callable[[Any], int], op_name: str) -> List[dict]:
    """FFAT TPU forests: per-slot host arrays (K_cap,) + device trees
    (K_cap, 2F) re-bucket by slot-row gather. All contributing sources
    must share the ring depth F — tree node layout is F-dependent, and
    relayouting a segment-tree ring across depths is not implemented;
    the caller surfaces this as a loud error."""
    import numpy as np

    fs = {d["F"] for d in ffats if d["slot_of_key"]}
    if len(fs) > 1:
        raise WindFlowError(
            f"repartition: {op_name!r} replicas checkpointed FFAT forests "
            f"with different ring depths F={sorted(fs)}; merging rings of "
            "different depth is not supported — checkpoint at a quieter "
            "moment (F converges) or rescale before backlog builds up")
    per_dest: List[List[Tuple[Any, int, int]]] = [[] for _ in range(new_n)]
    for si, d in enumerate(ffats):
        for key, slot in d["slot_of_key"].items():
            per_dest[dest(key)].append((key, si, slot))
    proto = ffats[0]
    F = next(iter(fs), proto["F"])
    outs = []
    for j in range(new_n):
        sel = per_dest[j]
        k_cap = 4
        while k_cap < max(1, len(sel)):
            k_cap *= 2
        out = {
            "slot_of_key": {key: i for i, (key, _, _) in enumerate(sel)},
            "out_keys_by_slot": [key for key, _, _ in sel],
            "K_cap": k_cap, "F": F,
            "keys_all_int": all(d["keys_all_int"] for d in ffats),
            "key_dtype": proto["key_dtype"],
            "saw_new_key": True,  # force key-table refresh on first batch
            "leaf_frontier": max(d["leaf_frontier"] for d in ffats),
            "fire_ewma": max(d["fire_ewma"] for d in ffats),
            "rebuild_dirty": True,  # level caches are stale by definition
            "ignored": sum(d["ignored"] for d in ffats) if j == 0 else 0,
        }
        for field in ("next_fire", "fired", "max_leaf", "count", "keys_np"):
            protos = np.asarray(proto[field])
            arr = np.zeros((k_cap,) + protos.shape[1:], dtype=protos.dtype)
            if field == "max_leaf":
                arr[:] = -1
            for i, (_, si, slot) in enumerate(sel):
                arr[i] = np.asarray(ffats[si][field])[slot]
            out[field] = arr
        # device trees: gather slot rows (axis 0); valid mask likewise
        src_tree = next((d for d in ffats
                         if d.get("trees") is not None and d["slot_of_key"]),
                        None)
        if src_tree is None or not sel:
            out["trees"] = None
            out["tvalid"] = None
        else:
            import jax

            leaves, treedef = jax.tree_util.tree_flatten(src_tree["trees"])
            tleaves = [None if d.get("trees") is None
                       else jax.tree_util.tree_leaves(d["trees"])
                       for d in ffats]
            new_leaves = []
            for li, pl in enumerate(leaves):
                pl = np.asarray(pl)
                buf = np.zeros((k_cap,) + pl.shape[1:], dtype=pl.dtype)
                for i, (_, si, slot) in enumerate(sel):
                    if tleaves[si] is None:
                        raise WindFlowError(
                            f"repartition: {op_name!r} replica {si} "
                            "registered keys but checkpointed no forest")
                    buf[i] = np.asarray(tleaves[si][li])[slot]
                new_leaves.append(buf)
            out["trees"] = jax.tree_util.tree_unflatten(treedef, new_leaves)
            tv = np.zeros((k_cap, 2 * F), dtype=bool)
            for i, (_, si, slot) in enumerate(sel):
                src_tv = ffats[si].get("tvalid")
                if src_tv is not None:
                    tv[i] = np.asarray(src_tv)[slot]
            out["tvalid"] = tv
        outs.append(out)
    return outs


# ---------------------------------------------------------------------------
# collector state
# ---------------------------------------------------------------------------
def _msg_sort_key(msg) -> Tuple[int, int]:
    from ..message import Batch
    if isinstance(msg, Batch):
        ts = msg.rows[0][1] if msg.rows else 0
    else:
        ts = msg.ts
    return (ts, msg.id)


def _filter_msg(msg, keep: Callable[[Any], bool]):
    """The sub-message of ``msg`` whose payloads satisfy ``keep`` (None
    when nothing survives). Batches split row-wise; id/wm/tag are
    preserved so (ts, id) merge order stays stable."""
    from ..message import Batch
    if isinstance(msg, Batch):
        rows = [(p, ts) for p, ts in msg.rows if keep(p)]
        if not rows:
            return None
        if len(rows) == len(msg.rows):
            return msg
        nb = Batch(rows, msg.wm, msg.is_punct, msg.stream_tag)
        nb.id = msg.id
        return nb
    return msg if keep(msg.payload) else None


def split_collector_states(colls: List[Optional[dict]], new_n: int,
                           key_fn: Callable[[Any], Any],
                           dest: Callable[[Any], int],
                           op_name: str) -> List[Optional[dict]]:
    """Split the RESCALED operator's own collector states (ordering /
    K-slack buffers, id sequencers hold PRE-BARRIER input the replica has
    not consumed yet — dropping them would lose data). Messages re-bucket
    by key; per-channel buffers keep their channel identity (the upstream
    producer set is unchanged)."""
    olds = [c for c in colls if c]
    if not olds:
        return [None] * new_n
    outs: List[Optional[dict]] = []
    n_ch = max(len(c.get("bufs", c.get("ch_wm", []))) for c in olds)
    for j in range(new_n):
        def keep(p, _j=j):
            return dest(key_fn(p)) == _j
        st: dict = {}
        if any("ch_wm" in c for c in olds):
            st["ch_wm"] = [
                min((c["ch_wm"][ch] for c in olds if "ch_wm" in c
                     and ch < len(c["ch_wm"])), default=0)
                for ch in range(n_ch)]
        if any("bufs" in c for c in olds):  # OrderingCollector
            bufs: List[list] = [[] for _ in range(n_ch)]
            for c in olds:
                for ch, buf in enumerate(c.get("bufs", [])):
                    for m in buf:
                        sub = _filter_msg(m, keep)
                        if sub is not None:
                            bufs[ch].append(sub)
            st["bufs"] = [sorted(b, key=_msg_sort_key) for b in bufs]
        if any("next" in c for c in olds):  # IDSequencerCollector
            st["next"] = {}
            st["pending"] = {}
            for c in olds:
                for k, v in c.get("next", {}).items():
                    if dest(k) == j:
                        st["next"][k] = max(v, st["next"].get(k, 0))
                for k, pend in c.get("pending", {}).items():
                    if dest(k) == j:
                        st["pending"].setdefault(k, {}).update(pend)
        if any("heap" in c and "K" in c for c in olds):  # KSlack
            heap = []
            for c in olds:
                for ts, seq, m in c.get("heap", []):
                    sub = _filter_msg(m, keep)
                    if sub is not None:
                        heap.append((ts, seq, sub))
            st["heap"] = sorted(heap)
            st["K"] = max(c.get("K", 0) for c in olds)
            st["max_ts"] = max(c.get("max_ts", 0) for c in olds)
            st["frontier"] = min(c.get("frontier", -1) for c in olds)
            st["seq"] = max(c.get("seq", 0) for c in olds)
        if any("heap" in c and "ch_wm" in c and "K" not in c
               for c in olds):
            raise WindFlowError(
                f"rescale: {op_name!r} sits behind a DP-join collector; "
                "DP interval joins are not repartitionable")
        outs.append(st or None)
    return outs


def remap_neighbor_collector(st: dict, old_inputs: List[Tuple[int, int]],
                             new_inputs: List[Tuple[int, int]],
                             changed_edges: set) -> dict:
    """Re-index a NEIGHBOR stage's collector state when the rescaled
    stage changed its input-channel layout (its parallelism is part of
    the channel numbering). Matched ``(edge, producer)`` entries keep
    their data; buffered messages from the rescaled edge's vanished
    channels merge (sorted) into that edge's first new channel; fresh
    channels seed conservatively (min watermark — late, never wrong)."""
    pos_new = {key: i for i, key in enumerate(new_inputs)}
    first_of_edge = {}
    for i, (e, _) in enumerate(new_inputs):
        first_of_edge.setdefault(e, i)
    out = dict(st)
    if "ch_wm" in st:
        per_edge_min: Dict[int, int] = {}
        for (e, pi), v in zip(old_inputs, st["ch_wm"]):
            per_edge_min[e] = min(per_edge_min.get(e, v), v)
        wm = []
        for i, (e, pi) in enumerate(new_inputs):
            try:
                oi = old_inputs.index((e, pi))
                keep = (e not in changed_edges)
            except ValueError:
                oi, keep = -1, False
            wm.append(st["ch_wm"][oi] if keep and oi < len(st["ch_wm"])
                      else per_edge_min.get(e, 0))
        out["ch_wm"] = wm
    if "bufs" in st:
        bufs: List[list] = [[] for _ in range(len(new_inputs))]
        spill: Dict[int, list] = {}
        for (e, pi), buf in zip(old_inputs, st["bufs"]):
            tgt = pos_new.get((e, pi)) if e not in changed_edges else None
            if tgt is not None:
                bufs[tgt].extend(buf)
            else:
                spill.setdefault(e, []).extend(buf)
        for e, msgs in spill.items():
            tgt = first_of_edge.get(e)
            if tgt is None:
                if msgs:
                    raise WindFlowError(
                        "rescale: buffered collector messages from a "
                        "removed edge have no destination channel")
                continue
            bufs[tgt] = sorted(bufs[tgt] + msgs, key=_msg_sort_key)
        out["bufs"] = bufs
    if "heap" in st and "ch_wm" in st and "K" not in st:  # DPJoin heap
        heap = []
        for ts, ch, mid, m in st["heap"]:
            e, pi = old_inputs[ch] if ch < len(old_inputs) else (0, 0)
            tgt = pos_new.get((e, pi))
            if tgt is None or e in changed_edges:
                tgt = first_of_edge.get(e, 0)
            heap.append((ts, tgt, mid, m))
        out["heap"] = sorted(heap)
    return out


# ---------------------------------------------------------------------------
# emitter state
# ---------------------------------------------------------------------------
def stretch_emitter_state(st: Optional[dict], new_len: int) -> dict:
    """Synthesize a routing-counter state for an emitter whose
    destination count changed: every per-destination id starts at the
    GLOBAL max of the old counters, so ids stay monotone per channel and
    (ts, id) ties order checkpoint-buffered messages before post-rescale
    ones."""
    st = st or {}
    if "inner" in st:  # SplittingEmitter: stretch every branch
        return {"inner": [stretch_emitter_state(s, new_len)
                          for s in st["inner"]]}
    mx = max(st.get("next_ids", []) or [0])
    return {"next_ids": [mx] * new_len,
            "emit_count": st.get("emit_count", 0)}


def merge_emitter_states(sts: List[Optional[dict]], new_len: int) -> dict:
    """Per-destination counters for the RESCALED op's new emitters: the
    max over every old replica and destination (safe for any old/new
    dest-count combination)."""
    mx = 0
    for st in sts:
        if not st:
            continue
        inner = st.get("inner")
        if inner:
            for s in inner:
                mx = max(mx, max(s.get("next_ids", []) or [0]))
        mx = max(mx, max(st.get("next_ids", []) or [0]))
    return {"next_ids": [mx] * new_len, "emit_count": 0}


# ---------------------------------------------------------------------------
# per-operator state split
# ---------------------------------------------------------------------------
def split_operator_states(op, olds: List[dict], new_n: int) -> List[dict]:
    """Split one operator's N replica state blobs into M. ``olds`` must
    not contain ``__emitter__`` / ``__collector__`` (handled by the
    caller, which knows the wiring)."""
    refusal = repartition_refusal(op)
    if refusal is not None:
        raise WindFlowError(
            f"rescale: operator {op.name!r} is not repartitionable — "
            f"{refusal}")
    dest = dest_fn_for(op, new_n)
    wm = _merged_wm(olds)
    news: List[dict] = [{"cur_wm": wm} for _ in range(new_n)]
    handled = set(_BENIGN_KEYS)

    if any("key_state" in st for st in olds):  # CPU Reduce
        for j, d in enumerate(_split_keyed_dict(
                [st.get("key_state", {}) for st in olds], new_n, dest)):
            news[j]["key_state"] = d
        handled.add("key_state")
    if any("engine" in st for st in olds):  # WindowEngine (SEQ role only)
        engines = [st.get("engine", {}) for st in olds]
        kms = _split_keyed_dict([e.get("key_map", {}) for e in engines],
                                new_n, dest)
        for j in range(new_n):
            news[j]["engine"] = {
                "key_map": kms[j],
                "ignored_tuples": (sum(e.get("ignored_tuples", 0)
                                       for e in engines) if j == 0 else 0),
                "cur_wm": max((e.get("cur_wm", 0) for e in engines),
                              default=0)}
        handled.add("engine")
    if any("keys" in st for st in olds):  # FlatFAT CPU / KP interval join
        for j, d in enumerate(_split_keyed_dict(
                [st.get("keys", {}) for st in olds], new_n, dest)):
            news[j]["keys"] = d
        if any("ignored" in st for st in olds):
            news[0]["ignored"] = sum(st.get("ignored", 0) for st in olds)
            for j in range(1, new_n):
                news[j]["ignored"] = 0
            handled.add("ignored")
        handled.add("keys")
    if any("scan" in st for st in olds):  # grid-scan stateful map/filter
        for j, d in enumerate(_split_scan([st.get("scan") for st in olds],
                                          new_n, dest, op.name)):
            news[j]["scan"] = d
        handled.add("scan")
    if any("ffat" in st for st in olds):  # FFAT TPU forest
        for j, d in enumerate(_split_ffat_tpu(
                [st.get("ffat", {}) for st in olds], new_n, dest, op.name)):
            news[j]["ffat"] = d
        handled.add("ffat")
    if any("__fused__" in st for st in olds):  # fused device chain
        sig = next(st["__fused__"] for st in olds if "__fused__" in st)
        subs = [st.get("fused_sub_states", []) for st in olds]
        n_sub = max((len(s) for s in subs), default=0)
        split_subs: List[List[Optional[dict]]] = [[] for _ in range(new_n)]
        for si in range(n_sub):
            col = [s[si] if si < len(s) else None for s in subs]
            if all(c is None for c in col):
                for j in range(new_n):
                    split_subs[j].append(None)
            else:
                for j, d in enumerate(_split_scan(col, new_n, dest,
                                                  op.name)):
                    split_subs[j].append(d)
        for j in range(new_n):
            news[j]["__fused__"] = sig
            news[j]["fused_sub_states"] = split_subs[j]
        handled.update(("__fused__", "fused_sub_states"))

    unknown = {k for st in olds for k in st} - handled
    if unknown:
        raise WindFlowError(
            f"rescale: operator {op.name!r} checkpointed state this "
            f"version cannot repartition: {sorted(unknown)} — refusing "
            "loudly rather than dropping it")
    return news
