"""TieredKeyStore: hot keys device-resident, cold tail host-spilled.

Every keyed device structure in the TPU plane is dense and padded: key
cardinality is a build-time constant capped by device memory. The real
shape of keyed traffic (the ``--replay`` bench's Zipf streams) is a
small hot set and a huge cold tail, so this module splits the key space
into two tiers:

- **hot tier**: the existing dense device table, capped at
  ``hot_capacity`` slots. Slots are recycled — the KeySlotMap maps only
  the currently-hot keys, demoted keys release their slot to a free
  list.
- **cold tier**: a host-side sqlite store (``ColdStore`` over the
  ``persistent.db_handle.DBHandle`` machinery) holding one row of state
  leaves per demoted key.

The policy deciding WHICH keys stay hot is the existing
``persistent.cache`` machinery (``policy="lru"|"lfu"`` via
``make_cache``), used as a pure recency/frequency tracker: victims come
from ``eviction_order()``, never from implicit auto-eviction, so the
tracker can never disagree with the slot map.

Movement between tiers is planned per BATCH and applied as vectorized
slot-row transfers (one gather + one scatter per batch, riding the
replica's ``DeviceDispatchQueue``), never per-key device_put calls —
``plan_batch`` returns a ``TierPlan`` naming the promoted keys with
their assigned slots and the demoted victims with the slots they free.

The overload governor's TUNE rung can shrink ``target_hot_capacity``
under memory pressure (restored on release); the next ``plan_batch``
then demotes down to the target before admitting new keys.

Env knobs: ``WF_TIER_DB_DIR`` (cold-store directory; defaults to the
``WF_DB_DIR`` scheme), ``WF_TIER_POLICY`` (default eviction policy when
``with_tiering`` is called without one), ``WF_TIER_MIN_HOT`` (floor the
governor's shrink lever cannot cross, default 64).
"""

from __future__ import annotations

import hashlib
import itertools
import os
import sqlite3
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..basic import KeyCapacityError, WindFlowError
from ..persistent.cache import make_cache
from ..persistent.db_handle import DBHandle


def _tier_db_dir() -> Optional[str]:
    return os.environ.get("WF_TIER_DB_DIR") or None


def default_tier_policy() -> str:
    return os.environ.get("WF_TIER_POLICY", "lru").strip().lower()


def tier_min_hot() -> int:
    try:
        return max(1, int(os.environ.get("WF_TIER_MIN_HOT", "64")))
    except ValueError:
        return 64


def _digest(data: bytes) -> str:
    return "sha256:" + hashlib.sha256(data).hexdigest()


def hot_table_digest(table) -> Optional[str]:
    """Canonical digest of a HOST-side state-table pytree: dtype + shape
    + raw bytes per leaf, in tree order. Deterministic across checkpoint
    round-trips (pickle bytes are not guaranteed to be), so the manifest
    can pin the hot tier independently of the cold image."""
    if table is None:
        return None
    import jax
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(table):
        a = np.ascontiguousarray(np.asarray(leaf))
        h.update(a.dtype.str.encode())
        h.update(np.asarray(a.shape, dtype=np.int64).tobytes())
        h.update(a.tobytes())
    return "sha256:" + h.hexdigest()


class TierConfig:
    """Builder-side tiering declaration (``with_tiering``), attached to
    the operator and consumed by its replicas' engines."""

    __slots__ = ("policy", "hot_capacity", "db_dir")

    def __init__(self, policy: Optional[str] = None,
                 hot_capacity: int = 1024,
                 db_dir: Optional[str] = None) -> None:
        from ..persistent.cache import _CACHE_POLICIES
        self.policy = (policy or default_tier_policy()).lower()
        if self.policy not in _CACHE_POLICIES:
            raise WindFlowError(
                f"with_tiering: unknown eviction policy {policy!r} "
                f"(expected one of {sorted(_CACHE_POLICIES)})")
        self.hot_capacity = int(hot_capacity)
        if self.hot_capacity < 1:
            raise WindFlowError("with_tiering: hot_capacity must be >= 1")
        self.db_dir = db_dir or _tier_db_dir()


class TierPlan:
    """One batch's tier maintenance: keys to promote (cold -> their
    assigned hot slots) and victims to demote (hot slots -> cold).
    Applied by the ENGINE as one slot-row gather + one scatter."""

    __slots__ = ("promote_keys", "promote_slots", "demote_keys",
                 "demote_slots")

    def __init__(self, promote_keys: List[Any], promote_slots: np.ndarray,
                 demote_keys: List[Any], demote_slots: np.ndarray) -> None:
        self.promote_keys = promote_keys
        self.promote_slots = promote_slots
        self.demote_keys = demote_keys
        self.demote_slots = demote_slots


class ColdStore:
    """Host-side cold tier: one sqlite row per demoted key, the value a
    tuple of the key's state LEAVES (the flattened state pytree row).
    Built on ``DBHandle`` so checkpointing reuses the sqlite online-
    backup image (``snapshot_bytes``/``restore_bytes``) unchanged."""

    def __init__(self, name: str, db_dir: Optional[str] = None,
                 fresh: bool = False) -> None:
        self.db = DBHandle(name, db_dir=db_dir)
        if fresh:
            # a NEW engine claiming this path starts empty: stale rows
            # from a crashed run must only come back via restore_bytes
            self.db.clear()
        # cached row count — gauges read len() every batch and a sqlite
        # COUNT(*) there is measurable; tier ownership is exclusive
        # (a demoted key is never already cold), so put/take deltas keep
        # the cache exact. None = unknown, recomputed lazily.
        self._count: Optional[int] = 0 if fresh else None
        # write-ahead delta log (WF_CKPT_DELTA): puts/deletes since the
        # last FULL checkpoint image, so an incremental snapshot ships
        # the churn instead of the whole sqlite backup. Collapsed per
        # key (a re-put cancels its delete and vice versa); disabled by
        # default — the TieredKeyStore enables it when deltas are on.
        self.wal_enabled = False
        self._wal_puts: Dict[Any, Any] = {}
        self._wal_dels: set = set()

    def put_rows(self, keys: List[Any], leaf_cols: List[np.ndarray]) -> None:
        """Batched demote write: ``leaf_cols[l][i]`` is leaf ``l`` of
        ``keys[i]``'s state row. One executemany, not one put per key;
        committed per batch so the connection never pins a write lock
        across batches."""
        if not keys:
            return
        rows = [(k, tuple(col[i] for col in leaf_cols))
                for i, k in enumerate(keys)]
        self.db.put_many(iter(rows))
        self.db._conn.commit()
        if self.wal_enabled:
            for k, row in rows:
                self._wal_puts[k] = row
                self._wal_dels.discard(k)
        if self._count is not None:
            self._count += len(keys)

    def take_rows(self, keys: List[Any],
                  default_leaves: List[Any],
                  leaf_dtypes: List[Any]) -> Tuple[List[np.ndarray], int]:
        """Batched promote read: per-leaf ``(len(keys),)`` columns, rows
        of keys the cold tier never saw filled from the initial state
        (a brand-new key IS a cold miss on nothing). Taken rows are
        deleted — promotion moves ownership to the hot tier. Returns
        ``(leaf_cols, n_cold_hits)``."""
        n = len(keys)
        cols = [np.full((n,), default_leaves[li], dtype=leaf_dtypes[li])
                for li in range(len(default_leaves))]
        hits = 0
        taken = []
        for i, k in enumerate(keys):
            row = self.db.get(k)
            if row is None:
                continue
            hits += 1
            taken.append(k)
            for li, v in enumerate(row):
                cols[li][i] = v
        if taken:
            self.db.delete_many(taken)
            if self.wal_enabled:
                for k in taken:
                    self._wal_dels.add(k)
                    self._wal_puts.pop(k, None)
            if self._count is not None:
                self._count -= len(taken)
        return cols, hits

    # -- delta WAL (WF_CKPT_DELTA) ------------------------------------------
    def wal_snapshot(self) -> Tuple[List[Tuple[Any, Any]], List[Any]]:
        """(puts, deletes) accumulated since the last ``wal_reset`` —
        the cold tier's churn relative to its last full image."""
        return list(self._wal_puts.items()), list(self._wal_dels)

    def wal_reset(self) -> None:
        self._wal_puts.clear()
        self._wal_dels.clear()

    def __len__(self) -> int:
        if self._count is None:
            self._count = len(self.db)
        return self._count

    def clear(self) -> None:
        self.db.clear()
        self._count = 0
        self.wal_reset()

    def keys(self):
        return self.db.keys()

    def items(self):
        return self.db.items()

    def snapshot_bytes(self) -> bytes:
        return self.db.snapshot_bytes()

    def restore_bytes(self, data: bytes) -> None:
        self.db.restore_bytes(data)
        self._count = None
        self.wal_reset()  # the restored image IS the new full baseline

    def close(self) -> None:
        self.db.close()


# -- checkpoint-image helpers (repartitioner / tests) -----------------------
def cold_items_from_image(data: bytes) -> List[Tuple[Any, Any]]:
    """Decode a ``ColdStore`` sqlite online-backup image into
    ``(key, leaf-tuple)`` items without touching any live store — the
    repartitioner re-buckets cold keys from checkpoint blobs."""
    import pickle
    fd, tmp = tempfile.mkstemp(suffix=".tierimg")
    os.close(fd)
    try:
        with open(tmp, "wb") as f:
            f.write(data)
        conn = sqlite3.connect(tmp)
        try:
            return [(pickle.loads(k), pickle.loads(v))
                    for k, v in conn.execute("SELECT k, v FROM kv")]
        finally:
            conn.close()
    finally:
        os.unlink(tmp)


def build_tier_blob(policy: str, hot_capacity: int, free_slots,
                    order, cold_items,
                    hot_digest: Optional[str] = None) -> dict:
    """Assemble a tier checkpoint sub-blob from parts — the
    repartitioner re-buckets hot and cold keys across destinations and
    needs blobs ``TieredKeyStore.restore`` accepts (per-tier digests
    included)."""
    image = cold_image_from_items(cold_items)
    d = {"policy": policy, "hot_capacity": int(hot_capacity),
         "free_slots": [int(s) for s in free_slots],
         "order": list(order),
         "cold_image": image,
         "digests": {"cold": _digest(image)}}
    if hot_digest is not None:
        d["digests"]["hot"] = hot_digest
    return d


def apply_tier_delta(base_blob: dict, node: dict) -> dict:
    """Materialize a FULL tier sub-blob from a base epoch's full blob
    plus a WAL delta node (``checkpoint.delta.make_tier_delta``): decode
    the base cold image, replay the collapsed puts/deletes, rebuild the
    image, and stamp a fresh cold digest (the delta blob itself is
    pinned by the manifest's whole-blob digest; the per-tier digest is
    recomputed over the reconstructed bytes)."""
    items = dict(cold_items_from_image(base_blob.get("cold_image")
                                       or cold_image_from_items([])))
    for k in node.get("wal_dels", []):
        items.pop(k, None)
    for k, row in node.get("wal_puts", []):
        items[k] = row
    image = cold_image_from_items(list(items.items()))
    out = dict(node.get("replace") or {})
    out["cold_image"] = image
    digests = dict(out.get("digests") or {})
    digests["cold"] = _digest(image)
    out["digests"] = digests
    return out


def cold_image_from_items(items) -> bytes:
    """Inverse of ``cold_items_from_image``: build a fresh ColdStore
    image holding ``items`` (the repartitioner's per-destination cold
    buckets)."""
    import pickle
    fd, tmp = tempfile.mkstemp(suffix=".tierimg")
    os.close(fd)
    try:
        conn = sqlite3.connect(tmp)
        try:
            conn.execute(
                "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB)")
            conn.executemany(
                "INSERT INTO kv (k, v) VALUES (?, ?)",
                [(pickle.dumps(k), pickle.dumps(v)) for k, v in items])
            conn.commit()
        finally:
            conn.close()
        with open(tmp, "rb") as f:
            return f.read()
    finally:
        os.unlink(tmp)


# distinguishes cold-store db files of same-named engines (rebuilt
# graphs in one process would otherwise contend for one sqlite file)
_store_seq = itertools.count()


class TieredKeyStore:
    """The tier control plane of ONE keyed engine: slot free list, the
    eviction-policy tracker, the cold store, and the per-batch planner.
    The engine owns the device table and applies the returned plans; the
    store never touches device memory itself (so one implementation
    serves both the single-chip grid scan and the mesh plane)."""

    def __init__(self, name: str, config: TierConfig,
                 stats=None) -> None:
        self.name = name
        self.policy = config.policy
        self.hot_capacity = int(config.hot_capacity)
        # governor TUNE lever: shrink target under memory pressure,
        # restored on release; plan_batch demotes down to it lazily
        self.target_hot_capacity = self.hot_capacity
        self.min_hot = tier_min_hot()
        # pure eviction-order tracker: capacity far above hot_capacity so
        # the cache NEVER auto-evicts — victims come only from plan_batch,
        # keeping tracker and slot map in lockstep
        self.tracker = make_cache(self.policy, 1 << 62)
        self.cold = ColdStore(f"{name}_{next(_store_seq)}",
                              db_dir=config.db_dir, fresh=True)
        # incremental checkpoints need the cold tier's churn log
        from ..checkpoint.delta import env_ckpt_delta
        self.cold.wal_enabled = env_ckpt_delta()
        self.free_slots: List[int] = list(range(self.hot_capacity - 1,
                                                -1, -1))
        self.stats = stats
        # batching observability: tests assert promoted keys >> scatter
        # calls (no per-key device traffic)
        self.promote_batches = 0
        self.demote_batches = 0
        self.promoted_keys = 0
        self.demoted_keys = 0
        self.lookups = 0
        self.misses = 0

    # -- per-batch planning ------------------------------------------------
    def plan_batch(self, keymap, batch_keys: List[Any]
                   ) -> Optional[TierPlan]:
        """Plan tier maintenance for one batch's DISTINCT keys: touch the
        policy for hot hits, pick victims for the misses (never a key of
        this batch), and assign recycled slots to the promotions.
        Mutates the keymap (evict/assign) so the subsequent vectorized
        ``slots_of`` resolves every key without on_new. Returns None in
        steady state (all keys hot, no shrink pending)."""
        sk = keymap.slot_of_key
        tr = self.tracker
        missing: List[Any] = []
        for k in batch_keys:
            if k in sk:
                tr.get(k)
            else:
                missing.append(k)
        self.lookups += len(batch_keys)
        self.misses += len(missing)
        eff_cap = min(self.hot_capacity,
                      max(self.min_hot, int(self.target_hot_capacity)))
        if len(batch_keys) > self.hot_capacity:
            raise KeyCapacityError(
                self.name, self.hot_capacity,
                len(batch_keys) - self.hot_capacity,
                hint="one batch touches more distinct keys than the hot "
                     "tier holds; raise with_tiering(hot_capacity=) above "
                     "the per-batch working set")
        # a governor-shrunk target never blocks a batch the PHYSICAL
        # tier can hold — shrinking resumes once working sets allow it
        eff_cap = max(eff_cap, len(batch_keys))
        n_evict = max(0, len(sk) + len(missing) - eff_cap)
        if not missing and not n_evict:
            # steady state (every key hot, no shrink pending): skip the
            # victim scan and empty-array plumbing — this path runs once
            # per batch on the dispatch thread
            return None
        demote_keys: List[Any] = []
        if n_evict:
            batch_set = set(batch_keys)
            for k in list(tr.eviction_order()):
                if k in batch_set:
                    continue
                demote_keys.append(k)
                if len(demote_keys) == n_evict:
                    break
            if len(demote_keys) < n_evict:  # pragma: no cover - guarded
                raise KeyCapacityError(self.name, eff_cap,
                                       n_evict - len(demote_keys))
        demote_slots = np.asarray([sk[k] for k in demote_keys],
                                  dtype=np.int64)
        for k in demote_keys:
            tr.pop(k)
            keymap.evict(k)
        self.free_slots.extend(int(s) for s in demote_slots)
        promote_slots = np.asarray(
            [self.free_slots.pop() for _ in missing], dtype=np.int64)
        for k, s in zip(missing, promote_slots):
            keymap.assign(k, int(s))
            tr.put(k, True)
        if not missing and not demote_keys:
            return None
        return TierPlan(missing, promote_slots, demote_keys, demote_slots)

    # -- accounting hooks (engines call these around the data movement) ----
    def note_demote(self, n_keys: int) -> None:
        self.demote_batches += 1
        self.demoted_keys += n_keys
        if self.stats is not None:
            self.stats.note_tier_demote(n_keys)

    def note_promote(self, n_keys: int, usec: float) -> None:
        self.promote_batches += 1
        self.promoted_keys += n_keys
        if self.stats is not None:
            self.stats.note_tier_promote(n_keys, usec)

    def publish_gauges(self, n_hot: int) -> None:
        if self.stats is not None:
            self.stats.note_tier_gauges(n_hot, len(self.cold),
                                        self.lookups, self.misses)

    def adopt_dense(self, slot_of_key: Dict[Any, int]) -> None:
        """Rebuild the tier bookkeeping from a DENSE checkpoint's key
        map (a pre-tiering blob restored into a tiered graph): every
        checkpointed key becomes hot at its dense slot, the cold tier
        starts empty, recency order = slot order. Refuses when the dense
        key count exceeds the hot tier."""
        n = len(slot_of_key)
        if n > self.hot_capacity:
            raise KeyCapacityError(
                self.name, self.hot_capacity, n - self.hot_capacity,
                hint="dense checkpoint holds more keys than the hot "
                     "tier; raise with_tiering(hot_capacity=) or restore "
                     "into a graph without tiering")
        used = set(int(s) for s in slot_of_key.values())
        self.free_slots = [s for s in range(self.hot_capacity - 1, -1, -1)
                           if s not in used]
        self.tracker = make_cache(self.policy, 1 << 62)
        for k, _s in sorted(slot_of_key.items(), key=lambda kv: kv[1]):
            self.tracker.put(k, True)
        self.cold.clear()
        self.target_hot_capacity = self.hot_capacity

    # -- checkpoint plane --------------------------------------------------
    def snapshot(self, hot_digest: Optional[str] = None) -> dict:
        """The tier's checkpoint sub-blob: policy + capacities, the slot
        free list, the tracker's eviction order, and the cold tier as
        the sqlite online-backup image — with PER-TIER digests recorded
        alongside (the manifest's blob digest covers the whole blob;
        these pin each tier individually so a torn cold image is named
        as such on restore)."""
        image = self.cold.snapshot_bytes()
        d = {
            "policy": self.policy,
            "hot_capacity": self.hot_capacity,
            "free_slots": list(self.free_slots),
            "order": list(self.tracker.eviction_order()),
            "cold_image": image,
            "digests": {"cold": _digest(image)},
        }
        if hot_digest is not None:
            d["digests"]["hot"] = hot_digest
        return d

    def snapshot_delta(self, base_ckpt: int) -> dict:
        """Incremental tier sub-blob: the cold tier as its WAL since the
        last full image plus the (small) bookkeeping fields, patching
        the ``base_ckpt`` epoch's full sub-blob at restore
        (``apply_tier_delta``). No hot digest is recorded — the delta
        path never materializes the full hot table on the host, and the
        manifest's whole-blob digest still pins the delta itself."""
        from ..checkpoint.delta import make_tier_delta
        puts, dels = self.cold.wal_snapshot()
        return make_tier_delta(base_ckpt, puts, dels, {
            "policy": self.policy,
            "hot_capacity": self.hot_capacity,
            "free_slots": list(self.free_slots),
            "order": list(self.tracker.eviction_order()),
        })

    def wal_reset(self) -> None:
        """A FULL snapshot was just taken: it is the new delta baseline."""
        self.cold.wal_reset()

    def restore(self, d: dict, hot_digest: Optional[str] = None) -> None:
        if int(d.get("hot_capacity", self.hot_capacity)) \
                != self.hot_capacity:
            raise WindFlowError(
                f"{self.name}: tiered restore holds hot_capacity="
                f"{d.get('hot_capacity')} but this graph declares "
                f"hot_capacity={self.hot_capacity}; restore with the "
                "checkpointed capacity (slot ids are positions in the "
                "hot table)")
        digests = d.get("digests") or {}
        image = d.get("cold_image")
        if image is not None:
            want = digests.get("cold")
            if want and _digest(image) != want:
                from ..checkpoint.store import CorruptCheckpointError
                raise CorruptCheckpointError(
                    f"{self.name}: cold-tier image digest mismatch "
                    f"(expected {want})")
            self.cold.restore_bytes(image)
        if hot_digest is not None and digests.get("hot") \
                and hot_digest != digests["hot"]:
            from ..checkpoint.store import CorruptCheckpointError
            raise CorruptCheckpointError(
                f"{self.name}: hot-tier table digest mismatch "
                f"(expected {digests['hot']}, got {hot_digest})")
        self.free_slots = [int(s) for s in d.get("free_slots", [])]
        # rebuild the tracker in checkpointed eviction order (LRU order
        # survives exactly; LFU frequencies reset to 1 — recency inside
        # the rebuilt order still breaks ties the same way)
        self.tracker = make_cache(self.policy, 1 << 62)
        for k in d.get("order", []):
            self.tracker.put(k, True)
        self.target_hot_capacity = self.hot_capacity
