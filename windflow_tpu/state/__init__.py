"""Tiered keyed-state plane (hot device tier + host cold tier).

``TieredKeyStore`` fronts the dense device key tables of the stateful
grid-scan operators (single-chip and mesh) with a host-side sqlite cold
store, making key capacity elastic: the device table holds only the
policy-selected hot set, the cold tail spills to the host, and batches
whose keys fall outside the hot set trigger BATCHED promote/demote
slot-row transfers — never per-key device traffic. Enabled with
``with_tiering(policy, hot_capacity)`` on the stateful TPU/mesh
builders; the dense path is byte-identical when tiering is off.
"""

from .tiered import (ColdStore, TierConfig, TieredKeyStore, TierPlan,
                     cold_image_from_items, cold_items_from_image)

__all__ = ["ColdStore", "TierConfig", "TieredKeyStore", "TierPlan",
           "cold_image_from_items", "cold_items_from_image"]
