"""Native runtime: C++ channel + staging encoders, loaded via ctypes.

The shared object is built on first use with g++ (no pip/pybind needed) and
cached next to the source. Absence of a toolchain degrades gracefully: the
Python channel and encoders keep working; ``native_available()`` reports
the state. Enable the native channel for PipeGraph workers with
``WF_NATIVE_CHANNELS=1``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sysconfig
import threading
from typing import Any, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "wfruntime.cpp")


def _so_path() -> str:
    """Cache keyed on a hash of the source (not mtimes: fresh-checkout
    mtimes are arbitrary and could silently shadow the source with a stale
    prebuilt binary). The .so is never committed."""
    import hashlib

    with open(_SRC, "rb") as f:
        h = hashlib.sha256(f.read()).hexdigest()[:12]
    return os.path.join(_HERE, f"_wfruntime-{h}.so")

_lock = threading.Lock()
_lib = None  # CDLL: queue functions (GIL released while blocking)
_pylib = None  # PyDLL: encoder functions (called with the GIL held)
_build_error: Optional[str] = None


def _build(so: str) -> Optional[str]:
    inc = sysconfig.get_paths()["include"]
    tmp = so + f".tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           f"-I{inc}", _SRC, "-o", tmp]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=240)
    except (OSError, subprocess.TimeoutExpired) as e:
        return f"native build failed: {e}"
    if r.returncode != 0:
        return f"native build failed: {r.stderr[-800:]}"
    try:
        os.replace(tmp, so)  # atomic publish for concurrent processes
    except OSError:
        if not os.path.exists(so):  # a peer may have published already
            return "native build failed: publish race lost and no .so"
    import glob
    import time
    for stale in glob.glob(os.path.join(_HERE, "_wfruntime-*")):
        if os.path.abspath(stale) == os.path.abspath(so):
            continue
        try:
            if ".tmp" in os.path.basename(stale) and (
                    time.time() - os.path.getmtime(stale) < 600):
                continue  # possibly a live peer's in-progress build
            os.unlink(stale)  # superseded hashes / orphaned .tmp files
        except OSError:
            pass
    return None


def _load() -> bool:
    global _lib, _pylib, _build_error
    with _lock:
        if _lib is not None:
            return True
        if _build_error is not None:
            return False
        so = _so_path()
        if not os.path.exists(so):
            err = _build(so)
            if err is not None:
                _build_error = err
                return False
        try:
            lib = ctypes.CDLL(so)
            pylib = ctypes.PyDLL(so)
        except OSError as e:
            _build_error = str(e)
            return False
        lib.wf_queue_create.restype = ctypes.c_void_p
        lib.wf_queue_create.argtypes = [ctypes.c_size_t]
        lib.wf_queue_destroy.argtypes = [ctypes.c_void_p]
        lib.wf_queue_push.restype = ctypes.c_int
        lib.wf_queue_push.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                      ctypes.c_size_t]
        lib.wf_queue_pop.restype = ctypes.c_int
        lib.wf_queue_pop.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_int64),
                                     ctypes.POINTER(ctypes.c_size_t),
                                     ctypes.c_long]
        lib.wf_queue_len.restype = ctypes.c_size_t
        lib.wf_queue_len.argtypes = [ctypes.c_void_p]
        for fn in ("wf_encode_i64", "wf_encode_f64", "wf_encode_i32",
                   "wf_encode_f32"):
            f = getattr(pylib, fn)
            f.restype = ctypes.c_int
            f.argtypes = [ctypes.py_object, ctypes.py_object,
                          ctypes.c_void_p]
        _lib = lib
        _pylib = pylib
        return True


def native_available() -> bool:
    return _load()


def native_build_error() -> Optional[str]:
    _load()
    return _build_error


class NativeChannel:
    """Drop-in replacement for runtime.channel.Channel backed by the C++
    MPSC ring. Message objects are kept alive by an incref on push
    (ctypes.py_object ownership transferred to the consumer on pop)."""

    __slots__ = ("_h", "capacity", "n_inputs")

    def __init__(self, capacity: int = 2048) -> None:
        if not _load():
            raise RuntimeError(_build_error or "native runtime unavailable")
        self._h = _lib.wf_queue_create(capacity)
        if not self._h:
            raise MemoryError("wf_queue_create failed")
        self.capacity = capacity
        self.n_inputs = 0

    def register_input(self) -> int:
        idx = self.n_inputs
        self.n_inputs += 1
        return idx

    def put(self, ch_idx: int, msg: Any) -> None:
        # hand one strong reference to the queue
        handle = id(msg)
        ctypes.pythonapi.Py_IncRef(ctypes.py_object(msg))
        _lib.wf_queue_push(self._h, ch_idx, handle)

    def get(self, timeout=None):
        """Blocking pop; with ``timeout`` (seconds) returns None if the
        queue stays empty (same idle-tick contract as Channel.get)."""
        tag = ctypes.c_int64()
        handle = ctypes.c_size_t()
        ms = -1 if timeout is None else max(1, int(timeout * 1000))
        if not _lib.wf_queue_pop(self._h, ctypes.byref(tag),
                                 ctypes.byref(handle), ms):
            return None
        msg = ctypes.cast(handle.value, ctypes.py_object).value
        ctypes.pythonapi.Py_DecRef(ctypes.py_object(msg))
        return tag.value, msg

    def get_nowait(self):
        tag = ctypes.c_int64()
        handle = ctypes.c_size_t()
        if not _lib.wf_queue_pop(self._h, ctypes.byref(tag),
                                 ctypes.byref(handle), 0):
            return None
        msg = ctypes.cast(handle.value, ctypes.py_object).value
        ctypes.pythonapi.Py_DecRef(ctypes.py_object(msg))
        return tag.value, msg

    def __len__(self) -> int:
        return int(_lib.wf_queue_len(self._h))

    def __del__(self):
        if not getattr(self, "_h", None):
            return  # construction failed before the ring existed
        try:
            while True:
                item = self.get_nowait()
                if item is None:
                    break
        except Exception:
            pass
        _lib.wf_queue_destroy(self._h)
        self._h = None


def encode_column(rows: list, field: str, out) -> None:
    """Fill ``out`` (1-D numpy int64/float64 view) from rows' field via the
    native encoder; raises on type/field errors."""
    import numpy as np

    if not _load():
        raise RuntimeError(_build_error or "native runtime unavailable")
    assert out.flags["C_CONTIGUOUS"]
    ptr = out.ctypes.data
    fns = {np.dtype(np.int64): _pylib.wf_encode_i64,
           np.dtype(np.float64): _pylib.wf_encode_f64,
           np.dtype(np.int32): _pylib.wf_encode_i32,
           np.dtype(np.float32): _pylib.wf_encode_f32}
    fn = fns.get(out.dtype)
    if fn is None:
        raise TypeError(f"encode_column: unsupported dtype {out.dtype}")
    rc = fn(rows, field, ptr)
    if rc != 0:
        ctypes.pythonapi.PyErr_Clear()
        raise RuntimeError(f"native encode failed for field {field!r}")


ENCODABLE_DTYPES = ("int32", "int64", "float32", "float64")
