// wfruntime: native runtime primitives for the CPU plane.
//
// The reference rides FastFlow's lock-free SPSC queues between pinned
// threads (SURVEY.md L0). This library provides the same substrate for the
// Python plane without taking the interpreter on the hot path:
//
//  - wf_queue: bounded MPSC ring of (channel_id, PyObject*) pairs with a
//    mutex/condvar protocol tuned for the single-consumer case (one worker
//    thread per replica chain, like ff_minode). Blocking waits release the
//    GIL (callers use ctypes CDLL for push/pop wrappers that never touch
//    Python state while blocked); object reference counts are managed by
//    the Python wrapper, which owns one strong reference per enqueued
//    message (transferred to the consumer on pop).
//  - wf_encode_*: row->column staging encoders driven through the CPython
//    API (built as part of the same shared object, called under the GIL via
//    PyDLL): one C pass extracts a named attribute (or dict item) from a
//    sequence of tuples straight into numpy-owned buffers, replacing the
//    per-row per-field Python interpreter loop at the device boundary.
//
// Build: g++ -O3 -shared -fPIC (see native/__init__.py); no external
// dependencies beyond Python.h.

#include <Python.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <new>

extern "C" {

// ---------------------------------------------------------------------------
// Bounded MPSC queue
// ---------------------------------------------------------------------------
struct WfItem {
    int64_t tag;       // channel id (or EOS marker from the wrapper)
    uintptr_t handle;  // PyObject* owned by the producer-side incref
};

struct WfQueue {
    WfItem* buf;
    size_t capacity;
    size_t head;  // consumer index
    size_t tail;  // producer index
    size_t count;
    std::mutex m;
    std::condition_variable not_full;
    std::condition_variable not_empty;
};

void* wf_queue_create(size_t capacity) {
    WfQueue* q = new (std::nothrow) WfQueue();
    if (!q) return nullptr;
    q->buf = new (std::nothrow) WfItem[capacity];
    if (!q->buf) {
        delete q;
        return nullptr;
    }
    q->capacity = capacity;
    q->head = q->tail = q->count = 0;
    return q;
}

void wf_queue_destroy(void* h) {
    WfQueue* q = static_cast<WfQueue*>(h);
    if (!q) return;
    delete[] q->buf;
    delete q;
}

// Blocking push; returns 1 on success. Called WITHOUT the GIL (ctypes CDLL
// releases it), so this may block freely.
int wf_queue_push(void* h, int64_t tag, uintptr_t handle) {
    WfQueue* q = static_cast<WfQueue*>(h);
    std::unique_lock<std::mutex> lk(q->m);
    q->not_full.wait(lk, [q] { return q->count < q->capacity; });
    q->buf[q->tail] = WfItem{tag, handle};
    q->tail = (q->tail + 1) % q->capacity;
    q->count++;
    lk.unlock();
    q->not_empty.notify_one();
    return 1;
}

// Blocking pop; fills tag/handle, returns 1. timeout_ms < 0 => wait forever;
// returns 0 on timeout.
int wf_queue_pop(void* h, int64_t* tag, uintptr_t* handle,
                 long timeout_ms) {
    WfQueue* q = static_cast<WfQueue*>(h);
    std::unique_lock<std::mutex> lk(q->m);
    if (timeout_ms < 0) {
        q->not_empty.wait(lk, [q] { return q->count > 0; });
    } else {
        if (!q->not_empty.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                   [q] { return q->count > 0; }))
            return 0;
    }
    WfItem it = q->buf[q->head];
    q->head = (q->head + 1) % q->capacity;
    q->count--;
    lk.unlock();
    q->not_full.notify_one();
    *tag = it.tag;
    *handle = it.handle;
    return 1;
}

size_t wf_queue_len(void* h) {
    WfQueue* q = static_cast<WfQueue*>(h);
    std::lock_guard<std::mutex> lk(q->m);
    return q->count;
}

// ---------------------------------------------------------------------------
// Columnar staging encoders (called WITH the GIL via ctypes.PyDLL)
// ---------------------------------------------------------------------------
// rows: PyObject* to a list of payload objects; attr: field name;
// out: pointer to an int64/float64 buffer of length >= n.
// Returns 0 on success, -1 on error (Python exception set).

static inline PyObject* wf_get_field(PyObject* row, PyObject* attr) {
    if (PyDict_Check(row)) {
        PyObject* v = PyDict_GetItemWithError(row, attr);  // borrowed
        if (v) Py_INCREF(v);
        else if (!PyErr_Occurred())
            PyErr_SetObject(PyExc_KeyError, attr);
        return v;
    }
    return PyObject_GetAttr(row, attr);
}

int wf_encode_i64(PyObject* rows, PyObject* attr, int64_t* out) {
    Py_ssize_t n = PyList_Size(rows);
    if (n < 0) return -1;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* row = PyList_GET_ITEM(rows, i);  // borrowed
        PyObject* v = wf_get_field(row, attr);
        if (!v) return -1;
        long long x = PyLong_AsLongLong(v);
        Py_DECREF(v);
        if (x == -1 && PyErr_Occurred()) return -1;
        out[i] = (int64_t)x;
    }
    return 0;
}

int wf_encode_f64(PyObject* rows, PyObject* attr, double* out) {
    Py_ssize_t n = PyList_Size(rows);
    if (n < 0) return -1;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* row = PyList_GET_ITEM(rows, i);  // borrowed
        PyObject* v = wf_get_field(row, attr);
        if (!v) return -1;
        double x = PyFloat_AsDouble(v);
        Py_DECREF(v);
        if (x == -1.0 && PyErr_Occurred()) return -1;
        out[i] = x;
    }
    return 0;
}

int wf_encode_i32(PyObject* rows, PyObject* attr, int32_t* out) {
    Py_ssize_t n = PyList_Size(rows);
    if (n < 0) return -1;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* row = PyList_GET_ITEM(rows, i);  // borrowed
        PyObject* v = wf_get_field(row, attr);
        if (!v) return -1;
        long long x = PyLong_AsLongLong(v);
        Py_DECREF(v);
        if (x == -1 && PyErr_Occurred()) return -1;
        out[i] = (int32_t)x;
    }
    return 0;
}

int wf_encode_f32(PyObject* rows, PyObject* attr, float* out) {
    Py_ssize_t n = PyList_Size(rows);
    if (n < 0) return -1;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject* row = PyList_GET_ITEM(rows, i);  // borrowed
        PyObject* v = wf_get_field(row, attr);
        if (!v) return -1;
        double x = PyFloat_AsDouble(v);
        Py_DECREF(v);
        if (x == -1.0 && PyErr_Occurred()) return -1;
        out[i] = (float)x;
    }
    return 0;
}

}  // extern "C"
