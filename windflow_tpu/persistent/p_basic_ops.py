"""Persistent basic operators: keyed state that survives in an embedded DB.

Parity: ``wf/persistent/`` p_filter/p_map/p_flatmap/p_reduce/p_sink — the
same operator logic as the in-memory versions, but each tuple's processing
reads/modifies/writes its key's state through a DBHandle fronted by an LRU
cache. Functor signatures follow the reference's persistent forms: the
user function receives (tuple, state) and returns (result, new_state) —
or mutates the state object and returns just the result. ``initial_state``
is deep-copied per key on first sight.

State durability: each replica owns one sqlite file named
``<graph>_<op>_r<idx>``; at EOS the cache is flushed so the database holds
the complete final keyed state (the reference's closest analog to
checkpointing, SURVEY.md §5).
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Optional

from ..basic import OpType, RoutingMode, WindFlowError
from ..operators.base import BasicOperator, BasicReplica, arity
from ..operators.basic_ops import Shipper
from .cache import LRUStore
from .db_handle import DBHandle


class _PersistentOperator(BasicOperator):
    def __init__(self, func: Callable, key_extractor, initial_state: Any,
                 name: str, parallelism: int, output_batch_size: int,
                 db_dir: Optional[str] = None, cache_capacity: int = 1024,
                 serialize=None, deserialize=None,
                 input_routing: RoutingMode = RoutingMode.KEYBY,
                 cache_policy: str = "lru") -> None:
        if key_extractor is None:
            raise WindFlowError(f"{name}: persistent operators require a "
                                "key extractor")
        super().__init__(name, parallelism, input_routing, key_extractor,
                         output_batch_size)
        self.func = func
        self.initial_state = initial_state
        self.db_dir = db_dir
        self.cache_capacity = cache_capacity
        self.cache_policy = cache_policy
        self.serialize = serialize
        self.deserialize = deserialize
        self._riched = arity(func) >= 3

    @property
    def is_chainable(self) -> bool:
        return False

    replica_cls: type = None

    def build_replicas(self) -> None:
        self.replicas = [self.replica_cls(self, i)
                         for i in range(self.parallelism)]


class _PersistentReplica(BasicReplica):
    def __init__(self, op: _PersistentOperator, idx: int) -> None:
        super().__init__(op, idx)
        self.db = DBHandle(f"{op.name}_r{idx}", op.serialize, op.deserialize,
                           op.db_dir)
        self.state = LRUStore(self.db, op.cache_capacity,
                              policy=op.cache_policy)

    def _get_state(self, key):
        try:
            return self.state[key]
        except KeyError:
            return copy.deepcopy(self.op.initial_state)

    def _call(self, *args):
        if self.op._riched:
            return self.op.func(*args, self.context)
        return self.op.func(*args)

    def flush_on_termination(self) -> None:
        self.state.flush()

    def terminate(self) -> None:
        super().terminate()
        self.db.close()

    # -- checkpointing -----------------------------------------------------
    # Keyed state lives in cache+DB; spill the cache and snapshot the DB
    # file as one consistent image. Restore REPLACES the on-disk contents:
    # after a crash the file holds post-checkpoint writes that must roll
    # back to the barrier point.
    def snapshot_state(self) -> dict:
        st = super().snapshot_state()
        self.state.flush()
        st["db"] = self.db.snapshot_bytes()
        return st

    def restore_state(self, state: dict) -> None:
        super().restore_state(state)
        blob = state.get("db")
        if blob is not None:
            self.db.restore_bytes(blob)


# ---------------------------------------------------------------------------
class P_Map(_PersistentOperator):
    """func(tuple, state) -> (mapped, new_state). The pair is mandatory —
    a mutate-style functor returns (mapped, state) with the same (mutated)
    state object; inferring intent from the return shape would corrupt
    state whenever the mapped value itself is a 2-tuple."""


class PMapReplica(_PersistentReplica):
    def process(self, payload, ts, wm, tag):
        key = self.op.key_extractor(payload)
        st = self._get_state(key)
        out = self._call(payload, st)
        if not (isinstance(out, tuple) and len(out) == 2):
            raise WindFlowError(
                f"{self.op.name}: P_Map functor must return "
                "(result, new_state)")
        result, st = out
        self.state[key] = st
        if result is not None:
            self.emitter.emit(result, ts, wm)


P_Map.replica_cls = PMapReplica


class P_Filter(_PersistentOperator):
    """func(tuple, state) -> (keep, new_state); the pair is mandatory
    (see P_Map)."""


class PFilterReplica(_PersistentReplica):
    def process(self, payload, ts, wm, tag):
        key = self.op.key_extractor(payload)
        st = self._get_state(key)
        out = self._call(payload, st)
        if not (isinstance(out, tuple) and len(out) == 2):
            raise WindFlowError(
                f"{self.op.name}: P_Filter functor must return "
                "(keep, new_state)")
        keep, st = out
        self.state[key] = st
        if keep:
            self.emitter.emit(payload, ts, wm)
        else:
            self.stats.inputs_ignored += 1


P_Filter.replica_cls = PFilterReplica


class P_FlatMap(_PersistentOperator):
    """func(tuple, shipper, state) -> new_state (or mutate state)."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._riched = arity(self.func) >= 4


class PFlatMapReplica(_PersistentReplica):
    def __init__(self, op, idx):
        super().__init__(op, idx)
        self.shipper = Shipper(self)

    def process(self, payload, ts, wm, tag):
        key = self.op.key_extractor(payload)
        st = self._get_state(key)
        self.shipper._ts = ts
        self.shipper._wm = wm
        out = self._call(payload, self.shipper, st)
        self.state[key] = out if out is not None else st


P_FlatMap.replica_cls = PFlatMapReplica


class P_Reduce(_PersistentOperator):
    """Keyed running reduce with durable state: func(tuple, state) ->
    new_state; the updated state is emitted after each update (like
    Reduce)."""


class PReduceReplica(_PersistentReplica):
    def process(self, payload, ts, wm, tag):
        key = self.op.key_extractor(payload)
        st = self._get_state(key)
        out = self._call(payload, st)
        if out is not None:
            st = out
        self.state[key] = st
        self.emitter.emit(copy.copy(st), ts, wm)


P_Reduce.replica_cls = PReduceReplica


class P_Sink(_PersistentOperator):
    """func(Optional[tuple], state) -> new_state per tuple; None at EOS."""

    op_type = OpType.SINK
    # exactly-once mode: the sqlite file carries the 2PC epoch marker and
    # a replica-generation fence (windflow_tpu.sinks.transactional)
    supports_exactly_once = True

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.exactly_once = False

    def build_replicas(self) -> None:
        cls = PTxnSinkReplica if self.exactly_once else PSinkReplica
        self.replicas = [cls(self, i) for i in range(self.parallelism)]


class PSinkReplica(_PersistentReplica):
    def process(self, payload, ts, wm, tag):
        key = self.op.key_extractor(payload)
        st = self._get_state(key)
        out = self._call(payload, st)
        self.state[key] = out if out is not None else st

    def flush_on_termination(self) -> None:
        # EOS marker per key (the in-memory Sink gets one func(None) call;
        # the keyed persistent sink finalizes every key's state)
        for key, st in list(self.state.items()):
            out = self._call(None, st)
            if out is not None:
                self.state[key] = out
        super().flush_on_termination()


P_Sink.replica_cls = PSinkReplica


# ---------------------------------------------------------------------------
# Exactly-once persistent sink: epoch-fenced sqlite writer
# ---------------------------------------------------------------------------
class _PSinkTxnBackend:
    """2PC backend over the replica's own sqlite file. The staged state
    IS the database: between barriers every write sits in the cache or
    the open implicit sqlite transaction; pre-commit spills the cache and
    commits data + ``epoch`` marker atomically; phase-2 commit only
    advances the ``finalized`` marker (the visibility watermark external
    readers compare against ``epoch``). Restore replaces the whole file
    with the checkpoint image, so roll-forward/abort reduce to stamping
    the markers at the restored epoch. Every durable step first checks
    the generation fence — a zombie pre-rescale replica is refused before
    it can commit anything."""

    always_seal = True  # the tail epoch lives in the DB, not in buffer

    def __init__(self, replica: "PTxnSinkReplica") -> None:
        self.r = replica

    def do_precommit(self, epoch: int, records) -> None:
        r = self.r
        r._check_fence()
        for k, v in list(r.state.cache.items()):
            r.db.put(k, v)
        r.db.meta_put("epoch", epoch)
        r.db.commit()

    def do_commit(self, epoch: int):
        r = self.r
        r._check_fence()
        r.db.meta_put("finalized", epoch)
        r.db.commit()
        return None

    def do_abort(self, epoch: int) -> None:
        pass  # nothing staged outside the DB image

    def do_recover(self, last_epoch: int):
        # the checkpoint image (already restored into the file by
        # restore_state) is exactly the barrier state of ``last_epoch``:
        # stamp both markers there and re-assert this replica's fence
        # over whatever generation the image recorded
        r = self.r
        r.db.meta_put("fence", r._fence)
        r.db.meta_put("epoch", last_epoch)
        r.db.meta_put("finalized", last_epoch)
        r.db.commit()
        return [], []


class PTxnSinkReplica(PSinkReplica):
    def __init__(self, op, idx):
        super().__init__(op, idx)
        from ..sinks.transactional import EpochTxnDriver
        # acquire this replica generation's fence token: one atomic bump
        # of the in-DB generation — rebuilding the runtime plane (a live
        # rescale, a restore) creates a new replica and fences the old
        self._fence = (self.db.meta_get("fence") or 0) + 1
        self.db.meta_put("fence", self._fence)
        self.db.commit()
        self._txn = EpochTxnDriver(_PSinkTxnBackend(self), self.stats)
        self.on_idle = self._txn.poll

    def _check_fence(self) -> None:
        # accounting (Sink_txn_fenced_writes + the txn:fenced span)
        # happens in the driver, which wraps every backend verb
        from ..sinks.transactional import FencedWriteError
        stored = self.db.meta_get("fence")
        if stored != self._fence:
            raise FencedWriteError(
                f"{self.op.name} replica {self.idx}: sqlite epoch fence "
                f"{self._fence} is stale (current {stored}); a newer "
                "replica generation owns this database — refusing the "
                "write")

    # -- worker / coordinator hooks ----------------------------------------
    def bind_txn_coordinator(self, coordinator) -> None:
        self._txn.bind(coordinator)

    def precommit_epoch(self, ckpt_id: int) -> None:
        self._txn.precommit_epoch(ckpt_id)

    def handle_msg(self, ch, msg):
        t = self._txn
        if t._pending and min(t._pending) <= t._commit_ready:
            t.poll()
        super().handle_msg(ch, msg)

    # -- checkpointing ------------------------------------------------------
    def snapshot_state(self) -> dict:
        # the precommit hook already spilled + committed the epoch; the
        # inherited snapshot captures the image (markers included)
        st = super().snapshot_state()
        st.update(self._txn.snapshot())
        return st

    def restore_state(self, state: dict) -> None:
        super().restore_state(state)  # replaces the DB with the image
        self._txn.restore(state)      # -> do_recover stamps markers+fence

    def flush_on_termination(self) -> None:
        # per-key EOS finalization mutates state like normal processing:
        # it belongs to the tail epoch, staged (pre-committed) here and
        # finalized in txn_complete on a clean end of run
        for key, st in list(self.state.items()):
            out = self._call(None, st)
            if out is not None:
                self.state[key] = out
        self._txn.seal_tail()

    def terminate(self) -> None:
        # keep the DB open: txn_complete still has markers to commit
        if self.terminated:
            return
        self.terminated = True
        self.flush_on_termination()
        if self.op.closing_func is not None:
            if arity(self.op.closing_func) >= 1:
                self.op.closing_func(self.context)
            else:
                self.op.closing_func()
        if self.emitter is not None:
            self.emitter.flush()
        self.stats.is_terminated = True

    def txn_complete(self) -> None:
        self._txn.complete_all()
        self.db.close()
