from .db_handle import DBHandle
from .cache import LFUCache, LRUCache, LRUStore
from .p_basic_ops import (P_Filter, P_FlatMap, P_Map, P_Reduce, P_Sink)
from .p_keyed_windows import P_Keyed_Windows
from .builders_persistent import (P_Filter_Builder, P_FlatMap_Builder,
                                  P_Keyed_Windows_Builder, P_Map_Builder,
                                  P_Reduce_Builder, P_Sink_Builder)

__all__ = [
    "DBHandle", "LFUCache", "LRUCache", "LRUStore",
    "P_Map", "P_Filter", "P_FlatMap", "P_Reduce", "P_Sink",
    "P_Keyed_Windows",
    "P_Map_Builder", "P_Filter_Builder", "P_FlatMap_Builder",
    "P_Reduce_Builder", "P_Sink_Builder", "P_Keyed_Windows_Builder",
]
