"""Hot-state caches for persistent operators.

Parity: ``wf/persistent/cache/*.hpp`` — the reference keeps an LRU/LFU
cache of hot window buffers in front of RocksDB
(``p_window_replica.hpp:121``). ``LRUStore`` is a MutableMapping that the
window engine / keyed operators use directly: hot entries live in memory,
evictions spill to the DBHandle, lookups fall back to it.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Iterator, MutableMapping

from .db_handle import DBHandle

_MISSING = object()


class LRUCache:
    """Plain bounded LRU with an eviction callback."""

    def __init__(self, capacity: int, on_evict=None) -> None:
        self.capacity = max(1, capacity)
        self.on_evict = on_evict
        self._d: OrderedDict = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, key, default=None):
        v = self._d.get(key, _MISSING)
        if v is _MISSING:
            self.misses += 1
            return default
        self.hits += 1
        self._d.move_to_end(key)
        return v

    def put(self, key, value) -> None:
        self._d[key] = value
        self._d.move_to_end(key)
        while len(self._d) > self.capacity:
            k, v = self._d.popitem(last=False)
            if self.on_evict is not None:
                self.on_evict(k, v)

    def pop(self, key, default=None):
        return self._d.pop(key, default)

    def __contains__(self, key) -> bool:
        return key in self._d

    def __len__(self) -> int:
        return len(self._d)

    def items(self):
        return self._d.items()


class LRUStore(MutableMapping):
    """Dict-like keyed-state store: LRU cache over a DBHandle. Satisfies
    the access pattern of the window engine and keyed operators
    (get/setitem/items), so persistent variants reuse the exact same
    processing logic with out-of-core state."""

    def __init__(self, db: DBHandle, capacity: int = 1024) -> None:
        self.db = db
        self.cache = LRUCache(capacity, on_evict=self._spill)

    def _spill(self, key, value) -> None:
        self.db.put(key, value)

    # -- MutableMapping ----------------------------------------------------
    def __getitem__(self, key):
        v = self.cache.get(key, _MISSING)
        if v is not _MISSING:
            return v
        v = self.db.get(key, _MISSING)
        if v is _MISSING:
            raise KeyError(key)
        self.cache.put(key, v)
        return v

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            return default

    def __setitem__(self, key, value) -> None:
        self.cache.put(key, value)

    def __delitem__(self, key) -> None:
        self.cache.pop(key, None)
        self.db.delete(key)

    def __iter__(self) -> Iterator:
        seen = set()
        for k in list(self.cache._d.keys()):
            seen.add(k)
            yield k
        for k in self.db.keys():
            if k not in seen:
                yield k

    def __len__(self) -> int:
        n = len(self.cache)
        for k in self.db.keys():
            if k not in self.cache:
                n += 1
        return n

    def items(self):
        for k in list(self):
            yield k, self[k]

    def flush(self) -> None:
        """Spill every cached entry so the DB is complete (EOS/checkpoint)."""
        for k, v in list(self.cache.items()):
            self.db.put(k, v)
        self.db.commit()
