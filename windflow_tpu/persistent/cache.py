"""Hot-state caches for persistent operators.

Parity: ``wf/persistent/cache/*.hpp`` — the reference keeps an LRU/LFU
cache of hot window buffers in front of RocksDB, selectable per operator
(``p_window_replica.hpp:121``). ``LRUStore`` is a MutableMapping that the
window engine / keyed operators use directly: hot entries live in memory,
evictions spill to the DBHandle, lookups fall back to it. The eviction
policy is pluggable (``policy="lru"|"lfu"``): LRU suits scan-heavy key
access, LFU keeps a stable hot set resident under a skewed (zipfian)
key distribution where recency alone would churn it.
"""

from __future__ import annotations

from collections import OrderedDict, defaultdict
from typing import Any, Dict, Iterator, MutableMapping

from ..basic import WindFlowError
from .db_handle import DBHandle

_MISSING = object()


class LRUCache:
    """Plain bounded LRU with an eviction callback."""

    def __init__(self, capacity: int, on_evict=None) -> None:
        self.capacity = max(1, capacity)
        self.on_evict = on_evict
        self._d: OrderedDict = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, key, default=None):
        v = self._d.get(key, _MISSING)
        if v is _MISSING:
            self.misses += 1
            return default
        self.hits += 1
        self._d.move_to_end(key)
        return v

    def put(self, key, value) -> None:
        self._d[key] = value
        self._d.move_to_end(key)
        while len(self._d) > self.capacity:
            k, v = self._d.popitem(last=False)
            if self.on_evict is not None:
                self.on_evict(k, v)

    def pop(self, key, default=None):
        return self._d.pop(key, default)

    def __contains__(self, key) -> bool:
        return key in self._d

    def __len__(self) -> int:
        return len(self._d)

    def keys(self):
        return self._d.keys()

    def items(self):
        return self._d.items()

    def eviction_order(self):
        """Keys in the order the policy would evict them (LRU first).
        Snapshot before mutating — this iterates the live structure."""
        return iter(self._d.keys())


class LFUCache:
    """Bounded LFU with LRU tie-break inside a frequency class (the
    classic O(1) two-level structure: value dict + per-frequency ordered
    key buckets). Same surface as LRUCache so ``LRUStore`` can host
    either policy."""

    def __init__(self, capacity: int, on_evict=None) -> None:
        self.capacity = max(1, capacity)
        self.on_evict = on_evict
        self._vals: Dict[Any, Any] = {}
        self._freq: Dict[Any, int] = {}
        # freq -> ordered set of keys (OrderedDict keys; LRU order inside
        # the class so equal-frequency eviction is deterministic)
        self._buckets: Dict[int, OrderedDict] = defaultdict(OrderedDict)
        # lower bound of the minimum live frequency (never above it; the
        # eviction scan advances it past emptied buckets)
        self._minf = 1
        self.hits = 0
        self.misses = 0

    def _touch(self, key) -> None:
        f = self._freq[key]
        bucket = self._buckets[f]
        del bucket[key]
        if not bucket:
            del self._buckets[f]
        self._freq[key] = f + 1
        self._buckets[f + 1][key] = None

    def get(self, key, default=None):
        if key not in self._vals:
            self.misses += 1
            return default
        self.hits += 1
        self._touch(key)
        return self._vals[key]

    def put(self, key, value) -> None:
        if key in self._vals:
            self._vals[key] = value
            self._touch(key)
            return
        while len(self._vals) >= self.capacity:
            self._evict_one()
        self._vals[key] = value
        self._freq[key] = 1
        self._buckets[1][key] = None
        self._minf = 1

    def _evict_one(self) -> None:
        while self._minf not in self._buckets:
            self._minf += 1
        bucket = self._buckets[self._minf]
        key, _ = bucket.popitem(last=False)  # LRU within the class
        if not bucket:
            del self._buckets[self._minf]
        del self._freq[key]
        v = self._vals.pop(key)
        if self.on_evict is not None:
            self.on_evict(key, v)

    def pop(self, key, default=None):
        if key not in self._vals:
            return default
        f = self._freq.pop(key)
        bucket = self._buckets[f]
        del bucket[key]
        if not bucket:
            del self._buckets[f]
        return self._vals.pop(key)

    def __contains__(self, key) -> bool:
        return key in self._vals

    def __len__(self) -> int:
        return len(self._vals)

    def keys(self):
        return self._vals.keys()

    def items(self):
        return self._vals.items()

    def eviction_order(self):
        """Keys in the order the policy would evict them (ascending
        frequency, LRU inside each class). Snapshot before mutating."""
        for f in sorted(self._buckets):
            yield from self._buckets[f].keys()


_CACHE_POLICIES = {"lru": LRUCache, "lfu": LFUCache}


def make_cache(policy: str, capacity: int, on_evict=None):
    """Cache factory shared by the store and the builders (ONE place
    that knows the policy names)."""
    cls = _CACHE_POLICIES.get(str(policy).lower())
    if cls is None:
        raise WindFlowError(
            f"unknown cache policy {policy!r} (expected one of "
            f"{sorted(_CACHE_POLICIES)})")
    return cls(capacity, on_evict=on_evict)


class LRUStore(MutableMapping):
    """Dict-like keyed-state store: a bounded hot cache (LRU by default,
    LFU via ``policy="lfu"``) over a DBHandle. Satisfies the access
    pattern of the window engine and keyed operators (get/setitem/items),
    so persistent variants reuse the exact same processing logic with
    out-of-core state."""

    def __init__(self, db: DBHandle, capacity: int = 1024,
                 policy: str = "lru") -> None:
        self.db = db
        self.cache = make_cache(policy, capacity, on_evict=self._spill)

    def _spill(self, key, value) -> None:
        self.db.put(key, value)

    # -- MutableMapping ----------------------------------------------------
    def __getitem__(self, key):
        v = self.cache.get(key, _MISSING)
        if v is not _MISSING:
            return v
        v = self.db.get(key, _MISSING)
        if v is _MISSING:
            raise KeyError(key)
        self.cache.put(key, v)
        return v

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            return default

    def __setitem__(self, key, value) -> None:
        self.cache.put(key, value)

    def __delitem__(self, key) -> None:
        self.cache.pop(key, None)
        self.db.delete(key)

    def __iter__(self) -> Iterator:
        seen = set()
        for k in list(self.cache.keys()):
            seen.add(k)
            yield k
        for k in self.db.keys():
            if k not in seen:
                yield k

    def __len__(self) -> int:
        n = len(self.cache)
        for k in self.db.keys():
            if k not in self.cache:
                n += 1
        return n

    def items(self):
        for k in list(self):
            yield k, self[k]

    def flush(self) -> None:
        """Spill every cached entry so the DB is complete (EOS/checkpoint)."""
        for k, v in list(self.cache.items()):
            self.db.put(k, v)
        self.db.commit()
