"""Builders for persistent operators (reference
``wf/persistent/builders_rocksdb.hpp``: withDBPath, withSerializer/
Deserializer, withCacheCapacity on top of the usual surface; the cache
POLICY mirrors the reference's pluggable hot-buffer cache,
``p_window_replica.hpp:121`` — LRU by default, LFU for skewed key
distributions via ``with_cache_policy("lfu")``)."""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..basic import WindFlowError, WinType
from ..builders import BasicBuilder
from .p_basic_ops import P_Filter, P_FlatMap, P_Map, P_Reduce, P_Sink
from .p_keyed_windows import P_Keyed_Windows


class _PersistentBuilder(BasicBuilder):
    def __init__(self, func: Callable) -> None:
        super().__init__(func)
        self._key_extractor = None
        self._initial_state: Any = None
        self._db_dir: Optional[str] = None
        self._cache_capacity = 1024
        self._cache_policy = "lru"
        self._serialize = None
        self._deserialize = None

    def with_key_by(self, key_extractor):
        self._key_extractor = key_extractor
        return self

    def with_initial_state(self, state: Any):
        self._initial_state = state
        return self

    def with_db_path(self, path: str):
        self._db_dir = path
        return self

    def with_cache_capacity(self, n: int):
        self._cache_capacity = n
        return self

    def with_cache_policy(self, policy: str):
        """Hot-cache eviction policy: "lru" (default) or "lfu" (keeps a
        stable hot set under skewed key distributions). Validated here
        so a typo fails at build time, not at the first eviction."""
        from .cache import make_cache
        make_cache(policy, 1)  # raises WindFlowError on unknown policy
        self._cache_policy = policy
        return self

    def with_serializers(self, serialize: Callable, deserialize: Callable):
        self._serialize = serialize
        self._deserialize = deserialize
        return self

    op_cls: type = None

    def build(self):
        if self._key_extractor is None:
            raise WindFlowError(f"{type(self).__name__}: withKeyBy mandatory")
        return self._finish(self.op_cls(
            self._func, self._key_extractor, self._initial_state, self._name,
            self._parallelism, self._output_batch_size, self._db_dir,
            self._cache_capacity, self._serialize, self._deserialize,
            cache_policy=self._cache_policy))


class P_Map_Builder(_PersistentBuilder):
    _default_name = "p_map"
    op_cls = P_Map


class P_Filter_Builder(_PersistentBuilder):
    _default_name = "p_filter"
    op_cls = P_Filter


class P_FlatMap_Builder(_PersistentBuilder):
    _default_name = "p_flatmap"
    op_cls = P_FlatMap


class P_Reduce_Builder(_PersistentBuilder):
    _default_name = "p_reduce"
    op_cls = P_Reduce


class P_Sink_Builder(_PersistentBuilder):
    _default_name = "p_sink"
    op_cls = P_Sink

    def __init__(self, func: Callable) -> None:
        super().__init__(func)
        self._exactly_once = False

    def with_exactly_once(self):
        """Exactly-once via the epoch-fenced sqlite writer: data and the
        ``epoch`` marker commit in one sqlite transaction at the barrier,
        the ``finalized`` marker advances only on coordinator finalize,
        and a stale (pre-rescale zombie) replica generation is refused by
        the in-DB fence before it can commit anything."""
        self._exactly_once = True
        return self

    def build(self):
        op = super().build()
        op.exactly_once = self._exactly_once
        return op


class P_Keyed_Windows_Builder(_PersistentBuilder):
    _default_name = "p_keyed_windows"

    def __init__(self, win_func: Callable) -> None:
        super().__init__(win_func)
        self._win_len = 0
        self._slide_len = 0
        self._win_type = None
        self._lateness = 0
        self._incremental = False
        self._initial = None

    def with_cb_windows(self, win_len: int, slide_len: int):
        self._win_type = WinType.CB
        self._win_len, self._slide_len = win_len, slide_len
        return self

    def with_tb_windows(self, win_usec: int, slide_usec: int):
        self._win_type = WinType.TB
        self._win_len, self._slide_len = win_usec, slide_usec
        return self

    def with_lateness(self, lateness_usec: int):
        self._lateness = lateness_usec
        return self

    def incremental(self, initial_value=None):
        self._incremental = True
        self._initial = initial_value
        return self

    def build(self) -> P_Keyed_Windows:
        if self._win_type is None:
            raise WindFlowError("P_Keyed_Windows_Builder: call "
                                "with_cb_windows()/with_tb_windows()")
        if self._key_extractor is None:
            raise WindFlowError("P_Keyed_Windows_Builder: withKeyBy "
                                "mandatory")
        return self._finish(P_Keyed_Windows(
            self._func, self._key_extractor, self._win_len, self._slide_len,
            self._win_type, self._lateness, self._incremental, self._initial,
            self._name, self._parallelism, self._output_batch_size,
            self._db_dir, self._cache_capacity, self._serialize,
            self._deserialize, cache_policy=self._cache_policy))
