"""P_Keyed_Windows: keyed windows with out-of-core per-key window state.

Parity: ``wf/persistent/p_window_replica.hpp:69-659`` — the reference
buffers window content as fragmented lists in RocksDB with an LRU cache of
hot window buffers. Here the SAME WindowEngine as Keyed_Windows runs with
its per-key descriptor map replaced by an ``LRUStore``: hot keys stay in
memory, cold key descriptors (open windows + archives) spill to the
replica's sqlite file and reload on access. Window semantics are therefore
identical to Keyed_Windows by construction; only state residency differs.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..basic import WinType
from ..operators.windows import Keyed_Windows, _WindowReplica
from .cache import LRUStore
from .db_handle import DBHandle


class P_Keyed_Windows(Keyed_Windows):
    def __init__(self, win_func: Callable, key_extractor, win_len: int,
                 slide_len: int, win_type: WinType = WinType.CB,
                 lateness: int = 0, incremental: bool = False,
                 initial_value: Any = None, name: str = "p_keyed_windows",
                 parallelism: int = 1, output_batch_size: int = 0,
                 db_dir: Optional[str] = None, cache_capacity: int = 256,
                 serialize=None, deserialize=None,
                 cache_policy: str = "lru") -> None:
        super().__init__(win_func, key_extractor, win_len, slide_len,
                         win_type, lateness, incremental, initial_value,
                         name, parallelism, output_batch_size)
        self.db_dir = db_dir
        self.cache_capacity = cache_capacity
        self.cache_policy = cache_policy
        self.serialize = serialize
        self.deserialize = deserialize

    def build_replicas(self) -> None:
        self.replicas = [PKeyedWindowsReplica(self, i)
                         for i in range(self.parallelism)]


class PKeyedWindowsReplica(_WindowReplica):
    def __init__(self, op: P_Keyed_Windows, idx: int) -> None:
        super().__init__(op, idx)
        self.db = DBHandle(f"{op.name}_r{idx}", op.serialize, op.deserialize,
                           op.db_dir)
        # swap the engine's key map for the cache-backed store
        self.engine.key_map = LRUStore(self.db, op.cache_capacity,
                                       policy=op.cache_policy)

    def flush_on_termination(self) -> None:
        super().flush_on_termination()
        self.engine.key_map.flush()
        self.db.close()

    # -- checkpointing -----------------------------------------------------
    # The engine's key map is the cache-backed store: spill it and ship
    # the DB image instead of materializing every cold key into the blob.
    # Restore replaces the DB contents (a crashed run's file holds
    # post-checkpoint descriptors that must roll back).
    def snapshot_state(self) -> dict:
        from ..operators.base import BasicReplica
        st = BasicReplica.snapshot_state(self)
        self.engine.key_map.flush()
        st["db"] = self.db.snapshot_bytes()
        st["engine_meta"] = {"ignored_tuples": self.engine.ignored_tuples,
                             "cur_wm": self.engine.cur_wm}
        return st

    def restore_state(self, state: dict) -> None:
        from ..operators.base import BasicReplica
        BasicReplica.restore_state(self, state)
        blob = state.get("db")
        if blob is not None:
            self.db.restore_bytes(blob)
        meta = state.get("engine_meta", {})
        self.engine.ignored_tuples = meta.get("ignored_tuples", 0)
        self.engine.cur_wm = meta.get("cur_wm", 0)
