"""DBHandle: durable keyed state for persistent operators.

Parity: ``wf/persistent/db_handle.hpp:54-345`` — the reference opens one
RocksDB instance per replica (path per pid, L87) and moves user state
through user-provided serialize/deserialize functions keyed by the
serialized stream key. RocksDB is not in this image; sqlite3 (stdlib)
provides the same embedded ordered-KV capability: one database file per
replica, a single ``kv`` table, WAL mode for concurrent reader safety.

Serialization defaults to pickle; users can supply ``serialize`` /
``deserialize`` callables exactly like the reference builders do
(``wf/persistent/builders_rocksdb.hpp``).
"""

from __future__ import annotations

import os
import pickle
import sqlite3
import tempfile
from typing import Any, Callable, Iterator, Optional, Tuple


def default_db_dir() -> str:
    """Reference: path per pid (``db_handle.hpp:87``)."""
    d = os.environ.get("WF_DB_DIR",
                       os.path.join(tempfile.gettempdir(),
                                    f"windflow_tpu_db_{os.getpid()}"))
    os.makedirs(d, exist_ok=True)
    return d


class DBHandle:
    def __init__(self, name: str,
                 serialize: Optional[Callable[[Any], bytes]] = None,
                 deserialize: Optional[Callable[[bytes], Any]] = None,
                 db_dir: Optional[str] = None,
                 shared: bool = False) -> None:
        if db_dir is not None:
            os.makedirs(db_dir, exist_ok=True)
        self.path = os.path.join(db_dir or default_db_dir(), f"{name}.db")
        self._ser = serialize or pickle.dumps
        self._de = deserialize or pickle.loads
        # handles are built on the main thread and then used by exactly one
        # worker thread; sqlite's same-thread guard must not apply
        self._conn = sqlite3.connect(self.path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB)")
        self._conn.commit()

    def _kbytes(self, key: Any) -> bytes:
        return pickle.dumps(key)

    def get(self, key: Any, default: Any = None) -> Any:
        row = self._conn.execute("SELECT v FROM kv WHERE k = ?",
                                 (self._kbytes(key),)).fetchone()
        if row is None:
            return default
        return self._de(row[0])

    def put(self, key: Any, value: Any) -> None:
        self._conn.execute(
            "INSERT INTO kv (k, v) VALUES (?, ?) "
            "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
            (self._kbytes(key), self._ser(value)))

    def put_many(self, items) -> None:
        """Batched upsert (one executemany) — the tiered cold store's
        demote path writes whole victim batches, never one row at a
        time."""
        self._conn.executemany(
            "INSERT INTO kv (k, v) VALUES (?, ?) "
            "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
            [(self._kbytes(k), self._ser(v)) for k, v in items])

    def delete(self, key: Any) -> None:
        self._conn.execute("DELETE FROM kv WHERE k = ?", (self._kbytes(key),))

    def delete_many(self, keys) -> None:
        self._conn.executemany("DELETE FROM kv WHERE k = ?",
                               [(self._kbytes(k),) for k in keys])

    def clear(self) -> None:
        """Drop every row (a fresh owner claiming a reused db path must
        not inherit a previous run's state)."""
        self._conn.execute("DELETE FROM kv")
        self._conn.commit()

    def contains(self, key: Any) -> bool:
        return self._conn.execute("SELECT 1 FROM kv WHERE k = ?",
                                  (self._kbytes(key),)).fetchone() is not None

    def items(self) -> Iterator[Tuple[Any, Any]]:
        for k, v in self._conn.execute("SELECT k, v FROM kv"):
            yield pickle.loads(k), self._de(v)

    def keys(self):
        for k, in self._conn.execute("SELECT k FROM kv"):
            yield pickle.loads(k)

    def __len__(self) -> int:
        return self._conn.execute("SELECT COUNT(*) FROM kv").fetchone()[0]

    # -- transaction metadata (exactly-once sinks) -------------------------
    # One tiny side table holds the 2PC bookkeeping INSIDE the same
    # database file, so an epoch marker and its data commit in one sqlite
    # transaction and snapshot/restore carries both: 'fence' (replica
    # generation — stale writers are refused), 'epoch' (last pre-committed
    # epoch) and 'finalized' (last epoch the coordinator finalized).
    def _ensure_meta(self) -> None:
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS wf_txn (k TEXT PRIMARY KEY, v INTEGER)")

    def meta_get(self, key: str) -> Optional[int]:
        self._ensure_meta()
        row = self._conn.execute("SELECT v FROM wf_txn WHERE k = ?",
                                 (key,)).fetchone()
        return None if row is None else int(row[0])

    def meta_put(self, key: str, value: int) -> None:
        self._ensure_meta()
        self._conn.execute(
            "INSERT INTO wf_txn (k, v) VALUES (?, ?) "
            "ON CONFLICT(k) DO UPDATE SET v = excluded.v", (key, int(value)))

    def commit(self) -> None:
        """Durable, atomic commit of all pending puts/deletes.

        The transaction itself was always atomic (sqlite journal), but the
        original in-place flow left committed rows in the ``-wal`` side
        file until some later automatic checkpoint: a crash that lost or
        orphaned the WAL (or any backup/copy of just the ``.db`` file)
        silently dropped the last commits. ``commit()`` now folds the WAL
        into the main database through sqlite's atomic checkpoint
        protocol, so after it returns the ``.db`` file alone is a
        complete, self-contained image of the committed state."""
        self._conn.commit()
        try:
            self._conn.execute("PRAGMA wal_checkpoint(TRUNCATE)")
        except sqlite3.DatabaseError:  # pragma: no cover - locked reader
            pass

    def close(self) -> None:
        self.commit()
        self._conn.close()

    # -- checkpointing (windflow_tpu.checkpoint) ---------------------------
    def snapshot_bytes(self) -> bytes:
        """Consistent point-in-time image of the whole database (sqlite
        online backup of the live connection), as bytes for a checkpoint
        blob. Pending writes are committed first."""
        self._conn.commit()
        fd, tmp = tempfile.mkstemp(suffix=".snap",
                                   dir=os.path.dirname(self.path) or ".")
        os.close(fd)
        try:
            dst = sqlite3.connect(tmp)
            try:
                self._conn.backup(dst)
            finally:
                dst.close()
            with open(tmp, "rb") as f:
                return f.read()
        finally:
            os.unlink(tmp)

    def restore_bytes(self, data: bytes) -> None:
        """Replace the database's entire contents with a ``snapshot_bytes``
        image (crash recovery: the on-disk file may hold post-checkpoint
        writes from the crashed run). Staged via temp file + atomic rename
        so a crash mid-restore cannot leave a torn image behind."""
        tmp = self.path + ".restore.tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        final = self.path + ".restore"
        os.replace(tmp, final)
        # the backup destination must hold no open transaction
        self._conn.commit()
        try:
            src = sqlite3.connect(final)
            try:
                src.backup(self._conn)
            finally:
                src.close()
            self.commit()
        finally:
            os.unlink(final)

    def export_to(self, path: str) -> None:
        """Write a standalone copy of the database to ``path`` via temp
        file + atomic rename: readers of ``path`` see either the previous
        complete export or the new one, never a torn file."""
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(self.snapshot_bytes())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
