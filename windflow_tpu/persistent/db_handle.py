"""DBHandle: durable keyed state for persistent operators.

Parity: ``wf/persistent/db_handle.hpp:54-345`` — the reference opens one
RocksDB instance per replica (path per pid, L87) and moves user state
through user-provided serialize/deserialize functions keyed by the
serialized stream key. RocksDB is not in this image; sqlite3 (stdlib)
provides the same embedded ordered-KV capability: one database file per
replica, a single ``kv`` table, WAL mode for concurrent reader safety.

Serialization defaults to pickle; users can supply ``serialize`` /
``deserialize`` callables exactly like the reference builders do
(``wf/persistent/builders_rocksdb.hpp``).
"""

from __future__ import annotations

import os
import pickle
import sqlite3
import tempfile
from typing import Any, Callable, Iterator, Optional, Tuple


def default_db_dir() -> str:
    """Reference: path per pid (``db_handle.hpp:87``)."""
    d = os.environ.get("WF_DB_DIR",
                       os.path.join(tempfile.gettempdir(),
                                    f"windflow_tpu_db_{os.getpid()}"))
    os.makedirs(d, exist_ok=True)
    return d


class DBHandle:
    def __init__(self, name: str,
                 serialize: Optional[Callable[[Any], bytes]] = None,
                 deserialize: Optional[Callable[[bytes], Any]] = None,
                 db_dir: Optional[str] = None,
                 shared: bool = False) -> None:
        self.path = os.path.join(db_dir or default_db_dir(), f"{name}.db")
        self._ser = serialize or pickle.dumps
        self._de = deserialize or pickle.loads
        # handles are built on the main thread and then used by exactly one
        # worker thread; sqlite's same-thread guard must not apply
        self._conn = sqlite3.connect(self.path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS kv (k BLOB PRIMARY KEY, v BLOB)")
        self._conn.commit()

    def _kbytes(self, key: Any) -> bytes:
        return pickle.dumps(key)

    def get(self, key: Any, default: Any = None) -> Any:
        row = self._conn.execute("SELECT v FROM kv WHERE k = ?",
                                 (self._kbytes(key),)).fetchone()
        if row is None:
            return default
        return self._de(row[0])

    def put(self, key: Any, value: Any) -> None:
        self._conn.execute(
            "INSERT INTO kv (k, v) VALUES (?, ?) "
            "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
            (self._kbytes(key), self._ser(value)))

    def delete(self, key: Any) -> None:
        self._conn.execute("DELETE FROM kv WHERE k = ?", (self._kbytes(key),))

    def contains(self, key: Any) -> bool:
        return self._conn.execute("SELECT 1 FROM kv WHERE k = ?",
                                  (self._kbytes(key),)).fetchone() is not None

    def items(self) -> Iterator[Tuple[Any, Any]]:
        for k, v in self._conn.execute("SELECT k, v FROM kv"):
            yield pickle.loads(k), self._de(v)

    def keys(self):
        for k, in self._conn.execute("SELECT k FROM kv"):
            yield pickle.loads(k)

    def __len__(self) -> int:
        return self._conn.execute("SELECT COUNT(*) FROM kv").fetchone()[0]

    def commit(self) -> None:
        self._conn.commit()

    def close(self) -> None:
        self._conn.commit()
        self._conn.close()
