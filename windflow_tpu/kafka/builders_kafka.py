"""Kafka builders (reference ``wf/kafka/builders_kafka.hpp``: withBrokers,
withTopics, withGroupID, withOffsets, withIdleness)."""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ..basic import WindFlowError
from ..builders import BasicBuilder, _SourceOverloadMixin
from .connectors import Kafka_Sink, Kafka_Source


class Kafka_Source_Builder(_SourceOverloadMixin, BasicBuilder):
    _default_name = "kafka_source"

    def __init__(self, deser_func: Callable) -> None:
        super().__init__(deser_func)
        self._brokers: Optional[str] = None
        self._topics: List[str] = []
        self._group_id = "windflow"
        self._offsets: Dict[Tuple[str, int], int] = {}
        self._idleness_ms = 100
        self._block_size: Optional[int] = None  # with_columnar_blocks

    def with_brokers(self, brokers: str):
        self._brokers = brokers
        return self

    def with_topics(self, *topics: str):
        self._topics = list(topics)
        return self

    def with_group_id(self, group_id: str):
        self._group_id = group_id
        return self

    def with_offsets(self, offsets: Dict[Tuple[str, int], int]):
        """Explicit start offsets per (topic, partition) — the replayable
        source positions the checkpoint/resume story builds on."""
        self._offsets = dict(offsets)
        return self

    def with_idleness(self, ms: int):
        self._idleness_ms = ms
        return self

    def with_columnar_blocks(self, block_size: int = 512):
        """Columnar block mode: the deserialization functor receives a
        non-empty LIST of KafkaMessages (one batch poll, up to
        ``block_size``) instead of single messages, decodes them
        vectorized and calls ``shipper.push_columns`` — no per-tuple
        Python on the ingest path. ``None`` (idle timeout) and the
        ``False`` stop flag keep their meaning; per-partition offset
        snapshots and barrier placement are unchanged."""
        if block_size <= 0:
            raise WindFlowError(
                "with_columnar_blocks: block_size must be positive")
        self._block_size = block_size
        return self

    def build(self) -> Kafka_Source:
        if not self._brokers:
            raise WindFlowError("Kafka_Source_Builder: withBrokers mandatory")
        if not self._topics:
            raise WindFlowError("Kafka_Source_Builder: withTopics mandatory")
        op = self._finish_overload(self._finish(Kafka_Source(
            self._func, self._brokers, self._topics, self._group_id,
            self._offsets, self._idleness_ms, self._name, self._parallelism,
            self._output_batch_size)))
        if self._block_size is not None:
            op.block_mode = True
            op.block_size = self._block_size
        return op


class Kafka_Sink_Builder(BasicBuilder):
    _default_name = "kafka_sink"

    def __init__(self, ser_func: Callable) -> None:
        super().__init__(ser_func)
        self._brokers: Optional[str] = None
        self._exactly_once = False
        self._txn_dir: Optional[str] = None

    def with_brokers(self, brokers: str):
        self._brokers = brokers
        return self

    def with_exactly_once(self, staging_dir: Optional[str] = None):
        """Exactly-once via per-epoch broker transactions driven by
        checkpoint finalize (transactional producer with a stable
        ``wf-txn-<op>-r<idx>`` id; zombie replicas fenced). memory://
        brokers model the full prepare/commit/abort/fence surface;
        real brokers need confluent_kafka (kafka-python has no
        transactions — build fails loudly). ``staging_dir`` holds the
        real-broker epoch staging (default ``$WF_TXN_DIR``)."""
        self._exactly_once = True
        if staging_dir is not None:
            self._txn_dir = staging_dir
        return self

    def build(self) -> Kafka_Sink:
        if not self._brokers:
            raise WindFlowError("Kafka_Sink_Builder: withBrokers mandatory")
        op = self._finish(Kafka_Sink(self._func, self._brokers, self._name,
                                     self._parallelism))
        op.exactly_once = self._exactly_once
        op.txn_dir = self._txn_dir
        return op
