"""Kafka connectors: external ingestion/egress with replayable offsets.

Parity: ``wf/kafka/kafka_source.hpp:127-519`` (consumer-group replicas, a
poll loop with idle timeout, a user deserialization functor returning a
continue flag, explicit start offsets) and ``wf/kafka/kafka_sink.hpp:71-379``
(user serializer returning (topic, partition, payload)).

The reference links librdkafka; here the transport is pluggable behind one
small interface (subscribe/consume/produce/flush/close):

- broker string ``"memory://<name>"`` uses the built-in in-process
  ``MemoryBroker`` (partitioned topics, offsets, consumer groups) — it
  exercises the full replay/offset surface without a server;
- any other broker string goes through ``ConfluentTransport``
  (confluent_kafka / librdkafka, preferred) or ``KafkaPythonTransport``
  (kafka-python). A missing client library fails fast at operator
  CONSTRUCTION with a clear error, never silently at runtime; the
  adapters are unit-tested against injected fake client modules.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..basic import OpType, RoutingMode, WindFlowError, current_time_usecs
from ..operators.base import BasicOperator, BasicReplica, arity
from ..operators.source import SourceShipper
from ..sinks.transactional import FencedWriteError


# ---------------------------------------------------------------------------
# transient-error retry (jittered exponential backoff): a broker hiccup
# must not surface as a worker crash — bounded attempts with backoff, a
# Kafka_reconnects stat per retry, THEN the error propagates to the
# supervisor/wait_end like any other failure
# ---------------------------------------------------------------------------
def _kafka_retry_attempts() -> int:
    try:
        return max(0, int(os.environ.get("WF_KAFKA_RETRIES", "5")))
    except ValueError:
        return 5  # malformed knob must not take down the graph


def _kafka_retry_base_s() -> float:
    try:
        return max(0.0,
                   float(os.environ.get("WF_KAFKA_RETRY_BASE_MS", "100"))
                   / 1e3)
    except ValueError:
        return 0.1


def _retrying(transport, fn: Callable, what: str):
    """Run ``fn`` with bounded retry on the transport's transient error
    classes: the k-th retry sleeps ``base * 2**k`` seconds with uniform
    jitter in [0.5, 1.0] of that value (a replica fleet must not retry a
    flapping broker in lockstep). Every retry invokes
    ``transport.on_retry`` (the replica counts it as Kafka_reconnects);
    exhausted attempts re-raise the last error."""
    transients = transport._transient_excs()
    if not transients:
        return fn()
    attempts = _kafka_retry_attempts()
    base = _kafka_retry_base_s()
    for attempt in range(attempts + 1):
        try:
            return fn()
        except transients as e:
            # confluent wraps a KafkaError carrying .fatal() in args[0]:
            # authentication/config errors never heal by retry
            inner = e.args[0] if getattr(e, "args", None) else None
            fatal = getattr(inner, "fatal", None)
            if callable(fatal) and fatal():
                raise
            if attempt >= attempts:
                raise WindFlowError(
                    f"Kafka {what}: still failing after {attempts} "
                    f"retr{'y' if attempts == 1 else 'ies'}: "
                    f"{type(e).__name__}: {e}") from e
            cb = getattr(transport, "on_retry", None)
            if cb is not None:
                cb()
            delay = base * (2 ** attempt)
            time.sleep(delay * (0.5 + 0.5 * random.random()))


class KafkaMessage:
    __slots__ = ("topic", "partition", "offset", "payload", "timestamp")

    def __init__(self, topic, partition, offset, payload, timestamp) -> None:
        self.topic = topic
        self.partition = partition
        self.offset = offset
        self.payload = payload
        self.timestamp = timestamp


# ---------------------------------------------------------------------------
# In-process broker (the test transport)
# ---------------------------------------------------------------------------
class MemoryBroker:
    _registry: Dict[str, "MemoryBroker"] = {}
    _reg_lock = threading.Lock()

    def __init__(self, name: str, n_partitions: int = 4) -> None:
        self.name = name
        self.n_partitions = n_partitions
        self._topics: Dict[str, List[List[KafkaMessage]]] = {}
        self._lock = threading.Lock()
        self._group_assign: Dict[Tuple[str, str], Dict[int, int]] = {}
        # consumer-group committed offsets ((group, topic, partition) ->
        # next offset) — written by MemoryTransport.commit_offsets when a
        # checkpoint finalizes, mirroring a real broker's offset store
        self.committed: Dict[Tuple[str, str, int], int] = {}
        # transactional-producer state (exactly-once sinks): per
        # transactional id a fence generation (zombie producers are
        # refused, Kafka's producer-epoch fencing), prepared-but-
        # uncommitted epoch buffers (durable across a producer's death —
        # the analog of the broker's transaction log), and the committed
        # epoch set (idempotent commit: a replayed epoch is discarded)
        self.txn_fences: Dict[str, int] = {}
        self.txn_prepared: Dict[str, Dict[int, List[Tuple]]] = {}
        self.txn_committed: Dict[str, set] = {}
        self.fenced_attempts = 0

    @classmethod
    def get(cls, name: str, n_partitions: int = 4) -> "MemoryBroker":
        with cls._reg_lock:
            b = cls._registry.get(name)
            if b is None:
                b = cls._registry[name] = MemoryBroker(name, n_partitions)
            return b

    @classmethod
    def reset(cls) -> None:
        with cls._reg_lock:
            cls._registry.clear()

    def _topic(self, topic: str) -> List[List[KafkaMessage]]:
        with self._lock:
            t = self._topics.get(topic)
            if t is None:
                t = self._topics[topic] = [[] for _ in range(self.n_partitions)]
            return t

    def produce(self, topic: str, payload: Any,
                partition: Optional[int] = None, key: Any = None) -> None:
        t = self._topic(topic)
        with self._lock:
            if partition is None:
                partition = (hash(key) % self.n_partitions if key is not None
                             else sum(len(p) for p in t) % self.n_partitions)
            part = t[partition % self.n_partitions]
            part.append(KafkaMessage(topic, partition % self.n_partitions,
                                     len(part), payload,
                                     current_time_usecs()))

    def assign_partitions(self, topic: str, group: str, member: int,
                          n_members: int) -> List[int]:
        """Cooperative assignment: partition p -> member p % n_members
        (the reference relies on Kafka's group rebalance,
        ``kafka_source.hpp:77-115``)."""
        return [p for p in range(self.n_partitions) if p % n_members == member]

    def poll(self, topic: str, partition: int, offset: int
             ) -> Optional[KafkaMessage]:
        t = self._topic(topic)
        with self._lock:
            part = t[partition]
            if offset < len(part):
                return part[offset]
        return None

    def poll_run(self, topic: str, partition: int, offset: int,
                 max_n: int) -> List[KafkaMessage]:
        """Contiguous run from one partition — the batch-poll primitive
        the columnar block adapter rides (one lock round per partition
        instead of one per message)."""
        t = self._topic(topic)
        with self._lock:
            return t[partition][offset:offset + max_n]

    def end_offset(self, topic: str, partition: int) -> int:
        t = self._topic(topic)
        with self._lock:
            return len(t[partition])

    # -- transactions (exactly-once sinks) ---------------------------------
    def txn_init(self, txn_id: str) -> int:
        """(Re)initialize a transactional producer: bump the fence
        generation — every older producer of the same id is now a zombie
        whose writes are refused (``kafka_sink`` EOS parity with Kafka's
        ``initTransactions`` producer-epoch bump)."""
        with self._lock:
            gen = self.txn_fences.get(txn_id, 0) + 1
            self.txn_fences[txn_id] = gen
            self.txn_prepared.setdefault(txn_id, {})
            self.txn_committed.setdefault(txn_id, set())
            return gen

    def _txn_check(self, txn_id: str, gen: int) -> None:
        if self.txn_fences.get(txn_id) != gen:
            self.fenced_attempts += 1
            raise FencedWriteError(
                f"Kafka transactional producer {txn_id!r} generation "
                f"{gen} is fenced (current generation "
                f"{self.txn_fences.get(txn_id)}): a newer replica owns "
                "this transaction log")

    def txn_check(self, txn_id: str, gen: int) -> None:
        with self._lock:
            self._txn_check(txn_id, gen)

    def txn_prepare(self, txn_id: str, gen: int, epoch: int,
                    records: List[Tuple]) -> None:
        """Phase 1: the epoch's records become durable in the broker's
        transaction log, invisible to consumers until commit."""
        with self._lock:
            self._txn_check(txn_id, gen)
            self.txn_prepared[txn_id][epoch] = list(records)

    def txn_is_committed(self, txn_id: str, epoch: int) -> bool:
        with self._lock:
            return epoch in self.txn_committed.get(txn_id, ())

    def txn_commit(self, txn_id: str, gen: int, epoch: int) -> bool:
        """Phase 2: append the prepared records to their topics. False
        when the epoch was already committed (idempotent — the replayed
        duplicate is discarded)."""
        with self._lock:
            self._txn_check(txn_id, gen)
            if epoch in self.txn_committed[txn_id]:
                self.txn_prepared[txn_id].pop(epoch, None)
                return False
            records = self.txn_prepared[txn_id].pop(epoch, [])
            self.txn_committed[txn_id].add(epoch)
        for topic, partition, key, payload in records:
            self.produce(topic, payload, partition, key)
        return True

    def txn_abort(self, txn_id: str, gen: int, epoch: int) -> bool:
        with self._lock:
            self._txn_check(txn_id, gen)
            return self.txn_prepared[txn_id].pop(epoch, None) is not None

    def txn_prepared_epochs(self, txn_id: str) -> List[int]:
        with self._lock:
            return sorted(self.txn_prepared.get(txn_id, {}))


def _parse_brokers(brokers: str):
    if brokers.startswith("memory://"):
        return ("memory", brokers[len("memory://"):])
    return ("kafka", brokers)


def _require_kafka_client():
    try:
        import confluent_kafka  # noqa: F401
        return "confluent"
    except ImportError:
        pass
    try:
        import kafka  # noqa: F401
        return "kafka-python"
    except ImportError:
        raise WindFlowError(
            "Kafka connector: no Kafka client library available "
            "(confluent_kafka / kafka-python); use a memory:// broker or "
            "install a client") from None


# ---------------------------------------------------------------------------
# Transports: the replica loops speak this small interface; memory:// is
# the in-process test transport, real brokers go through confluent_kafka
# or kafka-python (the reference links librdkafka directly,
# ``kafka_source.hpp:127-519`` / ``kafka_sink.hpp:71-379``)
# ---------------------------------------------------------------------------
class MemoryTransport:
    supports_transactions = True

    def __init__(self, name: str) -> None:
        self.broker = MemoryBroker.get(name)
        self._parts: List[Tuple[str, int]] = []
        self._pos: Dict[Tuple[str, int], int] = {}
        self._rr = 0
        self._group = "windflow"
        self.on_retry = None  # in-process broker: no transient failures

    def _transient_excs(self) -> tuple:
        return ()

    def subscribe(self, topics, group, member, n_members, offsets) -> bool:
        self._group = group
        if offsets:
            # explicit offsets = explicit assignment of ONLY the listed
            # partitions (identical semantics to the real transports)
            for (t, p), o in _member_share(offsets, member,
                                           n_members).items():
                self._parts.append((t, p))
                self._pos[(t, p)] = o
        else:
            for t in topics:
                for p in self.broker.assign_partitions(t, group, member,
                                                       n_members):
                    self._parts.append((t, p))
                    self._pos[(t, p)] = 0
        return bool(self._parts)

    def consume(self) -> Optional[KafkaMessage]:
        for _ in range(len(self._parts)):
            tp = self._parts[self._rr]
            self._rr = (self._rr + 1) % len(self._parts)
            msg = self.broker.poll(tp[0], tp[1], self._pos[tp])
            if msg is not None:
                self._pos[tp] += 1
                return msg
        return None

    def consume_batch(self, max_n: int) -> List[KafkaMessage]:
        """Batch poll for the columnar block adapter: up to ``max_n``
        messages as contiguous per-partition runs (round-robin across
        assigned partitions), advancing the same per-partition cursors
        ``snapshot_positions`` records — offset semantics are identical
        to the per-message path."""
        out: List[KafkaMessage] = []
        for _ in range(len(self._parts)):
            if len(out) >= max_n:
                break
            tp = self._parts[self._rr]
            self._rr = (self._rr + 1) % len(self._parts)
            run = self.broker.poll_run(tp[0], tp[1], self._pos[tp],
                                       max_n - len(out))
            if run:
                self._pos[tp] += len(run)
                out.extend(run)
        return out

    def produce(self, topic, payload, partition=None, key=None) -> None:
        self.broker.produce(topic, payload, partition, key)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    # -- checkpointing -----------------------------------------------------
    def snapshot_positions(self) -> Dict[Tuple[str, int], int]:
        """Next-to-consume offset per assigned partition (the replayable
        cursor a checkpoint records)."""
        return dict(self._pos)

    def commit_offsets(self, offsets: Dict[Tuple[str, int], int]) -> None:
        """Group-offset commit on checkpoint finalize (at-least-once: a
        restart WITHOUT a checkpoint resumes from these)."""
        with self.broker._lock:
            for (t, p), o in offsets.items():
                self.broker.committed[(self._group, t, p)] = o


def _member_share(offsets, member: int, n_members: int):
    """Deterministic split of explicitly-assigned partitions across the
    replica group (partition p -> member p % n_members, the same rule
    MemoryBroker.assign_partitions uses). ALL transports treat a
    non-empty offsets map as an explicit assignment: only the listed
    partitions are consumed, from the given positions — so memory:// and
    real brokers behave identically."""
    return {(t, p): o for (t, p), o in offsets.items()
            if p % n_members == member}


class ConfluentTransport:
    """confluent_kafka (librdkafka) adapter. ``module`` is injectable for
    tests (a fake with Consumer/Producer/TopicPartition)."""

    supports_transactions = True  # librdkafka transactional producer

    def __init__(self, brokers: str, module=None) -> None:
        if module is None:
            import confluent_kafka as module  # noqa: PLC0415
        self._ck = module
        self.brokers = brokers
        self._consumer = None
        self._producer = None
        self._txn_producer_obj = None
        self._delivery_errors = 0
        # checkpointing turns auto-commit OFF: offsets commit only when
        # the coordinator finalizes a checkpoint (at-least-once end to
        # end); KafkaSourceReplica flips this before subscribe
        self.auto_commit = True
        # transient-error retry: the owning replica wires this to its
        # Kafka_reconnects counter
        self.on_retry = None

    def _transient_excs(self) -> tuple:
        exc = getattr(self._ck, "KafkaException", None)
        return (exc,) if isinstance(exc, type) else ()

    def subscribe(self, topics, group, member, n_members, offsets) -> bool:
        ck = self._ck

        def _connect():
            return ck.Consumer({
                "bootstrap.servers": self.brokers,
                "group.id": group,
                "enable.auto.commit": self.auto_commit,
                "auto.offset.reset": "earliest",
            })

        self._consumer = _retrying(self, _connect, "consumer connect")
        if offsets:
            # explicit offsets = explicit assignment (reference
            # kafka_source.hpp manual-offset mode): the listed partitions
            # are split across the replica group deterministically so
            # parallel replicas never double-consume
            mine = _member_share(offsets, member, n_members)
            if not mine:
                return False
            self._consumer.assign([ck.TopicPartition(t, p, o)
                                   for (t, p), o in mine.items()])
        else:
            self._consumer.subscribe(list(topics))
        return True

    def consume(self) -> Optional[KafkaMessage]:
        msg = _retrying(self, lambda: self._consumer.poll(0.01), "consume")
        if msg is None:
            return None
        err = msg.error()
        if err is not None:
            if getattr(err, "fatal", lambda: False)():
                raise WindFlowError(f"Kafka consumer error: {err}")
            return None  # transient (e.g. partition EOF)
        ts = msg.timestamp()
        ts_us = ts[1] * 1000 if ts and ts[1] > 0 else current_time_usecs()
        return KafkaMessage(msg.topic(), msg.partition(), msg.offset(),
                            msg.value(), ts_us)

    def consume_batch(self, max_n: int) -> List[KafkaMessage]:
        """librdkafka batch poll (``Consumer.consume``); falls back to
        repeated single polls when the client (or an injected fake)
        lacks it. Transient per-message errors are skipped, fatal ones
        raise — same policy as ``consume``."""
        batch_fn = getattr(self._consumer, "consume", None)
        if batch_fn is None:
            out = []
            while len(out) < max_n:
                m = self.consume()
                if m is None:
                    break
                out.append(m)
            return out
        msgs = _retrying(self, lambda: batch_fn(max_n, 0.01), "consume")
        out = []
        for msg in msgs or ():
            err = msg.error()
            if err is not None:
                if getattr(err, "fatal", lambda: False)():
                    raise WindFlowError(f"Kafka consumer error: {err}")
                continue
            ts = msg.timestamp()
            ts_us = (ts[1] * 1000 if ts and ts[1] > 0
                     else current_time_usecs())
            out.append(KafkaMessage(msg.topic(), msg.partition(),
                                    msg.offset(), msg.value(), ts_us))
        return out

    def _ensure_producer(self):
        if self._producer is None:
            self._producer = self._ck.Producer(
                {"bootstrap.servers": self.brokers})
            self._delivery_errors = 0

        return self._producer

    def _on_delivery(self, err, msg) -> None:
        if err is not None:
            self._delivery_errors += 1

    def produce(self, topic, payload, partition=None, key=None) -> None:
        kwargs = {"on_delivery": self._on_delivery}
        if partition is not None:
            kwargs["partition"] = partition
        if key is not None:
            kwargs["key"] = key
        p = self._ensure_producer()

        def _produce_once():
            p.produce(topic, value=payload, **kwargs)

        for attempt in range(60):
            try:
                _retrying(self, _produce_once, "produce")
                break
            except BufferError:
                # local librdkafka queue full: backpressure, don't crash
                p.poll(1.0)
        else:
            raise WindFlowError(
                "Kafka sink: local producer queue stayed full for 60s")
        p.poll(0)  # serve delivery callbacks

    def flush(self) -> None:
        if self._producer is None:
            return
        remaining = self._producer.flush(10)
        if remaining or self._delivery_errors:
            raise WindFlowError(
                f"Kafka sink lost data: {self._delivery_errors} delivery "
                f"error(s), {remaining or 0} message(s) still queued at "
                "flush timeout")

    def close(self) -> None:
        if self._consumer is not None:
            self._consumer.close()

    # -- transactions (exactly-once sinks) ---------------------------------
    def txn_produce_epoch(self, txn_id: str, records) -> None:
        """Produce one finalized epoch atomically inside a Kafka
        transaction: consumers in ``read_committed`` see the whole epoch
        or none of it. The transactional id is stable per sink replica,
        so a zombie pre-rebuild producer is fenced by the broker itself
        (``init_transactions`` bumps the producer epoch)."""
        ck = self._ck
        if self._txn_producer_obj is None:
            p = ck.Producer({"bootstrap.servers": self.brokers,
                             "transactional.id": txn_id,
                             "enable.idempotence": True})
            p.init_transactions(30.0)
            self._txn_producer_obj = p
        p = self._txn_producer_obj
        p.begin_transaction()
        try:
            for topic, partition, key, payload in records:
                kwargs = {"on_delivery": self._on_delivery}
                if partition is not None:
                    kwargs["partition"] = partition
                if key is not None:
                    kwargs["key"] = key
                p.produce(topic, value=payload, **kwargs)
            remaining = p.flush(10)
            if remaining or self._delivery_errors:
                raise WindFlowError(
                    f"Kafka exactly-once sink: {self._delivery_errors} "
                    f"delivery error(s), {remaining or 0} message(s) "
                    "unflushed inside the epoch transaction")
            p.commit_transaction(30.0)
        except Exception:
            try:
                p.abort_transaction(10.0)
            except Exception:
                pass  # surfacing the original failure matters more
            raise

    # -- checkpointing -----------------------------------------------------
    def snapshot_positions(self) -> Dict[Tuple[str, int], int]:
        if self._consumer is None:
            return {}
        try:
            tps = self._consumer.assignment()
            return {(tp.topic, tp.partition): tp.offset
                    for tp in self._consumer.position(tps)
                    if tp.offset >= 0}
        except Exception:
            return {}

    def commit_offsets(self, offsets: Dict[Tuple[str, int], int]) -> None:
        if self._consumer is None or not offsets:
            return
        ck = self._ck
        try:
            self._consumer.commit(
                offsets=[ck.TopicPartition(t, p, o)
                         for (t, p), o in offsets.items()],
                asynchronous=False)
        except Exception:
            pass  # best effort: a failed commit only widens the replay


class KafkaPythonTransport:
    """kafka-python adapter (pure-python client). ``module`` injectable."""

    supports_transactions = False  # no transactional producer in kafka-python

    def __init__(self, brokers: str, module=None) -> None:
        if module is None:
            import kafka as module  # noqa: PLC0415
        self._kp = module
        self.brokers = brokers.split(",")
        self._consumer = None
        self._producer = None
        self.auto_commit = True  # see ConfluentTransport
        self.on_retry = None

    def _transient_excs(self) -> tuple:
        exc = getattr(getattr(self._kp, "errors", None), "KafkaError", None)
        return (exc,) if isinstance(exc, type) else ()

    def subscribe(self, topics, group, member, n_members, offsets) -> bool:
        kp = self._kp

        def _connect():
            return kp.KafkaConsumer(
                bootstrap_servers=self.brokers, group_id=group,
                enable_auto_commit=self.auto_commit,
                auto_offset_reset="earliest")

        self._consumer = _retrying(self, _connect, "consumer connect")
        if offsets:
            mine = _member_share(offsets, member, n_members)
            if not mine:
                return False
            tps = [kp.TopicPartition(t, p) for (t, p) in mine]
            self._consumer.assign(tps)
            for (t, p), o in mine.items():
                self._consumer.seek(kp.TopicPartition(t, p), o)
        else:
            self._consumer.subscribe(list(topics))
        return True

    def consume(self) -> Optional[KafkaMessage]:
        polled = _retrying(
            self, lambda: self._consumer.poll(timeout_ms=10, max_records=1),
            "consume")
        for _tp, records in polled.items():
            for r in records:
                ts_us = (r.timestamp * 1000 if getattr(r, "timestamp", 0)
                         else current_time_usecs())
                return KafkaMessage(r.topic, r.partition, r.offset,
                                    r.value, ts_us)
        return None

    def consume_batch(self, max_n: int) -> List[KafkaMessage]:
        """kafka-python batch poll: one ``poll(max_records=max_n)``
        flattened across partitions (records within a partition stay in
        offset order)."""
        polled = _retrying(
            self, lambda: self._consumer.poll(timeout_ms=10,
                                              max_records=max_n),
            "consume")
        out = []
        for _tp, records in polled.items():
            for r in records:
                ts_us = (r.timestamp * 1000 if getattr(r, "timestamp", 0)
                         else current_time_usecs())
                out.append(KafkaMessage(r.topic, r.partition, r.offset,
                                        r.value, ts_us))
        return out

    def _ensure_producer(self):
        if self._producer is None:
            self._producer = self._kp.KafkaProducer(
                bootstrap_servers=self.brokers)
        return self._producer

    def produce(self, topic, payload, partition=None, key=None) -> None:
        p = self._ensure_producer()
        _retrying(self, lambda: p.send(topic, value=payload,
                                       partition=partition, key=key),
                  "produce")

    def flush(self) -> None:
        if self._producer is not None:
            self._producer.flush(timeout=10)

    def close(self) -> None:
        if self._consumer is not None:
            self._consumer.close()

    # -- checkpointing -----------------------------------------------------
    def snapshot_positions(self) -> Dict[Tuple[str, int], int]:
        if self._consumer is None:
            return {}
        try:
            return {(tp.topic, tp.partition): self._consumer.position(tp)
                    for tp in self._consumer.assignment()}
        except Exception:
            return {}

    def commit_offsets(self, offsets: Dict[Tuple[str, int], int]) -> None:
        if self._consumer is None or not offsets:
            return
        kp = self._kp
        try:
            self._consumer.commit(
                {kp.TopicPartition(t, p): kp.OffsetAndMetadata(o, None)
                 for (t, p), o in offsets.items()})
        except Exception:
            pass  # best effort: a failed commit only widens the replay


def make_transport(brokers: str):
    """memory:// -> MemoryTransport; anything else -> the first available
    real client (confluent_kafka preferred, then kafka-python)."""
    kind, target = _parse_brokers(brokers)
    if kind == "memory":
        return MemoryTransport(target)
    client = _require_kafka_client()
    if client == "confluent":
        return ConfluentTransport(target)
    return KafkaPythonTransport(target)


# ---------------------------------------------------------------------------
# Kafka_Source
# ---------------------------------------------------------------------------
class Kafka_Source(BasicOperator):
    """Replicas share a consumer group: partitions split across replicas;
    the user deserialization functor receives (Optional[KafkaMessage],
    shipper) and returns False to stop consuming (``kafka_source.hpp``:
    deser functor returns a continue flag; None message = idle timeout).

    Columnar block mode (``with_columnar_blocks`` on the builder): the
    SAME functor slot instead receives a non-empty LIST of KafkaMessages
    per call (one batch poll, up to ``block_size``) and is expected to
    decode them vectorized and call ``shipper.push_columns`` — no
    per-tuple Python on the hot path. ``None`` still signals the idle
    timeout and ``False`` still stops. Offsets snapshot per-partition
    exactly as in per-message mode (the batch poll advances the same
    cursors), and barriers inject only BETWEEN polls, so the checkpoint
    covers exactly the shipped blocks."""

    op_type = OpType.SOURCE

    def __init__(self, deser_func: Callable, brokers: str,
                 topics: List[str], group_id: str = "windflow",
                 offsets: Optional[Dict[Tuple[str, int], int]] = None,
                 idleness_ms: int = 100, name: str = "kafka_source",
                 parallelism: int = 1, output_batch_size: int = 0) -> None:
        super().__init__(name, parallelism, RoutingMode.NONE,
                         output_batch_size=output_batch_size)
        self.deser_func = deser_func
        self.brokers = brokers
        self.topics = list(topics)
        self.group_id = group_id
        self.offsets = dict(offsets or {})
        self.idleness_ms = idleness_ms
        self._riched = arity(deser_func) >= 3
        self.block_mode = False    # set by with_columnar_blocks
        self.block_size = 512
        kind, _ = _parse_brokers(brokers)
        if kind != "memory":
            _require_kafka_client()

    def build_replicas(self) -> None:
        self.replicas = [KafkaSourceReplica(self, i)
                         for i in range(self.parallelism)]


class KafkaSourceReplica(BasicReplica):
    def __init__(self, op, idx):
        super().__init__(op, idx)
        # aligned checkpointing (windflow_tpu.checkpoint): barriers inject
        # BETWEEN Kafka messages (never between the pushes of one deser
        # call) so the snapshot offsets cover exactly the shipped prefix
        self._coord = None
        self._inject_cb = None
        self._last_ckpt = 0
        self._restore_offsets: Optional[Dict[Tuple[str, int], int]] = None
        self._transport = None
        # offsets captured at each injected barrier, committed to the
        # broker only when the coordinator finalizes that checkpoint —
        # from THIS thread (consumers are not thread-safe): the finalize
        # listener only flips _commit_ready
        self._pending_commits: Dict[int, Dict[Tuple[str, int], int]] = {}
        self._commit_ready = 0
        self._committed = 0
        # overload admission control (windflow_tpu.overload): installed
        # by the governor while shedding, same contract as
        # SourceReplica._gate (shed before emit; a shed Kafka record's
        # offset is already consumed, so it never replays)
        self._gate = None
        # gate-buffered records caught by a snapshot (their offsets are
        # already consumed — run_source re-emits them after restore)
        self._restore_gate_pending = None

    def process(self, payload, ts, wm, tag):  # pragma: no cover
        raise WindFlowError("Kafka_Source has no input")

    def _note_reconnect(self) -> None:
        """Transport retry hook: one transient-error retry/reconnect
        (``Kafka_reconnects`` / ``windflow_kafka_reconnects_total``)."""
        self.stats.kafka_reconnects += 1

    # -- checkpointing -----------------------------------------------------
    def bind_checkpoint(self, coordinator, inject_cb) -> None:
        self._coord = coordinator
        self._inject_cb = inject_cb
        self._last_ckpt = coordinator.requested_id
        coordinator.add_finalize_listener(self._on_finalized)

    def request_checkpoint(self):
        # injection happens at the consume loop's next message boundary
        return None if self._coord is None \
            else self._coord.trigger(force=True)

    def _on_finalized(self, ckpt_id: int) -> None:
        # runs on another worker's thread: only publish the watermark
        if ckpt_id > self._commit_ready:
            self._commit_ready = ckpt_id

    def _maybe_inject(self) -> None:
        from ..message import Barrier
        cid = self._coord.requested_id
        if cid > self._last_ckpt:
            self._last_ckpt = cid
            if self._transport is not None:
                self._pending_commits[cid] = \
                    self._transport.snapshot_positions()
            self._inject_cb(Barrier(cid))

    def final_checkpoint(self) -> None:
        """Worker hook at consume-loop exit (see SourceReplica): inject a
        pending epoch's barrier with the final offsets before EOS."""
        if self._coord is not None and self._transport is not None:
            if self._coord.requested_id != self._last_ckpt:
                self._maybe_inject()
            self._maybe_commit()

    def _maybe_commit(self) -> None:
        ready = self._commit_ready
        if ready <= self._committed or self._transport is None:
            return
        best = max((c for c in self._pending_commits if c <= ready),
                   default=None)
        if best is not None:
            self._transport.commit_offsets(self._pending_commits[best])
            for c in [c for c in self._pending_commits if c <= best]:
                del self._pending_commits[c]
        self._committed = ready

    def snapshot_state(self) -> dict:
        st = super().snapshot_state()
        if self._transport is not None:
            # keys are (topic, partition) tuples — pickle keeps them
            st["offsets"] = self._transport.snapshot_positions()
        # shed accounting rides the snapshot (same contract as
        # SourceReplica): restore must not zero permanent drops
        st["shed_records"] = self.stats.shed_records
        st["shed_bytes"] = self.stats.shed_bytes
        gate = self._gate
        if gate is not None and gate.pending:
            # records accepted into the gate but still awaiting tokens:
            # their offsets are covered by the snapshot positions above,
            # so they never replay from the broker — they must ride the
            # snapshot or a restore loses them (neither admitted nor
            # shed)
            st["gate_pending"] = gate.snapshot_pending()
        return st

    def restore_state(self, state: dict) -> None:
        super().restore_state(state)
        offs = state.get("offsets")
        if offs is not None:
            self._restore_offsets = dict(offs)
        self._restore_gate_pending = state.get("gate_pending")
        self.stats.shed_records = state.get("shed_records", 0)
        self.stats.shed_bytes = state.get("shed_bytes", 0)

    def run_source(self) -> None:
        op = self.op
        pend = self._restore_gate_pending
        if pend:
            # re-emit the snapshot's gate-buffered records before the
            # consume loop resumes (their offsets never replay); ahead
            # of the subscribe so a no-partition early return cannot
            # drop them
            self._restore_gate_pending = None
            for p, t, w in pend:
                self._advance_wm(w)
                self._emit_admitted(p, t)
        transport = make_transport(op.brokers)
        if self._coord is not None and hasattr(transport, "auto_commit"):
            transport.auto_commit = False  # commits ride checkpoints only
        transport.on_retry = self._note_reconnect
        self._transport = transport
        offsets = op.offsets
        if self._restore_offsets is not None:
            # resume from the checkpoint's recorded positions. The
            # snapshot was taken per replica AFTER the group share split,
            # so it is already this member's slice — subscribe must not
            # re-split it (member 0 of 1): same-parallelism restore maps
            # replica idx -> its own recorded partitions
            offsets = self._restore_offsets
            member, n_members = 0, 1
        else:
            member, n_members = self.idx, op.parallelism
        try:
            if not transport.subscribe(op.topics, op.group_id, member,
                                       n_members, offsets):
                return
            self._consume_loop(transport)
            gate = self._gate
            if gate is not None and gate.pending:
                # end-of-stream with records still buffered in the
                # gate: they were ACCEPTED (only awaiting tokens) —
                # emit before the final barrier injects, mirroring
                # SourceReplica.run_source
                for p, t, w in gate.drain_pending():
                    self._advance_wm(w)
                    self._emit_admitted(p, t)
        finally:
            # the worker's final_checkpoint hook runs after run_source —
            # too late for the transport; inject any pending epoch with
            # the final offsets here, while the consumer is still open
            self.final_checkpoint()
            transport.close()
            self._transport = None

    def _consume_loop(self, transport) -> None:
        op = self.op
        shipper = SourceShipper(self)
        idle_budget_us = op.idleness_ms * 1000
        last_progress = current_time_usecs()
        block_n = op.block_size if op.block_mode else 0
        while True:
            if self._coord is not None:
                if self._coord.requested_id != self._last_ckpt:
                    self._maybe_inject()
                self._maybe_commit()
            if block_n:
                # columnar block mode: one batch poll, the functor
                # decodes the whole list vectorized (push_columns).
                # Barriers land only between polls — the offsets
                # snapshotted at injection cover exactly the blocks
                # already shipped, same cursor semantics as per-message
                msgs = transport.consume_batch(block_n)
                if msgs:
                    last_progress = current_time_usecs()
                    cont = (op.deser_func(msgs, shipper, self.context)
                            if op._riched else op.deser_func(msgs, shipper))
                    if cont is False:
                        return
                    continue
            else:
                msg = transport.consume()
                if msg is not None:
                    last_progress = current_time_usecs()
                    cont = (op.deser_func(msg, shipper, self.context)
                            if op._riched else op.deser_func(msg, shipper))
                    if cont is False:
                        return
                    continue
            if current_time_usecs() - last_progress > idle_budget_us:
                # idle timeout: give the functor a chance to stop
                cont = (op.deser_func(None, shipper, self.context)
                        if op._riched else op.deser_func(None, shipper))
                if cont is False:
                    return
                last_progress = current_time_usecs()
            time.sleep(0.001)

    def ship(self, payload: Any, ts: int, wm: int) -> None:
        gate = self._gate
        if gate is not None:
            # watermark rides each record through the gate (see
            # SourceReplica.ship): a buffered record emits under its
            # accept-time watermark, never one the stream advanced to
            # while it waited
            for p, t, w in gate.offer(payload, ts, wm):
                self._advance_wm(w)
                self._emit_admitted(p, t)
            if gate.released and not gate.pending:
                self._gate = None
            return
        if wm > self.cur_wm:
            self.cur_wm = wm
        self._emit_admitted(payload, ts)

    def _emit_admitted(self, payload: Any, ts: int) -> None:
        st = self.stats
        st.inputs_received += 1
        # sampled latency tracing, same mask gate as SourceReplica.ship
        if not (st.inputs_received & (st.sample_every - 1)):
            self.emitter.trace_ts = current_time_usecs()
        self.emitter.emit(payload, ts, self.cur_wm)

    def ship_columns(self, cols, ts_arr, wm: int) -> None:
        """Columnar twin of ``ship`` (``shipper.push_columns`` lands
        here): same gate / watermark / trace contract as
        ``SourceReplica.ship_columns``, minus barrier injection — in the
        Kafka loop barriers land between polls, never inside a block."""
        t0_ns = time.perf_counter_ns()
        gate = self._gate
        if gate is not None:
            if gate.pending:
                # row-path records accepted into the gate's buffer
                # precede this block: emit them first (accept-time
                # watermarks) or the stream reorders
                for p, t, w in gate.drain_pending():
                    self._advance_wm(w)
                    self._emit_admitted(p, t)
            if gate.released:
                self._gate = None
            else:
                cols, ts_arr, n = gate.offer_columns(cols, ts_arr)
                if n == 0:
                    return
        if wm > self.cur_wm:
            self.cur_wm = wm
        st = self.stats
        n = len(ts_arr)
        base = st.inputs_received
        st.inputs_received = base + n
        trace_rows = None
        se = st.sample_every
        if se:
            # vectorized mask gate — the cohort the row path would stamp
            first = (-(base + 1)) % se
            if first < n:
                trace_rows = np.arange(first, n, se)
                self.emitter.trace_ts = current_time_usecs()
        self.emitter.emit_columns(cols, ts_arr, self.cur_wm, trace_rows)
        st.note_ingest_block(n, time.perf_counter_ns() - t0_ns)



# ---------------------------------------------------------------------------
# Kafka_Sink
# ---------------------------------------------------------------------------
class Kafka_Sink(BasicOperator):
    """User serializer returns (topic, partition_or_None, payload) or None
    to drop (``kafka_sink.hpp``: wf_kafka_sink_msg)."""

    op_type = OpType.SINK
    # exactly-once mode (windflow_tpu.sinks.transactional): epoch
    # transactions on the broker — prepared at the barrier, committed
    # only on coordinator finalize, zombie producers fenced
    supports_exactly_once = True

    def __init__(self, ser_func: Callable, brokers: str,
                 name: str = "kafka_sink", parallelism: int = 1) -> None:
        super().__init__(name, parallelism, RoutingMode.FORWARD)
        self.ser_func = ser_func
        self.brokers = brokers
        self._riched = arity(ser_func) >= 2
        kind, _ = _parse_brokers(brokers)
        if kind != "memory":
            _require_kafka_client()
        self.exactly_once = False
        self.txn_dir: Optional[str] = None  # staging root (real brokers)

    def build_replicas(self) -> None:
        cls = TxnKafkaSinkReplica if self.exactly_once else KafkaSinkReplica
        self.replicas = [cls(self, i) for i in range(self.parallelism)]


class KafkaSinkReplica(BasicReplica):
    def __init__(self, op, idx):
        super().__init__(op, idx)
        self._transport = make_transport(op.brokers)
        self._transport.on_retry = self._note_reconnect
        # terminal operator: record end-to-end latency of traced tuples
        self._e2e = self.stats.hist_e2e

    def _note_reconnect(self) -> None:
        self.stats.kafka_reconnects += 1

    def process(self, payload, ts, wm, tag):
        out = (self.op.ser_func(payload, self.context) if self.op._riched
               else self.op.ser_func(payload))
        if out is None:
            return
        topic, partition, data = out
        self._transport.produce(topic, data, partition)

    # -- checkpointing -----------------------------------------------------
    def snapshot_state(self) -> dict:
        # flush the producer and fail LOUDLY on delivery errors before
        # this worker's ack can let the coordinator count the epoch
        # finalized: a lost in-flight produce used to be silent — the
        # checkpoint then recorded source offsets past data that never
        # reached the broker, and a restart skipped it forever
        self._transport.flush()
        return super().snapshot_state()

    def flush_on_termination(self) -> None:
        self._transport.flush()
        self._transport.close()


# ---------------------------------------------------------------------------
# Exactly-once Kafka sink: epoch transactions driven by the checkpoint
# coordinator (windflow_tpu.sinks.transactional)
# ---------------------------------------------------------------------------
class _MemoryTxnBackend:
    """2PC backend over ``MemoryBroker``'s transaction log: prepared
    epochs live in the broker (they survive the producer's death, like a
    real broker's transaction markers) and zombie generations are fenced
    broker-side."""

    def __init__(self, broker: MemoryBroker, txn_id: str) -> None:
        self.broker = broker
        self.txn_id = txn_id
        self.gen = broker.txn_init(txn_id)

    def check_fence(self) -> None:
        self.broker.txn_check(self.txn_id, self.gen)

    def is_committed(self, epoch: int) -> bool:
        return self.broker.txn_is_committed(self.txn_id, epoch)

    def do_precommit(self, epoch: int, records) -> None:
        self.broker.txn_prepare(self.txn_id, self.gen, epoch, records)

    def do_commit(self, epoch: int):
        self.broker.txn_commit(self.txn_id, self.gen, epoch)
        return None  # no functor delivery: the topic IS the output

    def do_abort(self, epoch: int) -> None:
        self.broker.txn_abort(self.txn_id, self.gen, epoch)

    def do_recover(self, last_epoch: int):
        rolled, aborted = [], []
        for epoch in self.broker.txn_prepared_epochs(self.txn_id):
            if epoch <= last_epoch:
                if self.broker.txn_commit(self.txn_id, self.gen, epoch):
                    rolled.append((epoch, None))
            else:
                self.broker.txn_abort(self.txn_id, self.gen, epoch)
                aborted.append(epoch)
        return rolled, aborted


class _StagedKafkaBackend:
    """Real-broker backend: epochs stage durably in a local
    ``EpochSegmentStore`` (the broker holds nothing until finalize), and
    each commit produces the whole epoch inside one Kafka transaction
    (``txn_produce_epoch``) so ``read_committed`` consumers see epochs
    atomically. The local ``.seg`` rename is the commit marker; the
    window between the broker transaction committing and the rename is
    the one crash window that can duplicate an epoch on roll-forward
    (closing it needs Kafka's resumable-transaction surface, which the
    plain client API does not expose — documented in docs/API.md)."""

    def __init__(self, root: str, transport, txn_id: str) -> None:
        from ..sinks.transactional import SegmentBackend
        self._seg = SegmentBackend(root)
        self.transport = transport
        self.txn_id = txn_id

    def is_committed(self, epoch: int) -> bool:
        return self._seg.is_committed(epoch)

    def do_precommit(self, epoch: int, records) -> None:
        self._seg.do_precommit(epoch, records)

    def do_commit(self, epoch: int):
        import pickle as _pickle
        records = self._seg._records.get(epoch)
        if records is None and not self._seg.is_committed(epoch):
            records = _pickle.loads(self._seg.store.read(epoch,
                                                         pending=True))
        if records:
            self.transport.txn_produce_epoch(self.txn_id, records)
        self._seg.do_commit(epoch)
        return None

    def do_abort(self, epoch: int) -> None:
        self._seg.do_abort(epoch)

    def do_recover(self, last_epoch: int):
        import pickle as _pickle
        self._seg.store.reap_tmp()
        rolled, aborted = [], []
        for epoch in self._seg.store.pending_epochs():
            if epoch <= last_epoch:
                records = _pickle.loads(
                    self._seg.store.read(epoch, pending=True))
                if records:
                    self.transport.txn_produce_epoch(self.txn_id, records)
                self._seg.store.commit(epoch)
                rolled.append((epoch, None))
            else:
                self._seg.store.abort(epoch)
                aborted.append(epoch)
        return rolled, aborted


class TxnKafkaSinkReplica(KafkaSinkReplica):
    """Kafka sink in exactly-once mode: serialized records buffer per
    epoch, prepare on the broker (memory://) or in a local staged
    segment (real brokers) at the barrier, and reach the topic only when
    the coordinator finalizes the epoch. The transactional id
    ``wf-txn-<op>-r<idx>`` is stable across restarts and rebuilds, so
    zombie replicas left unwinding by a rescale are fenced."""

    def __init__(self, op, idx):
        super().__init__(op, idx)
        from ..sinks.transactional import EpochTxnDriver, txn_dir_for
        txn_id = f"wf-txn-{op.name}-r{idx}"
        if isinstance(self._transport, MemoryTransport):
            backend = _MemoryTxnBackend(self._transport.broker, txn_id)
        elif getattr(self._transport, "supports_transactions", False):
            backend = _StagedKafkaBackend(
                txn_dir_for(op.name, idx, op.txn_dir), self._transport,
                txn_id)
        else:
            raise WindFlowError(
                f"{op.name}: exactly-once needs a transactional producer "
                "— use a memory:// broker or confluent_kafka "
                "(kafka-python has no transactions)")
        self._txn = EpochTxnDriver(backend, self.stats)
        self.on_idle = self._txn.poll

    def process(self, payload, ts, wm, tag):
        out = (self.op.ser_func(payload, self.context) if self.op._riched
               else self.op.ser_func(payload))
        if out is None:
            return
        check = getattr(self._txn.backend, "check_fence", None)
        if check is not None:
            try:
                check()
            except FencedWriteError:
                self.stats.txn_fenced_writes += 1
                raise
        topic, partition, data = out
        self._txn.buffer.append((topic, partition, None, data))

    def handle_msg(self, ch, msg):
        t = self._txn
        if t._pending and min(t._pending) <= t._commit_ready:
            t.poll()
        super().handle_msg(ch, msg)

    # -- worker / coordinator hooks ----------------------------------------
    def bind_txn_coordinator(self, coordinator) -> None:
        self._txn.bind(coordinator)

    def precommit_epoch(self, ckpt_id: int) -> None:
        self._txn.precommit_epoch(ckpt_id)

    def snapshot_state(self) -> dict:
        st = BasicReplica.snapshot_state(self)  # no blind producer flush:
        st.update(self._txn.snapshot())  # records ride the epoch txn
        return st

    def restore_state(self, state: dict) -> None:
        BasicReplica.restore_state(self, state)
        self._txn.restore(state)

    def flush_on_termination(self) -> None:
        # EOS: stage the post-barrier tail as one final epoch; it (and
        # any not-yet-finalized epoch) commits in txn_complete once the
        # run is known to have finished cleanly
        self._txn.seal_tail()

    def txn_complete(self) -> None:
        self._txn.complete_all()
        self._transport.flush()
        self._transport.close()
