"""Kafka connectors: external ingestion/egress with replayable offsets.

Parity: ``wf/kafka/kafka_source.hpp:127-519`` (consumer-group replicas, a
poll loop with idle timeout, a user deserialization functor returning a
continue flag, explicit start offsets) and ``wf/kafka/kafka_sink.hpp:71-379``
(user serializer returning (topic, partition, payload)).

The reference links librdkafka; this image has no Kafka client library, so
the transport is pluggable:

- broker string ``"memory://<name>"`` uses the built-in in-process
  ``MemoryBroker`` (partitioned topics, offsets, consumer groups) — this is
  what the tests run against and it exercises the full replay/offset
  surface;
- any other broker string requires ``confluent_kafka`` or ``kafka-python``
  at runtime; absence raises a clear error at build() (capability gated,
  not stubbed silently).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..basic import OpType, RoutingMode, WindFlowError, current_time_usecs
from ..operators.base import BasicOperator, BasicReplica, arity
from ..operators.source import SourceShipper


class KafkaMessage:
    __slots__ = ("topic", "partition", "offset", "payload", "timestamp")

    def __init__(self, topic, partition, offset, payload, timestamp) -> None:
        self.topic = topic
        self.partition = partition
        self.offset = offset
        self.payload = payload
        self.timestamp = timestamp


# ---------------------------------------------------------------------------
# In-process broker (the test transport)
# ---------------------------------------------------------------------------
class MemoryBroker:
    _registry: Dict[str, "MemoryBroker"] = {}
    _reg_lock = threading.Lock()

    def __init__(self, name: str, n_partitions: int = 4) -> None:
        self.name = name
        self.n_partitions = n_partitions
        self._topics: Dict[str, List[List[KafkaMessage]]] = {}
        self._lock = threading.Lock()
        self._group_assign: Dict[Tuple[str, str], Dict[int, int]] = {}

    @classmethod
    def get(cls, name: str, n_partitions: int = 4) -> "MemoryBroker":
        with cls._reg_lock:
            b = cls._registry.get(name)
            if b is None:
                b = cls._registry[name] = MemoryBroker(name, n_partitions)
            return b

    @classmethod
    def reset(cls) -> None:
        with cls._reg_lock:
            cls._registry.clear()

    def _topic(self, topic: str) -> List[List[KafkaMessage]]:
        with self._lock:
            t = self._topics.get(topic)
            if t is None:
                t = self._topics[topic] = [[] for _ in range(self.n_partitions)]
            return t

    def produce(self, topic: str, payload: Any,
                partition: Optional[int] = None, key: Any = None) -> None:
        t = self._topic(topic)
        with self._lock:
            if partition is None:
                partition = (hash(key) % self.n_partitions if key is not None
                             else sum(len(p) for p in t) % self.n_partitions)
            part = t[partition % self.n_partitions]
            part.append(KafkaMessage(topic, partition % self.n_partitions,
                                     len(part), payload,
                                     current_time_usecs()))

    def assign_partitions(self, topic: str, group: str, member: int,
                          n_members: int) -> List[int]:
        """Cooperative assignment: partition p -> member p % n_members
        (the reference relies on Kafka's group rebalance,
        ``kafka_source.hpp:77-115``)."""
        return [p for p in range(self.n_partitions) if p % n_members == member]

    def poll(self, topic: str, partition: int, offset: int
             ) -> Optional[KafkaMessage]:
        t = self._topic(topic)
        with self._lock:
            part = t[partition]
            if offset < len(part):
                return part[offset]
        return None

    def end_offset(self, topic: str, partition: int) -> int:
        t = self._topic(topic)
        with self._lock:
            return len(t[partition])


def _parse_brokers(brokers: str):
    if brokers.startswith("memory://"):
        return ("memory", brokers[len("memory://"):])
    return ("kafka", brokers)


def _require_kafka_client():
    try:
        import confluent_kafka  # noqa: F401
        return "confluent"
    except ImportError:
        pass
    try:
        import kafka  # noqa: F401
        return "kafka-python"
    except ImportError:
        raise WindFlowError(
            "Kafka connector: no Kafka client library available "
            "(confluent_kafka / kafka-python); use a memory:// broker or "
            "install a client") from None


# ---------------------------------------------------------------------------
# Kafka_Source
# ---------------------------------------------------------------------------
class Kafka_Source(BasicOperator):
    """Replicas share a consumer group: partitions split across replicas;
    the user deserialization functor receives (Optional[KafkaMessage],
    shipper) and returns False to stop consuming (``kafka_source.hpp``:
    deser functor returns a continue flag; None message = idle timeout)."""

    op_type = OpType.SOURCE

    def __init__(self, deser_func: Callable, brokers: str,
                 topics: List[str], group_id: str = "windflow",
                 offsets: Optional[Dict[Tuple[str, int], int]] = None,
                 idleness_ms: int = 100, name: str = "kafka_source",
                 parallelism: int = 1, output_batch_size: int = 0) -> None:
        super().__init__(name, parallelism, RoutingMode.NONE,
                         output_batch_size=output_batch_size)
        self.deser_func = deser_func
        self.brokers = brokers
        self.topics = list(topics)
        self.group_id = group_id
        self.offsets = dict(offsets or {})
        self.idleness_ms = idleness_ms
        self._riched = arity(deser_func) >= 3
        kind, _ = _parse_brokers(brokers)
        if kind != "memory":
            _require_kafka_client()

    def build_replicas(self) -> None:
        self.replicas = [KafkaSourceReplica(self, i)
                         for i in range(self.parallelism)]


class KafkaSourceReplica(BasicReplica):
    def process(self, payload, ts, wm, tag):  # pragma: no cover
        raise WindFlowError("Kafka_Source has no input")

    def run_source(self) -> None:
        op = self.op
        kind, target = _parse_brokers(op.brokers)
        if kind != "memory":
            raise WindFlowError("real Kafka transport not wired in this "
                                "environment; use memory://")
        broker = MemoryBroker.get(target)
        shipper = SourceShipper(self)
        positions: Dict[Tuple[str, int], int] = {}
        my_parts: List[Tuple[str, int]] = []
        for topic in op.topics:
            for p in broker.assign_partitions(topic, op.group_id, self.idx,
                                              op.parallelism):
                my_parts.append((topic, p))
                positions[(topic, p)] = op.offsets.get((topic, p), 0)
        if not my_parts:
            return
        idle_budget_us = op.idleness_ms * 1000
        last_progress = current_time_usecs()
        running = True
        while running:
            progressed = False
            for tp in my_parts:
                msg = broker.poll(tp[0], tp[1], positions[tp])
                if msg is None:
                    continue
                positions[tp] += 1
                progressed = True
                last_progress = current_time_usecs()
                cont = (op.deser_func(msg, shipper, self.context)
                        if op._riched else op.deser_func(msg, shipper))
                if cont is False:
                    running = False
                    break
            if not progressed:
                if current_time_usecs() - last_progress > idle_budget_us:
                    # idle timeout: give the functor a chance to stop
                    cont = (op.deser_func(None, shipper, self.context)
                            if op._riched else op.deser_func(None, shipper))
                    if cont is False:
                        break
                    last_progress = current_time_usecs()
                time.sleep(0.001)

    def ship(self, payload: Any, ts: int, wm: int) -> None:
        if wm > self.cur_wm:
            self.cur_wm = wm
        self.stats.inputs_received += 1
        self.emitter.emit(payload, ts, self.cur_wm)



# ---------------------------------------------------------------------------
# Kafka_Sink
# ---------------------------------------------------------------------------
class Kafka_Sink(BasicOperator):
    """User serializer returns (topic, partition_or_None, payload) or None
    to drop (``kafka_sink.hpp``: wf_kafka_sink_msg)."""

    op_type = OpType.SINK

    def __init__(self, ser_func: Callable, brokers: str,
                 name: str = "kafka_sink", parallelism: int = 1) -> None:
        super().__init__(name, parallelism, RoutingMode.FORWARD)
        self.ser_func = ser_func
        self.brokers = brokers
        self._riched = arity(ser_func) >= 2
        kind, _ = _parse_brokers(brokers)
        if kind != "memory":
            _require_kafka_client()

    def build_replicas(self) -> None:
        self.replicas = [KafkaSinkReplica(self, i)
                         for i in range(self.parallelism)]


class KafkaSinkReplica(BasicReplica):
    def __init__(self, op, idx):
        super().__init__(op, idx)
        kind, target = _parse_brokers(op.brokers)
        if kind != "memory":
            raise WindFlowError("real Kafka transport not wired in this "
                                "environment; use memory://")
        self._broker = MemoryBroker.get(target)

    def process(self, payload, ts, wm, tag):
        out = (self.op.ser_func(payload, self.context) if self.op._riched
               else self.op.ser_func(payload))
        if out is None:
            return
        topic, partition, data = out
        self._broker.produce(topic, data, partition)
