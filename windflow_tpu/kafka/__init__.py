from .connectors import Kafka_Sink, Kafka_Source, MemoryBroker
from .builders_kafka import Kafka_Sink_Builder, Kafka_Source_Builder

__all__ = ["Kafka_Source", "Kafka_Sink", "MemoryBroker",
           "Kafka_Source_Builder", "Kafka_Sink_Builder"]
