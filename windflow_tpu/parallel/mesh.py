"""Compatibility shim: the mesh collective core moved to
``windflow_tpu.mesh.core`` when the mesh execution plane became a
first-class subsystem (``windflow_tpu/mesh/``). Import from there."""

from ..mesh.core import (MESH_AXES, default_ring_panes, make_key_mesh,
                         make_mesh_table, make_sharded_state,
                         mesh_shard_count, pvary_fn, ring_pane_window_query,
                         sharded_ffat_forest, sharded_grid_scan,
                         sharded_keyby_window_step, sharded_keyed_reduce,
                         wf_shard_map, _route_flat, _route_to_owners)

__all__ = [
    "MESH_AXES", "default_ring_panes", "make_key_mesh", "make_mesh_table",
    "make_sharded_state", "mesh_shard_count", "pvary_fn",
    "ring_pane_window_query", "sharded_ffat_forest", "sharded_grid_scan",
    "sharded_keyby_window_step", "sharded_keyed_reduce", "wf_shard_map",
]
