"""Multi-chip scale-out: key-sharded streaming state over a device mesh.

The single-node reference has no distributed backend (SURVEY.md §5: FastFlow
shared-memory queues only). This module is the new surface: the keyby
shuffle — the core repartitioning primitive of the whole framework
(``wf/keyby_emitter*.hpp``) — expressed as XLA collectives over a
``jax.sharding.Mesh`` so keyed window state scales across chips:

- mesh axes ``('key', 'data')``: ingestion is data-parallel along ``data``
  (every chip stages its own micro-batches), keyed state is block-sharded
  along ``key`` (shard ``s`` owns keys ``[s*k_local, (s+1)*k_local)``, so
  global state row ``k`` is key ``k``);
- one jitted step per global batch, written with ``shard_map``:
  bucket-by-owner (local sort) -> ``lax.all_to_all`` along ``key`` (the
  ICI shuffle replacing the reference's lock-free queues) -> masked
  segment-sum into the local per-key pane accumulators -> ``psum`` along
  ``data`` to merge the data-parallel contributions -> global metrics via
  ``psum`` over both axes;
- collectives ride ICI: the all_to_all moves only tuple payloads, state
  never leaves its owner shard.

This is the dry-run surface validated on a virtual CPU mesh; the same
program runs unchanged on a real multi-chip TPU slice.
"""

from __future__ import annotations

import math

import numpy as np


def make_key_mesh(n_devices: int):
    """Largest 2D ('key', 'data') mesh for n devices (data axis >= 1)."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()[:n_devices]
    ka = n_devices
    da = 1
    # prefer a 2D mesh when the device count allows it
    for cand in (2, 4):
        if n_devices % cand == 0 and n_devices // cand >= 2:
            da = cand
            ka = n_devices // cand
            break
    arr = np.array(devs).reshape(ka, da)
    return Mesh(arr, ("key", "data"))


def make_sharded_state(mesh, n_keys: int, n_panes: int):
    """Per-key pane accumulators sharded along the 'key' axis (replicated
    along 'data'); zeros-initialized."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    ka = mesh.shape["key"]
    n_keys_padded = math.ceil(n_keys / ka) * ka
    state = jnp.zeros((n_keys_padded, n_panes), jnp.float32)
    counts = jnp.zeros((n_keys_padded, n_panes), jnp.int32)
    sharding = NamedSharding(mesh, P("key", None))
    return (jax.device_put(state, sharding),
            jax.device_put(counts, sharding))


def sharded_keyby_window_step(mesh, n_keys: int, n_panes: int,
                              local_batch: int):
    """Builds the jitted global step: (state, counts, keys, values, panes)
    -> (state', counts', global_tuple_count).

    ``keys``/``values``/``panes`` are global arrays of shape
    (ka*da*local_batch,) sharded over both mesh axes; the step re-shards
    tuples to their key-owner chips with all_to_all and folds them into the
    owner's pane accumulators.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    ka = mesh.shape["key"]
    da = mesh.shape["data"]
    n_keys_padded = math.ceil(n_keys / ka) * ka
    k_local = n_keys_padded // ka
    # per-destination bucket capacity: worst case all local tuples go to one
    # owner; pad to local_batch (masked)
    C = local_batch

    def local_step(state, counts, keys, values, panes):
        # state/counts: (k_local, n_panes); keys/values/panes: (B,)
        # BLOCK key ownership: shard s owns global keys
        # [s*k_local, (s+1)*k_local), so returned global row k IS key k
        B = keys.shape[0]
        dest = jnp.minimum(keys // k_local, ka - 1).astype(jnp.int32)
        # bucket tuples by destination shard: (ka, C) padded with mask
        order = jnp.argsort(dest, stable=True)
        dsort = dest[order]
        ksort = keys[order]
        vsort = values[order]
        psort = panes[order]
        # position of each tuple within its destination run
        start_of_dest = jnp.searchsorted(dsort, jnp.arange(ka))
        within = jnp.arange(B) - start_of_dest[dsort]
        ok = within < C
        bucket_k = jnp.full((ka, C), -1, dtype=keys.dtype)
        bucket_v = jnp.zeros((ka, C), dtype=values.dtype)
        bucket_p = jnp.zeros((ka, C), dtype=panes.dtype)
        flat = dsort * C + jnp.minimum(within, C - 1)
        bucket_k = bucket_k.reshape(-1).at[flat].set(
            jnp.where(ok, ksort, -1), mode="drop").reshape(ka, C)
        bucket_v = bucket_v.reshape(-1).at[flat].set(
            jnp.where(ok, vsort, 0), mode="drop").reshape(ka, C)
        bucket_p = bucket_p.reshape(-1).at[flat].set(
            jnp.where(ok, psort, 0), mode="drop").reshape(ka, C)
        # the ICI shuffle: block i of every chip goes to key-shard i
        recv_k = lax.all_to_all(bucket_k, "key", 0, 0, tiled=True)
        recv_v = lax.all_to_all(bucket_v, "key", 0, 0, tiled=True)
        recv_p = lax.all_to_all(bucket_p, "key", 0, 0, tiled=True)
        rk = recv_k.reshape(-1)
        rv = recv_v.reshape(-1)
        rp = recv_p.reshape(-1)
        valid = rk >= 0
        shard = lax.axis_index("key")
        local_key = jnp.where(valid, rk - shard * k_local, 0).astype(jnp.int32)
        pane_idx = jnp.where(valid, rp % n_panes, 0).astype(jnp.int32)
        flat_idx = jnp.where(valid, local_key * n_panes + pane_idx,
                             k_local * n_panes)
        # accumulate the DELTA only, then merge deltas across the
        # data-parallel replicas — psum of state+delta would multiply the
        # pre-existing accumulators by the data-axis size every step
        delta = jnp.zeros(k_local * n_panes, state.dtype).at[flat_idx].add(
            jnp.where(valid, rv, 0), mode="drop").reshape(k_local, n_panes)
        dcount = jnp.zeros(k_local * n_panes, counts.dtype).at[flat_idx].add(
            jnp.where(valid, 1, 0), mode="drop").reshape(k_local, n_panes)
        state = state + lax.psum(delta, "data")
        counts = counts + lax.psum(dcount, "data")
        n_tuples = lax.psum(jnp.sum(valid), ("key", "data"))
        return state, counts, n_tuples

    stepped = shard_map(
        local_step, mesh=mesh,
        in_specs=(P("key", None), P("key", None),
                  P(("key", "data")), P(("key", "data")), P(("key", "data"))),
        out_specs=(P("key", None), P("key", None), P()),
    )
    return jax.jit(stepped), n_keys_padded, ka * da * local_batch


def ring_pane_window_query(mesh, n_panes_global: int, win_panes: int,
                           slide_panes: int):
    """Sliding-window combines over a PANE-SHARDED timeline — the
    long-context analog: when one chip cannot hold a window's pane state
    (SURVEY.md §5: pane decomposition / window partitioning is how the
    reference scales window length), the pane axis itself is sharded over
    the mesh's 'key' axis; a shard owns the windows STARTING in its slice,
    which extend up to win-1 panes into the RIGHT neighbor, so each shard
    receives the head of its right neighbor via a RING exchange
    (``lax.ppermute`` over ICI), not a full all_gather.

    Builds a jitted fn: (pane_partials[P_global]) -> window_sums[W_global]
    where window w = sum of panes [w*slide, w*slide+win). Collectives move
    exactly the overlap, O(win) per link, independent of timeline length.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    n_shards = mesh.shape["key"]
    if n_panes_global % n_shards:
        raise ValueError("n_panes_global must divide the key axis")
    p_local = n_panes_global // n_shards
    halo = win_panes - 1
    if halo > p_local:
        raise ValueError("window span exceeds one shard + halo; increase "
                         "panes per shard")
    n_windows = (n_panes_global - win_panes) // slide_panes + 1

    def local(panes):
        # panes: (p_local,) this shard's slice of the timeline. A shard
        # owns the windows STARTING in its slice; those extend up to
        # win-1 panes into the RIGHT neighbor, so the halo is the right
        # neighbor's head (ring ppermute: shard i sends its head to i-1).
        perm = [(i, (i - 1) % n_shards) for i in range(n_shards)]
        right_head = lax.ppermute(panes[:halo], "key", perm) \
            if halo > 0 else jnp.zeros((0,), panes.dtype)
        shard = lax.axis_index("key")
        ext = jnp.concatenate([panes, right_head])  # (p_local + halo,)
        start0_global = shard * p_local
        first_w = (start0_global + slide_panes - 1) // slide_panes
        max_w_here = p_local // slide_panes + 1
        w_ids = first_w + jnp.arange(max_w_here)
        starts_local = w_ids * slide_panes - start0_global
        valid = (w_ids < n_windows) & (starts_local < p_local)
        idx = jnp.clip(starts_local[:, None]
                       + jnp.arange(win_panes)[None, :],
                       0, p_local + halo - 1)
        sums = jnp.where(valid[:, None], ext[idx], 0).sum(axis=1)
        # each window is produced by exactly one shard; psum assembles the
        # dense global window vector
        out = jnp.zeros((n_windows,), panes.dtype)
        out = out.at[jnp.clip(w_ids, 0, n_windows - 1)].add(
            jnp.where(valid, sums, 0))
        return lax.psum(out, "key")

    stepped = shard_map(local, mesh=mesh,
                        in_specs=(P("key"),), out_specs=P())
    return jax.jit(stepped), n_windows
