from .mesh import (make_key_mesh, make_sharded_state, ring_pane_window_query,
                   sharded_ffat_forest, sharded_keyby_window_step)

__all__ = ["make_key_mesh", "sharded_keyby_window_step",
           "make_sharded_state", "ring_pane_window_query",
           "sharded_ffat_forest"]
