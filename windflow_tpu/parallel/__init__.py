from .mesh import (make_key_mesh, sharded_keyby_window_step,
                   make_sharded_state)

__all__ = ["make_key_mesh", "sharded_keyby_window_step", "make_sharded_state"]
