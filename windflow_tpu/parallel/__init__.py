from .mesh import (make_key_mesh, ring_pane_window_query,
                   make_sharded_state, sharded_keyby_window_step)

__all__ = ["make_key_mesh", "sharded_keyby_window_step",
           "make_sharded_state", "ring_pane_window_query"]
