"""Per-replica runtime context and local storage.

Parity: ``wf/context.hpp:53-160`` (RuntimeContext passed to "riched" functor
variants) and ``wf/local_storage.hpp:57+`` (typed per-replica KV store whose
``get`` default-constructs on miss).
"""

from __future__ import annotations

from typing import Any, Callable, Dict


class LocalStorage:
    """Per-replica key-value store. ``get(name, factory)`` default-constructs
    on miss like the reference's ``get<T>(name)``."""

    def __init__(self) -> None:
        self._store: Dict[str, Any] = {}

    def is_contained(self, name: str) -> bool:
        return name in self._store

    def get(self, name: str, factory: Callable[[], Any] = dict) -> Any:
        if name not in self._store:
            self._store[name] = factory()
        return self._store[name]

    def put(self, name: str, value: Any) -> None:
        self._store[name] = value

    def remove(self, name: str) -> None:
        self._store.pop(name, None)

    @property
    def size(self) -> int:
        return len(self._store)


class RuntimeContext:
    """Visible to user functors in their "riched" form: operator parallelism,
    replica index, metadata of the tuple being processed, and local storage."""

    def __init__(self, parallelism: int, replica_index: int) -> None:
        self.parallelism = parallelism
        self.replica_index = replica_index
        self.local_storage = LocalStorage()
        # metadata of the message currently being processed (set by replicas)
        self._current_ts = 0
        self._current_wm = 0

    # -- metadata accessors (wf/context.hpp getCurrentTimestamp/Watermark) --
    def get_current_timestamp(self) -> int:
        return self._current_ts

    def get_current_watermark(self) -> int:
        return self._current_wm

    def _set_meta(self, ts: int, wm: int) -> None:
        self._current_ts = ts
        self._current_wm = wm

    def get_parallelism(self) -> int:
        return self.parallelism

    def get_replica_index(self) -> int:
        return self.replica_index

    def get_local_storage(self) -> LocalStorage:
        return self.local_storage
