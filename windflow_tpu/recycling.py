"""Message/buffer recycling.

Parity: ``wf/recycling.hpp`` / ``wf/recycling_gpu.hpp`` — every reference
emitter owns an MPMC pool; consumers return messages to the producer's pool
instead of freeing, avoiding allocator pressure on the hot path.

In the Python plane, message lifetime is garbage-collected and the hot
allocations that matter are the COLUMNAR STAGING BUFFERS of the device
boundary (one numpy array per field per staged batch). ``ArrayPool`` keeps
free lists keyed by (dtype, capacity); the staging path acquires buffers
from it and ``InFlightRecycler`` returns them once the device transfer is
COMMITTED (``device_put``'s host read can complete asynchronously when
dispatch queues deepen — premature reuse corrupts in-flight batches).
Set WF_NO_RECYCLING=1 to disable, mirroring the reference's macro."""

from __future__ import annotations

import os
import threading
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

import numpy as np

RECYCLING_ENABLED = os.environ.get("WF_NO_RECYCLING", "0") != "1"


class ArrayPool:
    """Thread-safe free lists of numpy buffers keyed by (dtype, capacity)."""

    def __init__(self, max_per_bucket: int = 32) -> None:
        self._free: Dict[Tuple[str, int], List[np.ndarray]] = defaultdict(list)
        self._lock = threading.Lock()
        self.max_per_bucket = max_per_bucket
        self.hits = 0
        self.misses = 0

    def acquire(self, dtype, capacity: int) -> np.ndarray:
        key = (str(np.dtype(dtype)), capacity)
        if RECYCLING_ENABLED:
            with self._lock:
                bucket = self._free.get(key)
                if bucket:
                    self.hits += 1
                    arr = bucket.pop()
                    arr.fill(0)
                    return arr
        self.misses += 1
        return np.zeros(capacity, dtype=dtype)

    def release(self, arr: np.ndarray) -> None:
        if not RECYCLING_ENABLED:
            return
        key = (str(arr.dtype), arr.shape[0])
        with self._lock:
            bucket = self._free[key]
            if len(bucket) < self.max_per_bucket:
                bucket.append(arr)


class InFlightRecycler:
    """Safe staging-buffer recycling over async H2D transfers.

    ``jax.device_put``'s read of the host buffer is DEFERRED: it executes
    when the async dispatch queue reaches it, so a staging buffer must not
    be touched until that read provably happened. ``jax.Array.is_ready()``
    is NOT that signal — it reports True while the read is still queued
    (verified empirically on the CPU backend: mutating the buffer after a
    True ``is_ready()`` corrupts the device array). The only sound signal
    is ``block_until_ready()`` returning, so this recycler keeps a bounded
    FIFO of in-flight batches (device arrays + the host buffers that fed
    them) and releases buffers to the ``ArrayPool`` ONLY on the blocking
    pop once depth exceeds ``max_in_flight``. At depth N the transfer
    being waited on was enqueued N batches ago — normally long done, so
    the block is free; when it isn't, the stall is exactly the
    backpressure the reference gets from an exhausted recycling pool
    (``wf/recycling_gpu.hpp:68-88``, in-transit counter
    ``wf/batch_gpu_t.hpp:66``; double-buffered staging
    ``wf/keyby_emitter_gpu.hpp:443-505``)."""

    def __init__(self, pool: ArrayPool, max_in_flight: Optional[int] = None,
                 force: bool = False) -> None:
        from collections import deque
        self.pool = pool
        if max_in_flight is None:
            # deferred device commits (WF_DISPATCH_DEPTH, the consumer's
            # dispatch pipeline) park H2D reads behind queued programs:
            # keep this FIFO comfortably deeper than the dispatch queue
            # so the blocking pop lands on transfers whose programs have
            # long since run instead of stalling on a parked one
            from .runtime.dispatch import dispatch_depth
            max_in_flight = max(8, 4 * dispatch_depth())
        self.max_in_flight = max_in_flight
        self._q = deque()  # (device arrays tuple, host buffers list)
        # Platform gate: the CPU backend's device_put may ALIAS the host
        # buffer indefinitely (zero-copy) — no Python-visible point where
        # reuse becomes safe, not even block_until_ready (verified: data
        # corrupts after it under dispatch-queue pressure). Accelerator
        # backends transfer with ImmutableUntilTransferCompletes
        # semantics, where the array's ready future IS the release
        # signal. ``force`` is for unit tests of the FIFO mechanics.
        if force:
            self.enabled = RECYCLING_ENABLED
        else:
            import jax
            self.enabled = (RECYCLING_ENABLED
                            and jax.default_backend() != "cpu")

    def track(self, dev_arrays, host_buffers) -> None:
        if not self.enabled:
            return
        self._q.append((tuple(dev_arrays), list(host_buffers)))
        while len(self._q) > self.max_in_flight:
            self._release_oldest()

    def _release_oldest(self) -> None:
        devs, bufs = self._q.popleft()
        for d in devs:
            d.block_until_ready()  # guarantees the host read is over
        for b in bufs:
            self.pool.release(b)

    def drain(self) -> None:
        """Release every tracked buffer (blocking; flush/EOS path)."""
        while self._q:
            self._release_oldest()


class ObjectPool:
    """Generic free list for message objects (Batch and friends)."""

    def __init__(self, factory, reset, max_size: int = 256) -> None:
        self._factory = factory
        self._reset = reset
        self._free: list = []
        self._lock = threading.Lock()
        self.max_size = max_size

    def acquire(self):
        if RECYCLING_ENABLED:
            with self._lock:
                if self._free:
                    obj = self._free.pop()
                    self._reset(obj)
                    return obj
        return self._factory()

    def release(self, obj) -> None:
        if not RECYCLING_ENABLED:
            return
        with self._lock:
            if len(self._free) < self.max_size:
                self._free.append(obj)
