"""Message/buffer recycling.

Parity: ``wf/recycling.hpp`` / ``wf/recycling_gpu.hpp`` — every reference
emitter owns an MPMC pool; consumers return messages to the producer's pool
instead of freeing, avoiding allocator pressure on the hot path.

In the Python plane, message lifetime is garbage-collected and the hot
allocations that matter are the COLUMNAR STAGING BUFFERS of the device
boundary (one numpy array per field per staged batch). ``ArrayPool`` keeps
free lists keyed by (dtype, capacity); the staging path acquires buffers
from it and ``BatchTPU`` returns them once the device copy is complete
(``jax.device_put(np_array)`` copies synchronously into the transfer
buffer on CPU/TPU backends before returning control, so reuse after
dispatch is safe; set WF_NO_RECYCLING=1 to disable, mirroring the
reference's macro)."""

from __future__ import annotations

import os
import threading
from collections import defaultdict
from typing import Dict, List, Tuple

import numpy as np

RECYCLING_ENABLED = os.environ.get("WF_NO_RECYCLING", "0") != "1"


class ArrayPool:
    """Thread-safe free lists of numpy buffers keyed by (dtype, capacity)."""

    def __init__(self, max_per_bucket: int = 32) -> None:
        self._free: Dict[Tuple[str, int], List[np.ndarray]] = defaultdict(list)
        self._lock = threading.Lock()
        self.max_per_bucket = max_per_bucket
        self.hits = 0
        self.misses = 0

    def acquire(self, dtype, capacity: int) -> np.ndarray:
        key = (str(np.dtype(dtype)), capacity)
        if RECYCLING_ENABLED:
            with self._lock:
                bucket = self._free.get(key)
                if bucket:
                    self.hits += 1
                    arr = bucket.pop()
                    arr.fill(0)
                    return arr
        self.misses += 1
        return np.zeros(capacity, dtype=dtype)

    def release(self, arr: np.ndarray) -> None:
        if not RECYCLING_ENABLED:
            return
        key = (str(arr.dtype), arr.shape[0])
        with self._lock:
            bucket = self._free[key]
            if len(bucket) < self.max_per_bucket:
                bucket.append(arr)


class ObjectPool:
    """Generic free list for message objects (Batch and friends)."""

    def __init__(self, factory, reset, max_size: int = 256) -> None:
        self._factory = factory
        self._reset = reset
        self._free: list = []
        self._lock = threading.Lock()
        self.max_size = max_size

    def acquire(self):
        if RECYCLING_ENABLED:
            with self._lock:
                if self._free:
                    obj = self._free.pop()
                    self._reset(obj)
                    return obj
        return self._factory()

    def release(self, obj) -> None:
        if not RECYCLING_ENABLED:
            return
        with self._lock:
            if len(self._free) < self.max_size:
                self._free.append(obj)
