"""Worker threads: one OS thread per replica-chain.

Parity: FastFlow spawns one pinned thread per node
(``wf/pipegraph.hpp:610-764`` run path); chained operators share a thread
(``wf/multipipe.hpp:569-585``), and the stage collector is fused in front of
the first replica. Termination mirrors the reference's EOS cascade: sources
finish their loop, EOS flows per-edge, each replica flushes windows/partial
batches on the way down (``wf/basic_operator.hpp:180-189``).

Error handling is stricter than the reference (which prints and
``exit(EXIT_FAILURE)``): a replica that throws records the error, drains its
inputs, and force-propagates EOS downstream so the whole graph unwinds and
``PipeGraph.wait_end`` can re-raise in the caller's thread.
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional

from ..message import EOS
from .channel import Channel


class Worker(threading.Thread):
    """Runs a chain ``[collector?] + [replica_op1, replica_op2, ...]``.

    For source stages ``channel`` is None and the first chain node must be a
    SourceReplica (drives its own generation loop).
    """

    def __init__(self, wname: str, chain: List[Any],
                 channel: Optional[Channel] = None) -> None:
        super().__init__(name=wname, daemon=True)
        self.chain = chain
        self.channel = channel
        self.error: Optional[BaseException] = None
        self._eos_seen = 0

    def run(self) -> None:
        try:
            self._process()
            self._shutdown()
        except BaseException as e:
            self.error = e
            # unwind so sibling workers never block on us: swallow the rest
            # of our input, then force EOS downstream
            try:
                self._drain_inputs()
            except BaseException:
                pass
            try:
                self._emergency_eos()
            except BaseException:
                pass

    # -- normal path -------------------------------------------------------
    def _process(self) -> None:
        head = self.chain[0]
        if self.channel is None:
            head.run_source()
            return
        n_inputs = self.channel.n_inputs
        has_coll = hasattr(head, "on_channel_eos")
        # anything that pipelines work (replica dispatch queues, emitter
        # D2H FIFOs) must not withhold results forever on an idle stream:
        # poll with a timeout and give it an idle tick when the channel
        # stays quiet. Chain order, node before its emitter — a drained
        # dispatch queue emits INTO the emitter's FIFO, which the same
        # tick then delivers.
        import os

        idle_sinks = []
        for node in self.chain:
            if hasattr(node, "on_idle"):
                idle_sinks.append(node)
            em = getattr(node, "emitter", None)
            if em is not None and hasattr(em, "on_idle"):
                idle_sinks.append(em)
        try:
            idle_ms = float(os.environ.get("WF_IDLE_DRAIN_MS", "50"))
        except ValueError:
            idle_ms = 50.0  # malformed knob must not take down the graph
        # <= 0 disables the tick (a 0 timeout would busy-spin when idle)
        idle_s = idle_ms / 1e3 if idle_sinks and idle_ms > 0 else None
        # back off (up to 16x) when consecutive idle ticks find nothing to
        # drain, so a fully idle graph doesn't wake every worker at 20 Hz
        # on a small host; any real message resets the cadence
        idle_streak = 0
        # idle ticks are observability too: attribute them to the first
        # chain node that owns a StatsRecord (Worker_idle_ticks)
        stats = next((n.stats for n in self.chain
                      if getattr(n, "stats", None) is not None), None)
        while self._eos_seen < n_inputs:
            backoff = idle_s if idle_s is None else idle_s * min(
                16, 1 << min(idle_streak, 4))
            item = self.channel.get(backoff)
            if item is None:  # idle tick
                if stats is not None:
                    stats.worker_idle_ticks += 1
                did_work = False
                for sink in idle_sinks:
                    did_work = bool(sink.on_idle()) or did_work
                idle_streak = 0 if did_work else idle_streak + 1
                continue
            idle_streak = 0
            ch, msg = item
            if isinstance(msg, EOS):
                self._eos_seen += 1
                if has_coll:
                    head.on_channel_eos(ch)
                continue
            head.handle_msg(ch, msg)

    def _shutdown(self) -> None:
        # EOS cascade: terminate in chain order so that anything emitted by
        # an upstream node's flush is processed by the downstream fused nodes
        # before they flush themselves.
        for node in self.chain:
            node.terminate()
        last = self.chain[-1]
        if getattr(last, "emitter", None) is not None:
            last.emitter.send_eos_all()

    # -- error path --------------------------------------------------------
    def _drain_inputs(self) -> None:
        if self.channel is None:
            return
        n_inputs = self.channel.n_inputs
        while self._eos_seen < n_inputs:
            _, msg = self.channel.get()
            if isinstance(msg, EOS):
                self._eos_seen += 1

    def _emergency_eos(self) -> None:
        last = self.chain[-1]
        em = getattr(last, "emitter", None)
        if em is not None:
            for port in em.eos_ports():
                try:
                    port.send_eos()
                except BaseException:
                    pass
