"""Worker threads: one OS thread per replica-chain.

Parity: FastFlow spawns one pinned thread per node
(``wf/pipegraph.hpp:610-764`` run path); chained operators share a thread
(``wf/multipipe.hpp:569-585``), and the stage collector is fused in front of
the first replica. Termination mirrors the reference's EOS cascade: sources
finish their loop, EOS flows per-edge, each replica flushes windows/partial
batches on the way down (``wf/basic_operator.hpp:180-189``).

Checkpointing (no reference analog — ``windflow_tpu.checkpoint``): the
worker is also the alignment point for checkpoint barriers. ``Barrier``
messages ride the channels like EOS (one per producer edge, intercepted
here, never delivered to collectors/replicas); a ``BarrierAligner`` buffers
post-barrier input from already-barriered channels until every live channel
delivered the barrier, then ``checkpoint_now`` drains the chain's device
dispatch queues, flushes partial output batches, forwards the barrier
downstream, snapshots every fused node (collector included) and acks the
coordinator with the blobs.

Error handling is stricter than the reference (which prints and
``exit(EXIT_FAILURE)``): a replica that throws records the error, drains its
inputs, and force-propagates EOS downstream so the whole graph unwinds and
``PipeGraph.wait_end`` can re-raise in the caller's thread.
"""

from __future__ import annotations

import threading
import time
from typing import Any, List, Optional

from ..basic import RescaleTeardown
from ..message import EOS, Barrier
from .channel import Channel
from .collectors import BarrierAligner


class Worker(threading.Thread):
    """Runs a chain ``[collector?] + [replica_op1, replica_op2, ...]``.

    For source stages ``channel`` is None and the first chain node must be a
    SourceReplica (drives its own generation loop).
    """

    def __init__(self, wname: str, chain: List[Any],
                 channel: Optional[Channel] = None,
                 coordinator: Optional[Any] = None,
                 flightrec: Optional[Any] = None) -> None:
        super().__init__(name=wname, daemon=True)
        self.chain = chain
        self.channel = channel
        self.coordinator = coordinator  # CheckpointCoordinator or None
        self.error: Optional[BaseException] = None
        # flight recorder (monitoring/flightrec.py): this worker's event
        # ring, shared with every chain node's StatsRecord so the stats
        # hooks (svc/prep/commit/snapshot) append spans to it
        self.flightrec = flightrec
        # crash hook (PipeGraph wires a post-mortem trace dump); the
        # watchdog needs idle ticks even without idle sinks, so a
        # blocked-forever-on-input worker still advances its counter
        self.on_crash: Optional[Any] = None
        # supervised recovery (windflow_tpu.supervision): when wired, a
        # dying worker notifies the supervisor and exits WITHOUT the
        # drain + emergency-EOS unwind — an EOS mid-recovery would tell
        # sinks the stream completed; the supervisor owns the teardown
        self.on_failure: Optional[Any] = None
        self.force_idle_tick = False
        self._progress = 0  # channel deliveries + idle ticks (watchdog)
        self._eos_seen = 0
        self._has_coll = hasattr(chain[0], "on_channel_eos")
        # replicas = chain nodes that carry operator state (the collector,
        # when present, is snapshotted alongside the first replica).
        # Deduped by identity: every sub-op of a fused device stage
        # aliases ONE FusedTPUReplica, which must drain/snapshot/
        # terminate exactly once
        self._replicas = []
        for n in chain:
            if hasattr(n, "snapshot_state") and hasattr(n, "op") \
                    and not any(n is r for r in self._replicas):
                self._replicas.append(n)
        if flightrec is not None:
            for n in chain:
                st = getattr(n, "stats", None)
                if st is not None:
                    st.recorder = flightrec
        self._aligner: Optional[BarrierAligner] = None
        if coordinator is not None and channel is None and chain:
            # source chain: the source replica injects barriers at tuple
            # boundaries and hands the chain snapshot back to us
            bind = getattr(chain[0], "bind_checkpoint", None)
            if bind is not None:
                bind(coordinator, self.checkpoint_now)
        if coordinator is not None:
            # exactly-once sinks (windflow_tpu.sinks.transactional):
            # register their commit-on-finalize listener with the
            # coordinator that drives their epochs
            for n in self._replicas:
                bind = getattr(n, "bind_txn_coordinator", None)
                if bind is not None:
                    bind(coordinator)

    def run(self) -> None:
        if self.flightrec is not None:
            # blocked channel puts/gets and shared-program compiles find
            # this thread's ring through the TLS slot
            from ..monitoring.flightrec import set_thread_recorder
            set_thread_recorder(self.flightrec)
        try:
            self._process()
            self._retire()
            self._shutdown()
        except RescaleTeardown:
            # elastic rescale (windflow_tpu.scaling): the controller is
            # rebuilding the runtime plane from the checkpoint we just
            # acked — exit silently, no EOS cascade, no retirement (our
            # channels and emitters are about to be discarded)
            return
        except BaseException as e:
            self.error = e
            # crash visibility FIRST (while the ring still holds the
            # run-up): record the error into the stats plane, then the
            # post-mortem dump hook — only then unwind
            try:
                self._record_crash(e)
            except BaseException:
                pass
            if self.on_failure is not None:
                # supervised: the supervisor tears the plane down and
                # restores from checkpoint — no drain (the channels are
                # about to be discarded) and NO emergency EOS (sinks
                # must not see an end-of-stream marker mid-recovery)
                try:
                    self.on_failure(self)
                except BaseException:
                    pass
                return
            # unwind so sibling workers never block on us: swallow the rest
            # of our input, then force EOS downstream
            try:
                self._drain_inputs()
            except BaseException:
                pass
            try:
                self._emergency_eos()
            except BaseException:
                pass

    def _record_crash(self, e: BaseException) -> None:
        """The BaseException path used to die as a silent daemon thread;
        now the exception type + traceback land in ``Worker_last_error``
        (surfaced by ``PipeGraph.get_stats`` and the
        ``windflow_worker_crashes_total`` metric family), a ``crash``
        event enters the flight ring, and the PipeGraph's post-mortem
        hook dumps the trace."""
        import traceback

        stats = self._stats()
        if stats is not None:
            stats.worker_crashes += 1
            stats.worker_last_error = "".join(
                traceback.format_exception(type(e), e, e.__traceback__))
        if self.flightrec is not None:
            self.flightrec.event("crash", 0.0,
                                 f"{type(e).__name__}: {e}")
        if self.on_crash is not None:
            self.on_crash(self, e)

    def progress_value(self) -> int:
        """Monotone liveness counter for the stall watchdog: advances on
        every channel delivery and idle tick, plus tuples moved by the
        head replica (a source's loop never returns to ``_process``, and
        a worker stuck INSIDE one long message would otherwise look
        live)."""
        v = self._progress
        stats = self._stats()
        if stats is not None:
            # shed records count as progress: a source under admission
            # control is actively REFUSING work, not wedged
            v += (stats.inputs_received + stats.outputs_sent
                  + stats.shed_records)
        return v

    # -- normal path -------------------------------------------------------
    def _process(self) -> None:
        head = self.chain[0]
        if self.channel is None:
            head.run_source()
            # a pending epoch the loop never reached injects at EOS time:
            # a finished source's final position is a valid snapshot
            # (restore resumes it as already-complete), and without it the
            # checkpoint could never gather all acks
            fin = getattr(head, "final_checkpoint", None)
            if fin is not None:
                fin()
            return
        n_inputs = self.channel.n_inputs
        if self.coordinator is not None:
            self._aligner = BarrierAligner(n_inputs)
        # anything that pipelines work (replica dispatch queues, emitter
        # D2H FIFOs) must not withhold results forever on an idle stream:
        # poll with a timeout and give it an idle tick when the channel
        # stays quiet. Chain order, node before its emitter — a drained
        # dispatch queue emits INTO the emitter's FIFO, which the same
        # tick then delivers.
        import os

        idle_sinks = []
        for node in self.chain:
            if hasattr(node, "on_idle"):
                idle_sinks.append(node)
            em = getattr(node, "emitter", None)
            if em is not None and hasattr(em, "on_idle"):
                idle_sinks.append(em)
        try:
            idle_ms = float(os.environ.get("WF_IDLE_DRAIN_MS", "50"))
        except ValueError:
            idle_ms = 50.0  # malformed knob must not take down the graph
        # <= 0 disables the tick (a 0 timeout would busy-spin when idle)
        # (the stall watchdog forces the tick even without idle sinks:
        # a worker parked forever in channel.get would otherwise never
        # advance its progress counter and read as stalled)
        idle_s = idle_ms / 1e3 \
            if (idle_sinks or self.force_idle_tick) and idle_ms > 0 else None
        # back off (up to 16x) when consecutive idle ticks find nothing to
        # drain, so a fully idle graph doesn't wake every worker at 20 Hz
        # on a small host; any real message resets the cadence
        idle_streak = 0
        # idle ticks are observability too: attribute them to the first
        # chain node that owns a StatsRecord (Worker_idle_ticks)
        stats = self._stats()
        while self._eos_seen < n_inputs:
            backoff = idle_s if idle_s is None else idle_s * min(
                16, 1 << min(idle_streak, 4))
            item = self.channel.get(backoff)
            self._progress += 1  # liveness for the stall watchdog
            if item is None:  # idle tick
                if stats is not None:
                    stats.worker_idle_ticks += 1
                did_work = False
                for sink in idle_sinks:
                    did_work = bool(sink.on_idle()) or did_work
                idle_streak = 0 if did_work else idle_streak + 1
                continue
            idle_streak = 0
            self._handle_item(item[0], item[1])

    def _handle_item(self, ch: int, msg: Any) -> None:
        """One channel delivery: barrier alignment first, then the normal
        EOS / message path. Re-entered for buffered post-barrier items
        after a snapshot (a buffered item may itself be the next Barrier,
        opening the next alignment)."""
        al = self._aligner
        if al is not None and al.blocked(ch):
            # post-barrier input on an aligned channel: park it. EOS too
            # (consuming it early would mutate collector state
            # mid-snapshot), and so is a next-epoch Barrier — channels are
            # FIFO, so anything behind the current epoch's barrier belongs
            # to the next alignment and replays after the snapshot.
            al.buffered.append((ch, msg))
            return
        if isinstance(msg, Barrier):
            if al is not None and al.on_barrier(ch, msg):
                self._complete_alignment()
            return  # checkpointing off: stray barriers are dropped
        if isinstance(msg, EOS):
            self._eos_seen += 1
            if self._has_coll:
                self.chain[0].on_channel_eos(ch)
            if al is not None and al.on_eos(ch):
                self._complete_alignment()
            return
        self.chain[0].handle_msg(ch, msg)

    def _complete_alignment(self) -> None:
        barrier, stall_us, buffered = self._aligner.take()
        self.checkpoint_now(barrier, stall_us)
        for ch, msg in buffered:
            self._handle_item(ch, msg)

    # -- checkpointing -----------------------------------------------------
    def _stats(self):
        return next((n.stats for n in self.chain
                     if getattr(n, "stats", None) is not None), None)

    def checkpoint_now(self, barrier: Barrier, stall_us: float = 0.0) -> None:
        """Snapshot the whole chain for one aligned barrier. Runs on this
        worker's own thread (from ``_complete_alignment``, or from the
        source replica's injection hook mid-``run_source``), so no tuple
        is in flight anywhere in the chain.

        Order matters: (1) chain-ordered drain of each node's device
        dispatch queue + flush of its emitter, so every pre-barrier tuple
        lands in downstream channels (or fused successors) BEFORE the
        barrier; (2) barrier downstream via the last emitter (which
        flushes again first); (3) state capture; (4) ack with blobs —
        the coordinator commits once every worker acked."""
        coord = self.coordinator
        if coord is None:
            return
        t0 = time.perf_counter()
        replicas = self._replicas
        last = replicas[-1] if replicas else None
        for node in replicas:
            dq = getattr(node, "dispatch", None)
            if dq is not None:
                dq.drain(forced=True)
            em = node.emitter
            if em is not None and node is not last:
                em.flush()  # inline edge: feeds the next fused node now
        if last is not None and last.emitter is not None:
            last.emitter.send_barrier_all(barrier)
        # exactly-once sinks pre-commit the epoch BEFORE the blobs are
        # captured (and before our ack can let the coordinator finalize
        # it): everything staged since the previous barrier becomes this
        # epoch's durable, not-yet-visible segment/transaction
        for node in replicas:
            hook = getattr(node, "precommit_epoch", None)
            if hook is not None:
                hook(barrier.ckpt_id)
        # the capture runs under the snapshot context: engines that
        # track touched slots may emit delta-form states (WF_CKPT_DELTA)
        # for THIS epoch against their last full snapshot. The capture
        # is a copy (device_get / host copies), so in async mode
        # (WF_CKPT_ASYNC) the ack returns as soon as the blobs are
        # registered and the pause the barrier imposes ends HERE — the
        # serialization + writes happen on the coordinator's uploader.
        from ..checkpoint import delta as _ckpt_delta
        with _ckpt_delta.capturing(barrier.ckpt_id, coord.store):
            blobs = self._capture_blobs()
        nbytes = coord.ack(barrier.ckpt_id, self.name, blobs)
        cut_us = (time.perf_counter() - t0) * 1e6
        stats = self._stats()
        if stats is not None:
            stats.note_checkpoint(cut_us, nbytes, stall_us, cut_us=cut_us)
        if self.flightrec is not None:
            self.flightrec.event("ckpt:cut", cut_us,
                                 {"ckpt_id": barrier.ckpt_id,
                                  "bytes": nbytes})
            if _ckpt_delta.env_ckpt_delta():
                ndelta = sum(1 for st in blobs.values()
                             if _ckpt_delta.delta_bases(st))
                if ndelta:
                    self.flightrec.event("ckpt:delta", 0.0,
                                         {"ckpt_id": barrier.ckpt_id,
                                          "delta_blobs": ndelta})
            self.flightrec.event("ckpt_ack", 0.0,
                                 {"ckpt_id": barrier.ckpt_id,
                                  "bytes": nbytes})
        # rescale quiesce point (windflow_tpu.scaling): a held epoch
        # parks every worker right here — after the ack, with all
        # pre-barrier output flushed and the barrier forwarded, before
        # any post-barrier tuple is produced
        t_park = time.perf_counter()
        directive = coord.park_if_held(barrier.ckpt_id, self.name)
        if directive is not None:
            if self.flightrec is not None:
                self.flightrec.event(
                    "rescale:parked",
                    (time.perf_counter() - t_park) * 1e6,
                    {"ckpt_id": barrier.ckpt_id,
                     "directive": directive})
            if directive == "abandon":
                raise RescaleTeardown()

    def _capture_blobs(self) -> dict:
        blobs = {}
        for node in self._replicas:
            dq = getattr(node, "dispatch", None)
            if dq is not None:
                dq.drain(forced=True)
            state = node.snapshot_state()
            if node.emitter is not None:
                state["__emitter__"] = node.emitter.emitter_state()
            blobs[(node.op.name, node.idx)] = state
        if self._has_coll and self._replicas:
            coll_state = self.chain[0].snapshot_state()
            if coll_state:
                blobs[(self._replicas[0].op.name,
                       self._replicas[0].idx)]["__collector__"] = coll_state
        return blobs

    def _retire(self) -> None:
        """Clean exit with checkpointing on: hand the coordinator our
        final state so epochs opened after we finish still complete (a
        finished worker's state is frozen — captured BEFORE the EOS
        flush, so a restore re-runs the flush exactly like a live
        replica would)."""
        if self.coordinator is not None:
            self.coordinator.retire(self.name, self._capture_blobs())

    def _shutdown(self) -> None:
        # EOS cascade: terminate in chain order so that anything emitted by
        # an upstream node's flush is processed by the downstream fused nodes
        # before they flush themselves.
        for node in self.chain:
            node.terminate()
        last = self.chain[-1]
        if getattr(last, "emitter", None) is not None:
            last.emitter.send_eos_all()

    # -- error path --------------------------------------------------------
    def _drain_inputs(self) -> None:
        if self.channel is None:
            return
        n_inputs = self.channel.n_inputs
        while self._eos_seen < n_inputs:
            _, msg = self.channel.get()
            if isinstance(msg, EOS):
                self._eos_seen += 1

    def _emergency_eos(self) -> None:
        last = self.chain[-1]
        em = getattr(last, "emitter", None)
        if em is not None:
            for port in em.eos_ports():
                try:
                    port.send_eos()
                except BaseException:
                    pass
