"""Bounded channels and ports connecting replicas.

This is the FastFlow replacement (reference L0). WindFlow replicas are
FastFlow nodes joined by lock-free SPSC queues with pinned threads
(``SURVEY.md`` L0); here every consumer worker owns one bounded MPSC
``Channel`` that merges all of its input edges (like ``ff_minode``), and each
producer edge is a ``QueuePort`` stamping the consumer-side channel index
(``ff::ff_minode::get_channel_id`` equivalent). Chained (fused) stages talk
through ``InlinePort`` — a plain function call, the analog of FastFlow's
``combine_with_laststage`` thread fusion (``wf/multipipe.hpp:576-582``).

A native C++ SPSC ring (windflow_tpu/native) can replace the stdlib deque
backing transparently; the Python fallback keeps zero hard dependencies.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, List, Optional, Tuple

from ..basic import DEFAULT_BUFFER_CAPACITY, SupervisorTeardown
from ..message import EOS_SENTINEL


def _teardown() -> SupervisorTeardown:
    return SupervisorTeardown(
        "channel closed: the supervisor is rebuilding the runtime plane")
# flight-recorder spans for blocked puts/gets: recorded into the CALLING
# thread's own ring (a producer blocks on the consumer's channel, so the
# channel itself cannot own a single-writer ring); only the already-slow
# blocked paths ever touch this
from ..monitoring.flightrec import thread_recorder


class Channel:
    """Bounded blocking MPSC queue of ``(channel_idx, msg)`` pairs.

    Bounded => backpressure, like FastFlow's FF_BOUNDED_BUFFER mode.
    """

    __slots__ = ("_q", "_lock", "_not_empty", "_not_full", "capacity",
                 "n_inputs", "depth_max", "puts_blocked", "blocked_put_ns",
                 "blocked_get_ns", "closed")

    def __init__(self, capacity: int = DEFAULT_BUFFER_CAPACITY) -> None:
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self.capacity = capacity
        self.n_inputs = 0  # number of producer edges; assigned at wiring
        # supervised teardown (windflow_tpu.supervision): close() poisons
        # the channel — every blocked and future put/get raises
        # SupervisorTeardown so the whole plane unwinds without an EOS
        # cascade. One bool check on paths that already hold the lock.
        self.closed = False
        # backpressure / occupancy instrumentation (monitoring plane):
        # producers blocked on a full queue (this stage IS the bottleneck)
        # vs the consumer blocked on an empty one (it is starved). Clocks
        # are read only on the blocked paths — the uncontended hot path
        # pays one compare for the high-water mark.
        self.depth_max = 0
        self.puts_blocked = 0
        self.blocked_put_ns = 0
        self.blocked_get_ns = 0

    def register_input(self) -> int:
        """Returns the channel index assigned to a new producer edge."""
        idx = self.n_inputs
        self.n_inputs += 1
        return idx

    def put(self, ch_idx: int, msg: Any) -> None:
        with self._not_full:
            if self.closed:
                raise _teardown()
            if len(self._q) >= self.capacity:
                self.puts_blocked += 1
                t0 = time.monotonic_ns()
                while len(self._q) >= self.capacity:
                    self._not_full.wait()
                    if self.closed:
                        raise _teardown()
                dt = time.monotonic_ns() - t0
                self.blocked_put_ns += dt
                rec = thread_recorder()
                if rec is not None:
                    rec.event("ch_put_blocked", dt / 1e3)
            self._q.append((ch_idx, msg))
            if len(self._q) > self.depth_max:
                self.depth_max = len(self._q)
            self._not_empty.notify()

    def get(self, timeout: Optional[float] = None) -> Optional[Tuple[int, Any]]:
        """Blocking pop; with ``timeout`` (seconds) returns None if the
        channel stays empty that long (the worker's idle tick). The timeout
        is a single deadline: spurious wakeups / raced notifies do not
        restart it, so the idle tick is never delayed past ``timeout``."""
        if timeout is None:
            with self._not_empty:
                if not self._q:
                    if self.closed:
                        raise _teardown()
                    t0 = time.monotonic_ns()
                    while not self._q:
                        self._not_empty.wait()
                        if self.closed and not self._q:
                            raise _teardown()
                    dt = time.monotonic_ns() - t0
                    self.blocked_get_ns += dt
                    rec = thread_recorder()
                    if rec is not None:
                        rec.event("ch_get_blocked", dt / 1e3)
                item = self._q.popleft()
                self._not_full.notify()
                return item
        deadline = time.monotonic() + timeout
        with self._not_empty:
            if not self._q:
                if self.closed:
                    raise _teardown()
                t0 = time.monotonic_ns()
                while not self._q:
                    if self.closed:
                        raise _teardown()
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        self.blocked_get_ns += time.monotonic_ns() - t0
                        return None
                    self._not_empty.wait(remaining)
                dt = time.monotonic_ns() - t0
                self.blocked_get_ns += dt
                # data arrived after a real wait: span (timeouts return
                # None above without an event — idle waits would flood
                # the ring on a quiet stream)
                rec = thread_recorder()
                if rec is not None:
                    rec.event("ch_get_blocked", dt / 1e3)
            item = self._q.popleft()
            self._not_full.notify()
            return item

    def get_nowait(self) -> Optional[Tuple[int, Any]]:
        with self._lock:
            if not self._q:
                return None
            item = self._q.popleft()
            self._not_full.notify()
            return item

    def close(self) -> None:
        """Poison the channel (supervised teardown): every blocked and
        future put/get raises ``SupervisorTeardown``. Buffered messages
        still drain through ``get`` — only an EMPTY closed channel
        raises on the consumer side, so a worker unwinds at a message
        boundary, never mid-prefix. Idempotent."""
        with self._lock:
            self.closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)


class Port:
    """Destination of an emitter edge."""

    __slots__ = ()

    def send(self, msg: Any) -> None:
        raise NotImplementedError

    def send_eos(self) -> None:
        raise NotImplementedError


class QueuePort(Port):
    """Edge to a replica running in another thread."""

    __slots__ = ("channel", "ch_idx")

    def __init__(self, channel: Channel) -> None:
        self.channel = channel
        self.ch_idx = channel.register_input()

    def send(self, msg: Any) -> None:
        self.channel.put(self.ch_idx, msg)

    def send_eos(self) -> None:
        self.channel.put(self.ch_idx, EOS_SENTINEL)


class InlinePort(Port):
    """Edge to a replica fused in the same thread (chaining). ``send`` is a
    synchronous call into the downstream replica's message handler."""

    __slots__ = ("node",)

    def __init__(self, node: Any) -> None:
        self.node = node  # object with handle_msg(ch, msg); single channel 0

    def send(self, msg: Any) -> None:
        self.node.handle_msg(0, msg)

    def send_eos(self) -> None:
        # EOS through a chain is driven by the worker's termination cascade
        # (Worker.run), not by in-band sentinels.
        pass
