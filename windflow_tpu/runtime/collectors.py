"""Collectors: the routing plane on the consumer side.

One collector is fused in front of the first replica of a stage (the
reference fuses a FastFlow node with ``combine_with_firststage``,
``wf/multipipe.hpp:200-244``); here it is simply the head of the worker's
chain, invoked in the same thread.

- ``WatermarkCollector`` (DEFAULT mode): per-input-channel max watermark;
  outgoing watermark = min over still-open channels
  (``wf/watermark_collector.hpp:65-80``). Optionally tags join streams A/B by
  channel id vs. a separator (``watermark_collector.hpp:121-134``).
- ``OrderingCollector`` (DETERMINISTIC mode): k-way merge of per-channel
  ordered streams into a total order by (ts, id)
  (``wf/ordering_collector.hpp:50-272``).
- ``KSlackCollector`` (PROBABILISTIC mode): K-slack buffering with adaptive
  K = max observed delay; late tuples are dropped and counted
  (``wf/kslack_collector.hpp:52-243``).
"""

from __future__ import annotations

import heapq
import threading
from collections import deque
from typing import Any, List, Optional

from ..message import Batch, Single

MAX_WM = (1 << 63) - 1


class AtomicCounter:
    """Shared dropped-tuple counter (``wf/pipegraph.hpp:91-92``)."""

    def __init__(self) -> None:
        self._v = 0
        self._lock = threading.Lock()

    def add(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._v


class BarrierAligner:
    """Per-worker aligned-checkpoint barrier bookkeeping (Flink's barrier
    alignment; the checkpoint twin of the per-channel EOS counting in
    ``Worker._process``).

    A checkpoint's barrier arrives once per input channel. The first
    arrival opens an alignment: from then on, input from channels that
    already delivered their barrier is BUFFERED (those tuples are
    post-barrier and must not reach the snapshot), while the remaining
    channels keep processing. When every live channel has delivered the
    barrier — or gone EOS, a finished producer sends no more data — the
    worker snapshots and the buffered backlog replays in arrival order.
    Buffering (instead of blocking the channel) means alignment can never
    deadlock the bounded channels upstream."""

    __slots__ = ("live", "waiting", "arrived", "buffered", "align_t0_ns")

    def __init__(self, n_channels: int) -> None:
        self.live = set(range(n_channels))
        self.waiting: Optional[Any] = None  # the in-flight Barrier
        self.arrived: set = set()
        self.buffered: list = []  # (ch, msg) from already-barriered channels
        self.align_t0_ns = 0

    def blocked(self, ch: int) -> bool:
        return self.waiting is not None and ch in self.arrived

    def on_barrier(self, ch: int, barrier: Any) -> bool:
        """Returns True when alignment is complete (snapshot now)."""
        import time
        if self.waiting is None:
            self.waiting = barrier
            self.arrived = {ch}
            self.align_t0_ns = time.monotonic_ns()
            # flight-recorder marker: the stall span itself is recorded
            # by the worker at take() time; this instant marks the OPEN
            # so the trace shows which channel's barrier arrived first
            from ..monitoring.flightrec import thread_recorder
            rec = thread_recorder()
            if rec is not None:
                rec.event("barrier_open", 0.0,
                          {"ckpt_id": getattr(barrier, "ckpt_id", None),
                           "channel": ch})
        else:
            self.arrived.add(ch)
        return self.live.issubset(self.arrived)

    def on_eos(self, ch: int) -> bool:
        """A closed channel sends no more data (all of its input was
        pre-barrier); it stops counting toward alignment. Returns True
        when this completes a pending alignment."""
        self.live.discard(ch)
        return (self.waiting is not None
                and self.live.issubset(self.arrived))

    def take(self):
        """Close the alignment: ``(barrier, stall_us, buffered)``."""
        import time
        barrier = self.waiting
        stall_us = (time.monotonic_ns() - self.align_t0_ns) / 1e3
        buffered = self.buffered
        self.waiting = None
        self.arrived = set()
        self.buffered = []
        return barrier, stall_us, buffered


class BasicCollector:
    """Chain-node protocol: handle_msg(ch, msg) / on_channel_eos(ch) /
    terminate(). ``next_node`` is the stage's first replica."""

    def __init__(self, n_channels: int, next_node: Any,
                 separator_id: Optional[int] = None) -> None:
        self.n_channels = n_channels
        self.next_node = next_node
        self.separator_id = separator_id  # join A/B channel split point
        self.live = set(range(n_channels))

    def _tag(self, ch: int, msg: Any) -> None:
        if self.separator_id is not None:
            msg.stream_tag = 0 if ch < self.separator_id else 1

    def handle_msg(self, ch: int, msg: Any) -> None:
        raise NotImplementedError

    def on_channel_eos(self, ch: int) -> None:
        self.live.discard(ch)

    def terminate(self) -> None:
        pass

    # -- checkpointing (aligned snapshots, windflow_tpu.checkpoint) --------
    # Collectors buffer pre-barrier messages the replica has not seen yet
    # (ordering/K-slack heaps, id sequencing), so their buffers are part
    # of the worker's snapshot. ``live`` is NOT snapshotted: restore
    # rebuilds the topology with every channel open and sources replay.
    def snapshot_state(self) -> dict:
        return {}

    def restore_state(self, state: dict) -> None:
        pass


class WatermarkCollector(BasicCollector):
    def __init__(self, n_channels: int, next_node: Any,
                 separator_id: Optional[int] = None) -> None:
        super().__init__(n_channels, next_node, separator_id)
        self._ch_wm = [0] * n_channels

    def _out_wm(self) -> int:
        if not self.live:
            return max(self._ch_wm) if self._ch_wm else 0
        return min(self._ch_wm[c] for c in self.live)

    def handle_msg(self, ch: int, msg: Any) -> None:
        wm = msg.min_watermark()
        if wm > self._ch_wm[ch]:
            self._ch_wm[ch] = wm
        self._tag(ch, msg)
        msg.wm = self._out_wm()
        self.next_node.handle_msg(0, msg)

    def snapshot_state(self) -> dict:
        return {"ch_wm": list(self._ch_wm)}

    def restore_state(self, state: dict) -> None:
        wm = state.get("ch_wm")
        if wm is not None and len(wm) == len(self._ch_wm):
            self._ch_wm = list(wm)


class OrderingCollector(BasicCollector):
    """Each input channel is locally ordered (per-destination ids are
    assigned monotonically by emitters); merge to a total order. A message is
    releasable once every live channel has something buffered (its head is a
    lower bound for anything that channel will send)."""

    def __init__(self, n_channels: int, next_node: Any,
                 separator_id: Optional[int] = None,
                 by_timestamp: bool = True) -> None:
        super().__init__(n_channels, next_node, separator_id)
        self.by_timestamp = by_timestamp
        self._bufs: List[deque] = [deque() for _ in range(n_channels)]

    def _key(self, msg: Any):
        if isinstance(msg, Batch):
            ts = msg.rows[0][1] if msg.rows else 0
        else:
            ts = msg.ts
        return (ts, msg.id) if self.by_timestamp else (msg.id, ts)

    def handle_msg(self, ch: int, msg: Any) -> None:
        if msg.is_punct:  # no punctuations in DETERMINISTIC mode; absorb
            return
        self._tag(ch, msg)
        self._bufs[ch].append(msg)
        self._drain()

    def _drain(self) -> None:
        while True:
            best_ch = -1
            best_key = None
            for c in self.live:
                if not self._bufs[c]:
                    return  # an open channel is empty: cannot release yet
                k = self._key(self._bufs[c][0])
                if best_key is None or k < best_key:
                    best_key, best_ch = k, c
            for c in range(self.n_channels):  # closed channels may hold leftovers
                if c not in self.live and self._bufs[c]:
                    k = self._key(self._bufs[c][0])
                    if best_key is None or k < best_key:
                        best_key, best_ch = k, c
            if best_ch < 0:
                return
            self.next_node.handle_msg(0, self._bufs[best_ch].popleft())

    def on_channel_eos(self, ch: int) -> None:
        super().on_channel_eos(ch)
        self._drain()

    def terminate(self) -> None:
        # all channels closed: total merge of leftovers
        heap = []
        for c, buf in enumerate(self._bufs):
            for i, m in enumerate(buf):
                heapq.heappush(heap, (self._key(m), c, i, m))
        while heap:
            _, _, _, m = heapq.heappop(heap)
            self.next_node.handle_msg(0, m)
        self._bufs = [deque() for _ in range(self.n_channels)]

    def snapshot_state(self) -> dict:
        return {"bufs": [list(b) for b in self._bufs]}

    def restore_state(self, state: dict) -> None:
        bufs = state.get("bufs")
        if bufs is not None and len(bufs) == len(self._bufs):
            self._bufs = [deque(b) for b in bufs]


class IDSequencerCollector(BasicCollector):
    """Per-key id sequencer in front of WLQ/REDUCE window stages (used in
    EVERY execution mode — reference ``wf/multipipe.hpp:221-224`` installs an
    Ordering_Collector in ID mode for ``Parallel_Windows_WLQ/REDUCE``).

    Upstream PLQ/MAP replicas stamp each partial result with a dense global
    id per key (pane id, or ``gwid*map_parallelism + replica``); this
    collector releases them in exactly id order per key, so the consumer's
    count-based windows see a deterministic sequence regardless of arrival
    interleaving. Gaps never persist (the id space is dense per key across
    producers); leftovers are drained in id order at EOS."""

    def __init__(self, n_channels: int, next_node: Any,
                 key_extractor) -> None:
        super().__init__(n_channels, next_node, None)
        self.key_of = key_extractor
        self._next: dict = {}  # key -> next expected id
        self._pending: dict = {}  # key -> {id: msg}

    def handle_msg(self, ch: int, msg: Any) -> None:
        if msg.is_punct:
            return  # watermark progress is carried by released messages
        key = self.key_of(msg.payload)
        nxt = self._next.get(key, 0)
        if msg.id == nxt:
            self.next_node.handle_msg(0, msg)
            nxt += 1
            pend = self._pending.get(key)
            while pend:
                m = pend.pop(nxt, None)
                if m is None:
                    break
                self.next_node.handle_msg(0, m)
                nxt += 1
            self._next[key] = nxt
        else:
            self._pending.setdefault(key, {})[msg.id] = msg

    def terminate(self) -> None:
        for key, pend in self._pending.items():
            for i in sorted(pend):
                self.next_node.handle_msg(0, pend[i])
        self._pending.clear()

    def snapshot_state(self) -> dict:
        return {"next": dict(self._next),
                "pending": {k: dict(v) for k, v in self._pending.items()}}

    def restore_state(self, state: dict) -> None:
        self._next = dict(state.get("next", {}))
        self._pending = {k: dict(v)
                         for k, v in state.get("pending", {}).items()}


class DPJoinCollector(BasicCollector):
    """For DP-mode Interval_Join in DEFAULT mode (reference
    ``wf/join_collector.hpp``): every broadcast replica must observe the
    SAME tuple sequence so their round-robin storage assignment agrees.
    Messages buffer until the min watermark across channels STRICTLY
    passes their timestamp, then release in total (ts, channel, id) order —
    a content-determined order identical on every replica regardless of
    arrival interleaving (releasing ts == bound on arrival would expose
    cross-channel arrival order for ties). Punctuations are forwarded after
    the releases they trigger."""

    def __init__(self, n_channels: int, next_node: Any,
                 separator_id: Optional[int] = None) -> None:
        super().__init__(n_channels, next_node, separator_id)
        self._ch_wm = [0] * n_channels
        self._heap: list = []  # (ts, ch, id, msg)

    def _min_wm(self) -> int:
        if not self.live:
            return MAX_WM
        return min(self._ch_wm[c] for c in self.live)

    def handle_msg(self, ch: int, msg: Any) -> None:
        wm = msg.min_watermark()
        if wm > self._ch_wm[ch]:
            self._ch_wm[ch] = wm
        self._tag(ch, msg)
        if not msg.is_punct:
            if isinstance(msg, Batch):
                # flatten: ordering whole batches by their first row would
                # break the per-row ts order the DP purge frontier relies on
                for ri, (payload, ts) in enumerate(msg.rows):
                    row = Single(payload, (msg.id << 20) | ri, ts, msg.wm)
                    row.stream_tag = msg.stream_tag
                    heapq.heappush(self._heap, (ts, ch, row.id, row))
            else:
                heapq.heappush(self._heap, (msg.ts, ch, msg.id, msg))
        bound = self._min_wm()
        self._release(bound)
        if msg.is_punct:
            msg.wm = bound if bound < MAX_WM else wm
            self.next_node.handle_msg(0, msg)

    def _release(self, bound: int) -> None:
        # strict: a message with ts == bound could still be followed by a
        # same-ts message on another channel
        while self._heap and self._heap[0][0] < bound:
            _, _, _, m = heapq.heappop(self._heap)
            if bound < MAX_WM:
                m.wm = bound
            # post-EOS drain (bound == MAX_WM): keep each message's own
            # watermark — inflating it would purge the join archives while
            # pending pairs still need them
            self.next_node.handle_msg(0, m)

    def on_channel_eos(self, ch: int) -> None:
        super().on_channel_eos(ch)
        self._release(self._min_wm())

    def terminate(self) -> None:
        while self._heap:
            _, _, _, m = heapq.heappop(self._heap)
            self.next_node.handle_msg(0, m)

    def snapshot_state(self) -> dict:
        return {"ch_wm": list(self._ch_wm), "heap": list(self._heap)}

    def restore_state(self, state: dict) -> None:
        wm = state.get("ch_wm")
        if wm is not None and len(wm) == len(self._ch_wm):
            self._ch_wm = list(wm)
        self._heap = list(state.get("heap", []))
        heapq.heapify(self._heap)


class KSlackCollector(BasicCollector):
    """Adaptive K-slack (``wf/kslack_collector.hpp:99-118``): K tracks the
    maximum observed disorder ``max_ts - ts``; buffered tuples are released in
    timestamp order once ``ts <= max_ts - K``. Tuples older than the released
    frontier are dropped and counted."""

    def __init__(self, n_channels: int, next_node: Any,
                 dropped_counter: Optional[AtomicCounter] = None,
                 separator_id: Optional[int] = None) -> None:
        super().__init__(n_channels, next_node, separator_id)
        self.K = 0
        self._max_ts = 0
        self._frontier = -1  # max ts already released
        self._heap: list = []  # (ts, seq, msg)
        self._seq = 0
        self.dropped = dropped_counter if dropped_counter is not None else AtomicCounter()

    @staticmethod
    def _ts_of(msg: Any) -> int:
        if isinstance(msg, Batch):
            return msg.rows[0][1] if msg.rows else 0
        return msg.ts

    def handle_msg(self, ch: int, msg: Any) -> None:
        if msg.is_punct:
            return
        self._tag(ch, msg)
        ts = self._ts_of(msg)
        # adapt K from EVERY arrival (including late ones we then drop) —
        # otherwise K never learns the stream's disorder and the frontier
        # drops everything behind it
        if ts > self._max_ts:
            self._max_ts = ts
        delay = self._max_ts - ts
        if delay > self.K:
            self.K = delay
        if ts <= self._frontier:
            n = msg.size if isinstance(msg, Batch) else 1
            self.dropped.add(n)
            return
        heapq.heappush(self._heap, (ts, self._seq, msg))
        self._seq += 1
        self._release(self._max_ts - self.K)

    def _release(self, up_to: int) -> None:
        while self._heap and self._heap[0][0] <= up_to:
            ts, _, m = heapq.heappop(self._heap)
            if ts > self._frontier:
                self._frontier = ts
            self.next_node.handle_msg(0, m)

    def terminate(self) -> None:
        self._release(MAX_WM)

    def snapshot_state(self) -> dict:
        return {"K": self.K, "max_ts": self._max_ts,
                "frontier": self._frontier, "heap": list(self._heap),
                "seq": self._seq}

    def restore_state(self, state: dict) -> None:
        self.K = state.get("K", 0)
        self._max_ts = state.get("max_ts", 0)
        self._frontier = state.get("frontier", -1)
        self._seq = state.get("seq", 0)
        self._heap = list(state.get("heap", []))
        heapq.heapify(self._heap)
