"""DeviceDispatchQueue: the per-replica device-ahead dispatch pipeline.

Every TPU replica's per-batch work has two halves:

- a HOST-PREP stage — pure host control plane over the batch's host
  metadata: key -> slot resolution, leaf/pane bookkeeping, fire-pack and
  grid assembly (numpy, no device handles touched);
- a DEVICE-COMMIT stage — the XLA program call(s) on the replica's
  device state plus the downstream emit, including any readback the emit
  path needs (compaction counts, routing columns).

XLA's async dispatch already overlaps *device execution* with later host
work, but the commit stage itself still serializes with the next batch's
host prep: its Python-side program-call overhead, the donation hand-off
of the replica's device state, and above all the emit path's readbacks
(an ``np.asarray``/``int()`` on a fresh program output blocks until that
program ran). This queue defers the commit stage of up to ``depth``
batches, mirroring ``_D2HPipeline`` on the exit edges: by the time a
commit is popped, ``depth`` later batches have been prepped and the
deferred readbacks land on long-materialized results instead of
stalling. ``WF_DISPATCH_DEPTH=0`` restores the fully synchronous path
(commit runs inside ``submit``), which the differential tests pin
against depth >= 2 for exact result equality.

Ordering contract: commits run strictly in submission order, on the
replica's own worker thread (no cross-thread hand-off — the queue is a
deferral buffer, not a concurrency primitive). The replica drains it at
every ordering point: before punctuation propagates, at EOS/terminate,
before any host code touches the replica's device state (forest/table
growth, program warm-up), and on the worker's idle tick so a quiet
stream never parks prepared batches. A commit that raises marks the
pipeline broken and discards the remaining entries — they were prepped
against control-plane state the failed batch already advanced, so
re-running them after the error would emit from an inconsistent forest;
the error itself unwinds the worker (drain-inputs + emergency EOS).

MEGABATCH (``WF_MEGABATCH=K``, default 1 = off): when the queue
overflows, a FRONT run of commits carrying the same ``scan_sig`` (same
fused chain, same program signature, same capacity bucket —
``tpu/fused_ops.py`` attaches the attribute) is popped as ONE group and
handed to the commits' ``scan_runner``, which executes all of them in a
single jitted ``lax.scan`` over the chain program: K batches, ONE host
dispatch. Only the largest power-of-two prefix of the run groups (so
the set of compiled scan programs stays enumerable for the pre-warm);
mixed-signature, non-fused, or lone commits run as singles. ``drain``
always runs singles, so every ordering point — punctuation, EOS,
checkpoint snapshot, device-state access, error unwind — degrades to
K=1 and the alignment/exactly-once/rescale semantics are untouched.
Commits still run strictly in submission order either way (the scan
body IS the chain program, threading the same carried state
batch-to-batch).

Per-stage instrumentation lands in the replica's ``StatsRecord``
(``Dispatch_host_prep_usec`` / ``Dispatch_commit_usec`` EWMAs + totals,
forced-drain stall count, max queue depth) so the host-prep/device split
is measured, not asserted — ``scripts/microbench.py --dispatch`` reports
the split and the overlap efficiency it buys.
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Callable, Optional

from ..monitoring.tracing import device_span

_DEFAULT_DEPTH = 2


def dispatch_depth(default: int = _DEFAULT_DEPTH) -> int:
    """The configured pipeline depth (``WF_DISPATCH_DEPTH``, default 2;
    0 = synchronous). Malformed values fall back to the default — a bad
    knob must not take down the graph."""
    try:
        return max(0, int(os.environ.get("WF_DISPATCH_DEPTH",
                                         str(default))))
    except ValueError:
        return default


def megabatch_k(default: int = 1) -> int:
    """The configured megabatch width (``WF_MEGABATCH``, default 1;
    0/1 = off — every commit runs as its own program). Malformed values
    fall back to the default."""
    try:
        return max(1, int(os.environ.get("WF_MEGABATCH", str(default))))
    except ValueError:
        return default


class DeviceDispatchQueue:
    """Bounded FIFO of deferred device-commit thunks (see module doc)."""

    def __init__(self, stats=None, depth: Optional[int] = None,
                 megabatch: Optional[int] = None) -> None:
        self.depth = dispatch_depth() if depth is None else max(0, depth)
        self.megabatch = (megabatch_k() if megabatch is None
                          else max(1, megabatch))
        # a K-wide megabatch can only form if K prepped commits can sit
        # in the queue; the scan loop implies at least that much lag.
        # depth 0 (synchronous) wins: commits never queue at all.
        if self.depth > 0 and self.megabatch > 1:
            self.depth = max(self.depth, self.megabatch)
        self.stats = stats
        # jax.profiler span label so captured device traces line up with
        # the Dispatch_commit stats (prep span lives in the replica)
        self._span_commit = "wf:commit:" + (
            stats.op_name if stats is not None and stats.op_name else "?")
        # entries are (commit, enqueue_perf_counter): the enqueue stamp
        # feeds the flight recorder's dispatch_wait span (how long the
        # prepared batch sat in the queue before its commit ran)
        self._q: "deque" = deque()

    def __len__(self) -> int:
        return len(self._q)

    # ------------------------------------------------------------------
    def submit(self, commit: Callable[[], None],
               prep_us: float = 0.0) -> None:
        """Record the host-prep time and queue (or, at depth 0, run) one
        batch's device-commit stage. Overflowing ``depth`` commits the
        oldest entry — the blocking pop that gives the pipeline its
        bounded lag."""
        if self.stats is not None:
            self.stats.note_host_prep(prep_us)
        if self.depth == 0:
            self._run(commit, None)
            return
        self._q.append((commit, time.perf_counter()))
        # record the PEAK occupancy (post-append, pre-pop): a pipeline
        # running steady-state at full depth overflows on every submit,
        # and recording only the post-pop length would under-report
        # Dispatch_queue_depth_max as never-saturated
        if self.stats is not None:
            self.stats.note_dispatch_depth(len(self._q))
            rec = self.stats.recorder
            if rec is not None:
                rec.event("dispatch_submit", 0.0, len(self._q))
        while len(self._q) > self.depth:
            self._pop_run()

    def drain(self, forced: bool = False) -> None:
        """Commit everything in flight. ``forced=True`` marks an
        ordering-point drain (punctuation/EOS/device-state access) in the
        stats as a readback stall — the pipeline had to give up its lag."""
        if forced and self._q and self.stats is not None:
            self.stats.note_dispatch_stall()
        while self._q:
            self._run(*self._q.popleft())

    def on_idle(self) -> bool:
        """Worker idle tick: a quiet stream must not park prepared
        batches (same contract as ``_D2HPipeline.on_idle``). Returns
        whether anything was committed (drives the worker's backoff)."""
        had = bool(self._q)
        self.drain()
        return had

    def abort(self) -> None:
        """Discard pending commits WITHOUT running them (error unwind:
        the entries were prepped against control-plane state the failed
        batch already advanced)."""
        self._q.clear()

    # ------------------------------------------------------------------
    def _pop_run(self) -> None:
        """Overflow pop: commit the oldest entry — or, with megabatching
        on, the longest same-signature power-of-two FRONT run as one
        grouped scan dispatch. Popping never reorders: the group is a
        contiguous prefix and the scan walks it in submission order."""
        q = self._q
        k = self.megabatch
        sig = (getattr(q[0][0], "scan_sig", None) if k > 1 else None)
        if sig is None:
            self._run(*q.popleft())
            return
        run = 1
        while run < k and run < len(q) \
                and getattr(q[run][0], "scan_sig", None) == sig:
            run += 1
        g = 1 << (run.bit_length() - 1)  # largest power of two <= run
        if g < 2:
            self._run(*q.popleft())
            return
        self._run_group([q.popleft() for _ in range(g)])

    def _run_group(self, entries) -> None:
        """Run a same-signature group through the commits' scan runner
        (``FusedTPUReplica._run_megabatch``): one program, one dispatch,
        len(entries) batches. Error unwind matches ``_run`` — a failed
        group aborts the remaining pipeline entries."""
        t0 = time.perf_counter()
        if self.stats is not None:
            rec = self.stats.recorder
            if rec is not None:
                for _commit, enq_t in entries:
                    rec.event("dispatch_wait", (t0 - enq_t) * 1e6)
        commits = [commit for commit, _t in entries]
        try:
            with device_span(self._span_commit):
                commits[0].scan_runner(commits)
        except BaseException:
            self.abort()
            raise
        finally:
            if self.stats is not None:
                self.stats.note_dispatch_commit(
                    (time.perf_counter() - t0) * 1e6)

    def _run(self, commit: Callable[[], None],
             enq_t: Optional[float] = None) -> None:
        t0 = time.perf_counter()
        if enq_t is not None and self.stats is not None:
            rec = self.stats.recorder
            if rec is not None:
                rec.event("dispatch_wait", (t0 - enq_t) * 1e6)
        try:
            with device_span(self._span_commit):
                commit()
        except BaseException:
            self.abort()
            raise
        finally:
            if self.stats is not None:
                self.stats.note_dispatch_commit(
                    (time.perf_counter() - t0) * 1e6)
