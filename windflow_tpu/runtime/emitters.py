"""Emitters: the routing plane on the producer side.

Parity notes:
- Protocol mirrors ``wf/basic_emitter.hpp:49-121`` (emit, propagate
  punctuation, flush, clone-per-replica); the reference's function-pointer
  ``doEmit`` devirtualization is unnecessary in Python — the analogous
  optimization here is micro-batching, which amortizes per-message costs and
  is also what feeds the device plane.
- Forward/round-robin: ``wf/forward_emitter.hpp``; KeyBy hash routing with
  watermark punctuation generation: ``wf/keyby_emitter.hpp:210-259,305-376``;
  Broadcast multicast: ``wf/broadcast_emitter.hpp``; Splitting tree emitter:
  ``wf/splitting_emitter.hpp:48-341``.
- Watermark-punctuation cadence: every ``DEFAULT_WM_AMOUNT`` emitted tuples
  the emitter checks whether ``DEFAULT_WM_INTERVAL_USEC`` elapsed and, if so,
  flushes partial batches and sends a punctuation carrying the producer's
  current watermark so idle destinations keep making event-time progress
  (``wf/basic.hpp:199-216``).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

from ..basic import (DEFAULT_WM_AMOUNT, DEFAULT_WM_INTERVAL_USEC,
                     ExecutionMode, RoutingMode, current_time_usecs)
from ..message import Batch, Single, make_punctuation
from .channel import Port


class BasicEmitter:
    """Base: owns destination ports, optional micro-batching, per-destination
    id counters (DETERMINISTIC ordering), punctuation cadence."""

    mode: RoutingMode = RoutingMode.NONE

    def __init__(self, num_dests: int, output_batch_size: int = 0,
                 execution_mode: ExecutionMode = ExecutionMode.DEFAULT,
                 punct_generation: bool = True) -> None:
        self.num_dests = num_dests
        self.output_batch_size = output_batch_size
        self.execution_mode = execution_mode
        self.punct_generation = punct_generation  # off for inline chain edges
        self.ports: List[Port] = []  # wired by the topology layer
        self._next_ids = [0] * num_dests
        self._emit_count = 0
        self._last_punct_usec = current_time_usecs()
        self.stats = None  # optional StatsRecord of the owning replica
        # transient latency-tracing origin stamp: the owning replica (or
        # source shipper) sets it just before emit; the first message
        # created while it is non-zero carries it and clears it
        # (monitoring/tracing.py — 0 means "current tuple untraced")
        self.trace_ts = 0

    # -- wiring ------------------------------------------------------------
    def set_stats(self, stats) -> None:
        self.stats = stats

    def set_ports(self, ports: Sequence[Port]) -> None:
        assert len(ports) == self.num_dests, (len(ports), self.num_dests)
        self.ports = list(ports)

    # -- core send helpers -------------------------------------------------
    def _send_single(self, dest: int, payload: Any, ts: int, wm: int,
                     msg_id: Optional[int] = None) -> None:
        """``msg_id`` overrides the per-destination counter: window replicas
        stamp result/pane identifiers consumed by downstream ID-sequencing
        collectors (reference ``doEmit`` identifier argument)."""
        msg = Single(payload,
                     self._next_ids[dest] if msg_id is None else msg_id,
                     ts, wm)
        if self.trace_ts:
            msg.trace_ts = self.trace_ts
            self.trace_ts = 0
        self._next_ids[dest] += 1
        if self.stats is not None:
            self.stats.outputs_sent += 1
        self.ports[dest].send(msg)

    def _send_batch(self, dest: int, batch: Batch) -> None:
        batch.id = self._next_ids[dest]
        self._next_ids[dest] += 1
        if self.stats is not None:
            self.stats.outputs_sent += batch.size
        self.ports[dest].send(batch)

    def _send_punct(self, dest: int, wm: int) -> None:
        p = make_punctuation(wm)
        p.id = self._next_ids[dest]
        self._next_ids[dest] += 1
        if self.stats is not None:
            self.stats.punct_sent += 1
        self.ports[dest].send(p)

    # -- punctuation cadence (generate_punctuation, keyby_emitter.hpp:305) --
    def _maybe_generate_punctuation(self, wm: int) -> None:
        if not self.punct_generation or self.execution_mode is not ExecutionMode.DEFAULT:
            return
        self._emit_count += 1
        if self._emit_count % DEFAULT_WM_AMOUNT != 0:
            return
        now = current_time_usecs()
        if now - self._last_punct_usec < DEFAULT_WM_INTERVAL_USEC:
            return
        self._last_punct_usec = now
        self.propagate_punctuation(wm)

    # -- public API --------------------------------------------------------
    def emit(self, payload: Any, ts: int, wm: int,
             msg_id: Optional[int] = None) -> None:
        raise NotImplementedError

    def emit_columns(self, cols, ts_arr, wm: int, trace_rows=None) -> None:
        """Columnar push (SourceShipper.push_columns). Generic emitters
        materialize dict rows; the device staging emitter overrides this
        with a vectorized path that never touches individual tuples.
        ``trace_rows`` (optional int indices into the block) marks the
        traced cohort: each marked row re-arms ``trace_ts`` so sampling
        matches the row path exactly."""
        names = list(cols)
        pulled = [cols[n] for n in names]
        t0 = self.trace_ts
        marks = None
        nxt = -1
        if t0 and trace_rows is not None and len(trace_rows):
            self.trace_ts = 0
            marks = iter(trace_rows)
            nxt = int(next(marks, -1))
        for i in range(len(ts_arr)):
            if i == nxt:
                self.trace_ts = t0
                nxt = int(next(marks, -1))
            self.emit({n: p[i].item() for n, p in zip(names, pulled)},
                      int(ts_arr[i]), wm)

    def propagate_punctuation(self, wm: int) -> None:
        """Flush partial batches then punctuate every destination; flushing
        first preserves per-channel watermark monotonicity."""
        self.flush()
        for d in range(self.num_dests):
            self._send_punct(d, wm)

    def flush(self) -> None:
        """Send any partially-filled output batches (EOS / punctuation)."""

    def send_eos_all(self) -> None:
        self.flush()
        for port in self.ports:
            port.send_eos()

    def send_barrier_all(self, barrier) -> None:
        """Checkpoint-barrier propagation: flush partial batches FIRST so
        every already-emitted tuple stays pre-barrier on its channel, then
        send the barrier on every edge (one per port, like EOS — never
        batched, never reordered)."""
        self.flush()
        for port in self.ports:
            port.send(barrier.copy_for_dest())

    def eos_ports(self) -> Sequence[Port]:
        """All queue ports (for emergency EOS propagation on worker error)."""
        return self.ports

    # -- checkpointing: routing counters travel with the replica blob ------
    # (per-destination ids keep DETERMINISTIC-mode collectors' monotonic-id
    # contract across a restore; the round-robin cursor keeps FORWARD
    # placement deterministic)
    def emitter_state(self) -> dict:
        st = {"next_ids": list(self._next_ids),
              "emit_count": self._emit_count}
        rr = getattr(self, "_rr", None)
        if rr is not None:
            st["rr"] = rr
        return st

    def restore_emitter_state(self, state: dict) -> None:
        ids = state.get("next_ids")
        if ids is not None and len(ids) == len(self._next_ids):
            self._next_ids = list(ids)
        self._emit_count = state.get("emit_count", 0)
        if "rr" in state and hasattr(self, "_rr"):
            self._rr = state["rr"]


class ForwardEmitter(BasicEmitter):
    """FORWARD / REBALANCING: round-robin across destinations; with batching,
    fills one batch at a time and round-robins full batches
    (``wf/forward_emitter.hpp``)."""

    mode = RoutingMode.FORWARD

    def __init__(self, num_dests: int, output_batch_size: int = 0,
                 execution_mode: ExecutionMode = ExecutionMode.DEFAULT) -> None:
        super().__init__(num_dests, output_batch_size, execution_mode)
        self._rr = 0
        self._batch: Optional[Batch] = None

    def emit(self, payload: Any, ts: int, wm: int,
             msg_id: Optional[int] = None) -> None:
        if self.output_batch_size <= 0:
            self._send_single(self._rr, payload, ts, wm, msg_id)
            self._rr = (self._rr + 1) % self.num_dests
        else:
            if self._batch is None:
                self._batch = Batch()
            self._batch.add_tuple(payload, ts, wm)
            if self.trace_ts:
                self._batch.note_trace(self.trace_ts)
                self.trace_ts = 0
            if self._batch.size >= self.output_batch_size:
                self._send_batch(self._rr, self._batch)
                self._rr = (self._rr + 1) % self.num_dests
                self._batch = None
        self._maybe_generate_punctuation(wm)

    def flush(self) -> None:
        if self._batch is not None and self._batch.size > 0:
            self._send_batch(self._rr, self._batch)
            self._rr = (self._rr + 1) % self.num_dests
            self._batch = None


class KeyByEmitter(BasicEmitter):
    """KEYBY: ``dest = hash(key(payload)) % num_dests`` with per-destination
    output batches (``wf/keyby_emitter.hpp:210-259``)."""

    mode = RoutingMode.KEYBY

    def __init__(self, key_extractor: Callable[[Any], Any], num_dests: int,
                 output_batch_size: int = 0,
                 execution_mode: ExecutionMode = ExecutionMode.DEFAULT) -> None:
        super().__init__(num_dests, output_batch_size, execution_mode)
        self.key_extractor = key_extractor
        self._batches: List[Optional[Batch]] = [None] * num_dests

    def emit(self, payload: Any, ts: int, wm: int,
             msg_id: Optional[int] = None) -> None:
        dest = hash(self.key_extractor(payload)) % self.num_dests
        if self.output_batch_size <= 0:
            self._send_single(dest, payload, ts, wm, msg_id)
        else:
            b = self._batches[dest]
            if b is None:
                b = self._batches[dest] = Batch()
            b.add_tuple(payload, ts, wm)
            if self.trace_ts:
                b.note_trace(self.trace_ts)
                self.trace_ts = 0
            if b.size >= self.output_batch_size:
                self._send_batch(dest, b)
                self._batches[dest] = None
        self._maybe_generate_punctuation(wm)

    def flush(self) -> None:
        for d, b in enumerate(self._batches):
            if b is not None and b.size > 0:
                self._send_batch(d, b)
                self._batches[d] = None


class BroadcastEmitter(BasicEmitter):
    """BROADCAST: every destination receives a copy
    (``wf/broadcast_emitter.hpp``; the reference shares one refcounted message,
    we copy — payload objects are shared, so broadcast-fed in-place operators
    must copy-on-write, ``wf/map.hpp:348``)."""

    mode = RoutingMode.BROADCAST

    def __init__(self, num_dests: int, output_batch_size: int = 0,
                 execution_mode: ExecutionMode = ExecutionMode.DEFAULT) -> None:
        super().__init__(num_dests, output_batch_size, execution_mode)
        self._batch: Optional[Batch] = None

    def emit(self, payload: Any, ts: int, wm: int,
             msg_id: Optional[int] = None) -> None:
        if self.output_batch_size <= 0:
            for d in range(self.num_dests):
                self._send_single(d, payload, ts, wm, msg_id)
        else:
            if self._batch is None:
                self._batch = Batch()
            self._batch.add_tuple(payload, ts, wm)
            if self.trace_ts:
                self._batch.note_trace(self.trace_ts)
                self.trace_ts = 0
            if self._batch.size >= self.output_batch_size:
                self._broadcast_batch(self._batch)
                self._batch = None
        self._maybe_generate_punctuation(wm)

    def _broadcast_batch(self, batch: Batch) -> None:
        for d in range(self.num_dests):
            self._send_batch(d, batch.copy_for_dest() if d > 0 else batch)

    def flush(self) -> None:
        if self._batch is not None and self._batch.size > 0:
            self._broadcast_batch(self._batch)
            self._batch = None


def check_branch_index(s: int, n_branches: int) -> int:
    """Shared split-branch validation (CPU and device planes)."""
    if not 0 <= s < n_branches:
        from ..basic import WindFlowError
        raise WindFlowError(
            f"splitting logic returned branch index {s} outside "
            f"[0, {n_branches})")
    return s


class SplittingEmitter(BasicEmitter):
    """Tree emitter for MultiPipe::split: user logic selects branch index(es);
    one inner emitter per branch (``wf/splitting_emitter.hpp:48-341``)."""

    mode = RoutingMode.NONE

    def __init__(self, splitting_logic: Callable[[Any], Any],
                 inner_emitters: List[BasicEmitter],
                 execution_mode: ExecutionMode = ExecutionMode.DEFAULT) -> None:
        super().__init__(sum(e.num_dests for e in inner_emitters), 0, execution_mode)
        self.splitting_logic = splitting_logic
        self.inner = inner_emitters

    def set_ports(self, ports: Sequence[Port]) -> None:
        # ports are laid out branch-by-branch in order
        self.ports = list(ports)
        off = 0
        for e in self.inner:
            e.set_ports(ports[off:off + e.num_dests])
            off += e.num_dests

    def emit(self, payload: Any, ts: int, wm: int,
             msg_id: Optional[int] = None) -> None:
        sel = self.splitting_logic(payload)
        if sel is None:
            self.trace_ts = 0
            return
        t0 = self.trace_ts
        if t0:
            self.trace_ts = 0
        n = len(self.inner)
        if isinstance(sel, int):
            inner = self.inner[check_branch_index(sel, n)]
            inner.trace_ts = t0
            inner.emit(payload, ts, wm, msg_id)
        else:
            for s in sel:
                inner = self.inner[check_branch_index(s, n)]
                inner.trace_ts = t0
                inner.emit(payload, ts, wm, msg_id)

    def propagate_punctuation(self, wm: int) -> None:
        for e in self.inner:
            e.propagate_punctuation(wm)

    def flush(self) -> None:
        for e in self.inner:
            e.flush()

    def send_eos_all(self) -> None:
        for e in self.inner:
            e.send_eos_all()

    def send_barrier_all(self, barrier) -> None:
        for e in self.inner:
            e.send_barrier_all(barrier)

    def eos_ports(self):
        return [p for e in self.inner for p in e.eos_ports()]

    def emitter_state(self) -> dict:
        return {"inner": [e.emitter_state() for e in self.inner]}

    def restore_emitter_state(self, state: dict) -> None:
        inner = state.get("inner", [])
        for e, st in zip(self.inner, inner):
            e.restore_emitter_state(st)


class NullEmitter(BasicEmitter):
    """Terminal operators (Sink) have no output."""

    def __init__(self) -> None:
        super().__init__(0, 0)

    def emit(self, payload: Any, ts: int, wm: int,
             msg_id: Optional[int] = None) -> None:  # pragma: no cover
        raise RuntimeError("Sink cannot emit")

    def propagate_punctuation(self, wm: int) -> None:
        pass

    def send_eos_all(self) -> None:
        pass
