"""windflow_tpu — a TPU-native data-stream-processing framework.

Same capabilities as ParaGroup/WindFlow (Storm/Flink-style operators over
micro-batched streams, watermark-based out-of-order handling, four window
parallelism strategies, DAG composition via MultiPipe/PipeGraph, fluent
builders) with the CUDA device plane replaced by a JAX/XLA one: micro-batches
are staged into TPU HBM as columnar arrays, per-batch operator functors are
JIT-compiled XLA programs, keyed shuffles become sort/segment programs, and
the FlatFAT sliding-window tree is a batched segment tree in HBM
(``Ffat_Windows_TPU``). Multi-chip scale-out (a surface the single-node
reference lacks) shards the whole keyed-state plane over a
``jax.sharding.Mesh`` (``windflow_tpu.mesh``: sharded FFAT windows,
stateful Map/Filter grid tables, keyed Reduce — KEYBY lowered to
in-program ``lax.all_to_all`` collectives, with sharded
checkpoint/restore onto any mesh factorization).

Import layering: ``import windflow_tpu`` pulls only the CPU plane (no jax);
``windflow_tpu.tpu`` loads the device plane lazily. Subpackages:
``windflow_tpu.tpu`` (device operators), ``windflow_tpu.mesh``
(the mesh execution plane; ``windflow_tpu.parallel`` is its compat
shim), ``windflow_tpu.persistent`` (out-of-core keyed state),
``windflow_tpu.kafka`` (connectors), ``windflow_tpu.monitoring``.
"""

from .basic import (ExecutionMode, JoinMode, KeyCapacityError, RoutingMode,
                    TimePolicy, WindFlowError, WinType)
from .builders import (Columnar_Source_Builder, Ffat_Windows_Builder,
                       Filter_Builder, Interval_Join_Builder,
                       FlatMap_Builder, Keyed_Windows_Builder, Map_Builder,
                       MapReduce_Windows_Builder, Paned_Windows_Builder,
                       Parallel_Windows_Builder, Reduce_Builder, Sink_Builder,
                       Source_Builder)
from .checkpoint import CorruptCheckpointError
from .context import LocalStorage, RuntimeContext
from .message import Batch, Single
from .operators.basic_ops import (Filter, FlatMap, Map, Reduce, Shipper, Sink)
from .operators.ffat import Ffat_Windows
from .operators.join import Interval_Join
from .operators.flatfat import FlatFAT
from .operators.window_engine import WinResult
from .operators.windows import (Keyed_Windows, MapReduce_Windows,
                                Paned_Windows, Parallel_Windows)
from .operators.source import (ArrayBlockSource, Columnar_Source, Source,
                               SourceShipper, arrow_block_source)
from .overload import GovernorPolicy, ShedLog, TokenBucket
from .scaling.autoscaler import AutoscalePolicy
from .sinks.transactional import FencedWriteError
from .supervision import (DeadLetterQueue, ErrorPolicy, RestartPolicy,
                          SupervisionEscalated)
from .topology.multipipe import MultiPipe
from .topology.pipegraph import PipeGraph

__version__ = "0.1.0"

__all__ = [
    "ExecutionMode", "TimePolicy", "WinType", "RoutingMode", "JoinMode",
    "WindFlowError", "KeyCapacityError", "FencedWriteError",
    "CorruptCheckpointError",
    "PipeGraph", "MultiPipe",
    "Source", "Columnar_Source", "Map", "Filter", "FlatMap", "Reduce", "Sink",
    "SourceShipper", "Shipper",
    "ArrayBlockSource", "arrow_block_source",
    "RuntimeContext", "LocalStorage",
    "Single", "Batch",
    "Source_Builder", "Columnar_Source_Builder",
    "Map_Builder", "Filter_Builder", "FlatMap_Builder",
    "Reduce_Builder", "Sink_Builder",
    "Keyed_Windows", "Parallel_Windows", "Paned_Windows",
    "MapReduce_Windows", "Ffat_Windows", "FlatFAT", "WinResult",
    "Keyed_Windows_Builder", "Parallel_Windows_Builder",
    "Paned_Windows_Builder", "MapReduce_Windows_Builder",
    "Ffat_Windows_Builder", "Interval_Join", "Interval_Join_Builder",
    "AutoscalePolicy",
    "GovernorPolicy", "TokenBucket", "ShedLog",
    "RestartPolicy", "ErrorPolicy", "DeadLetterQueue",
    "SupervisionEscalated",
    "__version__",
]
