"""Dataflow diagram rendering.

The reference renders its PipeGraph with Graphviz — an SVG for the web
dashboard and a PDF at ``wait_end`` (``wf/pipegraph.hpp:525-534,732-734``).
Here rendering is two-tier:

- ``render_graphviz(dot_src, fmt)`` shells out to the ``dot`` binary when
  one is installed (full parity: any format Graphviz supports);
- ``stages_to_svg(stages)`` is a dependency-free layered renderer (longest
  -path layering, one column per depth) so the dashboard always has a real
  picture even on images without Graphviz — which is the common case for
  TPU pods.
"""

from __future__ import annotations

import html
import shutil
import subprocess
from typing import List, Optional


def render_graphviz(dot_src: str, fmt: str = "svg") -> Optional[bytes]:
    """Render through the ``dot`` binary; None when unavailable/failed."""
    exe = shutil.which("dot")
    if exe is None:
        return None
    try:
        r = subprocess.run([exe, f"-T{fmt}"], input=dot_src.encode(),
                           capture_output=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    return r.stdout if r.returncode == 0 else None


_BOX_W, _BOX_H, _GAP_X, _GAP_Y, _PAD = 156, 46, 64, 26, 28


def stages_to_svg(stages: List, title: str = "") -> str:
    """Layered SVG of the stage DAG (no external dependencies).

    ``stages`` is PipeGraph._stages: each has ``id``, ``describe()``,
    ``ops`` (with ``parallelism``), ``upstreams`` (edges with ``stage`` and
    ``branch``)."""
    depth = {}

    def _depth(s) -> int:
        if s.id in depth:
            return depth[s.id]
        depth[s.id] = 0  # breaks cycles defensively; DAGs have none
        d = 0
        for e in s.upstreams:
            d = max(d, _depth(e.stage) + 1)
        depth[s.id] = d
        return d

    for s in stages:
        _depth(s)
    columns: dict = {}
    for s in stages:
        columns.setdefault(depth[s.id], []).append(s)
    pos = {}
    n_rows = max((len(c) for c in columns.values()), default=1)
    for d, col in sorted(columns.items()):
        for r, s in enumerate(col):
            x = _PAD + d * (_BOX_W + _GAP_X)
            y = _PAD + r * (_BOX_H + _GAP_Y) + (
                (n_rows - len(col)) * (_BOX_H + _GAP_Y)) // 2
            pos[s.id] = (x, y)
    width = _PAD * 2 + (max(columns, default=0) + 1) * (_BOX_W + _GAP_X)
    height = _PAD * 2 + n_rows * (_BOX_H + _GAP_Y)
    out = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
        f'height="{height}" viewBox="0 0 {width} {height}" '
        f'font-family="monospace" font-size="11">',
        '<defs><marker id="arr" markerWidth="8" markerHeight="8" refX="7" '
        'refY="3" orient="auto"><path d="M0,0 L7,3 L0,6 z" fill="#555"/>'
        "</marker></defs>",
    ]
    # escape only &<> (quote=False): quote escaping would emit &#x27;
    # numeric entities that the dashboard's reject-by-default sanitizer
    # refuses (an apostrophe is legal XML text as-is)
    if title:
        out.append(f'<text x="{_PAD}" y="16" font-size="13" '
                   f'fill="#333">{html.escape(title, quote=False)}</text>')
    for s in stages:  # edges under boxes
        x1, y1 = pos[s.id]
        for e in s.upstreams:
            x0, y0 = pos[e.stage.id]
            ax, ay = x0 + _BOX_W, y0 + _BOX_H // 2
            bx, by = x1, y1 + _BOX_H // 2
            mx = (ax + bx) / 2
            out.append(
                f'<path d="M{ax},{ay} C{mx},{ay} {mx},{by} {bx},{by}" '
                'fill="none" stroke="#555" stroke-width="1.2" '
                'marker-end="url(#arr)"/>')
            if e.branch is not None:
                out.append(f'<text x="{mx - 8}" y="{(ay + by) / 2 - 4}" '
                           f'fill="#a33">b{e.branch}</text>')
    for s in stages:
        x, y = pos[s.id]
        # truncate BEFORE escaping: clipping an entity mid-way would make
        # the standalone .svg invalid XML
        label = html.escape(s.describe()[:22], quote=False)
        par = "|".join(str(o.parallelism) for o in s.ops)
        is_dev = any(getattr(o, "is_tpu", False) for o in s.ops)
        fill = "#e8f0fe" if is_dev else "#f5f5f5"
        refused = getattr(s, "chain_refused", None)
        # chain() fallback diagnostics as a hover tooltip (the dot output
        # carries the same reason as a label line)
        tooltip = ("<title>" + html.escape(f"unchained: {refused}",
                                           quote=False) + "</title>"
                   if refused else "")
        out.append(
            f'<rect x="{x}" y="{y}" width="{_BOX_W}" height="{_BOX_H}" '
            f'rx="7" fill="{fill}" stroke="#888">{tooltip}</rect>')
        out.append(f'<text x="{x + _BOX_W / 2}" y="{y + 19}" '
                   f'text-anchor="middle">{label}</text>')
        out.append(f'<text x="{x + _BOX_W / 2}" y="{y + 36}" '
                   f'text-anchor="middle" fill="#666">({par})</text>')
    out.append("</svg>")
    return "\n".join(out)
