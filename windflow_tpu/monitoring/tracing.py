"""Sampled per-tuple latency tracing — configuration and span helpers.

The tracing plane has three parts (none of which replaces the EWMAs —
those stay for dashboard parity):

- SOURCES stamp a sampled subset of tuples with a wall-clock origin
  (``current_time_usecs``, monotonic and process-wide comparable). The
  stamp rides ``Single.trace_ts``; CPU batches carry ``trace_min`` /
  ``trace_max`` over their traced constituents and the TPU staging path
  propagates the same pair through ``BatchTPU`` — device batches never
  materialize per-tuple stamps.
- SINKS record end-to-end latency (now - origin) into their replica's
  ``LatencyHistogram``; every replica additionally records sampled
  service time and (device plane) dispatch prep/commit latency.
- Device-plane stages are wrapped in ``jax.profiler.TraceAnnotation``
  spans (``wf:prep:<op>`` / ``wf:commit:<op>``) so a device trace
  captured with ``jax.profiler.trace`` lines up with these host stats.

Sampling knob: ``WF_LATENCY_SAMPLE`` globally, or per operator via the
builders' ``with_latency_tracing(rate)``. A rate is ``1`` (every
tuple), a fraction ``"1/64"``, a float ``0.01``, or ``0`` (off — the
default: no clock reads, no histogram work on the hot path). Internally
a rate becomes a sampling INTERVAL (record every Nth), so sampling is
deterministic and divides exactly under test.
"""

from __future__ import annotations

import os
from contextlib import nullcontext
from typing import Optional

__all__ = ["parse_sample_rate", "env_sample_every", "resolve_sample_every",
           "device_span"]


def parse_sample_rate(value) -> int:
    """Sampling rate -> interval N (record every Nth sample; 0 = off).

    Accepts 1 / "1" (every tuple), "1/64" (every 64th), a float in
    (0, 1], or 0/""/None (off). Malformed values fall back to off — a
    bad knob must not take down the graph. Intervals round UP to a
    power of two: the source's per-tuple sampling gate is then a single
    integer AND against ``interval - 1`` — the same cost whether
    sampling is on or off, so enabling 1/64 tracing costs only the
    sampled work itself (microbench --latency measures this)."""
    if value is None:
        return 0
    if isinstance(value, str):
        value = value.strip()
        if not value:
            return 0
        if "/" in value:
            try:
                num, den = value.split("/", 1)
                rate = float(num) / float(den)
            except (ValueError, ZeroDivisionError):
                return 0
        else:
            try:
                rate = float(value)
            except ValueError:
                return 0
    else:
        try:
            rate = float(value)
        except (TypeError, ValueError):
            return 0
    if rate <= 0:
        return 0
    if rate >= 1:
        return 1
    n = max(1, round(1.0 / rate))
    return 1 << (n - 1).bit_length()  # next power of two >= n


def env_sample_every() -> int:
    """The global sampling interval from ``WF_LATENCY_SAMPLE`` (0=off)."""
    return parse_sample_rate(os.environ.get("WF_LATENCY_SAMPLE"))


def resolve_sample_every(op) -> int:
    """Per-operator interval: the builder knob wins over the env. The
    result is always 0 or a power of two (the mask-gate contract)."""
    s = getattr(op, "latency_sample", None)
    if s is None:
        return env_sample_every()
    s = max(0, int(s))
    if s & (s - 1):  # direct op.latency_sample writes may skip the parse
        s = 1 << (s - 1).bit_length()
    return s


_TRACE_ANNOTATION = None  # resolved lazily; nullcontext when jax absent


def device_span(name: str):
    """A ``jax.profiler.TraceAnnotation`` context manager (host TraceMe
    span visible in device profiles), or a no-op when jax is absent —
    the CPU plane must not pay a jax import for observability."""
    global _TRACE_ANNOTATION
    if _TRACE_ANNOTATION is None:
        try:
            from jax.profiler import TraceAnnotation
            _TRACE_ANNOTATION = TraceAnnotation
        except Exception:  # pragma: no cover - no jax in the venv
            _TRACE_ANNOTATION = _null_span
    return _TRACE_ANNOTATION(name)


def _null_span(name: str):  # pragma: no cover - no jax in the venv
    return nullcontext()
