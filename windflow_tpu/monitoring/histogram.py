"""Fixed-bucket log2 latency histogram (HDR-style).

The reference's observability stops at EWMA service times
(``wf/stats_record.hpp``); distribution-level latency needs a histogram
that is (a) O(1) to record with no allocation on the hot path, (b)
single-writer lock-free — each replica owns its own instance and only
its worker thread records, while the monitoring thread reads a possibly
slightly-stale snapshot (the GIL makes the int reads safe), and (c)
mergeable across replicas so per-operator percentiles exist.

Bucket layout (HDR idea, base 2): values are microseconds rounded down
to int. The first ``2**SUB_BITS`` values get exact unit buckets; above
that each power-of-two octave is split into ``2**SUB_BITS`` linear
sub-buckets, so the relative bucket width is bounded by
``1 / 2**SUB_BITS`` (25% at SUB_BITS=2) at every magnitude. The top
bucket absorbs overflow (> ~2^39 µs ≈ 6 days).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

SUB_BITS = 2
_SUB = 1 << SUB_BITS  # sub-buckets per octave
_MAX_EXP = 36  # octaves above the linear range
N_BUCKETS = (_MAX_EXP + 1) * _SUB  # 148 (last bucket = overflow)


def bucket_index(us: int) -> int:
    """Bucket of a non-negative integer microsecond value."""
    if us < _SUB:
        return us if us >= 0 else 0
    e = us.bit_length() - 1 - SUB_BITS
    if e >= _MAX_EXP:
        return N_BUCKETS - 1
    return ((e + 1) << SUB_BITS) | ((us >> e) & (_SUB - 1))


def bucket_bounds(idx: int) -> tuple:
    """[lo, hi) microsecond range covered by bucket ``idx``."""
    if idx < _SUB:
        return idx, idx + 1
    e = (idx >> SUB_BITS) - 1
    sub = idx & (_SUB - 1)
    lo = (_SUB + sub) << e
    if idx == N_BUCKETS - 1:
        return lo, float("inf")
    return lo, lo + (1 << e)


class LatencyHistogram:
    """Log2 HDR-style histogram over microsecond latencies."""

    __slots__ = ("counts", "count", "sum_us", "max_us")

    def __init__(self) -> None:
        self.counts: List[int] = [0] * N_BUCKETS
        self.count = 0
        self.sum_us = 0.0
        self.max_us = 0.0

    # -- hot path (single writer) ------------------------------------------
    def record(self, us: float) -> None:
        if us < 0:
            us = 0.0
        self.counts[bucket_index(int(us))] += 1
        self.count += 1
        self.sum_us += us
        if us > self.max_us:
            self.max_us = us

    def record_many(self, us_arr) -> None:
        """Vectorized ``record`` for batched engines (FFAT TPU/mesh late
        masks): one bucket computation over a numpy array instead of a
        Python loop per row. Bucket math mirrors ``bucket_index`` —
        ``frexp`` gives bit_length for the octave (exact for the int64
        microsecond range, which sits far below float64's 2^53)."""
        import numpy as np
        us = np.maximum(np.asarray(us_arr).astype(np.int64, copy=False), 0)
        n = int(us.size)
        if n == 0:
            return
        e = np.frexp(us.astype(np.float64))[1] - 1 - SUB_BITS
        e_safe = np.maximum(e, 0)
        idx = ((e_safe + 1) << SUB_BITS) | ((us >> e_safe) & (_SUB - 1))
        idx = np.where(us < _SUB, us, idx)
        idx = np.where(e >= _MAX_EXP, N_BUCKETS - 1, idx)
        binned = np.bincount(idx, minlength=N_BUCKETS)
        c = self.counts
        for i in np.flatnonzero(binned):
            c[i] += int(binned[i])
        self.count += n
        self.sum_us += float(us.sum())
        m = float(us.max())
        if m > self.max_us:
            self.max_us = m

    # -- reading -----------------------------------------------------------
    def percentile(self, q: float) -> float:
        """Upper edge of the bucket holding the q-quantile (nearest-rank
        over bucket counts); exact max for q at/above the last sample."""
        n = self.count
        if n == 0:
            return 0.0
        rank = max(1, int(q * n + 0.9999999999))
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= rank:
                lo, hi = bucket_bounds(i)
                if hi == float("inf") or hi > self.max_us:
                    return float(self.max_us)
                return float(hi)
        return float(self.max_us)

    @property
    def p50(self) -> float:
        return self.percentile(0.50)

    @property
    def p90(self) -> float:
        return self.percentile(0.90)

    @property
    def p99(self) -> float:
        return self.percentile(0.99)

    # -- merge / transport --------------------------------------------------
    def merge_from(self, other: "LatencyHistogram") -> None:
        oc = other.counts
        c = self.counts
        for i in range(N_BUCKETS):
            if oc[i]:
                c[i] += oc[i]
        self.count += other.count
        self.sum_us += other.sum_us
        if other.max_us > self.max_us:
            self.max_us = other.max_us

    @classmethod
    def merged(cls, parts: Iterable["LatencyHistogram"]) -> "LatencyHistogram":
        out = cls()
        for p in parts:
            out.merge_from(p)
        return out

    def to_sparse(self) -> Dict[str, object]:
        """Wire form for stats reports: only occupied buckets travel."""
        return {
            "counts": {str(i): c for i, c in enumerate(self.counts) if c},
            "count": self.count,
            "sum_us": round(self.sum_us, 1),
            "max_us": round(self.max_us, 1),
        }

    @classmethod
    def from_sparse(cls, d: Optional[dict]) -> "LatencyHistogram":
        h = cls()
        if not d:
            return h
        for k, c in (d.get("counts") or {}).items():
            try:
                i, c = int(k), int(c)
            except (TypeError, ValueError):
                continue  # reports arrive over an untrusted port
            if 0 <= i < N_BUCKETS and c > 0:
                h.counts[i] += c
        h.count = max(0, int(d.get("count", 0) or 0))
        try:
            h.sum_us = float(d.get("sum_us", 0.0) or 0.0)
            h.max_us = float(d.get("max_us", 0.0) or 0.0)
        except (TypeError, ValueError):
            pass
        return h

    def cumulative_buckets(self) -> List[tuple]:
        """Prometheus-shape ``(le_bound_usec, cumulative_count)`` pairs,
        occupied prefix only (+inf handled by the caller via count)."""
        out = []
        acc = 0
        top = 0
        for i, c in enumerate(self.counts):
            if c:
                top = i
        for i in range(top + 1):
            acc += self.counts[i]
            lo, hi = bucket_bounds(i)
            if self.counts[i]:
                out.append((hi, acc))
        return out

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (f"<LatencyHistogram n={self.count} p50={self.p50:.0f}us "
                f"p99={self.p99:.0f}us max={self.max_us:.0f}us>")
