"""Interactive dashboard web client (single file, no build step).

The reference ships a React app (``dashboard/web_client/``, 36 source
files) talking to a Spring REST server. The equivalent here is a
dependency-free client served by ``MonitoringServer.serve_http``: it polls
``/json`` once per second and renders, without page reloads,

- a graph selector with live mode/threads/dropped badges,
- per-operator tables (parallelism, in/out, ignored, tuples/s, service
  time, device programs, staging pool hits) that update in place,
- a canvas sparkline of each graph's total throughput history (kept
  client-side, 120 samples),
- a second sparkline of the worst sink-side p99 end-to-end latency
  (populated when latency tracing is sampling — WF_LATENCY_SAMPLE /
  with_latency_tracing), plus svc/e2e p99 latency columns,
- rescale-event markers on the p99 sparkline (dashed ticks where
  ``Rescale_events`` advanced) plus a rescale badge with the last
  operator/parallelism/pause — the per-operator ``par`` column is live,
  so a scaling action is visible the moment it lands,
- a degraded badge while the recovery plane runs with excluded devices
  (device-loss failover), with the last restore's ladder depth,
- the dataflow SVG diagram (server-sanitized),
- per-replica drill-down on click,
- event-time health: a watermark-lag column + late-records column with a
  drop badge, late-drop markers on the p99 sparkline (orange ticks where
  ``Late_dropped`` advanced), and a pipeline-doctor verdict banner
  (ranked bottleneck attribution from the server-side diagnosis that
  rides in every ``/json`` snapshot).
"""

CLIENT_HTML = r"""<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8"/>
<title>windflow_tpu dashboard</title>
<style>
 body { font-family: monospace; margin: 18px; background:#fafafa; }
 h1 { font-size: 18px; }
 .badge { display:inline-block; padding:2px 8px; border-radius:10px;
          background:#e8f0fe; margin-right:6px; font-size:11px; }
 .badge.warn { background:#fde8e8; }
 table { border-collapse: collapse; margin: 8px 0; }
 th, td { border: 1px solid #ccc; padding: 3px 8px; font-size: 12px;
          text-align: right; }
 th { background:#f0f0f0; } td.l, th.l { text-align:left; }
 .tabs button { margin-right:4px; font-family:monospace; }
 .tabs button.active { background:#2b6cb0; color:#fff; }
 canvas { border:1px solid #ddd; background:#fff; }
 #diagram svg { max-width:100%; }
 tr.rep { background:#f7fbff; font-size:11px; }
 .muted { color:#777; font-size:11px; }
 #doctor { margin:6px 0; padding:5px 10px; border-radius:6px;
           font-size:12px; background:#e6f4ea; display:none; }
 #doctor.sick { background:#fdecd2; }
</style>
</head>
<body>
<h1>windflow_tpu dashboard <span id="conn" class="muted"></span></h1>
<div class="tabs" id="tabs"></div>
<div id="badges"></div>
<div id="doctor"></div>
<canvas id="spark" width="720" height="80"></canvas>
<div class="muted">total tuples/s (last 120 s)</div>
<canvas id="sparklat" width="720" height="60"></canvas>
<div class="muted">worst p99 end-to-end latency µs (sampled tracing;
flat at 0 when sampling is off) — ⇅ rescale, ✕ late drops</div>
<div id="ops"></div>
<details open id="diagram"><summary>dataflow graph</summary></details>
<script>
"use strict";
let current = null;            // selected graph
let graphList = [], opNames = [];  // index -> name (XSS-safe handlers)
const hist = {};               // graph -> [throughput samples]
const lhist = {};              // graph -> [p99 e2e latency samples]
const rmark = {};              // graph -> [bool: rescale at this sample]
const rseen = {};              // graph -> last Rescale_events count
const dmark = {};              // graph -> [bool: late drops this sample]
const dseen = {};              // graph -> last Late_dropped total
const open = new Set();        // operator names with replica drill-down
function fmt(n){ return (n===undefined||n===null)?"":
  Number(n).toLocaleString("en-US",{maximumFractionDigits:1}); }
function el(id){ return document.getElementById(id); }
// every server-supplied string is untrusted (monitoring TCP port is
// unauthenticated): escape before any innerHTML interpolation
function esc(s){ return String(s).replace(/[&<>"']/g, c =>
  ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c])); }
function render(snap){
  const graphs = Object.keys(snap.reports);
  if (graphs.length && (current===null || !graphs.includes(current)))
    current = graphs[0];
  graphList = graphs;
  el("tabs").innerHTML = graphs.map((g,i) =>
    `<button class="${g===current?'active':''}" onclick="pick(${i})">`+
    `${esc(g)}</button>`).join("");
  if (!current) { el("ops").innerHTML = "<p class=muted>waiting for "+
    "reports…</p>"; return; }
  const st = snap.reports[current];
  el("badges").innerHTML =
    `<span class=badge>${esc(st.Mode)}</span>`+
    `<span class=badge>${esc(st.Time_policy)}</span>`+
    `<span class=badge>threads ${st.Threads|0}</span>`+
    `<span class="badge ${st.Dropped_tuples? 'warn':''}">dropped `+
    `${fmt(st.Dropped_tuples)}</span>`+
    (st.Worker_errors? `<span class="badge warn">crashed `+
    `${Object.keys(st.Worker_errors).length} worker(s)</span>` : "");
  let total = 0, worstP99 = 0, rows = [];
  let tierHot = 0, tierCold = 0, tierMiss = 0, tierOn = false;
  let lateRecs = 0, lateDrop = 0, worstWmLag = 0;
  opNames = (st.Operators||[]).map(o=>o.name);
  (st.Operators||[]).forEach((o, oi) => {
    const r = o.replicas, s = (k)=>r.reduce((a,x)=>a+(x[k]||0),0);
    const m = (k)=>Math.max(...r.map(x=>x[k]||0));
    const tput = s("Throughput_tuples_sec"); total += tput;
    if (r.some(x=>"Tier_hot_keys" in x)) {
      tierOn = true; tierHot += s("Tier_hot_keys");
      tierCold += s("Tier_cold_keys");
      tierMiss = Math.max(tierMiss, m("Tier_miss_rate"));
    }
    worstP99 = Math.max(worstP99, m("Latency_e2e_p99_usec"));
    const wmLagMs = m("Watermark_lag_usec")/1000;
    // idle replicas park their watermark by design; only flag lag where
    // traffic is flowing (mirrors the doctor's stall condition)
    if (!r.every(x=>x.Watermark_idle)) worstWmLag =
      Math.max(worstWmLag, wmLagMs);
    lateRecs += s("Late_records"); lateDrop += s("Late_dropped");
    rows.push(`<tr onclick="tog(${oi})"><td class=l>${esc(o.name)}</td>`+
      `<td class=l>${esc(o.kind)}</td><td>${o.parallelism|0}</td>`+
      `<td>${fmt(s("Inputs_received"))}</td>`+
      `<td>${fmt(s("Outputs_sent"))}</td>`+
      `<td>${fmt(s("Inputs_ignored"))}</td><td>${fmt(tput)}</td>`+
      `<td>${fmt(m("Service_time_usec"))}</td>`+
      `<td>${fmt(m("Latency_service_p99_usec"))}</td>`+
      `<td>${fmt(m("Latency_e2e_p99_usec"))}</td>`+
      `<td>${fmt(wmLagMs)}</td>`+
      `<td>${fmt(s("Late_records"))}`+
      `${s("Late_dropped")?" ("+fmt(s("Late_dropped"))+"✕)":""}</td>`+
      `<td>${fmt(m("Checkpoint_cut_pause_usec"))}</td>`+
      `<td>${fmt(m("Queue_len"))}/${fmt(m("Queue_depth_max"))}</td>`+
      `<td>${fmt(s("Device_programs_run"))}</td>`+
      `<td>${fmt(s("Compile_count"))}/${fmt(s("Compile_cache_hits"))}</td>`+
      `<td>${fmt(s("Staging_pool_hits"))}</td></tr>`);
    if (open.has(o.name))
      for (const x of r)
        rows.push(`<tr class=rep><td class=l>&nbsp;&nbsp;replica `+
          `${x.Replica_id}</td><td class=l>${x.isTerminated?"done":"run"}`+
          `</td><td></td><td>${fmt(x.Inputs_received)}</td>`+
          `<td>${fmt(x.Outputs_sent)}</td><td>${fmt(x.Inputs_ignored)}</td>`+
          `<td>${fmt(x.Throughput_tuples_sec)}</td>`+
          `<td>${fmt(x.Service_time_usec)}</td>`+
          `<td>${fmt(x.Latency_service_p99_usec)}</td>`+
          `<td>${fmt(x.Latency_e2e_p99_usec)}</td>`+
          `<td>${fmt((x.Watermark_lag_usec||0)/1000)}</td>`+
          `<td>${fmt(x.Late_records)}`+
          `${x.Late_dropped?" ("+fmt(x.Late_dropped)+"✕)":""}</td>`+
          `<td>${fmt(x.Checkpoint_cut_pause_usec)}</td>`+
          `<td>${fmt(x.Queue_len)}/${fmt(x.Queue_depth_max)}</td>`+
          `<td>${fmt(x.Device_programs_run)}</td>`+
          `<td title="${esc(x.Compile_last_signature||"")}">`+
          `${fmt(x.Compile_count)}/${fmt(x.Compile_cache_hits)}</td>`+
          `<td>${fmt(x.Staging_pool_hits)}</td></tr>`);
  });
  el("ops").innerHTML =
    `<table><tr><th class=l>operator</th><th class=l>kind</th><th>par</th>`+
    `<th>in</th><th>out</th><th>ignored</th><th>tuples/s</th>`+
    `<th>svc µs</th><th>svc p99</th><th>e2e p99</th>`+
    `<th title="wall-clock time since the watermark last advanced">`+
    `wm lag ms</th>`+
    `<th title="tuples behind the watermark (✕ = dropped past the `+
    `allowed lateness)">late</th>`+
    `<th title="barrier cut pause (state capture + ack) of the last `+
    `checkpoint">cut µs</th><th>queue</th>`+
    `<th>device progs</th><th>compiles/hits</th><th>pool hits</th></tr>`+
    rows.join("")+`</table>`+
    `<div class=muted>click an operator row for per-replica detail; `+
    `queue = occupancy/high-water of the operator's input channel</div>`;
  (hist[current] = hist[current]||[]).push(total);
  if (hist[current].length > 120) hist[current].shift();
  spark(hist[current]);
  (lhist[current] = lhist[current]||[]).push(worstP99);
  if (lhist[current].length > 120) lhist[current].shift();
  // rescale-event markers: a tick on the p99 sparkline wherever the
  // graph's Rescale_events counter advanced between polls, so a scaling
  // action is visible right where its latency effect shows up
  const rs = (st.Rescales||{});
  const ev = rs.Rescale_events|0;
  (rmark[current] = rmark[current]||[]).push(
    ev > (rseen[current]|0));
  rseen[current] = ev;
  if (rmark[current].length > 120) rmark[current].shift();
  const rbadge = ev ? `<span class=badge>rescales ${ev}`+
    (rs.Rescale_last_op ? ` (last: ${esc(rs.Rescale_last_op)} → `+
     `${rs.Rescale_last_to|0}, pause `+
     `${fmt((rs.Rescale_last_pause_s||0)*1e3)}ms)` : "")+`</span>` : "";
  if (rbadge) el("badges").innerHTML += rbadge;
  // supervised-restart badge: restarts so far + last MTTR; warn style
  // while escalated (the graph gave up and surfaced the aggregate error)
  const sv = (st.Supervision||{});
  const rst = sv.Supervision_restarts|0;
  if (rst || sv.Supervision_escalated)
    el("badges").innerHTML +=
      `<span class="badge ${sv.Supervision_escalated?'warn':''}">`+
      `restarts ${rst}`+
      (rst ? ` (MTTR ${fmt((sv.Supervision_last_restart_s||0)*1e3)}ms)`
           : "")+
      (sv.Supervision_escalated ? " — escalated" : "")+`</span>`;
  // degraded-mesh badge: devices the recovery plane excluded after a
  // device loss; warn style until the probe sees them return and a
  // planned restart re-expands the mesh to full shape
  const dg = sv.Recovery_degraded_devices|0;
  if (dg) el("badges").innerHTML +=
    `<span class="badge warn">degraded: ${dg} device(s) excluded`+
    ((sv.Recovery_ladder_depth|0) ?
      ` · ladder depth ${sv.Recovery_ladder_depth|0}` : "")+`</span>`;
  // tiered-keyed-state badge: hot/cold key split of the tiered stores
  // (with_tiering) plus the worst per-replica hot-tier miss rate
  if (tierOn) el("badges").innerHTML +=
    `<span class=badge>tiered: ${fmt(tierHot)} hot / `+
    `${fmt(tierCold)} cold · miss ${(tierMiss*100).toFixed(1)}%</span>`;
  // late-drop markers: a tick on the p99 sparkline wherever the graph's
  // Late_dropped total advanced between polls, plus a warn badge with
  // the running dropped/seen-late split
  (dmark[current] = dmark[current]||[]).push(
    lateDrop > (dseen[current]|0));
  dseen[current] = lateDrop;
  if (dmark[current].length > 120) dmark[current].shift();
  if (lateRecs) el("badges").innerHTML +=
    `<span class="badge ${lateDrop?'warn':''}">late ${fmt(lateRecs)}`+
    (lateDrop? ` (dropped ${fmt(lateDrop)})` : "")+`</span>`;
  if (worstWmLag > 1000) el("badges").innerHTML +=
    `<span class="badge warn">wm lag ${fmt(worstWmLag)}ms</span>`;
  // pipeline-doctor banner: the server diagnoses every report tick; the
  // banner shows the ranked verdict for the selected graph
  const doc = el("doctor"), diag = (snap.doctor||{})[current];
  if (diag) {
    doc.style.display = "block";
    doc.className = diag.healthy ? "" : "sick";
    const finds = (diag.findings||[]).slice(0,3).map(f =>
      `<b>${esc(f.operator)}</b> ${esc(f.verdict)}`+
      (f.by? `&nbsp;→ <b>${esc(f.by)}</b>` : "")+
      ` <span class=muted>[${fmt(f.score)}]</span>`).join(" · ");
    doc.innerHTML = `doctor: ${esc(diag.summary||"")}`+
      (finds? `<br>${finds}` : "");
  } else { doc.style.display = "none"; }
  const dlq = st.Dead_letters|0;
  if (dlq) el("badges").innerHTML +=
    `<span class="badge warn">dead letters ${fmt(dlq)}</span>`;
  // overload-governor badge: ladder state + shed accounting (warn
  // style while actively shedding — the graph is refusing work to
  // hold its latency SLO)
  const ov = (st.Overload||{});
  if (ov.Overload_state_name && (ov.Overload_state|0) > 0
      || (ov.Overload_shed_records|0) > 0)
    el("badges").innerHTML +=
      `<span class="badge ${ov.Overload_shedding?'warn':''}">`+
      `overload: ${esc(ov.Overload_state_name||"?")}`+
      (ov.Overload_shedding
        ? ` (admit ${fmt(ov.Overload_admit_rate_tps)}/s)` : "")+
      ((ov.Overload_shed_records|0) > 0
        ? ` — shed ${fmt(ov.Overload_shed_records)}` : "")+`</span>`;
  sparkLine("sparklat", lhist[current], "#b0452b", "µs", rmark[current],
            dmark[current]);
  const svg = (snap.svgs||{})[current];  // server-sanitized
  el("diagram").innerHTML = "<summary>dataflow graph</summary>"+
    (svg || "<pre>"+esc(snap.diagrams[current]||"")+"</pre>");
}
function spark(h){ sparkLine("spark", h, "#2b6cb0", " t/s"); }
function tickMarks(ctx, c, marks, color, glyph){
  ctx.strokeStyle = color; ctx.lineWidth = 1;
  marks.forEach((m,i)=>{
    if (!m) return;
    const x = i*(c.width/120);
    ctx.beginPath(); ctx.setLineDash([3,3]);
    ctx.moveTo(x, 2); ctx.lineTo(x, c.height-2); ctx.stroke();
    ctx.setLineDash([]);
    ctx.fillStyle = color; ctx.font = "9px monospace";
    ctx.fillText(glyph, Math.min(x+2, c.width-10), c.height-4);
  });
}
function sparkLine(id, h, color, unit, marks, marks2){
  const c = el(id), ctx = c.getContext("2d");
  ctx.clearRect(0,0,c.width,c.height);
  if (!h.length) return;
  const max = Math.max(...h, 1);
  // vertical ticks: rescale events (purple) and late-drop surges (orange)
  if (marks) tickMarks(ctx, c, marks, "#7a5cb0", "⇅");
  if (marks2) tickMarks(ctx, c, marks2, "#d97706", "✕");
  ctx.beginPath(); ctx.strokeStyle = color; ctx.lineWidth = 1.6;
  h.forEach((v,i)=>{
    const x = i*(c.width/120), y = c.height-4-(v/max)*(c.height-12);
    i? ctx.lineTo(x,y) : ctx.moveTo(x,y);
  });
  ctx.stroke();
  ctx.fillStyle="#555"; ctx.font="10px monospace";
  ctx.fillText(fmt(max)+unit, 4, 10);
}
function pick(i){ current = graphList[i]; }
function tog(i){ const n = opNames[i];
  open.has(n)? open.delete(n) : open.add(n); }
async function tick(){
  try {
    const r = await fetch("/json", {cache:"no-store"});
    render(await r.json());
    el("conn").textContent = "";
  } catch (e) { el("conn").textContent = "(disconnected)"; }
}
setInterval(tick, 1000); tick();
</script>
</body>
</html>
"""
