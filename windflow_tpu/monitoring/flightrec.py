"""Flight recorder: per-worker ring buffers of span events, Chrome/
Perfetto trace export, the stall watchdog and XLA compile attribution.

The stats plane (PR 2) answers "how fast is each operator on average";
this module answers "where did THIS slow batch spend its time" and "why
did throughput just collapse". Each worker thread owns one
``FlightRecorder`` — a fixed-size, single-writer ring of structured
events recorded at the points where the dispatch pipeline and the
latency-tracing plane already take timestamps (host prep, deferred
device commit, channel blocked put/get, barrier alignment, checkpoint
snapshots, jit compiles), so the steady-state cost of an enabled
recorder is one clock read plus a couple of array stores per batch
(``scripts/microbench.py --flightrec`` gates it at <= 2%). The rings
export as Chrome trace-event JSON (loadable in Perfetto /
``chrome://tracing``): ``tid`` = worker, ``pid`` = stage/operator,
``args`` carry batch sizes, checkpoint ids and compile signatures.

Three ways out of the ring:

- ``PipeGraph.dump_trace(path)`` — explicit dump any time;
- ``GET /trace?ms=N`` on ``MonitoringServer`` — an on-demand capture
  window over every registered in-process graph;
- automatic post-mortem — a worker that dies, or one the stall
  watchdog flags (no progress-counter advance for ``WF_STALL_SEC``),
  dumps its graph's rings plus ``sys._current_frames()`` stacks for
  every runtime thread into ``WF_LOG_DIR``.

Compile attribution: ``instrumented_jit`` wraps every ``jax.jit`` entry
point of the device plane (``tpu/ops_tpu.py`` / ``tpu/fused_ops.py``)
with an abstract-signature tracker — a call with an unseen
(shape, dtype) signature is a (re)trace and its elapsed time is the
compile cost; a seen signature is a cache hit. A retrace STORM (the
compile-cache churn that dominates fused-program cost when batch
signatures vary — Snider & Liang, arXiv:2301.13062) then shows up as a
wall of ``compile`` spans in the trace and a climbing
``windflow_compile_total`` in ``/metrics``.
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

__all__ = ["FlightRecorder", "set_thread_recorder", "thread_recorder",
           "env_flightrec_events", "env_stall_sec", "instrumented_jit",
           "to_chrome_trace", "thread_stacks", "register_graph",
           "capture_trace", "StallWatchdog", "DEFAULT_EVENTS"]

DEFAULT_EVENTS = 4096

# threads record into their own ring only (single-writer contract);
# call sites that run on a foreign thread (a producer blocking on a
# consumer's channel, a shared compiled program) resolve the CURRENT
# thread's ring through this TLS slot instead of reaching for an
# owner's ring across threads
_tls = threading.local()


def set_thread_recorder(rec: Optional["FlightRecorder"]) -> None:
    _tls.rec = rec


def thread_recorder() -> Optional["FlightRecorder"]:
    return getattr(_tls, "rec", None)


def env_flightrec_events() -> int:
    """Ring capacity from ``WF_FLIGHTREC_EVENTS`` (0/unset/malformed =
    recorder off — a bad knob must not take down the graph)."""
    try:
        return max(0, int(os.environ.get("WF_FLIGHTREC_EVENTS", "0")))
    except ValueError:
        return 0


def env_stall_sec() -> float:
    """Watchdog threshold from ``WF_STALL_SEC`` (seconds; 0/unset/
    malformed = watchdog off)."""
    try:
        return max(0.0, float(os.environ.get("WF_STALL_SEC", "0")))
    except ValueError:
        return 0.0


class FlightRecorder:
    """Fixed-size single-writer ring of ``(end_ns, name, dur_us, arg)``
    events. ``event()`` is the hot path: one clock read, one tuple, one
    slot store, one index bump — no locks, no allocation growth. The
    ring keeps the newest ``capacity`` events; wraparound drops
    oldest-first. Readers (watchdog/dump threads) take a racy snapshot:
    a torn read can at worst miss or double-see the event being written
    this instant, which trace export tolerates (events are re-sorted by
    timestamp)."""

    __slots__ = ("capacity", "pid_label", "tid_label", "_buf", "_n")

    def __init__(self, capacity: int = DEFAULT_EVENTS,
                 pid_label: str = "", tid_label: str = "") -> None:
        self.capacity = max(1, int(capacity))
        self.pid_label = pid_label
        self.tid_label = tid_label
        self._buf: List[Any] = [None] * self.capacity
        self._n = 0

    def event(self, name: str, dur_us: float = 0.0, arg: Any = None) -> None:
        """Record one span that ENDS now and lasted ``dur_us`` (0 for an
        instant event). Call sites pass durations they already measured
        for the stats plane, so no second clock base is needed."""
        i = self._n
        self._buf[i % self.capacity] = (time.perf_counter_ns(), name,
                                        dur_us, arg)
        self._n = i + 1

    def __len__(self) -> int:
        return min(self._n, self.capacity)

    @property
    def dropped(self) -> int:
        """Events lost to wraparound (oldest-first)."""
        return max(0, self._n - self.capacity)

    def snapshot(self) -> List[Any]:
        """Events oldest-first (racy vs the writer; see class doc)."""
        n = self._n
        buf = list(self._buf)  # one slice: consistent enough
        if n <= self.capacity:
            out = buf[:n]
        else:
            i = n % self.capacity
            out = buf[i:] + buf[:i]
        return [e for e in out if e is not None]


# ---------------------------------------------------------------------------
# Chrome trace-event export
# ---------------------------------------------------------------------------
def to_chrome_trace(recorders, stacks: Optional[Dict[str, Any]] = None,
                    extra: Optional[Dict[str, Any]] = None,
                    since_ns: Optional[int] = None) -> Dict[str, Any]:
    """Render rings as a Chrome trace-event JSON document (the object
    form: ``traceEvents`` plus arbitrary metadata keys, which Perfetto
    and ``chrome://tracing`` both load). Every span is a complete
    ``ph:"X"`` event; ``pid`` groups by stage/operator label and ``tid``
    by worker, with ``process_name``/``thread_name`` metadata events
    carrying the human labels. ``since_ns`` keeps only events ending at
    or after that ``perf_counter_ns`` instant (the /trace capture
    window)."""
    pids: Dict[str, int] = {}
    tids: Dict[str, int] = {}
    raw = []
    for rec in recorders:
        pid = pids.setdefault(rec.pid_label or "windflow", len(pids) + 1)
        tid = tids.setdefault(rec.tid_label or f"ring{pid}", len(tids) + 1)
        for ev in rec.snapshot():
            end_ns, name, dur_us, arg = ev
            if since_ns is not None and end_ns < since_ns:
                continue
            raw.append((end_ns, name, dur_us, arg, pid, tid))
    raw.sort(key=lambda e: e[0] - e[2] * 1e3)
    origin_ns = (raw[0][0] - raw[0][2] * 1e3) if raw else 0.0
    events: List[Dict[str, Any]] = []
    for label, pid in pids.items():
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": label}})
    for rec in recorders:
        pid = pids[rec.pid_label or "windflow"]
        tid = tids[rec.tid_label or f"ring{pid}"]
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": tid, "args": {"name": rec.tid_label}})
    for end_ns, name, dur_us, arg, pid, tid in raw:
        args = arg if isinstance(arg, dict) else (
            {} if arg is None else {"v": arg})
        events.append({"name": name, "ph": "X", "cat": "windflow",
                       "ts": round((end_ns - origin_ns) / 1e3 - dur_us, 3),
                       "dur": round(dur_us, 3), "pid": pid, "tid": tid,
                       "args": args})
    doc: Dict[str, Any] = {"traceEvents": events,
                           "displayTimeUnit": "ms"}
    dropped = sum(getattr(r, "dropped", 0) for r in recorders)
    if dropped:
        doc["droppedEvents"] = dropped
    if stacks is not None:
        doc["stacks"] = stacks
    if extra:
        doc.update(extra)
    return doc


def thread_stacks() -> Dict[str, List[str]]:
    """Formatted stacks for every runtime thread (the post-mortem's
    "where is everyone RIGHT NOW" section), keyed by thread name."""
    import sys
    import traceback

    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for ident, frame in sys._current_frames().items():
        out[names.get(ident, f"thread-{ident}")] = \
            traceback.format_stack(frame)
    return out


# ---------------------------------------------------------------------------
# in-process graph registry (feeds MonitoringServer's /trace endpoint)
# ---------------------------------------------------------------------------
_graphs: "weakref.WeakSet" = weakref.WeakSet()


def register_graph(graph) -> None:
    """Called by ``PipeGraph.start``; weak so finished graphs vanish
    with their last reference."""
    _graphs.add(graph)


def active_recorders() -> List[FlightRecorder]:
    recs: List[FlightRecorder] = []
    for g in list(_graphs):
        recs.extend(getattr(g, "_recorders", []))
    return recs


def capture_trace(window_ms: float) -> Dict[str, Any]:
    """The ``GET /trace?ms=N`` body: sleep one capture window, then
    export every registered graph's events that ended inside it."""
    window_ms = min(10_000.0, max(1.0, float(window_ms)))
    t0 = time.perf_counter_ns()
    time.sleep(window_ms / 1e3)
    return to_chrome_trace(active_recorders(), since_ns=t0,
                           extra={"captureWindowMs": window_ms})


# ---------------------------------------------------------------------------
# XLA compile attribution
# ---------------------------------------------------------------------------
def _abstract_signature(args) -> tuple:
    """Hashable abstract signature of a call: (shape, dtype) per array
    leaf, the type name for scalars. Matches jax.jit's retrace rule
    closely enough to attribute compiles: a new shape or dtype is a new
    signature (a dtype-change retrace is therefore counted), while
    value-only changes are cache hits."""
    import jax

    parts = []
    for leaf in jax.tree_util.tree_leaves(args):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append((tuple(shape), str(dtype)))
        else:
            parts.append(type(leaf).__name__)
    return tuple(parts)


def instrumented_jit(fn, stats=None, label: str = "", **jit_kwargs):
    """``jax.jit`` with compile-vs-cache-hit attribution. The wrapped
    callable tracks the abstract signatures it has served: an unseen
    signature means jit will trace+compile synchronously inside this
    call, so the call's elapsed time is recorded as the compile cost
    (``StatsRecord.note_compile`` -> ``Compile_*`` stats,
    ``windflow_compile_*`` metric families, and a ``compile`` span in
    the current thread's flight ring); a seen signature bumps the
    cache-hit counter only. Signature checks cost one small tree walk
    per batch — noise against the program the batch is about to run.

    Shared program caches (the grid scan, fused chains) attribute
    compiles to the stats record of the replica that built the program;
    compiles are per-program events, so counts stay exact even when
    sibling replicas hit the shared cache."""
    import jax

    jitted = jax.jit(fn, **jit_kwargs)
    seen = set()

    def wrapper(*args):
        key = _abstract_signature(args)
        if key in seen:
            if stats is not None:
                stats.compile_cache_hits += 1
            return jitted(*args)
        t0 = time.perf_counter()
        out = jitted(*args)
        dt_us = (time.perf_counter() - t0) * 1e6
        seen.add(key)
        sig = f"{label or getattr(fn, '__name__', 'prog')}:{key}"
        if stats is not None:
            stats.note_compile(dt_us, sig)
        rec = thread_recorder()
        if rec is not None:
            rec.event("compile", dt_us, {"op": label, "signature": sig})
        return out

    wrapper._seen_signatures = seen  # introspection / tests
    wrapper._wrapped_jit = jitted
    return wrapper


# ---------------------------------------------------------------------------
# stall watchdog
# ---------------------------------------------------------------------------
class StallWatchdog(threading.Thread):
    """Monitor-thread tick that flags live workers whose progress
    counter (channel deliveries + idle ticks + tuples moved) has not
    advanced for ``stall_sec``. Firing calls ``dump_fn(worker_name)``
    once per stall episode (re-armed by any later progress) — the
    PipeGraph wires that to a post-mortem trace dump with
    ``sys._current_frames()`` stacks. Default off (``WF_STALL_SEC``
    unset): a healthy-idle worker parked in a long ``channel.get`` would
    otherwise look identical to a deadlocked one, which is why workers
    run their idle tick whenever the watchdog is armed."""

    def __init__(self, graph, stall_sec: float, dump_fn=None) -> None:
        super().__init__(name=f"stallwatch:{graph.name}", daemon=True)
        self.graph = graph
        self.stall_sec = float(stall_sec)
        self.dump_fn = dump_fn
        self.fired: List[str] = []  # worker names, in firing order
        self._stop_evt = threading.Event()
        self._seen: Dict[str, Any] = {}  # wname -> [progress, t, flagged]

    def stop(self) -> None:
        self._stop_evt.set()

    def run(self) -> None:
        tick = min(1.0, max(0.05, self.stall_sec / 4.0))
        while not self._stop_evt.wait(tick):
            self._check(time.monotonic())

    def _check(self, now: float) -> None:
        if getattr(self.graph, "_rescaling", False) \
                or getattr(self.graph, "_supervising", False):
            # a rescale parks every worker at the barrier on purpose (and
            # a supervised recovery tears the plane down mid-flight);
            # re-arm from scratch once the new plane is running
            self._seen.clear()
            return
        gov = getattr(self.graph, "_overload_governor", None)
        if gov is not None and gov.shedding:
            # active load shedding: a fully gated source emits nothing
            # BY DESIGN (and its downstream can legitimately go quiet) —
            # flagging that as a stall would dump postmortems during
            # every overload; re-arm once admission control releases
            self._seen.clear()
            return
        for w in self.graph._workers:
            if not w.is_alive():
                self._seen.pop(w.name, None)
                continue
            cur = w.progress_value()
            ent = self._seen.get(w.name)
            if ent is None or ent[0] != cur:
                self._seen[w.name] = [cur, now, False]
                continue
            if not ent[2] and now - ent[1] >= self.stall_sec:
                ent[2] = True  # one dump per stall episode
                self.fired.append(w.name)
                rec = getattr(w, "flightrec", None)
                if rec is not None:
                    rec_evt_safe(rec, "stall_detected",
                                 (now - ent[1]) * 1e6, w.name)
                if self.dump_fn is not None:
                    try:
                        self.dump_fn(w.name)
                    except Exception:
                        pass  # a dump failure must not kill the watchdog


def rec_evt_safe(rec: FlightRecorder, name: str, dur_us: float,
                 arg: Any) -> None:
    """Cross-thread event append (watchdog only): the stall marker is
    worth the single racy slot write — at worst it overwrites the event
    the stalled worker is NOT writing (it is stalled)."""
    try:
        rec.event(name, dur_us, arg)
    except Exception:
        pass


def write_trace(path: str, recorders, stacks=None, extra=None) -> str:
    """Serialize ``to_chrome_trace`` to ``path`` (dirs created)."""
    doc = to_chrome_trace(recorders, stacks=stacks, extra=extra)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path
