"""Per-replica statistics (reference ``wf/stats_record.hpp:49-160``).

Counters: inputs/outputs received/sent, ignored (dropped) tuples, service time
EWMA (``wf/basic_operator.hpp:144-158``), and device-plane traffic (batches
staged to/from the TPU, bytes moved — the analog of the reference's kernels
launched / bytes H2D/D2H). Serialized to JSON by the PipeGraph at wait_end
(``wf/pipegraph.hpp:464-522``).

On top of the reference's counters this record carries the latency-tracing
plane (monitoring/tracing.py): per-replica log2 histograms of service time,
dispatch prep/commit latency and (sinks) end-to-end latency — allocated only
when sampling is enabled, so the default hot path never touches them — plus
queue-occupancy/backpressure gauges read from the replica's input channel
and the emitter-side FIFOs.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

_EWMA_ALPHA = 0.1


def _wm_stall_sec() -> float:
    """Watermark stall threshold (``WF_WM_STALL_SEC``): a replica whose
    watermark has not advanced for this long WHILE inputs keep arriving is
    event-time-stalled (frozen source watermark, wedged punctuation path).
    Quiet replicas (no new inputs either) are ``idle``, never stalled."""
    try:
        return max(0.1, float(os.environ.get("WF_WM_STALL_SEC", "5")))
    except ValueError:
        return 5.0


class StatsRecord:
    __slots__ = (
        "op_name", "replica_idx", "start_time",
        "inputs_received", "bytes_received", "outputs_sent", "bytes_sent",
        "inputs_ignored", "punct_received", "punct_sent",
        "service_time_us", "eff_service_time_us",
        "device_batches_in", "device_batches_out",
        "device_bytes_h2d", "device_bytes_d2h", "device_programs_run",
        "staging_pool_hits", "staging_pool_misses",
        "dispatch_host_prep_us", "dispatch_commit_us",
        "dispatch_host_prep_total_us", "dispatch_commit_total_us",
        "dispatch_batches", "dispatch_stalls", "dispatch_depth_max",
        # megabatch scan loop (runtime/dispatch.py + tpu/fused_ops.py):
        # grouped dispatches (loops), batches committed through them,
        # and the widest group observed — Programs_per_batch in to_dict
        # derives the amortization from device_programs_run
        "megabatch_loops", "megabatch_batches", "megabatch_max",
        # columnar ingest plane (SourceReplica.ship_columns): blocks
        # shipped, rows they carried, and host nanoseconds spent shipping
        # them — Ingest_block_ns_per_row in to_dict is the per-row host
        # cost of the block path (the row path has no analog: its cost
        # IS the per-tuple Python this plane removes)
        "ingest_blocks", "ingest_rows", "ingest_ns_total",
        # aligned-barrier checkpointing (windflow_tpu.checkpoint):
        # per-replica snapshot count/duration/size + barrier-alignment
        # stall time (multi-input workers buffering behind the barrier)
        "checkpoints_taken", "checkpoint_snapshot_total_us",
        "checkpoint_last_snapshot_us", "checkpoint_bytes_total",
        "checkpoint_align_total_us",
        # barrier CUT pause: how long the worker was actually fenced by
        # the barrier (capture + ack). Equals snapshot time in sync
        # mode; with WF_CKPT_ASYNC it excludes serialization + writes,
        # which run on the coordinator's background uploader
        "checkpoint_cut_total_us", "checkpoint_last_cut_us",
        # exactly-once sinks (windflow_tpu.sinks.transactional): per-epoch
        # two-phase-commit accounting — pre-commits at the barrier,
        # commits on coordinator finalize, aborts on restore/duplicate
        # discard, and fenced (refused) writes from stale zombie replicas
        "txn_precommits", "txn_commits", "txn_aborts", "txn_fenced_writes",
        # per-record error policies + dead-letter queue
        # (windflow_tpu.supervision.errors): quarantined records,
        # policy-skipped records, retry attempts; and Kafka transient-
        # error reconnect/retry events (kafka/connectors.py)
        "dlq_records", "dlq_skipped", "dlq_retries", "kafka_reconnects",
        # overload protection (windflow_tpu.overload): records/bytes shed
        # by admission control at the SOURCE boundary (before barriers
        # and the exactly-once plane — accounted, never silently lost)
        "shed_records", "shed_bytes",
        # mesh execution plane (windflow_tpu.mesh): per-shard visibility
        # for operators whose parallelism is a device mesh — steps run,
        # bytes through the in-program all_to_all shuffle, host-observed
        # step time, and slot occupancy/skew of the block-owner mapping.
        # mesh_devices == 0 marks a non-mesh replica; to_dict then omits
        # the Mesh_* keys so /metrics carries mesh series only where a
        # mesh exists
        "mesh_devices", "mesh_steps", "mesh_shuffle_bytes",
        "mesh_step_total_us", "mesh_shard_occupancy", "mesh_shard_skew",
        # devices this mesh replica is running WITHOUT because the
        # supervision plane excluded them (device-loss failover): > 0
        # means degraded capacity until the probe sees them return
        "mesh_degraded",
        # tiered keyed state (windflow_tpu.state.tiered): hot/cold key
        # gauges, batched promote/demote counters with promote time, and
        # the lookup/miss counters behind Tier_miss_rate. tier_enabled
        # marks a replica whose engine runs with_tiering — to_dict omits
        # the Tier_* keys elsewhere, the Mesh_* discipline
        "tier_enabled", "tier_hot_keys", "tier_cold_keys",
        "tier_promotes", "tier_demotes", "tier_promote_usec_total",
        "tier_lookups", "tier_misses",
        # event-time health plane: watermark progress gauges + unified
        # late-record accounting. ``wm_current``/``wm_advances`` are the
        # only hot-path writes (two stores on ADVANCE only, in
        # BasicReplica._advance_wm); lag/idle/stall derive at poll time
        # (to_dict / worker idle tick) so the per-tuple path stays flat.
        # ``wm_max_source_ts`` is tracked only on explicit event-time
        # source paths (push_with_timestamp / push_columns(ts=...)) —
        # ingress time has wm == ts, so event lag is identically zero
        "wm_current", "wm_advances", "wm_max_source_ts", "wm_stalls",
        "_wm_seen_advances", "_wm_mark_mono", "_wm_inputs_at_mark",
        "_wm_stalled", "_wm_idle", "_wm_stall_usec",
        # unified late-record accounting (every window engine: CPU keyed /
        # persistent / interval join / FFAT CPU / TPU / mesh / fused
        # terminators). late_records counts tuples that arrived behind the
        # watermark (or behind a fired window boundary); late_dropped the
        # subset discarded. Late_admitted derives (records - dropped), so
        # engines whose drop decision is deferred to a device program
        # (mesh FFAT) can count arrivals and drops at different sites and
        # the conservation invariant still holds at the totals
        "late_records", "late_dropped", "hist_lateness",
        "is_terminated", "_last_svc_start",
        # EWMA seeding: value==0.0 is NOT a reliable "unseeded" sentinel
        # (a genuine ~0 first sample would re-seed forever, biasing early
        # readings); explicit flags instead
        "_svc_seeded", "_prep_seeded", "_commit_seeded",
        # latency-tracing plane (None / 0 when sampling is off)
        "sample_every", "_svc_rec",
        "hist_service", "hist_prep", "hist_commit", "hist_e2e",
        # queue / backpressure plane
        "input_channel", "pipe_depth_max", "worker_idle_ticks",
        # device-chain fusion (tpu/fused_ops.py): number of sub-operators
        # fused into this replica's single per-batch program (0 = not a
        # fused replica)
        "fused_ops",
        # XLA compile attribution (monitoring/flightrec.instrumented_jit):
        # (re)traces vs cache hits on the replica's device programs, with
        # elapsed compile time and the triggering abstract signature
        "compile_count", "compile_usec_total", "compile_last_us",
        "compile_last_signature", "compile_cache_hits",
        # worker crash visibility: a replica chain that died records the
        # exception here instead of only dying as a silent daemon thread
        "worker_crashes", "worker_last_error",
        # flight recorder (monitoring/flightrec.py): the owning worker's
        # event ring, or None — every note_* hook below appends a span
        # when present
        "recorder",
    )

    def __init__(self, op_name: str = "", replica_idx: int = 0,
                 sample_every: int = 0) -> None:
        self.op_name = op_name
        self.replica_idx = replica_idx
        self.start_time = time.monotonic()
        self.inputs_received = 0
        self.bytes_received = 0
        self.outputs_sent = 0
        self.bytes_sent = 0
        self.inputs_ignored = 0
        self.punct_received = 0
        self.punct_sent = 0
        self.service_time_us = 0.0  # EWMA over svc() durations
        self.eff_service_time_us = 0.0
        self.device_batches_in = 0
        self.device_batches_out = 0
        self.device_bytes_h2d = 0
        self.device_bytes_d2h = 0
        self.device_programs_run = 0
        self.staging_pool_hits = 0  # recycled staging buffers (ArrayPool)
        self.staging_pool_misses = 0
        # device-ahead dispatch pipeline (runtime/dispatch.py): per-stage
        # split of the device-operator batch path — host control plane
        # (prep) vs program dispatch + emit readbacks (commit)
        self.dispatch_host_prep_us = 0.0  # EWMA
        self.dispatch_commit_us = 0.0  # EWMA
        self.dispatch_host_prep_total_us = 0.0
        self.dispatch_commit_total_us = 0.0
        self.dispatch_batches = 0
        self.dispatch_stalls = 0  # forced ordering-point drains
        self.dispatch_depth_max = 0
        self.megabatch_loops = 0
        self.megabatch_batches = 0
        self.megabatch_max = 0
        self.ingest_blocks = 0
        self.ingest_rows = 0
        self.ingest_ns_total = 0
        self.checkpoints_taken = 0
        self.checkpoint_snapshot_total_us = 0.0
        self.checkpoint_last_snapshot_us = 0.0
        self.checkpoint_bytes_total = 0
        self.checkpoint_align_total_us = 0.0
        self.checkpoint_cut_total_us = 0.0
        self.checkpoint_last_cut_us = 0.0
        self.txn_precommits = 0
        self.txn_commits = 0
        self.txn_aborts = 0
        self.txn_fenced_writes = 0
        self.dlq_records = 0
        self.dlq_skipped = 0
        self.dlq_retries = 0
        self.kafka_reconnects = 0
        self.shed_records = 0
        self.shed_bytes = 0
        self.mesh_devices = 0
        self.mesh_steps = 0
        self.mesh_shuffle_bytes = 0
        self.mesh_step_total_us = 0.0
        self.mesh_shard_occupancy = 0
        self.mesh_shard_skew = 0.0
        self.mesh_degraded = 0
        self.tier_enabled = False
        self.tier_hot_keys = 0
        self.tier_cold_keys = 0
        self.tier_promotes = 0
        self.tier_demotes = 0
        self.tier_promote_usec_total = 0.0
        self.tier_lookups = 0
        self.tier_misses = 0
        # -- event-time health plane ----------------------------------------
        self.wm_current = 0
        self.wm_advances = 0
        self.wm_max_source_ts = 0
        self.wm_stalls = 0
        self._wm_seen_advances = 0
        self._wm_mark_mono = self.start_time
        self._wm_inputs_at_mark = 0
        self._wm_stalled = False
        self._wm_idle = True
        self._wm_stall_usec = _wm_stall_sec() * 1e6
        self.late_records = 0
        self.late_dropped = 0
        self.is_terminated = False
        self._last_svc_start = 0.0
        self._svc_seeded = False
        self._prep_seeded = False
        self._commit_seeded = False
        # -- latency tracing (monitoring/histogram.py) ----------------------
        self.sample_every = max(0, int(sample_every))
        # service-histogram request flag: the replica's traced-message
        # branch sets it; the next end_svc consumes it. Keying service
        # sampling off TRACED messages keeps the end_svc hot path at one
        # bool check regardless of sampling rate (and records a cohort
        # consistent with the e2e samples).
        self._svc_rec = False
        if self.sample_every > 0:
            from .histogram import LatencyHistogram
            self.hist_service: Optional[Any] = LatencyHistogram()
            self.hist_prep: Optional[Any] = LatencyHistogram()
            self.hist_commit: Optional[Any] = LatencyHistogram()
            self.hist_e2e: Optional[Any] = LatencyHistogram()
            self.hist_lateness: Optional[Any] = LatencyHistogram()
        else:
            self.hist_service = None
            self.hist_prep = None
            self.hist_commit = None
            self.hist_e2e = None
            self.hist_lateness = None
        # -- queue / backpressure gauges ------------------------------------
        self.input_channel = None  # wired by PipeGraph._make_workers
        self.pipe_depth_max = 0  # emitter-side FIFO high-water mark
        self.worker_idle_ticks = 0
        self.fused_ops = 0  # sub-ops fused into this replica's program
        # -- compile attribution / crash visibility / flight recorder -------
        self.compile_count = 0
        self.compile_usec_total = 0.0
        self.compile_last_us = 0.0
        self.compile_last_signature = ""
        self.compile_cache_hits = 0
        self.worker_crashes = 0
        self.worker_last_error = ""
        self.recorder = None  # FlightRecorder, wired by the Worker

    # -- service-time recording (wf/basic_operator.hpp:134-158) -------------
    def start_svc(self) -> None:
        self._last_svc_start = time.perf_counter()

    def end_svc(self, n_tuples: int = 1) -> None:
        dt_us = (time.perf_counter() - self._last_svc_start) * 1e6
        per_tuple = dt_us / max(1, n_tuples)
        if not self._svc_seeded:
            self._svc_seeded = True
            self.service_time_us = per_tuple
        else:
            self.service_time_us += _EWMA_ALPHA * (per_tuple - self.service_time_us)
        self.eff_service_time_us = self.service_time_us
        if self._svc_rec:
            self._svc_rec = False
            if self.hist_service is not None:
                self.hist_service.record(per_tuple)
            # flight-recorder svc span rides the SAME traced-cohort gate
            # (one bool check already paid): no new per-tuple cost. The
            # op name is part of the span name: chained operators share
            # one ring, and an upstream op's svc interval CONTAINS its
            # inline-chained successors' — per-op names keep each
            # operator's own spans sequential and the nesting readable
            if self.recorder is not None:
                self.recorder.event("svc:" + self.op_name, dt_us, n_tuples)

    # -- dispatch-pipeline stages (runtime/dispatch.py) ----------------------
    def note_host_prep(self, us: float) -> None:
        self.dispatch_batches += 1
        self.dispatch_host_prep_total_us += us
        if not self._prep_seeded:
            self._prep_seeded = True
            self.dispatch_host_prep_us = us
        else:
            self.dispatch_host_prep_us += _EWMA_ALPHA * (
                us - self.dispatch_host_prep_us)
        if self.hist_prep is not None:
            self.hist_prep.record(us)
        if self.recorder is not None:
            self.recorder.event("host_prep", us)

    def note_dispatch_commit(self, us: float) -> None:
        self.dispatch_commit_total_us += us
        if not self._commit_seeded:
            self._commit_seeded = True
            self.dispatch_commit_us = us
        else:
            self.dispatch_commit_us += _EWMA_ALPHA * (
                us - self.dispatch_commit_us)
        if self.hist_commit is not None:
            self.hist_commit.record(us)
        if self.recorder is not None:
            self.recorder.event("commit", us)

    def note_megabatch(self, k: int, us: float) -> None:
        """One megabatch scan loop: K same-signature batches committed
        through ONE program dispatch (``FusedTPUReplica._run_megabatch``)."""
        self.megabatch_loops += 1
        self.megabatch_batches += k
        if k > self.megabatch_max:
            self.megabatch_max = k
        if self.recorder is not None:
            self.recorder.event("megabatch:scan", us, k)

    def note_ingest_block(self, n_rows: int, ns: int) -> None:
        """One column block through ``ship_columns``: ``n_rows`` admitted
        rows shipped in ``ns`` host nanoseconds (gate + routing + staging
        copy; the async H2D itself is excluded by dispatch)."""
        self.ingest_blocks += 1
        self.ingest_rows += n_rows
        self.ingest_ns_total += ns
        if self.recorder is not None:
            self.recorder.event("ingest:block", ns / 1e3, n_rows)

    def note_dispatch_depth(self, depth: int) -> None:
        if depth > self.dispatch_depth_max:
            self.dispatch_depth_max = depth

    def note_dispatch_stall(self) -> None:
        self.dispatch_stalls += 1

    # -- checkpointing (windflow_tpu.checkpoint) -----------------------------
    def note_checkpoint(self, snapshot_us: float, nbytes: int,
                        align_us: float,
                        cut_us: Optional[float] = None) -> None:
        """One aligned snapshot of this replica's worker chain:
        state-capture duration, blob bytes written, how long barrier
        alignment stalled the chain (0 for single-input workers), and
        the barrier CUT pause (capture + ack; defaults to the snapshot
        duration for call sites that don't distinguish the two)."""
        if cut_us is None:
            cut_us = snapshot_us
        self.checkpoints_taken += 1
        self.checkpoint_snapshot_total_us += snapshot_us
        self.checkpoint_last_snapshot_us = snapshot_us
        self.checkpoint_bytes_total += nbytes
        self.checkpoint_align_total_us += align_us
        self.checkpoint_cut_total_us += cut_us
        self.checkpoint_last_cut_us = cut_us
        if self.recorder is not None:
            if align_us > 0:
                self.recorder.event("barrier_align", align_us)
            self.recorder.event("ckpt_snapshot", snapshot_us,
                                {"bytes": nbytes})

    # -- compile attribution (monitoring/flightrec.instrumented_jit) ---------
    def note_compile(self, us: float, signature: str = "") -> None:
        """One XLA (re)trace+compile on this replica's device programs:
        elapsed time and the abstract signature that triggered it."""
        self.compile_count += 1
        self.compile_usec_total += us
        self.compile_last_us = us
        self.compile_last_signature = signature

    # -- mesh execution plane (windflow_tpu.mesh) -----------------------------
    def note_mesh_step(self, us: float, shuffle_bytes: int) -> None:
        """One sharded step: host-observed dispatch time + the bytes its
        in-program all_to_all moved (every tuple column crosses the
        shuffle exactly once per step)."""
        self.mesh_steps += 1
        self.mesh_step_total_us += us
        self.mesh_shuffle_bytes += shuffle_bytes
        if self.recorder is not None:
            self.recorder.event("mesh:step", us,
                                {"bytes": shuffle_bytes})

    # -- tiered keyed state (windflow_tpu.state.tiered) -----------------------
    def note_tier_promote(self, n_keys: int, usec: float) -> None:
        """One BATCHED promote (cold rows -> one slot-row scatter):
        ``n_keys`` keys moved hot in ``usec`` host-observed time."""
        self.tier_promotes += n_keys
        self.tier_promote_usec_total += usec
        if self.recorder is not None:
            self.recorder.event("tier:promote", usec, n_keys)

    def note_tier_demote(self, n_keys: int) -> None:
        """One BATCHED demote (slot-row gather -> cold writes)."""
        self.tier_demotes += n_keys
        if self.recorder is not None:
            self.recorder.event("tier:demote", 0.0, n_keys)

    def note_tier_gauges(self, hot: int, cold: int, lookups: int,
                         misses: int) -> None:
        self.tier_enabled = True
        self.tier_hot_keys = hot
        self.tier_cold_keys = cold
        self.tier_lookups = lookups
        self.tier_misses = misses

    # -- overload protection (windflow_tpu.overload) --------------------------
    def note_shed(self, n: int, nbytes: int) -> None:
        """Records shed by source admission control (never emitted, so
        they appear in NO other counter — offered = admitted + shed)."""
        self.shed_records += n
        self.shed_bytes += nbytes

    # -- event-time health plane ---------------------------------------------
    def note_late(self, n_records: int, n_dropped: int = 0,
                  lateness_us: Any = None) -> None:
        """Late-record accounting for one engine decision (or one batched
        block of decisions). ``n_records`` tuples observed behind the
        watermark / a fired boundary; ``n_dropped`` of the replica's late
        tuples discarded. The two may be counted at DIFFERENT call sites
        (device engines learn the drop count from a later readback), so
        pass ``n_records=0`` for drop-only updates of tuples already
        counted late on arrival. ``lateness_us`` — observed (wm - ts),
        scalar or array — feeds the lateness histogram when tracing is on."""
        self.late_records += n_records
        self.late_dropped += n_dropped
        h = self.hist_lateness
        if h is not None and lateness_us is not None:
            if hasattr(lateness_us, "__len__"):
                h.record_many(lateness_us)
            else:
                h.record(lateness_us)
        if self.recorder is not None and n_dropped:
            self.recorder.event("late:drop", 0.0, n_dropped)

    def poll_watermark(self, now: Optional[float] = None) -> float:
        """Derive watermark lag / idle / stall from the advance counter —
        called at observation points (to_dict, worker idle ticks), never
        per tuple. Returns the wall-clock lag in microseconds since the
        watermark last advanced. Stall detection is edge-triggered: a
        replica whose inputs keep arriving while the watermark has been
        frozen past ``WF_WM_STALL_SEC`` bumps ``wm_stalls`` once per
        freeze (and logs a ``wm:stall`` flight-recorder span); a replica
        with no new inputs either is ``idle``, not stalled."""
        if now is None:
            now = time.monotonic()
        adv = self.wm_advances
        if adv != self._wm_seen_advances:
            self._wm_seen_advances = adv
            self._wm_mark_mono = now
            self._wm_inputs_at_mark = self.inputs_received
            self._wm_stalled = False
            self._wm_idle = False
            return 0.0
        lag_us = max(0.0, (now - self._wm_mark_mono) * 1e6)
        self._wm_idle = self.inputs_received == self._wm_inputs_at_mark
        if (not self._wm_idle and not self._wm_stalled
                and lag_us > self._wm_stall_usec):
            self._wm_stalled = True
            self.wm_stalls += 1
            if self.recorder is not None:
                self.recorder.event("wm:stall", lag_us, self.wm_current)
        return lag_us

    # -- latency tracing -----------------------------------------------------
    def note_e2e(self, us: float) -> None:
        """End-to-end latency of one traced tuple (sink side)."""
        if self.hist_e2e is not None:
            self.hist_e2e.record(us)

    def note_pipe_depth(self, depth: int) -> None:
        """Emitter-side FIFO occupancy high-water mark (_D2HPipeline)."""
        if depth > self.pipe_depth_max:
            self.pipe_depth_max = depth

    def to_dict(self) -> Dict[str, Any]:
        elapsed = max(time.monotonic() - self.start_time, 1e-9)
        d = {
            "Operator_name": self.op_name,
            "Replica_id": self.replica_idx,
            "Inputs_received": self.inputs_received,
            "Bytes_received": self.bytes_received,
            "Outputs_sent": self.outputs_sent,
            "Bytes_sent": self.bytes_sent,
            "Inputs_ignored": self.inputs_ignored,
            "Punctuations_received": self.punct_received,
            "Punctuations_sent": self.punct_sent,
            "Service_time_usec": round(self.service_time_us, 3),
            "Eff_Service_time_usec": round(self.eff_service_time_us, 3),
            "Throughput_tuples_sec": round(self.inputs_received / elapsed, 1),
            "Device_batches_in": self.device_batches_in,
            "Device_batches_out": self.device_batches_out,
            "Device_bytes_H2D": self.device_bytes_h2d,
            "Device_bytes_D2H": self.device_bytes_d2h,
            "Device_programs_run": self.device_programs_run,
            "Fused_ops": self.fused_ops,
            "Staging_pool_hits": self.staging_pool_hits,
            "Staging_pool_misses": self.staging_pool_misses,
            "Dispatch_host_prep_usec": round(self.dispatch_host_prep_us, 3),
            "Dispatch_commit_usec": round(self.dispatch_commit_us, 3),
            "Dispatch_host_prep_total_usec": round(
                self.dispatch_host_prep_total_us, 1),
            "Dispatch_commit_total_usec": round(
                self.dispatch_commit_total_us, 1),
            "Dispatch_batches": self.dispatch_batches,
            "Dispatch_readback_stalls": self.dispatch_stalls,
            "Dispatch_queue_depth_max": self.dispatch_depth_max,
            # megabatch scan loop (0s with WF_MEGABATCH off or on
            # non-fused replicas; Programs_per_batch == 1.0 is the
            # un-amortized fused baseline, < 1.0 means the scan loop is
            # retiring multiple batches per dispatch)
            "Megabatch_loops": self.megabatch_loops,
            "Megabatch_batches_per_loop_avg": round(
                self.megabatch_batches / self.megabatch_loops, 2)
                if self.megabatch_loops else 0.0,
            "Megabatch_max": self.megabatch_max,
            # columnar ingest plane (0s on row-path-only sources)
            "Ingest_blocks": self.ingest_blocks,
            "Ingest_rows_per_block_avg": round(
                self.ingest_rows / self.ingest_blocks, 2)
                if self.ingest_blocks else 0.0,
            "Ingest_block_ns_per_row": round(
                self.ingest_ns_total / self.ingest_rows, 1)
                if self.ingest_rows else 0.0,
            "Programs_per_batch": round(
                self.device_programs_run / self.dispatch_batches, 3)
                if self.dispatch_batches else 0.0,
            "Checkpoint_snapshots": self.checkpoints_taken,
            "Checkpoint_snapshot_usec_total": round(
                self.checkpoint_snapshot_total_us, 1),
            "Checkpoint_last_snapshot_usec": round(
                self.checkpoint_last_snapshot_us, 1),
            "Checkpoint_bytes_total": self.checkpoint_bytes_total,
            "Checkpoint_align_stall_usec_total": round(
                self.checkpoint_align_total_us, 1),
            "Checkpoint_cut_pause_usec_total": round(
                self.checkpoint_cut_total_us, 1),
            "Checkpoint_cut_pause_usec": round(
                self.checkpoint_last_cut_us, 1),
            # exactly-once sink 2PC (0s unless with_exactly_once)
            "Sink_txn_precommits": self.txn_precommits,
            "Sink_txn_commits": self.txn_commits,
            "Sink_txn_aborts": self.txn_aborts,
            "Sink_txn_fenced_writes": self.txn_fenced_writes,
            # XLA compile attribution (flightrec.instrumented_jit wraps
            # the device plane's jit entry points; 0/"" on CPU replicas)
            "Compile_count": self.compile_count,
            "Compile_usec_total": round(self.compile_usec_total, 1),
            "Compile_last_usec": round(self.compile_last_us, 1),
            "Compile_last_signature": self.compile_last_signature,
            "Compile_cache_hits": self.compile_cache_hits,
            # per-record error policies / dead-letter quarantine
            # (0s on the default FAIL policy)
            "Dlq_records": self.dlq_records,
            "Dlq_skipped": self.dlq_skipped,
            "Dlq_retries": self.dlq_retries,
            # Kafka transient-error retry/backoff (kafka/connectors.py)
            "Kafka_reconnects": self.kafka_reconnects,
            # overload admission control (0s unless the governor sheds)
            "Shed_records": self.shed_records,
            "Shed_bytes": self.shed_bytes,
            # worker crash visibility (Worker records on its error path)
            "Worker_crashes": self.worker_crashes,
            "Worker_last_error": self.worker_last_error,
            "isTerminated": self.is_terminated,
        }
        # -- event-time health plane (always present: zero lag on a healthy
        # replica is itself the signal the doctor reads) --------------------
        wm_lag_us = self.poll_watermark()
        d["Watermark_current_ts"] = self.wm_current
        d["Watermark_advances"] = self.wm_advances
        d["Watermark_lag_usec"] = round(wm_lag_us, 1)
        d["Watermark_event_lag_usec"] = (
            max(0, self.wm_max_source_ts - self.wm_current)
            if self.wm_max_source_ts > 0 else 0)
        d["Watermark_idle"] = 1 if self._wm_idle else 0
        d["Watermark_stalls"] = self.wm_stalls
        d["Late_records"] = self.late_records
        d["Late_dropped"] = self.late_dropped
        d["Late_admitted"] = max(0, self.late_records - self.late_dropped)
        # -- mesh execution plane (mesh replicas only: a Mesh_* series on
        # every CPU replica would be noise — /metrics renders these only
        # where rep.get(field) exists) ---------------------------------------
        if self.mesh_devices > 0:
            d["Mesh_devices"] = self.mesh_devices
            d["Mesh_steps"] = self.mesh_steps
            d["Mesh_shuffle_bytes"] = self.mesh_shuffle_bytes
            d["Mesh_step_usec_total"] = round(self.mesh_step_total_us, 1)
            d["Mesh_shard_occupancy"] = self.mesh_shard_occupancy
            d["Mesh_shard_skew"] = self.mesh_shard_skew
            d["Mesh_degraded_devices"] = self.mesh_degraded
        # -- tiered keyed state (with_tiering replicas only) ----------------
        if self.tier_enabled:
            d["Tier_hot_keys"] = self.tier_hot_keys
            d["Tier_cold_keys"] = self.tier_cold_keys
            d["Tier_promotes"] = self.tier_promotes
            d["Tier_demotes"] = self.tier_demotes
            d["Tier_promote_usec_total"] = round(
                self.tier_promote_usec_total, 1)
            d["Tier_miss_rate"] = round(
                self.tier_misses / self.tier_lookups, 4) \
                if self.tier_lookups else 0.0
        # -- queue / backpressure plane (0s for sources and fused chains) ---
        ch = self.input_channel
        d["Queue_len"] = len(ch) if ch is not None else 0
        d["Queue_capacity"] = getattr(ch, "capacity", 0) if ch is not None \
            else 0
        d["Queue_depth_max"] = getattr(ch, "depth_max", 0) if ch is not None \
            else 0
        d["Queue_blocked_put_usec"] = round(
            getattr(ch, "blocked_put_ns", 0) / 1e3, 1) if ch is not None \
            else 0.0
        d["Queue_blocked_get_usec"] = round(
            getattr(ch, "blocked_get_ns", 0) / 1e3, 1) if ch is not None \
            else 0.0
        d["Queue_puts_blocked"] = getattr(ch, "puts_blocked", 0) \
            if ch is not None else 0
        d["Queue_emit_fifo_depth_max"] = self.pipe_depth_max
        d["Worker_idle_ticks"] = self.worker_idle_ticks
        # -- latency-tracing plane ------------------------------------------
        d["Latency_sample_every"] = self.sample_every
        for label, h in (("service", self.hist_service),
                         ("prep", self.hist_prep),
                         ("commit", self.hist_commit),
                         ("e2e", self.hist_e2e),
                         ("lateness", self.hist_lateness)):
            on = h is not None
            d[f"Latency_{label}_p50_usec"] = round(h.p50, 1) if on else 0.0
            d[f"Latency_{label}_p90_usec"] = round(h.p90, 1) if on else 0.0
            d[f"Latency_{label}_p99_usec"] = round(h.p99, 1) if on else 0.0
            d[f"Latency_{label}_max_usec"] = round(h.max_us, 1) if on else 0.0
            d[f"Latency_{label}_samples"] = h.count if on else 0
            if on and h.count:
                # sparse bucket transport: /metrics renders real histogram
                # series and per-operator merges from these
                d[f"Latency_{label}_hist"] = h.to_sparse()
        return d
