"""Per-replica statistics (reference ``wf/stats_record.hpp:49-160``).

Counters: inputs/outputs received/sent, ignored (dropped) tuples, service time
EWMA (``wf/basic_operator.hpp:144-158``), and device-plane traffic (batches
staged to/from the TPU, bytes moved — the analog of the reference's kernels
launched / bytes H2D/D2H). Serialized to JSON by the PipeGraph at wait_end
(``wf/pipegraph.hpp:464-522``).
"""

from __future__ import annotations

import time
from typing import Any, Dict

_EWMA_ALPHA = 0.1


class StatsRecord:
    __slots__ = (
        "op_name", "replica_idx", "start_time",
        "inputs_received", "bytes_received", "outputs_sent", "bytes_sent",
        "inputs_ignored", "punct_received", "punct_sent",
        "service_time_us", "eff_service_time_us",
        "device_batches_in", "device_batches_out",
        "device_bytes_h2d", "device_bytes_d2h", "device_programs_run",
        "staging_pool_hits", "staging_pool_misses",
        "dispatch_host_prep_us", "dispatch_commit_us",
        "dispatch_host_prep_total_us", "dispatch_commit_total_us",
        "dispatch_batches", "dispatch_stalls", "dispatch_depth_max",
        "is_terminated", "_last_svc_start",
    )

    def __init__(self, op_name: str = "", replica_idx: int = 0) -> None:
        self.op_name = op_name
        self.replica_idx = replica_idx
        self.start_time = time.monotonic()
        self.inputs_received = 0
        self.bytes_received = 0
        self.outputs_sent = 0
        self.bytes_sent = 0
        self.inputs_ignored = 0
        self.punct_received = 0
        self.punct_sent = 0
        self.service_time_us = 0.0  # EWMA over svc() durations
        self.eff_service_time_us = 0.0
        self.device_batches_in = 0
        self.device_batches_out = 0
        self.device_bytes_h2d = 0
        self.device_bytes_d2h = 0
        self.device_programs_run = 0
        self.staging_pool_hits = 0  # recycled staging buffers (ArrayPool)
        self.staging_pool_misses = 0
        # device-ahead dispatch pipeline (runtime/dispatch.py): per-stage
        # split of the device-operator batch path — host control plane
        # (prep) vs program dispatch + emit readbacks (commit)
        self.dispatch_host_prep_us = 0.0  # EWMA
        self.dispatch_commit_us = 0.0  # EWMA
        self.dispatch_host_prep_total_us = 0.0
        self.dispatch_commit_total_us = 0.0
        self.dispatch_batches = 0
        self.dispatch_stalls = 0  # forced ordering-point drains
        self.dispatch_depth_max = 0
        self.is_terminated = False
        self._last_svc_start = 0.0

    # -- service-time recording (wf/basic_operator.hpp:134-158) -------------
    def start_svc(self) -> None:
        self._last_svc_start = time.perf_counter()

    def end_svc(self, n_tuples: int = 1) -> None:
        dt_us = (time.perf_counter() - self._last_svc_start) * 1e6
        per_tuple = dt_us / max(1, n_tuples)
        if self.service_time_us == 0.0:
            self.service_time_us = per_tuple
        else:
            self.service_time_us += _EWMA_ALPHA * (per_tuple - self.service_time_us)
        self.eff_service_time_us = self.service_time_us

    # -- dispatch-pipeline stages (runtime/dispatch.py) ----------------------
    def note_host_prep(self, us: float) -> None:
        self.dispatch_batches += 1
        self.dispatch_host_prep_total_us += us
        if self.dispatch_host_prep_us == 0.0:
            self.dispatch_host_prep_us = us
        else:
            self.dispatch_host_prep_us += _EWMA_ALPHA * (
                us - self.dispatch_host_prep_us)

    def note_dispatch_commit(self, us: float) -> None:
        self.dispatch_commit_total_us += us
        if self.dispatch_commit_us == 0.0:
            self.dispatch_commit_us = us
        else:
            self.dispatch_commit_us += _EWMA_ALPHA * (
                us - self.dispatch_commit_us)

    def note_dispatch_depth(self, depth: int) -> None:
        if depth > self.dispatch_depth_max:
            self.dispatch_depth_max = depth

    def note_dispatch_stall(self) -> None:
        self.dispatch_stalls += 1

    def to_dict(self) -> Dict[str, Any]:
        elapsed = max(time.monotonic() - self.start_time, 1e-9)
        return {
            "Operator_name": self.op_name,
            "Replica_id": self.replica_idx,
            "Inputs_received": self.inputs_received,
            "Bytes_received": self.bytes_received,
            "Outputs_sent": self.outputs_sent,
            "Bytes_sent": self.bytes_sent,
            "Inputs_ignored": self.inputs_ignored,
            "Punctuations_received": self.punct_received,
            "Punctuations_sent": self.punct_sent,
            "Service_time_usec": round(self.service_time_us, 3),
            "Eff_Service_time_usec": round(self.eff_service_time_us, 3),
            "Throughput_tuples_sec": round(self.inputs_received / elapsed, 1),
            "Device_batches_in": self.device_batches_in,
            "Device_batches_out": self.device_batches_out,
            "Device_bytes_H2D": self.device_bytes_h2d,
            "Device_bytes_D2H": self.device_bytes_d2h,
            "Device_programs_run": self.device_programs_run,
            "Staging_pool_hits": self.staging_pool_hits,
            "Staging_pool_misses": self.staging_pool_misses,
            "Dispatch_host_prep_usec": round(self.dispatch_host_prep_us, 3),
            "Dispatch_commit_usec": round(self.dispatch_commit_us, 3),
            "Dispatch_host_prep_total_usec": round(
                self.dispatch_host_prep_total_us, 1),
            "Dispatch_commit_total_usec": round(
                self.dispatch_commit_total_us, 1),
            "Dispatch_batches": self.dispatch_batches,
            "Dispatch_readback_stalls": self.dispatch_stalls,
            "Dispatch_queue_depth_max": self.dispatch_depth_max,
            "isTerminated": self.is_terminated,
        }
