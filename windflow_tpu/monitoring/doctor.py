"""PipelineDoctor: bottleneck attribution over tick-over-tick stats deltas.

The monitoring plane (57+ metric families) answers "what happened";
answering "what is the bottleneck RIGHT NOW" today means manually
correlating queue blocked-put/get rates, dispatch prep/commit splits,
shed fractions, compile storms and watermark lag across operators. The
doctor encodes that correlation once: a PURE analyzer over two
consecutive graph stats snapshots (``PipeGraph.get_stats`` shape) that
emits a ranked per-operator verdict with the evidence behind each claim.

Verdicts (one vocabulary, shared by /doctor, scripts/doctor.py and the
web client banner):

- ``overloaded``        — source admission control is shedding (or the
                          overload governor sits on its shed rung);
- ``backpressured-by``  — the operator's producers spend their time
                          blocked on a FULL downstream channel; ``by``
                          names the operator that cannot drain;
- ``compute-bound``     — the named operator is the drain bottleneck:
                          its input channel is the most-downstream one
                          producers block on, and its own host path
                          dominates;
- ``dispatch-bound``    — same position, but the device dispatch plane
                          (commit share of prep+commit, or an XLA
                          recompile storm) dominates the operator's time;
- ``event-time-stalled``— inputs keep arriving while the watermark has
                          been frozen past ``WF_WM_STALL_SEC``;
- ``ingest-bound``      — nobody is backpressured and every downstream
                          operator starves on an empty input channel:
                          the sources cannot produce fast enough.

The analyzer never touches live objects: it consumes report dicts as
they arrive over the monitoring port, so it runs equally against a live
``MonitoringServer``, a dumped stats snapshot, or synthetic fixtures.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

# attribution thresholds (fractions of the observation interval)
BP_MIN_FRAC = 0.15        # producer blocked-put time => backpressure
STARVE_MIN_FRAC = 0.5     # consumer blocked-get time => starvation
DISPATCH_MIN_FRAC = 0.5   # prep+commit share of the tick => device-bound
COMMIT_SHARE = 0.6        # commit share of prep+commit => dispatch-bound
COMPILE_STORM = 3         # recompiles per tick => dispatch-bound (storm)

# score bands keep the ranking stable across mixed symptoms: an
# overloaded graph is overloaded even when it is ALSO backpressured
_SCORE_OVERLOAD = 1.2
_SCORE_BOTTLENECK = 0.2
_SCORE_STALL = 0.8


def _num(v: Any) -> float:
    return float(v) if isinstance(v, (int, float)) else 0.0


def _op_rollup(op: Dict[str, Any]) -> Dict[str, float]:
    """Aggregate one operator's replica records: counters sum, gauges max."""
    reps = [r for r in (op.get("replicas") or []) if isinstance(r, dict)]
    out: Dict[str, float] = {"replicas": float(len(reps)) or 1.0}
    sums = ("Inputs_received", "Outputs_sent", "Shed_records",
            "Queue_blocked_put_usec", "Queue_blocked_get_usec",
            "Dispatch_host_prep_total_usec", "Dispatch_commit_total_usec",
            "Compile_count", "Checkpoint_cut_pause_usec_total",
            "Watermark_stalls", "Late_records", "Late_dropped",
            "Late_admitted", "Queue_len", "Worker_idle_ticks")
    maxes = ("Service_time_usec", "Watermark_lag_usec", "Queue_capacity",
             "Watermark_event_lag_usec", "Tier_miss_rate")
    for f in sums:
        out[f] = sum(_num(r.get(f)) for r in reps)
    for f in maxes:
        out[f] = max((_num(r.get(f)) for r in reps), default=0.0)
    # idle only when EVERY replica is idle (any traffic => not idle)
    out["Watermark_idle"] = min((_num(r.get("Watermark_idle", 1))
                                 for r in reps), default=1.0)
    return out


class PipelineDoctor:
    """Stateful wrapper: feed ``observe`` each report as it arrives; it
    keeps the previous tick per graph and returns the fresh diagnosis
    (None on the first report, when no delta exists yet)."""

    def __init__(self, stall_sec: Optional[float] = None) -> None:
        from .stats import _wm_stall_sec
        self.stall_sec = stall_sec if stall_sec is not None \
            else _wm_stall_sec()
        self._prev: Dict[str, tuple] = {}

    def observe(self, graph: str, stats: Dict[str, Any],
                now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        if now is None:
            now = time.monotonic()
        prev = self._prev.get(graph)
        self._prev[graph] = (stats, now)
        if prev is None:
            return None
        prev_stats, prev_t = prev
        dt = max(now - prev_t, 1e-3)
        diag = diagnose(prev_stats, stats, dt, self.stall_sec)
        diag["graph"] = graph
        return diag


def diagnose(prev: Optional[Dict[str, Any]], cur: Dict[str, Any],
             dt_sec: float, stall_sec: float = 5.0) -> Dict[str, Any]:
    """Pure diagnosis of ``cur`` against ``prev`` over ``dt_sec``.
    ``prev=None`` analyzes cumulative totals (whole-run mode for dumped
    snapshots); pass the real elapsed run time as ``dt_sec`` then."""
    dt_us = max(dt_sec, 1e-3) * 1e6
    cur_ops = [o for o in (cur.get("Operators") or [])
               if isinstance(o, dict) and not o.get("retired")]
    prev_by_name: Dict[str, Dict[str, float]] = {}
    if prev is not None:
        for o in (prev.get("Operators") or []):
            if isinstance(o, dict) and not o.get("retired"):
                prev_by_name[str(o.get("name"))] = _op_rollup(o)

    rows: List[Dict[str, Any]] = []
    for o in cur_ops:
        name = str(o.get("name"))
        c = _op_rollup(o)
        p = prev_by_name.get(name, {})
        par = max(c["replicas"], 1.0)
        d = lambda f: max(0.0, c.get(f, 0.0) - p.get(f, 0.0))  # noqa: E731
        rows.append({
            "name": name,
            "kind": str(o.get("kind", "")),
            "is_source": str(o.get("kind", "")).lower() == "source",
            "is_sink": str(o.get("kind", "")).lower() == "sink",
            "par": par,
            "in_rate": d("Inputs_received") / dt_sec,
            "in_delta": d("Inputs_received"),
            "shed_delta": d("Shed_records"),
            # producer time blocked putting INTO this op's full channel,
            # as a fraction of the tick (can exceed 1 with >1 producer)
            "bp_frac": d("Queue_blocked_put_usec") / dt_us,
            # this op's own time blocked on an EMPTY channel, per replica
            "starve_frac": d("Queue_blocked_get_usec") / (dt_us * par),
            "dispatch_frac": (d("Dispatch_host_prep_total_usec")
                              + d("Dispatch_commit_total_usec"))
            / (dt_us * par),
            "commit_share": (d("Dispatch_commit_total_usec")
                             / max(1.0, d("Dispatch_host_prep_total_usec")
                                   + d("Dispatch_commit_total_usec"))),
            "compile_delta": d("Compile_count"),
            "ckpt_cut_frac": d("Checkpoint_cut_pause_usec_total")
            / (dt_us * par),
            "wm_lag_us": c.get("Watermark_lag_usec", 0.0),
            "wm_stall_delta": d("Watermark_stalls"),
            "wm_idle": bool(c.get("Watermark_idle", 1.0)),
            "late_drop_delta": d("Late_dropped"),
            "late_records_delta": d("Late_records"),
            "svc_us": c.get("Service_time_usec", 0.0),
            "queue_len": c.get("Queue_len", 0.0),
            "queue_cap": c.get("Queue_capacity", 0.0),
            "tier_miss_rate": c.get("Tier_miss_rate", 0.0),
        })

    findings: List[Dict[str, Any]] = []
    overload = cur.get("Overload") if isinstance(cur.get("Overload"), dict) \
        else {}

    # -- overloaded: admission control shed records this tick ---------------
    total_shed = sum(r["shed_delta"] for r in rows)
    total_in = sum(r["in_delta"] for r in rows if r["is_source"])
    gov_shedding = _num(overload.get("Overload_state")) >= 3
    if total_shed > 0 or gov_shedding:
        shed_frac = total_shed / max(1.0, total_shed + total_in)
        for r in rows:
            if r["shed_delta"] > 0 or (gov_shedding and r["is_source"]):
                findings.append({
                    "operator": r["name"], "verdict": "overloaded",
                    "score": round(_SCORE_OVERLOAD + min(0.5, shed_frac), 3),
                    "evidence": {
                        "shed_records_delta": r["shed_delta"],
                        "shed_fraction": round(shed_frac, 4),
                        "overload_state": _num(
                            overload.get("Overload_state")),
                        "window_p99_usec": _num(
                            overload.get("Overload_window_p99_usec")),
                    },
                    "detail": (f"admission control shed "
                               f"{int(r['shed_delta'])} records "
                               f"({shed_frac:.1%} of offered load)"),
                })

    # -- backpressure chain: the most-downstream full channel is the drain
    # bottleneck; everything upstream of it is backpressured-by it --------
    bottleneck_idx = -1
    for i, r in enumerate(rows):
        if r["bp_frac"] >= BP_MIN_FRAC:
            bottleneck_idx = i
    if bottleneck_idx >= 0:
        b = rows[bottleneck_idx]
        dispatch_bound = (b["dispatch_frac"] >= DISPATCH_MIN_FRAC
                          and b["commit_share"] >= COMMIT_SHARE) \
            or b["compile_delta"] >= COMPILE_STORM
        findings.append({
            "operator": b["name"],
            "verdict": "dispatch-bound" if dispatch_bound
            else "compute-bound",
            "score": round(_SCORE_BOTTLENECK + min(1.0, b["bp_frac"]), 3),
            "evidence": {
                "blocked_put_frac": round(b["bp_frac"], 4),
                "queue_len": b["queue_len"],
                "queue_capacity": b["queue_cap"],
                "service_time_usec": round(b["svc_us"], 1),
                "dispatch_frac": round(b["dispatch_frac"], 4),
                "commit_share": round(b["commit_share"], 4),
                "compile_delta": b["compile_delta"],
                "ckpt_cut_frac": round(b["ckpt_cut_frac"], 4),
                "tier_miss_rate": round(b["tier_miss_rate"], 4),
            },
            "detail": (f"producers spent {b['bp_frac']:.0%} of the tick "
                       f"blocked on {b['name']}'s full input channel"
                       + (f"; device dispatch dominates "
                          f"({b['commit_share']:.0%} commit share, "
                          f"{int(b['compile_delta'])} recompiles)"
                          if dispatch_bound else
                          f"; host path dominates "
                          f"(svc {b['svc_us']:.0f} µs/tuple)")),
        })
        for r in rows[:bottleneck_idx]:
            if r["is_source"] or r["bp_frac"] >= BP_MIN_FRAC \
                    or r["in_delta"] > 0:
                findings.append({
                    "operator": r["name"], "verdict": "backpressured-by",
                    "by": b["name"],
                    "score": round(min(1.0, b["bp_frac"]) * 0.5, 3),
                    "evidence": {
                        "bottleneck": b["name"],
                        "blocked_put_frac_downstream": round(
                            b["bp_frac"], 4)},
                    "detail": (f"{r['name']} is throttled by downstream "
                               f"{b['name']} (backpressure)"),
                })

    # -- event-time stall: traffic flows, watermark frozen ------------------
    stall_us = stall_sec * 1e6
    for r in rows:
        stalled = r["wm_stall_delta"] > 0 or (
            not r["wm_idle"] and r["wm_lag_us"] > stall_us)
        if stalled:
            findings.append({
                "operator": r["name"], "verdict": "event-time-stalled",
                "score": round(_SCORE_STALL
                               + min(0.3, r["wm_lag_us"] / (10 * stall_us)),
                               3),
                "evidence": {
                    "watermark_lag_usec": round(r["wm_lag_us"], 1),
                    "watermark_stalls_delta": r["wm_stall_delta"],
                    "inputs_delta": r["in_delta"],
                    "late_dropped_delta": r["late_drop_delta"],
                },
                "detail": (f"watermark frozen for "
                           f"{r['wm_lag_us'] / 1e6:.1f}s while "
                           f"{int(r['in_delta'])} inputs arrived"),
            })

    # -- dispatch-bound device ops even without a full input channel
    # (sources / fused chains have no input queue to blame) -----------------
    flagged = {f["operator"] for f in findings}
    for r in rows:
        if r["name"] in flagged:
            continue
        if (r["dispatch_frac"] >= DISPATCH_MIN_FRAC
                and r["commit_share"] >= COMMIT_SHARE) \
                or r["compile_delta"] >= COMPILE_STORM:
            findings.append({
                "operator": r["name"], "verdict": "dispatch-bound",
                "score": round(min(1.0, r["dispatch_frac"]) * 0.6
                               + (0.3 if r["compile_delta"]
                                  >= COMPILE_STORM else 0.0), 3),
                "evidence": {
                    "dispatch_frac": round(r["dispatch_frac"], 4),
                    "commit_share": round(r["commit_share"], 4),
                    "compile_delta": r["compile_delta"],
                },
                "detail": (f"device dispatch consumed "
                           f"{r['dispatch_frac']:.0%} of the tick"
                           + (f" with {int(r['compile_delta'])} XLA "
                              f"recompiles (compile storm)"
                              if r["compile_delta"] >= COMPILE_STORM
                              else "")),
            })

    # -- ingest-bound: nobody backpressured, downstream starves -------------
    if bottleneck_idx < 0 and total_shed == 0:
        downstream = [r for r in rows if not r["is_source"]]
        starving = [r for r in downstream
                    if r["starve_frac"] >= STARVE_MIN_FRAC
                    and r["queue_len"] <= 1]
        sources = [r for r in rows if r["is_source"] and r["in_delta"] > 0]
        if downstream and sources and len(starving) == len(downstream):
            starv = sum(r["starve_frac"] for r in downstream) \
                / len(downstream)
            for s in sources:
                findings.append({
                    "operator": s["name"], "verdict": "ingest-bound",
                    "score": round(min(1.0, starv), 3),
                    "evidence": {
                        "mean_downstream_starve_frac": round(starv, 4),
                        "source_rate_tuples_sec": round(s["in_rate"], 1),
                        "starving_operators": [r["name"]
                                               for r in starving],
                    },
                    "detail": (f"every downstream operator idles "
                               f"{starv:.0%} of the tick waiting on "
                               f"input: the source is the bottleneck"),
                })

    findings.sort(key=lambda f: f["score"], reverse=True)
    total_late_drop = sum(r["late_drop_delta"] for r in rows)
    diag: Dict[str, Any] = {
        "dt_sec": round(dt_sec, 3),
        "healthy": not findings,
        "findings": findings,
        "bottleneck": findings[0] if findings else None,
        "late_dropped_delta": total_late_drop,
        "summary": _summarize(findings, total_late_drop),
    }
    return diag


def _summarize(findings: List[Dict[str, Any]], late_drop: float) -> str:
    if not findings:
        return "healthy: no bottleneck detected this tick" + (
            f" ({int(late_drop)} late records dropped)" if late_drop else "")
    top = findings[0]
    verdict = top["verdict"]
    if verdict == "backpressured-by":
        head = f"{top['operator']} backpressured by {top.get('by', '?')}"
    else:
        head = f"{top['operator']} is {verdict}"
    extra = f"; {int(late_drop)} late records dropped" if late_drop else ""
    more = len(findings) - 1
    return head + (f" (+{more} more finding{'s' * (more > 1)})"
                   if more else "") + extra


def render_text(diag: Dict[str, Any], graph: str = "") -> str:
    """Human-readable doctor report (scripts/doctor.py and tests)."""
    lines = []
    name = diag.get("graph", graph) or "?"
    lines.append(f"== doctor: {name} "
                 f"(tick {diag.get('dt_sec', 0):.1f}s) ==")
    lines.append("  " + diag.get("summary", ""))
    for f in diag.get("findings") or []:
        by = f" -> {f['by']}" if f.get("by") else ""
        lines.append(f"  [{f['score']:.2f}] {f['operator']}: "
                     f"{f['verdict']}{by}")
        lines.append(f"         {f.get('detail', '')}")
        ev = f.get("evidence") or {}
        if ev:
            kv = ", ".join(f"{k}={v}" for k, v in ev.items())
            lines.append(f"         evidence: {kv}")
    return "\n".join(lines)
