from .histogram import LatencyHistogram
from .stats import StatsRecord
from .tracing import parse_sample_rate

__all__ = ["StatsRecord", "LatencyHistogram", "parse_sample_rate"]
