from .stats import StatsRecord

__all__ = ["StatsRecord"]
