"""Monitoring thread + collection server.

Parity: ``wf/monitoring.hpp:161-295`` — with WF_TRACING_ENABLED the
reference spawns one thread per PipeGraph that connects over raw TCP to the
Java dashboard, sends the graph diagram once, then 1 Hz JSON stat reports.
Here the protocol is newline-delimited JSON over TCP (machine/port from
WF_DASHBOARD_MACHINE / WF_DASHBOARD_PORT like the reference's macros):

    {"type": "diagram", "graph": ..., "dot": ...}
    {"type": "report", "graph": ..., "stats": {...}}    (1 Hz)

``MonitoringServer`` is the in-tree collector (the dashboard-server
analog): it accepts those connections and keeps the latest report per
graph, queryable in-process or dumpable to JSON.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Any, Dict, Optional


class MonitoringThread(threading.Thread):
    """Streams diagram + 1 Hz reports to the dashboard with BOUNDED
    reconnect/backoff: a dashboard absent at startup (or restarted
    mid-run) still gets reports once it comes up — the seed behavior
    (one ``create_connection`` then give up forever) silently lost the
    whole run's telemetry to a startup race."""

    # reconnect backoff: 0.5 s doubling to a 5 s cap; retries continue
    # until the graph stops (each attempt is one cheap connect() probe)
    _BACKOFF_MIN_S = 0.5
    _BACKOFF_MAX_S = 5.0

    def __init__(self, graph, machine: Optional[str] = None,
                 port: Optional[int] = None, period_sec: float = 1.0) -> None:
        super().__init__(name=f"monitor:{graph.name}", daemon=True)
        self.graph = graph
        self.machine = machine or os.environ.get("WF_DASHBOARD_MACHINE",
                                                 "127.0.0.1")
        self.port = int(port or os.environ.get("WF_DASHBOARD_PORT", "20300"))
        self.period = period_sec
        # NB: threading.Thread has a private _stop METHOD; don't shadow it
        self._stop_evt = threading.Event()
        self.connects = 0  # successful connections (observability/tests)

    def stop(self) -> None:
        self._stop_evt.set()

    def _connect(self) -> Optional[socket.socket]:
        try:
            return socket.create_connection((self.machine, self.port),
                                            timeout=2.0)
        except OSError:
            return None

    def run(self) -> None:
        backoff = self._BACKOFF_MIN_S
        while not self._stop_evt.is_set():
            sock = self._connect()
            if sock is None:
                # dashboard absent: back off and retry until stopped
                if self._stop_evt.wait(backoff):
                    return
                backoff = min(backoff * 2, self._BACKOFF_MAX_S)
                continue
            backoff = self._BACKOFF_MIN_S
            self.connects += 1
            try:
                f = sock.makefile("w")
                # (re)send the diagram on every connection: a freshly
                # started dashboard has no prior state
                f.write(json.dumps({"type": "diagram",
                                    "graph": self.graph.name,
                                    "dot": self.graph.to_dot(),
                                    "svg": self.graph.to_svg()}) + "\n")
                f.flush()
                while not self._stop_evt.wait(self.period):
                    f.write(json.dumps(
                        {"type": "report", "graph": self.graph.name,
                         "stats": self.graph.get_stats()}) + "\n")
                    f.flush()
                f.write(json.dumps({"type": "report",
                                    "graph": self.graph.name, "final": True,
                                    "stats": self.graph.get_stats()}) + "\n")
                f.flush()
                return  # clean final report delivered
            except OSError:
                pass  # connection lost mid-run: reconnect loop resumes
            finally:
                try:
                    sock.close()
                except OSError:
                    pass


def _prom_escape(v: str) -> str:
    """Prometheus label-value escaping (backslash, quote, newline)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


# (family, TYPE, HELP, stats-dict field, scale) — scalar per-replica series
_PROM_SCALARS = (
    ("windflow_inputs_received_total", "counter",
     "Tuples received by the replica", "Inputs_received", 1),
    ("windflow_outputs_sent_total", "counter",
     "Tuples sent downstream", "Outputs_sent", 1),
    ("windflow_inputs_ignored_total", "counter",
     "Tuples dropped/filtered by the replica", "Inputs_ignored", 1),
    ("windflow_punctuations_received_total", "counter",
     "Watermark punctuations received", "Punctuations_received", 1),
    ("windflow_throughput_tuples_per_second", "gauge",
     "Replica input throughput since start", "Throughput_tuples_sec", 1),
    ("windflow_service_time_ewma_usec", "gauge",
     "EWMA per-tuple service time (microseconds)", "Service_time_usec", 1),
    ("windflow_device_programs_run_total", "counter",
     "XLA programs dispatched by the replica", "Device_programs_run", 1),
    ("windflow_device_bytes_h2d_total", "counter",
     "Bytes staged host-to-device", "Device_bytes_H2D", 1),
    ("windflow_device_bytes_d2h_total", "counter",
     "Bytes fetched device-to-host", "Device_bytes_D2H", 1),
    ("windflow_dispatch_batches_total", "counter",
     "Batches through the device-ahead dispatch pipeline",
     "Dispatch_batches", 1),
    ("windflow_dispatch_stalls_total", "counter",
     "Forced ordering-point drains with commits in flight",
     "Dispatch_readback_stalls", 1),
    ("windflow_megabatch_loops_total", "counter",
     "Megabatch scan loops dispatched (K batches per loop)",
     "Megabatch_loops", 1),
    ("windflow_megabatch_batches_per_loop_avg", "gauge",
     "Mean batches retired per megabatch scan loop",
     "Megabatch_batches_per_loop_avg", 1),
    ("windflow_megabatch_max", "gauge",
     "Widest megabatch group committed by one scan dispatch",
     "Megabatch_max", 1),
    ("windflow_programs_per_batch", "gauge",
     "Device programs dispatched per prepped batch (1.0 = fused "
     "baseline, < 1.0 = megabatch amortization)",
     "Programs_per_batch", 1),
    ("windflow_ingest_blocks_total", "counter",
     "Column blocks shipped through the columnar ingest fast path",
     "Ingest_blocks", 1),
    ("windflow_ingest_rows_per_block_avg", "gauge",
     "Mean rows per ingested column block",
     "Ingest_rows_per_block_avg", 1),
    ("windflow_ingest_block_ns_per_row", "gauge",
     "Host ingest cost per row on the columnar path (nanoseconds)",
     "Ingest_block_ns_per_row", 1),
    ("windflow_queue_occupancy", "gauge",
     "Input channel occupancy (messages)", "Queue_len", 1),
    ("windflow_queue_capacity", "gauge",
     "Input channel capacity (messages)", "Queue_capacity", 1),
    ("windflow_queue_depth_max", "gauge",
     "Input channel occupancy high-water mark", "Queue_depth_max", 1),
    ("windflow_queue_blocked_put_seconds_total", "counter",
     "Producer time blocked on this full input channel (backpressure)",
     "Queue_blocked_put_usec", 1e-6),
    ("windflow_queue_blocked_get_seconds_total", "counter",
     "Consumer time blocked on this empty input channel (starvation)",
     "Queue_blocked_get_usec", 1e-6),
    ("windflow_emit_fifo_depth_max", "gauge",
     "Emitter-side pipelined FIFO high-water mark",
     "Queue_emit_fifo_depth_max", 1),
    ("windflow_worker_idle_ticks_total", "counter",
     "Worker idle-drain ticks", "Worker_idle_ticks", 1),
    ("windflow_checkpoint_snapshots_total", "counter",
     "Aligned checkpoint snapshots taken by the replica's worker",
     "Checkpoint_snapshots", 1),
    ("windflow_checkpoint_bytes_total", "counter",
     "Checkpoint blob bytes written by the replica's worker",
     "Checkpoint_bytes_total", 1),
    ("windflow_checkpoint_snapshot_seconds_total", "counter",
     "Time spent capturing checkpoint snapshots",
     "Checkpoint_snapshot_usec_total", 1e-6),
    ("windflow_checkpoint_align_stall_seconds_total", "counter",
     "Time multi-input workers stalled aligning checkpoint barriers",
     "Checkpoint_align_stall_usec_total", 1e-6),
    ("windflow_checkpoint_cut_pause_seconds", "counter",
     "Time the barrier actually fenced the worker (state cut + ack; "
     "excludes async uploads)", "Checkpoint_cut_pause_usec_total", 1e-6),
    ("windflow_sink_txn_precommits_total", "counter",
     "Exactly-once sink epochs pre-committed at the aligned barrier",
     "Sink_txn_precommits", 1),
    ("windflow_sink_txn_commits_total", "counter",
     "Exactly-once sink epochs committed on coordinator finalize",
     "Sink_txn_commits", 1),
    ("windflow_sink_txn_aborts_total", "counter",
     "Exactly-once sink epochs aborted (restore discard / replayed "
     "duplicate)", "Sink_txn_aborts", 1),
    ("windflow_sink_txn_fenced_writes_total", "counter",
     "Writes refused from stale (zombie) exactly-once sink replicas",
     "Sink_txn_fenced_writes", 1),
    ("windflow_compile_total", "counter",
     "XLA (re)trace+compiles of the replica's device programs",
     "Compile_count", 1),
    ("windflow_compile_cache_hits_total", "counter",
     "Device-program calls served by the jit compile cache",
     "Compile_cache_hits", 1),
    ("windflow_compile_seconds_total", "counter",
     "Time spent tracing+compiling device programs",
     "Compile_usec_total", 1e-6),
    ("windflow_worker_crashes_total", "counter",
     "Worker threads that died on an unhandled exception",
     "Worker_crashes", 1),
    ("windflow_dlq_records_total", "counter",
     "Poison records quarantined to the dead-letter queue "
     "(DEAD_LETTER error policy)", "Dlq_records", 1),
    ("windflow_dlq_skipped_total", "counter",
     "Records dropped by a SKIP error policy", "Dlq_skipped", 1),
    ("windflow_dlq_retries_total", "counter",
     "Record-level retry attempts under a RETRY error policy",
     "Dlq_retries", 1),
    ("windflow_kafka_reconnects_total", "counter",
     "Kafka transient-error retries/reconnects (connect/produce/consume)",
     "Kafka_reconnects", 1),
    ("windflow_shed_records_total", "counter",
     "Records shed by source admission control (overload governor)",
     "Shed_records", 1),
    ("windflow_shed_bytes_total", "counter",
     "Approximate bytes shed by source admission control",
     "Shed_bytes", 1),
    # mesh execution plane (windflow_tpu.mesh): present only on replicas
    # that drive a device mesh (StatsRecord omits Mesh_* elsewhere, so
    # these families carry series only where a mesh exists)
    ("windflow_mesh_devices", "gauge",
     "Devices of the mesh this replica drives (0 series absent = not a "
     "mesh operator)", "Mesh_devices", 1),
    ("windflow_mesh_steps_total", "counter",
     "Sharded shard_map steps dispatched over the mesh", "Mesh_steps", 1),
    ("windflow_mesh_shuffle_bytes_total", "counter",
     "Bytes moved by the in-program all_to_all KEYBY shuffle",
     "Mesh_shuffle_bytes", 1),
    ("windflow_mesh_step_seconds_total", "counter",
     "Host-observed time dispatching sharded mesh steps",
     "Mesh_step_usec_total", 1e-6),
    ("windflow_mesh_shard_occupancy", "gauge",
     "Max key-slot occupancy of any mesh shard (block-owner mapping)",
     "Mesh_shard_occupancy", 1),
    ("windflow_mesh_shard_skew", "gauge",
     "Max/mean shard occupancy (1.0 = even key spread)",
     "Mesh_shard_skew", 1),
    ("windflow_mesh_degraded_devices", "gauge",
     "Devices this mesh replica runs WITHOUT (device-loss failover)",
     "Mesh_degraded_devices", 1),
    # tiered keyed state (windflow_tpu.state): present only on replicas
    # with with_tiering enabled (StatsRecord omits Tier_* elsewhere)
    ("windflow_tier_hot_keys", "gauge",
     "Keys resident in the device (hot) tier of the tiered key store",
     "Tier_hot_keys", 1),
    ("windflow_tier_cold_keys", "gauge",
     "Keys spilled to the host (cold) tier of the tiered key store",
     "Tier_cold_keys", 1),
    ("windflow_tier_promotes_total", "counter",
     "Keys promoted cold -> hot (batched slot-row scatters)",
     "Tier_promotes", 1),
    ("windflow_tier_demotes_total", "counter",
     "Keys demoted hot -> cold (batched slot-row gathers)",
     "Tier_demotes", 1),
    ("windflow_tier_promote_seconds_total", "counter",
     "Host-observed time spent in batched tier promote/demote movement",
     "Tier_promote_usec_total", 1e-6),
    ("windflow_tier_miss_rate", "gauge",
     "Fraction of distinct batch keys absent from the hot tier",
     "Tier_miss_rate", 1),
    # event-time health plane: watermark progress + late-record accounting
    # (uniform across CPU window engines, FFAT TPU/mesh and fused chains;
    # conservation: inputs == on_time + late_admitted + late_dropped)
    ("windflow_watermark_timestamp_usec", "gauge",
     "Current watermark of the replica (event-time microseconds)",
     "Watermark_current_ts", 1),
    ("windflow_watermark_advances_total", "counter",
     "Watermark advances observed by the replica",
     "Watermark_advances", 1),
    ("windflow_watermark_lag_seconds", "gauge",
     "Wall-clock time since the replica's watermark last advanced",
     "Watermark_lag_usec", 1e-6),
    ("windflow_watermark_event_lag_seconds", "gauge",
     "Event-time gap between the max source timestamp seen and the "
     "current watermark (event-time source paths only)",
     "Watermark_event_lag_usec", 1e-6),
    ("windflow_watermark_idle", "gauge",
     "1 when no inputs arrived since the watermark last advanced "
     "(idle, not stalled)", "Watermark_idle", 1),
    ("windflow_watermark_stalls_total", "counter",
     "Watermark stall episodes: frozen past WF_WM_STALL_SEC while "
     "inputs kept arriving", "Watermark_stalls", 1),
    ("windflow_late_records_total", "counter",
     "Tuples observed behind the watermark/fired-window frontier",
     "Late_records", 1),
    ("windflow_late_dropped_total", "counter",
     "Late tuples discarded (behind the allowed-lateness frontier)",
     "Late_dropped", 1),
    ("windflow_late_admitted_total", "counter",
     "Late tuples still admitted into window state (within lateness)",
     "Late_admitted", 1),
)

# per-operator merged histograms: (family, HELP, stats hist field)
_PROM_HISTS = (
    ("windflow_service_latency_usec", "Sampled per-tuple service time",
     "Latency_service_hist"),
    ("windflow_dispatch_prep_latency_usec",
     "Host-prep stage latency per device batch", "Latency_prep_hist"),
    ("windflow_dispatch_commit_latency_usec",
     "Device-commit stage latency per device batch", "Latency_commit_hist"),
    ("windflow_e2e_latency_usec",
     "Sampled end-to-end tuple latency recorded at sinks",
     "Latency_e2e_hist"),
    ("windflow_lateness_usec",
     "Observed lateness (watermark - ts) of late tuples",
     "Latency_lateness_hist"),
)


def prometheus_text(snapshot: Dict[str, Any]) -> str:
    """Render the latest reports as Prometheus text exposition format
    (version 0.0.4). Scalars are per-replica series; latency histograms
    are merged per operator (the replica histograms are mergeable by
    construction — monitoring/histogram.py)."""
    from .histogram import LatencyHistogram

    reports = snapshot.get("reports", {})
    lines = []
    # scalar families
    for fam, typ, help_, field, scale in _PROM_SCALARS:
        body = []
        for graph, st in reports.items():
            if not isinstance(st, dict):
                continue
            g = _prom_escape(graph)
            for op in st.get("Operators", []) or []:
                o = _prom_escape(op.get("name", "?"))
                for rep in op.get("replicas", []) or []:
                    v = rep.get(field)
                    if not isinstance(v, (int, float)):
                        continue
                    body.append(
                        f'{fam}{{graph="{g}",operator="{o}",'
                        f'replica="{int(rep.get("Replica_id", 0))}"}} '
                        f'{v * scale:g}')
        if body:
            lines.append(f"# HELP {fam} {help_}")
            lines.append(f"# TYPE {fam} {typ}")
            lines.extend(body)
    # graph-level counters
    drop_body = []
    for graph, st in reports.items():
        if isinstance(st, dict) and isinstance(st.get("Dropped_tuples"),
                                               (int, float)):
            drop_body.append(
                f'windflow_dropped_tuples_total'
                f'{{graph="{_prom_escape(graph)}"}} '
                f'{st["Dropped_tuples"]:g}')
    if drop_body:
        lines.append("# HELP windflow_dropped_tuples_total Tuples dropped "
                     "by reordering collectors")
        lines.append("# TYPE windflow_dropped_tuples_total counter")
        lines.extend(drop_body)
    ckpt_body = []
    for graph, st in reports.items():
        ck = st.get("Checkpoints") if isinstance(st, dict) else None
        if isinstance(ck, dict) and isinstance(
                ck.get("Checkpoints_completed"), (int, float)):
            ckpt_body.append(
                f'windflow_checkpoints_completed_total'
                f'{{graph="{_prom_escape(graph)}"}} '
                f'{ck["Checkpoints_completed"]:g}')
    if ckpt_body:
        lines.append("# HELP windflow_checkpoints_completed_total Aligned "
                     "checkpoints committed by the coordinator")
        lines.append("# TYPE windflow_checkpoints_completed_total counter")
        lines.extend(ckpt_body)
    # checkpoint integrity + storage hardening (durable-recovery plane)
    _CKPT_FAMS = (
        ("windflow_ckpt_verify_failures_total", "counter",
         "Checkpoint blobs that failed sha256 verification on restore",
         "Checkpoint_verify_failures", 1),
        ("windflow_ckpt_failures_total", "counter",
         "Checkpoint epochs failed (timeout or storage write error)",
         "Checkpoint_failures", 1),
        ("windflow_ckpt_storage_failures_total", "counter",
         "Checkpoint epochs aborted by an OSError while staging blobs",
         "Checkpoint_storage_failures", 1),
        # incremental + async checkpointing (WF_CKPT_DELTA / WF_CKPT_ASYNC)
        ("windflow_checkpoint_delta_bytes_total", "counter",
         "Physical bytes of delta-form checkpoint blobs (dirty rows + "
         "WAL; unchanged ref'd shards cost zero)",
         "Checkpoint_delta_bytes", 1),
        ("windflow_checkpoint_async_uploads_total", "counter",
         "Background snapshot uploads completed by the coordinator's "
         "uploader", "Checkpoint_async_uploads", 1),
        ("windflow_checkpoint_async_pending", "gauge",
         "Async snapshot uploads currently in flight",
         "Checkpoint_async_pending", 1),
    )
    for fam, typ, help_, field, scale in _CKPT_FAMS:
        body = []
        for graph, st in reports.items():
            if not isinstance(st, dict):
                continue
            v = (st.get("Checkpoints") or {}).get(field)
            if isinstance(v, (int, float)):
                body.append(f'{fam}{{graph="{_prom_escape(graph)}"}} '
                            f'{v * scale:g}')
        if body:
            lines.append(f"# HELP {fam} {help_}")
            lines.append(f"# TYPE {fam} {typ}")
            lines.extend(body)
    # elastic rescaling (windflow_tpu.scaling): per-operator parallelism
    # gauge + per-graph rescale counters/timings so a scaling event is a
    # first-class Prometheus signal
    par_body = []
    for graph, st in reports.items():
        if not isinstance(st, dict):
            continue
        g = _prom_escape(graph)
        for op in st.get("Operators", []) or []:
            if op.get("retired"):
                continue  # mark-final replicas end series; no fresh gauge
            if isinstance(op.get("parallelism"), (int, float)):
                par_body.append(
                    f'windflow_operator_parallelism{{graph="{g}",'
                    f'operator="{_prom_escape(op.get("name", "?"))}"}} '
                    f'{op["parallelism"]:g}')
    if par_body:
        lines.append("# HELP windflow_operator_parallelism Current replica "
                     "count per operator (changes on rescale)")
        lines.append("# TYPE windflow_operator_parallelism gauge")
        lines.extend(par_body)
    _RESCALE_FAMS = (
        ("windflow_rescale_total", "counter",
         "Live rescales completed", "Rescale_events", 1),
        ("windflow_rescale_failures_total", "counter",
         "Rescale attempts that aborted", "Rescale_failures", 1),
        ("windflow_rescale_last_pause_seconds", "gauge",
         "Stop-the-world pause of the last rescale (quiesce->resume)",
         "Rescale_last_pause_s", 1),
        ("windflow_rescale_last_total_seconds", "gauge",
         "Trigger->resume duration of the last rescale",
         "Rescale_last_total_s", 1),
        ("windflow_autoscaler_decisions_total", "counter",
         "Autoscaler decisions acted on", "Autoscaler_decisions", 1),
    )
    for fam, typ, help_, field, scale in _RESCALE_FAMS:
        body = []
        for graph, st in reports.items():
            if not isinstance(st, dict):
                continue
            block = st.get("Rescales") if field.startswith("Rescale") \
                else st.get("Autoscaler")
            v = (block or {}).get(field)
            if isinstance(v, (int, float)):
                body.append(f'{fam}{{graph="{_prom_escape(graph)}"}} '
                            f'{v * scale:g}')
        if body:
            lines.append(f"# HELP {fam} {help_}")
            lines.append(f"# TYPE {fam} {typ}")
            lines.extend(body)
    # self-healing supervision (windflow_tpu.supervision): restart count
    # + last-event MTTR per graph, so availability is a first-class
    # Prometheus signal (alert on rate(restart_total) and on
    # restart_last_seconds spikes)
    _SUPERVISE_FAMS = (
        ("windflow_restart_total", "counter",
         "Supervised automatic restarts of the whole graph",
         "Supervision_restarts", 1),
        ("windflow_restart_last_seconds", "gauge",
         "Detect->resume duration (MTTR) of the last supervised restart",
         "Supervision_last_restart_s", 1),
        ("windflow_restart_seconds_total", "counter",
         "Cumulative detect->resume time across supervised restarts",
         "Supervision_restart_total_s", 1),
        # durable-recovery plane: fallback-ladder + device-loss signals
        ("windflow_recovery_ladder_depth", "gauge",
         "Checkpoint rungs skipped by the last supervised restore "
         "(0 = latest restored cleanly)", "Recovery_ladder_depth", 1),
        ("windflow_recovery_verify_failures_total", "counter",
         "Corrupt/unusable checkpoint rungs walked past by the "
         "fallback-ladder restore", "Recovery_verify_failures", 1),
        ("windflow_recovery_degraded_devices", "gauge",
         "Mesh devices currently excluded by the device-health probe "
         "(degraded capacity; 0 = full shape)",
         "Recovery_degraded_devices", 1),
        ("windflow_recovery_planned_restarts_total", "counter",
         "Planned supervised restarts (mesh re-expansion after a device "
         "returned)", "Supervision_planned_restarts", 1),
    )
    for fam, typ, help_, field, scale in _SUPERVISE_FAMS:
        body = []
        for graph, st in reports.items():
            if not isinstance(st, dict):
                continue
            v = (st.get("Supervision") or {}).get(field)
            if isinstance(v, (int, float)):
                body.append(f'{fam}{{graph="{_prom_escape(graph)}"}} '
                            f'{v * scale:g}')
        if body:
            lines.append(f"# HELP {fam} {help_}")
            lines.append(f"# TYPE {fam} {typ}")
            lines.extend(body)
    # overload-protection plane (windflow_tpu.overload): governor state
    # (0=idle 1=tune 2=scale 3=shed — alert on state==3 sustained),
    # escalation counters and the admitted-vs-offered rates that define
    # the shed fraction during an overload
    _OVERLOAD_FAMS = (
        ("windflow_overload_state", "gauge",
         "Overload-governor escalation rung (0=idle 1=tune 2=scale "
         "3=shed)", "Overload_state", 1),
        ("windflow_overload_escalations_total", "counter",
         "Overload-governor ladder escalations", "Overload_escalations", 1),
        ("windflow_overload_releases_total", "counter",
         "Overload-governor recovery releases (one rung down)",
         "Overload_releases", 1),
        ("windflow_overload_window_p99_seconds", "gauge",
         "Windowed sink-side e2e p99 the governor acted on last",
         "Overload_window_p99_usec", 1e-6),
        ("windflow_overload_slo_p99_seconds", "gauge",
         "Declared end-to-end p99 SLO", "Overload_slo_p99_usec", 1e-6),
        ("windflow_overload_admit_rate_tuples_per_second", "gauge",
         "Token-bucket admit rate while shedding (0 = not shedding)",
         "Overload_admit_rate_tps", 1),
        ("windflow_overload_offered_tuples_per_second", "gauge",
         "Offered rate at the sources (admitted + shed) last window",
         "Overload_offered_tps", 1),
        ("windflow_overload_shed_tuples_per_second", "gauge",
         "Shed rate last window", "Overload_shed_tps", 1),
    )
    for fam, typ, help_, field, scale in _OVERLOAD_FAMS:
        body = []
        for graph, st in reports.items():
            if not isinstance(st, dict):
                continue
            v = (st.get("Overload") or {}).get(field)
            if isinstance(v, (int, float)):
                body.append(f'{fam}{{graph="{_prom_escape(graph)}"}} '
                            f'{v * scale:g}')
        if body:
            lines.append(f"# HELP {fam} {help_}")
            lines.append(f"# TYPE {fam} {typ}")
            lines.extend(body)
    # pipeline doctor (monitoring/doctor.py): bottleneck attribution over
    # tick-over-tick deltas — findings count + per-finding scores + an
    # info-style bottleneck series (verdict rides in a label; alert on
    # windflow_doctor_healthy == 0 sustained)
    doctor = snapshot.get("doctor") or {}
    dr_healthy, dr_findings, dr_scores, dr_info = [], [], [], []
    for graph, diag in doctor.items():
        if not isinstance(diag, dict):
            continue
        g = _prom_escape(graph)
        dr_healthy.append(f'windflow_doctor_healthy{{graph="{g}"}} '
                          f'{1 if diag.get("healthy") else 0}')
        finds = diag.get("findings") or []
        dr_findings.append(f'windflow_doctor_findings{{graph="{g}"}} '
                           f'{len(finds)}')
        for fnd in finds:
            o = _prom_escape(fnd.get("operator", "?"))
            v = _prom_escape(fnd.get("verdict", "?"))
            dr_scores.append(
                f'windflow_doctor_verdict_score{{graph="{g}",'
                f'operator="{o}",verdict="{v}"}} '
                f'{float(fnd.get("score", 0)):g}')
        top = diag.get("bottleneck")
        if isinstance(top, dict):
            dr_info.append(
                f'windflow_doctor_bottleneck_info{{graph="{g}",'
                f'operator="{_prom_escape(top.get("operator", "?"))}",'
                f'verdict="{_prom_escape(top.get("verdict", "?"))}"}} 1')
    for fam, typ, help_, body in (
            ("windflow_doctor_healthy", "gauge",
             "1 when the pipeline doctor found no bottleneck this tick",
             dr_healthy),
            ("windflow_doctor_findings", "gauge",
             "Doctor findings emitted for the last tick", dr_findings),
            ("windflow_doctor_verdict_score", "gauge",
             "Severity score of each doctor finding (per operator and "
             "verdict)", dr_scores),
            ("windflow_doctor_bottleneck_info", "gauge",
             "Top-ranked doctor finding (operator + verdict in labels)",
             dr_info)):
        if body:
            lines.append(f"# HELP {fam} {help_}")
            lines.append(f"# TYPE {fam} {typ}")
            lines.extend(body)
    # compile attribution: the LAST retrace-triggering abstract signature
    # per replica as an info-style series (the string rides in a label;
    # the retrace-storm query is rate(windflow_compile_total) paired with
    # a churning signature label here)
    sig_body = []
    for graph, st in reports.items():
        if not isinstance(st, dict):
            continue
        g = _prom_escape(graph)
        for op in st.get("Operators", []) or []:
            o = _prom_escape(op.get("name", "?"))
            for rep in op.get("replicas", []) or []:
                sig = rep.get("Compile_last_signature")
                if not sig:
                    continue
                sig_body.append(
                    f'windflow_compile_last_signature_info{{graph="{g}",'
                    f'operator="{o}",'
                    f'replica="{int(rep.get("Replica_id", 0))}",'
                    f'signature="{_prom_escape(sig)}"}} 1')
    if sig_body:
        lines.append("# HELP windflow_compile_last_signature_info Abstract "
                     "signature that triggered the replica's last XLA "
                     "retrace")
        lines.append("# TYPE windflow_compile_last_signature_info gauge")
        lines.extend(sig_body)
    # merged per-operator histograms
    for fam, help_, field in _PROM_HISTS:
        body = []
        for graph, st in reports.items():
            if not isinstance(st, dict):
                continue
            g = _prom_escape(graph)
            for op in st.get("Operators", []) or []:
                parts = [LatencyHistogram.from_sparse(rep.get(field))
                         for rep in op.get("replicas", []) or []
                         if isinstance(rep, dict) and rep.get(field)]
                if not parts:
                    continue
                h = LatencyHistogram.merged(parts)
                if h.count == 0:
                    continue
                o = _prom_escape(op.get("name", "?"))
                base = f'graph="{g}",operator="{o}"'
                for le, cum in h.cumulative_buckets():
                    if le == float("inf"):
                        continue
                    body.append(f'{fam}_bucket{{{base},le="{le:g}"}} {cum}')
                body.append(f'{fam}_bucket{{{base},le="+Inf"}} {h.count}')
                body.append(f'{fam}_sum{{{base}}} {h.sum_us:g}')
                body.append(f'{fam}_count{{{base}}} {h.count}')
        if body:
            lines.append(f"# HELP {fam} {help_} (microseconds)")
            lines.append(f"# TYPE {fam} histogram")
            lines.extend(body)
    lines.append(f"# HELP windflow_reports_total Monitoring reports "
                 f"received by this server")
    lines.append("# TYPE windflow_reports_total counter")
    lines.append(f'windflow_reports_total {snapshot.get("n_reports", 0)}')
    return "\n".join(lines) + "\n"


def _safe_diagram(svg, dot: str) -> str:
    """Diagram data arrives over an unauthenticated TCP port, so it is
    untrusted: embed the SVG only when it provably carries no active
    content, otherwise fall back to the HTML-escaped dot source. The
    checks are deliberately over-broad (reject-by-default): legitimate
    diagrams come from our own renderer or Graphviz, which emit none of
    the rejected constructs — entity references, scripts, event handlers
    (any delimiter: space, /, quote), foreignObject, or URI schemes."""
    import html as _html
    import re

    if svg:
        low = svg.lower()
        if (low.lstrip().startswith("<svg")
                and "<script" not in low
                and "&#" not in low              # numeric entities (the
                # built-in renderer escapes only &<> — see stages_to_svg)
                and "&colon" not in low
                and "<foreignobject" not in low
                and not re.search(r"""[\s/"'=]on\w+\s*=""", low)
                and not re.search(r"""(javascript|data|vbscript)\s*:""",
                                  low)):
            return svg
    return f"<pre>{_html.escape(dot)}</pre>"


class MonitoringServer:
    """Accepts monitoring connections; keeps the latest diagram/report per
    graph (the dashboard-server analog, ``dashboard/Server`` in the
    reference)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(16)
        self.host, self.port = self._srv.getsockname()
        self.diagrams: Dict[str, str] = {}
        self.svgs: Dict[str, str] = {}  # rendered dataflow SVG per graph
        self.reports: Dict[str, Any] = {}
        self.n_reports = 0
        # pipeline doctor: reports arrive ~1 Hz per graph; diagnosing on
        # arrival (vs on query) gives every scrape a consistent tick delta
        from .doctor import PipelineDoctor
        self._doctor = PipelineDoctor()
        self.diagnoses: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._srv.settimeout(0.2)
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            f = conn.makefile("r")
            for line in f:
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    continue
                with self._lock:
                    if msg.get("type") == "diagram":
                        self.diagrams[msg["graph"]] = msg["dot"]
                        if msg.get("svg"):
                            self.svgs[msg["graph"]] = msg["svg"]
                    elif msg.get("type") == "report":
                        self.reports[msg["graph"]] = msg["stats"]
                        self.n_reports += 1
                        try:
                            diag = self._doctor.observe(msg["graph"],
                                                        msg["stats"])
                            if diag is not None:
                                self.diagnoses[msg["graph"]] = diag
                        except Exception:
                            pass  # a malformed report must not kill intake
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"diagrams": dict(self.diagrams),
                    "svgs": dict(self.svgs),
                    "reports": dict(self.reports),
                    "doctor": dict(self.diagnoses),
                    "n_reports": self.n_reports}

    # -- web view (the reference ships a Spring+React dashboard; this is
    # the minimal in-tree equivalent: JSON API + a static HTML view) ------
    def serve_http(self, port: int = 0) -> int:
        """Start the HTTP dashboard; returns the bound port.
        GET /        -> interactive client (polls /json, live tables,
                        throughput sparkline, SVG diagram, replica
                        drill-down — the reference's React app equivalent)
        GET /json    -> full snapshot (sanitized SVGs)
        GET /graph/<name> -> one graph's latest stats
        GET /metrics -> Prometheus text exposition (counters, queue
                        gauges, per-operator latency histograms); 503
                        until the first graph report arrives
        GET /doctor  -> pipeline-doctor diagnosis per graph (ranked
                        bottleneck verdicts over the last report tick);
                        503 until two reports give a delta
        GET /trace?ms=N -> capture N ms of flight-recorder events from
                        every in-process graph, returned as Chrome
                        trace-event JSON (requires the recorder enabled
                        and the graph running in THIS process)
        GET /plain   -> server-rendered static view (no JS)"""
        import http.server

        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code, body, ctype="application/json"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                import html as _html

                esc = _html.escape
                snap = server.snapshot()
                # untrusted diagram data is sanitized for every HTML/JSON
                # consumer (the client injects the svg via innerHTML);
                # a rejected svg falls back to the escaped dot source
                snap["svgs"] = {g: _safe_diagram(s, snap["diagrams"]
                                                 .get(g, ""))
                                for g, s in snap["svgs"].items()}
                if self.path == "/":
                    from .webclient import CLIENT_HTML
                    self._send(200, CLIENT_HTML, "text/html")
                elif self.path == "/metrics":
                    if not snap["reports"]:
                        # a scraper that lands before the first report
                        # must see "not ready", not an empty-but-200
                        # exposition it would record as all-zero series
                        self._send(503, "no monitoring reports received "
                                   "yet: graph not running, or "
                                   "WF_TRACING_ENABLED unset\n",
                                   "text/plain; charset=utf-8")
                    else:
                        self._send(200, prometheus_text(snap),
                                   "text/plain; version=0.0.4; "
                                   "charset=utf-8")
                elif self.path == "/doctor":
                    if not snap.get("doctor"):
                        # one report gives no delta to diagnose; mirror
                        # the /metrics not-ready contract
                        self._send(503, json.dumps(
                            {"error": "no diagnosis yet: need two "
                             "monitoring reports for a tick delta"}))
                    else:
                        self._send(200, json.dumps(snap["doctor"]))
                elif self.path.startswith("/trace"):
                    from urllib.parse import parse_qs, urlparse
                    from .flightrec import capture_trace
                    q = parse_qs(urlparse(self.path).query)
                    try:
                        ms = float(q.get("ms", ["100"])[0])
                    except ValueError:
                        self._send(400, json.dumps(
                            {"error": "ms must be a number"}))
                        return
                    # blocks THIS handler thread for the capture window
                    # (ThreadingHTTPServer: other endpoints stay live)
                    self._send(200, json.dumps(capture_trace(ms)))
                elif self.path == "/json":
                    self._send(200, json.dumps(snap))
                elif self.path.startswith("/graph/"):
                    name = self.path[len("/graph/"):]
                    st = snap["reports"].get(name)
                    if st is None:
                        self._send(404, json.dumps({"error": "unknown graph"}))
                    else:
                        self._send(200, json.dumps(st))
                else:  # /plain: server-rendered fallback view
                    rows = []
                    for g, st in snap["reports"].items():
                        ops = []
                        for o in st.get("Operators", []):
                            reps = o["replicas"]
                            tin = sum(r["Inputs_received"] for r in reps)
                            tout = sum(r["Outputs_sent"] for r in reps)
                            tput = sum(r.get("Throughput_tuples_sec", 0)
                                       for r in reps)
                            svc = max((r.get("Service_time_usec", 0)
                                       for r in reps), default=0)
                            dev = sum(r.get("Device_programs_run", 0)
                                      for r in reps)
                            ign = sum(r.get("Inputs_ignored", 0)
                                      for r in reps)
                            # report fields arrive over the untrusted
                            # monitoring port: escape before interpolation
                            ops.append(
                                f"<tr><td>{esc(str(o['name']))}</td>"
                                f"<td>{esc(str(o['kind']))}</td>"
                                f"<td>{int(o['parallelism'])}</td>"
                                f"<td>{tin}</td><td>{tout}</td><td>{ign}</td>"
                                f"<td>{tput:,.0f}</td><td>{svc:.1f}</td>"
                                f"<td>{dev}</td></tr>")
                        rows.append(
                            f"<h2>{esc(str(g))} <small>"
                            f"[{esc(str(st.get('Mode')))}] threads="
                            f"{int(st.get('Threads') or 0)} dropped="
                            f"{int(st.get('Dropped_tuples') or 0)}"
                            f"</small></h2>"
                            f"<table border=1 cellpadding=4 "
                            f"style='border-collapse:collapse'>"
                            f"<tr><th>op</th><th>kind</th><th>par</th>"
                            f"<th>in</th><th>out</th><th>ignored</th>"
                            f"<th>tuples/s</th><th>svc µs</th>"
                            f"<th>device progs</th></tr>"
                            + "".join(ops) + "</table>"
                            f"<details open><summary>dataflow graph</summary>"
                            + _safe_diagram(snap["svgs"].get(g),
                                            snap["diagrams"].get(g, ""))
                            + "</details>")
                    self._send(200,
                               "<html><head><meta http-equiv='refresh' "
                               "content='2'><title>windflow_tpu</title>"
                               "</head><body style='font-family:monospace'>"
                               "<h1>windflow_tpu dashboard</h1>"
                               + "".join(rows) + "</body></html>",
                               "text/html")

        httpd = http.server.ThreadingHTTPServer((self.host, port), Handler)
        self._httpd = httpd
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        return httpd.server_address[1]

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        httpd = getattr(self, "_httpd", None)
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()  # release the bound listening socket
