"""Admission control at the source boundary: token bucket + shed policy.

The gate sits inside ``SourceReplica.ship``/``ship_columns`` — BEFORE the
tuple is stamped into the emitter, before any checkpoint barrier and
before the exactly-once plane ever sees it. A shed record therefore
never enters a channel, a snapshot or a sink transaction: delivery
guarantees hold byte-identically over the ADMITTED record set, and every
shed is accounted (``Shed_records``/``Shed_bytes`` on the source
replica's stats, plus the optional ``WF_SHED_DIR`` JSONL audit log).

Policies (``WF_SHED_POLICY`` / ``GovernorPolicy(shed_policy=...)``):

- ``drop_newest``     — no tokens => the INCOMING record sheds (no
  reordering, zero buffering; the classic tail-drop);
- ``drop_oldest``     — a small admission buffer absorbs bursts; on
  overflow the OLDEST buffered record sheds (freshness-biased — right
  for feeds where stale data is worthless);
- ``probabilistic``   — every record admits with probability
  ``admit_rate / offered_rate`` (EWMA-estimated), spreading the shed
  uniformly over time instead of in bursts;
- ``key_priority``    — like drop_oldest, but overflow evicts the
  LOWEST-priority buffered record (``with_priority(fn)`` on the source
  builder), so Zipf-head keys survive a shed.

The gate is installed/removed by the ``OverloadGovernor`` at runtime;
sources pay one ``is None`` check per push while it is absent (the
``microbench.py --overload`` idle gate).
"""

from __future__ import annotations

import random
import sys
import time
from collections import deque
from typing import Any, Callable, List, Optional, Tuple

from ..basic import WindFlowError
from ..supervision.errors import DeadLetterQueue, _safe_repr

SHED_POLICIES = ("drop_newest", "drop_oldest", "probabilistic",
                 "key_priority")


def parse_shed_policy(spec: str) -> str:
    """Env-knob form (``WF_SHED_POLICY``); unknown values refuse loudly —
    a typo silently falling back to tail-drop would shed the wrong
    records."""
    s = (spec or "").strip().lower()
    if s not in SHED_POLICIES:
        raise WindFlowError(
            f"unknown shed policy {spec!r} (choose from {SHED_POLICIES})")
    return s


class TokenBucket:
    """Classic token bucket over ``time.monotonic``: ``rate`` tokens/s
    refill up to ``burst``. Single-threaded per gate (the source
    replica's own thread takes; the governor's rate updates are a plain
    float store)."""

    __slots__ = ("rate", "burst", "_tokens", "_t_last")

    def __init__(self, rate: float, burst: Optional[float] = None) -> None:
        self.rate = max(0.0, float(rate))
        self.burst = float(burst) if burst is not None \
            else max(1.0, self.rate * 0.05)  # ~50 ms of slack by default
        self._tokens = self.burst
        self._t_last = time.monotonic()

    def set_rate(self, rate: float, burst: Optional[float] = None) -> None:
        self.rate = max(0.0, float(rate))
        if burst is not None:
            self.burst = float(burst)
        elif self.rate > 0:
            self.burst = max(1.0, self.rate * 0.05)

    def _refill(self) -> None:
        now = time.monotonic()
        dt = now - self._t_last
        if dt > 0:
            self._t_last = now
            self._tokens = min(self.burst, self._tokens + dt * self.rate)

    def try_take(self, n: float = 1.0) -> bool:
        self._refill()
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    def take_up_to(self, n: int) -> int:
        """Grant as many of ``n`` whole tokens as are available (the
        columnar-push path: admit a prefix of the batch)."""
        self._refill()
        grant = min(int(n), int(self._tokens))
        if grant > 0:
            self._tokens -= grant
        return grant

    @property
    def tokens(self) -> float:
        self._refill()
        return self._tokens


class ShedLog(DeadLetterQueue):
    """The shed audit log: same bounded-ring + JSONL-stream machinery as
    the dead-letter queue (one ``<graph>.shed.jsonl`` file under
    ``WF_SHED_DIR``), with a shed-record schema — what was dropped,
    where, why — so a downstream job can re-drive or bill shed traffic.

    Record schema::

        {"operator": str, "replica": int, "payload": repr, "ts": int,
         "reason": "drop_newest"|..., "wall_time": float}
    """

    _suffix = ".shed.jsonl"
    _env_dir = "WF_SHED_DIR"

    def shed(self, operator: str, replica: int, payload: Any, ts: int,
             reason: str) -> None:
        self.put_raw({
            "operator": operator,
            "replica": int(replica),
            "payload": _safe_repr(payload),
            "ts": int(ts),
            "reason": reason,
            "wall_time": time.time(),
        })


def _approx_bytes(payload: Any) -> int:
    """Cheap shed-volume estimate (``Shed_bytes`` is a capacity-planning
    signal, not an exact wire size)."""
    try:
        return sys.getsizeof(payload)
    except TypeError:  # pragma: no cover - exotic payloads
        return 64


class AdmissionGate:
    """Per-source-replica admission controller (see module doc).

    ``offer(payload, ts, wm)`` returns ``(payload, ts, wm)`` triples to
    emit NOW (possibly buffered predecessors, possibly empty); shed
    records are accounted on the replica's stats and streamed to the
    shed log before the call returns. The gate never blocks and never
    reorders admitted records (priority only decides what gets
    EVICTED). The watermark rides each record: a buffered record must
    emit with the watermark current when it was ACCEPTED — emitting it
    under whatever the stream advanced to while it waited would land it
    past downstream window closures the gate never chose to shed it
    into."""

    def __init__(self, replica, policy: str, rate_tps: float,
                 priority_fn: Optional[Callable[[Any], Any]] = None,
                 shed_log: Optional[ShedLog] = None,
                 buffer_cap: int = 64, seed: int = 0x5eed) -> None:
        self.replica = replica
        self.policy = parse_shed_policy(policy)
        if self.policy == "key_priority" and priority_fn is None:
            raise WindFlowError(
                "key_priority shedding needs with_priority(fn) on the "
                "source builder (records have no priority otherwise)")
        self.bucket = TokenBucket(rate_tps)
        self.priority_fn = priority_fn
        self.shed_log = shed_log
        self.buffer_cap = max(1, int(buffer_cap))
        self._pending: deque = deque()  # (payload, ts, wm) awaiting tokens
        # recovery: the governor flips ``released`` (pass-through mode —
        # everything admits, buffered records first) and the SOURCE
        # thread clears its own ``_gate`` reference on the next push;
        # the governor never emits on a foreign thread
        self.released = False
        self._rng = random.Random(seed)
        # offered-rate EWMA for the probabilistic policy (records/s,
        # updated per offer from inter-arrival gaps)
        self._offered_ewma = 0.0
        self._t_prev = time.monotonic()

    # -- accounting --------------------------------------------------------
    def _account(self, payload: Any, ts: int, reason: str) -> None:
        st = self.replica.stats
        st.note_shed(1, _approx_bytes(payload))
        if self.shed_log is not None:
            self.shed_log.shed(self.replica.op.name, self.replica.idx,
                               payload, ts, reason)

    # -- row path ----------------------------------------------------------
    def offer(self, payload: Any, ts: int, wm: int = 0
              ) -> List[Tuple[Any, int, int]]:
        if self.released:  # pass-through: buffered first, then incoming
            out = self.drain_pending()
            out.append((payload, ts, wm))
            return out
        pol = self.policy
        if pol == "probabilistic":
            now = time.monotonic()
            gap = now - self._t_prev
            self._t_prev = now
            inst = 1.0 / gap if gap > 1e-6 else 1e6
            self._offered_ewma += 0.05 * (inst - self._offered_ewma)
            p_admit = 1.0 if self._offered_ewma <= 0 else min(
                1.0, self.bucket.rate / self._offered_ewma)
            if self._rng.random() < p_admit:
                return [(payload, ts, wm)]
            self._account(payload, ts, "probabilistic")
            return []
        if pol == "drop_newest":
            if self.bucket.try_take():
                return [(payload, ts, wm)]
            self._account(payload, ts, "drop_newest")
            return []
        # buffered policies: drop_oldest / key_priority
        self._pending.append((payload, ts, wm))
        out: List[Tuple[Any, int, int]] = []
        while self._pending and self.bucket.try_take():
            out.append(self._pending.popleft())
        while len(self._pending) > self.buffer_cap:
            if pol == "drop_oldest":
                victim = self._pending.popleft()
            else:  # key_priority: evict the lowest-priority entry
                fn = self.priority_fn
                vi = min(range(len(self._pending)),
                         key=lambda i: fn(self._pending[i][0]))
                victim = self._pending[vi]
                del self._pending[vi]
            self._account(victim[0], victim[1], pol)
        return out

    # -- columnar fast path ------------------------------------------------
    def offer_columns(self, cols, ts_arr):
        """Admit a prefix of the column batch per available tokens (the
        per-row policies would defeat the no-per-tuple-Python contract
        of ``push_columns``); the shed suffix is accounted in one step.
        Returns ``(cols, ts_arr, n_admitted)`` — slices when partial."""
        n = len(ts_arr)
        grant = self.bucket.take_up_to(n)
        if grant >= n:
            return cols, ts_arr, n
        n_shed = n - grant
        st = self.replica.stats
        nbytes = sum(int(v[grant:].nbytes) for v in cols.values())
        st.note_shed(n_shed, nbytes)
        if self.shed_log is not None:
            self.shed_log.shed(
                self.replica.op.name, self.replica.idx,
                f"<column batch suffix: {n_shed} rows>",
                int(ts_arr[grant]) if n_shed else 0, "columns_tail")
        if grant == 0:
            return cols, ts_arr, 0
        return ({k: v[:grant] for k, v in cols.items()},
                ts_arr[:grant], grant)

    # -- lifecycle ---------------------------------------------------------
    def drain_pending(self) -> List[Tuple[Any, int, int]]:
        """Disengage: everything still buffered is ADMITTED (it was
        accepted into the gate, only awaiting tokens — shedding it on
        recovery would drop records the overload no longer forces)."""
        out = list(self._pending)
        self._pending.clear()
        return out

    def snapshot_pending(self) -> List[Tuple[Any, int, int]]:
        """The buffered records, for the source replica's checkpoint
        snapshot. They were pushed (the source cursor is past them) but
        not emitted and not shed — a restore that dropped them would
        break offered == admitted + shed. The source re-emits the
        snapshot's copy after restore; the live gate keeps its buffer."""
        return list(self._pending)

    @property
    def pending(self) -> int:
        return len(self._pending)
