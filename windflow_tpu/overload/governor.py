"""OverloadGovernor: the SLO control loop and its escalation ladder.

The governor consumes three planes that already exist and closes the
loop none of them closes alone:

- the **latency plane** (PR 2/5): sink-side end-to-end latency
  histograms, diffed tick-over-tick into a WINDOWED p99 (the cumulative
  histograms would otherwise average the breach away);
- the **backpressure plane** (PR 2): per-operator
  ``Queue_blocked_put_usec`` rates name the bottleneck to scale;
- the **elastic plane** (PR 6): ``graph.rescale`` is the SCALE rung,
  bounded by the autoscaler's MAX_PAR.

Ladder (one rung per breach decision, hysteresis + cooldown between
decisions; a rung that is a structural no-op falls through to the next
within the same decision):

1. **TUNE**  — halve device dispatch-queue depths and CPU-plane output
   batch sizes (latency for throughput; restored on recovery);
2. **SCALE** — rescale the worst-backpressured eligible operator up
   (FACTOR-multiplied, bounded by MAX_PAR), synchronizing the
   autoscaler's cooldown so the two loops never double-act;
3. **SHED**  — install :class:`~.admission.AdmissionGate` on every
   source replica: token-bucket admission at the measured downstream
   capacity, AIMD-adjusted every tick (×``aimd_down`` while breached,
   ×``aimd_up`` while under), with the configured shed policy.

Recovery walks back down: ``recover_hysteresis`` consecutive
deep-under-SLO windows with the gate no longer limiting release one
rung per cooldown (gates disengage pass-through — buffered records are
admitted, never shed; tuned knobs restore last).

Interlocks: while the governor is actively shedding (or within its
cooldown), the autoscaler must not scale DOWN (post-surge lull ==
admission control working, not idle capacity) and the stall watchdog
stands down for source workers (a 100%-shed source makes no progress by
design). Both read :meth:`OverloadGovernor.blocks_scale_down` /
``.shedding``.

Env twins (builder: ``PipeGraph.with_slo(p99_ms, policy)``)::

    WF_SLO_P99_MS=50            declare the graph SLO (enables the governor)
    WF_SLO_INTERVAL=0.5         control-loop tick, seconds
    WF_SLO_COOLDOWN=2.0         seconds between ladder transitions
    WF_SLO_HYSTERESIS=2         breached windows before escalating
    WF_SLO_RECOVER_HYSTERESIS=4 under-SLO windows before releasing
    WF_SHED_POLICY=drop_newest  drop_oldest | probabilistic | key_priority
    WF_SHED_DIR=<dir>           JSONL shed audit log (off unless set)
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

from ..basic import WindFlowError
from .admission import AdmissionGate, ShedLog, parse_shed_policy

SLO_STATES = ("idle", "tune", "scale", "shed")
IDLE, TUNE, SCALE, SHED = range(4)


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default  # malformed knob must not take down the graph


class GovernorPolicy:
    """Pure ladder logic over windowed (p99, shed-rate) observations;
    unit-testable without a running graph. ``observe`` returns a
    directive for the actuator: ``"escalate"``, ``"release"``,
    ``"shed_down"``, ``"shed_up"``, or None."""

    def __init__(self,
                 slo_p99_ms: Optional[float] = None,
                 interval_s: Optional[float] = None,
                 cooldown_s: Optional[float] = None,
                 breach_hysteresis: Optional[int] = None,
                 recover_hysteresis: Optional[int] = None,
                 shed_policy: Optional[str] = None,
                 recover_margin: float = 0.8,
                 shed_setpoint: float = 0.7,
                 aimd_down: float = 0.8,
                 aimd_up: float = 1.05,
                 min_rate_tps: float = 10.0,
                 shed_start_factor: float = 0.9,
                 release_shed_tps: float = 1.0,
                 max_parallelism: Optional[int] = None,
                 shed_buffer: int = 64) -> None:
        slo = slo_p99_ms if slo_p99_ms is not None \
            else _env_f("WF_SLO_P99_MS", 0.0)
        if slo <= 0:
            raise WindFlowError(
                "GovernorPolicy: a positive SLO is required "
                "(with_slo(p99_ms) or WF_SLO_P99_MS)")
        self.slo_us = float(slo) * 1e3
        self.interval_s = interval_s if interval_s is not None \
            else _env_f("WF_SLO_INTERVAL", 0.5)
        self.cooldown_s = cooldown_s if cooldown_s is not None \
            else _env_f("WF_SLO_COOLDOWN", 2.0)
        self.breach_hysteresis = int(
            breach_hysteresis if breach_hysteresis is not None
            else _env_f("WF_SLO_HYSTERESIS", 2))
        self.recover_hysteresis = int(
            recover_hysteresis if recover_hysteresis is not None
            else _env_f("WF_SLO_RECOVER_HYSTERESIS", 4))
        self.shed_policy = parse_shed_policy(
            shed_policy if shed_policy is not None
            else os.environ.get("WF_SHED_POLICY") or "drop_newest")
        self.recover_margin = float(recover_margin)
        # the shed rung regulates to setpoint*SLO, NOT to the SLO: the
        # p99 signal lags by the standing queue, so a controller aimed
        # at the limit oscillates ACROSS it — aimed below, the probing
        # sawtooth's peaks stay inside the budget
        self.shed_setpoint = float(shed_setpoint)
        self.aimd_down = float(aimd_down)
        self.aimd_up = float(aimd_up)
        self.min_rate_tps = float(min_rate_tps)
        self.shed_start_factor = float(shed_start_factor)
        self.release_shed_tps = float(release_shed_tps)
        # MAX_PAR for the SCALE rung: explicit, else the autoscaler's
        # env knob so both loops agree where headroom ends
        self.max_parallelism = int(
            max_parallelism if max_parallelism is not None
            else _env_f("WF_AUTOSCALE_MAX_PAR", 8))
        self.shed_buffer = int(shed_buffer)
        self.rung = IDLE  # highest engaged rung
        self._breach_streak = 0
        self._ok_streak = 0
        self._last_action_t = float("-inf")

    # -- bookkeeping -------------------------------------------------------
    def note_action(self, now: float, rung: Optional[int] = None) -> None:
        self._last_action_t = now
        self._breach_streak = 0
        self._ok_streak = 0
        if rung is not None:
            self.rung = rung

    def _cooled(self, now: float) -> bool:
        return now - self._last_action_t >= self.cooldown_s

    # -- one decision step -------------------------------------------------
    def observe(self, p99_us: Optional[float], shed_tps: float,
                now: float) -> Optional[str]:
        if p99_us is None:
            return None  # no samples and no queue: hold
        if self.rung == SHED:
            # rate regulation runs every tick — it is the shed rung's
            # steady-state behavior, not a ladder transition
            set_us = self.slo_us * self.shed_setpoint
            if p99_us > set_us:
                self._ok_streak = 0
                return "shed_down"
            self._ok_streak += 1
            if self._ok_streak >= self.recover_hysteresis \
                    and shed_tps <= self.release_shed_tps \
                    and self._cooled(now):
                return "release"
            if p99_us <= 0.5 * set_us:
                return "shed_up"
            return None
        breach = p99_us > self.slo_us
        deep_ok = p99_us <= self.recover_margin * self.slo_us
        if breach:
            self._breach_streak += 1
            self._ok_streak = 0
        elif deep_ok:
            self._ok_streak += 1
            self._breach_streak = 0
        else:  # inside the hysteresis band: hold position
            self._breach_streak = 0
            self._ok_streak = 0
            return None
        if breach and self._breach_streak >= self.breach_hysteresis \
                and self._cooled(now):
            return "escalate"
        if self.rung > IDLE and deep_ok \
                and self._ok_streak >= self.recover_hysteresis \
                and self._cooled(now):
            return "release"
        return None


class OverloadGovernor(threading.Thread):
    """Actuator thread: windows the latency plane, feeds the policy,
    walks the ladder (see module doc). Attached by
    ``PipeGraph.with_slo`` / ``WF_SLO_P99_MS``."""

    def __init__(self, graph, policy: Optional[GovernorPolicy] = None
                 ) -> None:
        super().__init__(name=f"overload-governor:{graph.name}", daemon=True)
        self.graph = graph
        self.policy = policy or GovernorPolicy()
        self.shed_log = ShedLog(graph.name)
        self.escalations = 0  # ladder transitions upward
        self.releases = 0
        self.errors = 0
        self.last_error: Optional[str] = None
        self.history: List[Dict[str, Any]] = []  # transitions, newest last
        self.window_p99_us = 0.0
        self.admit_rate_tps = 0.0  # current per-graph token rate (shed rung)
        self.offered_tps = 0.0
        self.admitted_tps = 0.0
        self.shed_tps = 0.0
        self._stop_evt = threading.Event()
        self._gates: List[Any] = []  # engaged (replica, gate) pairs
        self._tuned: List[Any] = []  # (obj, attr, original) restore list
        self._prev_e2e: Optional[List[int]] = None
        self._prev_counts: Optional[Dict[str, float]] = None
        self._prev_t = 0.0
        # windowed blocked-put plane for the SCALE rung (sampled every
        # tick; _try_scale must rank the LIVE bottleneck, not whoever
        # accumulated the most backpressure since process start)
        self._prev_blocked: Optional[Dict[str, Dict[str, float]]] = None
        self._prev_blocked_t = 0.0
        self._blocked_rates: Dict[str, float] = {}
        self._last_shed_active_t = float("-inf")
        self._rec = None  # lazy flight ring ("overload" track)

    # -- interlocks (autoscaler / stall watchdog) --------------------------
    @property
    def shedding(self) -> bool:
        """Admission gates engaged right now (stall-watchdog interlock:
        a fully shed source makes no progress by design)."""
        return bool(self._gates)

    def blocks_scale_down(self, now: Optional[float] = None) -> bool:
        """Autoscaler interlock: a scale-DOWN while shedding (or within
        the governor cooldown after) reads admission control as idle
        capacity and flaps."""
        if self.shedding:
            return True
        now = time.monotonic() if now is None else now
        return now - self._last_shed_active_t < self.policy.cooldown_s

    # -- flight recorder ---------------------------------------------------
    def _recorder(self):
        if self._rec is None:
            g = self.graph
            events = g._stage_flightrec_events_max()
            if events > 0:
                from ..monitoring.flightrec import FlightRecorder
                self._rec = FlightRecorder(
                    events, pid_label="overload",
                    tid_label=f"{g.name}/overload-governor")
                g._recorders.append(self._rec)
        return self._rec

    def _span(self, name: str, dur_us: float = 0.0, arg: Any = None) -> None:
        rec = self._recorder()
        if rec is not None:
            try:
                rec.event(name, dur_us, arg)
            except Exception:
                pass  # telemetry must never fail the control loop

    # -- signal extraction -------------------------------------------------
    def _sink_replicas(self):
        from ..basic import OpType
        for op in self.graph._ops:
            if op.op_type == OpType.SINK:
                for r in {id(r): r for r in op.replicas}.values():
                    yield r

    def _source_replicas(self):
        from ..basic import OpType
        for op in self.graph._ops:
            if op.op_type == OpType.SOURCE:
                for r in op.replicas:
                    if hasattr(r, "_gate"):
                        yield r

    def _window_p99(self) -> Optional[float]:
        """p99 over THIS window: bucket-wise diff of the merged sink-side
        cumulative e2e histograms (rescale/restart counter resets clip to
        zero and cost one quiet window)."""
        from ..monitoring.histogram import N_BUCKETS, LatencyHistogram
        cum = [0] * N_BUCKETS
        for r in self._sink_replicas():
            h = r.stats.hist_e2e
            if h is None:
                continue
            c = h.counts
            for i in range(N_BUCKETS):
                if c[i]:
                    cum[i] += c[i]
        prev, self._prev_e2e = self._prev_e2e, cum
        if prev is None:
            return None
        win = LatencyHistogram()
        total = 0
        for i in range(N_BUCKETS):
            d = cum[i] - prev[i]
            if d > 0:
                win.counts[i] = d
                total += d
        if total == 0:
            return None
        win.count = total
        from ..monitoring.histogram import bucket_bounds
        hi_edge = 0.0
        for i in range(N_BUCKETS - 1, -1, -1):
            if win.counts[i]:
                hi_edge = bucket_bounds(i)[1]
                break
        win.max_us = hi_edge if hi_edge != float("inf") else 2 ** 40
        return win.percentile(0.99)

    def _queue_delay_us(self) -> float:
        """Instantaneous worst queue-drain estimate (Little's law:
        occupancy x per-tuple service EWMA). The windowed p99 LAGS by
        exactly the standing queue it measures; this gauge reads the
        queue being built RIGHT NOW, so the shed controller reacts a
        tick after an overshoot instead of a queue-drain later."""
        from ..basic import OpType
        worst = 0.0
        for op in self.graph._ops:
            if op.op_type == OpType.SOURCE:
                continue
            for r in {id(r): r for r in op.replicas}.values():
                ch = r.stats.input_channel
                if ch is None:
                    continue
                est = len(ch) * max(1.0, r.stats.service_time_us)
                if est > worst:
                    worst = est
        return worst

    def _window_rates(self, now: float) -> None:
        """offered/admitted/shed records per second over this window,
        from the source replicas' cumulative counters."""
        admitted = shed = 0
        for r in self._source_replicas():
            admitted += r.stats.inputs_received
            shed += r.stats.shed_records
        cur = {"admitted": float(admitted), "shed": float(shed)}
        prev, self._prev_counts = self._prev_counts, cur
        prev_t, self._prev_t = self._prev_t, now
        if prev is None or now <= prev_t:
            self.admitted_tps = self.shed_tps = self.offered_tps = 0.0
            return
        dt = now - prev_t
        self.admitted_tps = max(0.0, cur["admitted"] - prev["admitted"]) / dt
        self.shed_tps = max(0.0, cur["shed"] - prev["shed"]) / dt
        self.offered_tps = self.admitted_tps + self.shed_tps

    # -- control loop ------------------------------------------------------
    def stop(self) -> None:
        self._stop_evt.set()

    def run(self) -> None:
        while not self._stop_evt.wait(self.policy.interval_s):
            try:
                self._tick()
            except Exception as e:  # a bad tick must not kill the loop
                self.errors += 1
                self.last_error = f"{type(e).__name__}: {e}"

    def _tick(self) -> None:
        g = self.graph
        if g._ended or g._rescaling or getattr(g, "_supervising", False):
            return
        now = time.monotonic()
        if self._gates:
            # a supervised restart or rescale rebuilds the runtime plane
            # with FRESH replicas: prune gates bound to discarded ones
            # and re-engage on the new sources while the shed rung holds
            live = {id(r) for r in self._source_replicas()}
            self._gates = [(r, gt) for r, gt in self._gates
                           if id(r) in live]
        if not self._gates and self.policy.rung == SHED:
            self._engage_shed()
        p99 = self._window_p99()
        if p99 is not None:
            self.window_p99_us = p99
        # effective latency signal: the windowed p99 OR the live
        # queue-delay estimate, whichever is worse — a pegged queue must
        # register even when the starved sink produced no samples
        q_est = self._queue_delay_us()
        p99_eff = max(p99 or 0.0, q_est)
        if p99 is None and q_est <= 0.0:
            p99_eff = None
        self._window_rates(now)
        self._window_blocked(now)
        if self.shedding or self.shed_tps > 0:
            self._last_shed_active_t = now
        directive = self.policy.observe(p99_eff, self.shed_tps, now)
        if directive == "escalate":
            self._escalate(now, p99_eff)
        elif directive == "release":
            self._release(now, p99_eff)
        elif directive == "shed_down":
            # proportional cut toward the setpoint (bounded): a 2x
            # overshoot halves in one step instead of bleeding down
            set_us = self.policy.slo_us * self.policy.shed_setpoint
            factor = max(0.5, min(self.policy.aimd_down,
                                  set_us / max(p99_eff or 1.0, 1.0)))
            self._aimd(factor)
        elif directive == "shed_up":
            # probe upward only while the bucket is the binding
            # constraint (tokens fully consumed): raising the rate when
            # DOWNSTREAM is the limiter just rebuilds the queue
            if self.admitted_tps >= 0.7 * self.admit_rate_tps:
                self._aimd(self.policy.aimd_up)

    def _note(self, kind: str, now: float, p99: Optional[float],
              detail: Any) -> None:
        self.history.append({
            "t_unix": time.time(), "event": kind,
            "state": SLO_STATES[self.policy.rung],
            "window_p99_us": round(p99 or 0.0, 1),
            "detail": detail,
        })
        del self.history[:-64]
        self._span(f"overload:{kind}", 0.0,
                   {"state": SLO_STATES[self.policy.rung],
                    "p99_us": round(p99 or 0.0, 1), "detail": detail})

    def _degraded_devices(self) -> int:
        """Devices the supervision plane is currently running WITHOUT
        (device-loss failover): read from the graph's supervisor. While
        > 0 the graph's capacity is physically reduced — TUNE and SCALE
        cannot buy it back (mesh ops refuse to rescale, and the missing
        chip is the bottleneck), so escalation jumps straight to SHED."""
        sup = getattr(self.graph, "_supervisor", None)
        return int(getattr(sup, "degraded_devices", 0) or 0) \
            if sup is not None else 0

    # -- escalation ladder -------------------------------------------------
    def _escalate(self, now: float, p99: Optional[float]) -> None:
        pol = self.policy
        degraded = self._degraded_devices()
        if degraded > 0:
            # degraded mesh capacity: shed immediately instead of
            # silently overloading the surviving devices
            try:
                self._engage_shed()
            except WindFlowError as e:
                self.last_error = f"shed rung (degraded): {e}"
                return
            pol.note_action(now, SHED)
            self.escalations += 1
            self._note("escalate", now, p99,
                       f"shed (mesh degraded by {degraded} device(s))")
            return
        if pol.rung < TUNE and self._try_tune():
            pol.note_action(now, TUNE)
            self.escalations += 1
            self._note("escalate", now, p99, "tune")
            return
        if pol.rung < SHED and self._try_scale():
            pol.note_action(now, SCALE)
            self.escalations += 1
            self._note("escalate", now, p99, "scale")
            return
        self._engage_shed()
        pol.note_action(now, SHED)
        self.escalations += 1
        self._note("escalate", now, p99, "shed")

    def _release(self, now: float, p99: Optional[float]) -> None:
        pol = self.policy
        if pol.rung == SHED:
            self._disengage_shed()
            pol.note_action(now, SCALE)
        elif pol.rung == SCALE:
            # scale-DOWN is the autoscaler's decision (with our
            # interlock); the governor only releases its claim
            pol.note_action(now, TUNE)
        elif pol.rung == TUNE:
            self._restore_tuned()
            pol.note_action(now, IDLE)
        self.releases += 1
        self._note("release", now, p99, SLO_STATES[pol.rung])

    # -- rung 1: tune ------------------------------------------------------
    @staticmethod
    def _tier_stores(r) -> list:
        """Every TieredKeyStore a replica hosts: the single-chip engine's
        (``r.engine.tier``), the mesh replica's (``r._tier``), or one per
        stateful sub-engine of a fused chain (``r._engines``)."""
        stores = []
        eng = getattr(r, "engine", None)
        if eng is not None and getattr(eng, "tier", None) is not None:
            stores.append(eng.tier)
        if getattr(r, "_tier", None) is not None:
            stores.append(r._tier)
        for sub in getattr(r, "_engines", ()) or ():
            if sub is not None and getattr(sub, "tier", None) is not None:
                stores.append(sub.tier)
        return stores

    def _try_tune(self) -> bool:
        """Halve device dispatch depths and CPU-plane output batch sizes
        (recorded for restore). Returns False when there was nothing to
        tune — the ladder then falls through to SCALE."""
        touched = False
        for op in self.graph._ops:
            for r in {id(r): r for r in op.replicas}.values():
                dq = getattr(r, "dispatch", None)
                if dq is not None and dq.depth > 0:
                    self._tuned.append((dq, "depth", dq.depth))
                    dq.depth = dq.depth // 2
                    touched = True
                for tstore in self._tier_stores(r):
                    # tiering lever: shrink the hot tier toward its floor
                    # BEFORE the ladder reaches SHED — demotions free
                    # device memory at the cost of cold misses, which is
                    # still cheaper than dropping tuples
                    cur = int(tstore.target_hot_capacity)
                    nxt = max(tstore.min_hot, cur // 2)
                    if nxt < cur:
                        self._tuned.append(
                            (tstore, "target_hot_capacity", cur))
                        tstore.target_hot_capacity = nxt
                        touched = True
                em = getattr(r, "emitter", None)
                # CPU-plane emitters only: shrinking a TPU staging
                # emitter's batch would change its bucket signature and
                # trigger the retraces the compile plane exists to avoid
                if em is not None \
                        and type(em).__module__.endswith("runtime.emitters") \
                        and getattr(em, "output_batch_size", 0) > 1:
                    self._tuned.append((em, "output_batch_size",
                                        em.output_batch_size))
                    em.output_batch_size = max(1, em.output_batch_size // 2)
                    touched = True
        return touched

    def _restore_tuned(self) -> None:
        for obj, attr, orig in reversed(self._tuned):
            try:
                setattr(obj, attr, orig)
            except Exception:
                pass  # a replaced replica's knob is gone; harmless
        self._tuned = []

    # -- rung 2: scale -----------------------------------------------------
    def _eligible_totals(self) -> Dict[str, Dict[str, float]]:
        """Cumulative blocked-put totals for rescalable stages (the raw
        counters; ``_window_blocked`` diffs them tick-over-tick into the
        rates the SCALE rung actually ranks by)."""
        from ..scaling.repartition import repartition_refusal
        out: Dict[str, Dict[str, float]] = {}
        for s in self.graph._stages:
            if any(repartition_refusal(op) is not None for op in s.ops):
                continue
            op = s.first_op
            reps = {id(r): r for r in op.replicas}.values()
            blocked = 0.0
            for r in reps:
                ch = r.stats.input_channel
                if ch is not None:
                    blocked += getattr(ch, "blocked_put_ns", 0) / 1e3
            out[op.name] = {"parallelism": s.parallelism,
                            "blocked_put_usec": blocked}
        return out

    def _window_blocked(self, now: float) -> None:
        """Blocked-put usec/s per eligible stage over THIS window
        (tick-over-tick diff, the autoscaler's idiom): an operator with
        large historical backpressure but no current congestion must
        not outrank the live bottleneck."""
        cur = self._eligible_totals()
        prev, self._prev_blocked = self._prev_blocked, cur
        prev_t, self._prev_blocked_t = self._prev_blocked_t, now
        if prev is None or now <= prev_t:
            self._blocked_rates = {}
            return
        dt = now - prev_t
        rates: Dict[str, float] = {}
        for name, m in cur.items():
            p = prev.get(name)
            if p is None or p["parallelism"] != m["parallelism"]:
                continue  # fresh op or mid-rescale counter reset: skip
            rates[name] = max(
                0.0, m["blocked_put_usec"] - p["blocked_put_usec"]) / dt
        self._blocked_rates = rates

    def _try_scale(self) -> bool:
        g = self.graph
        if g._coordinator is None:
            return False  # rescale needs the checkpoint plane
        auto = getattr(g, "_autoscaler", None)
        max_par = auto.policy.max_parallelism if auto is not None \
            else self.policy.max_parallelism
        totals = self._eligible_totals()
        win = self._blocked_rates
        cand = []
        for name, m in totals.items():
            par = int(m["parallelism"])
            if par >= max_par:
                continue
            # windowed rate once a full tick exists; before the first
            # window the cumulative total is the only signal there is
            blocked = win[name] if name in win \
                else (0.0 if win else m["blocked_put_usec"])
            cand.append((blocked, name, par))
        if not cand:
            return False  # scale-out exhausted: the shed rung is next
        cand.sort(reverse=True)
        blocked, name, par = cand[0]
        if blocked <= 0:
            return False  # nothing backpressured: scaling would not help
        new = min(max_par, max(par + 1, par * 2))
        try:
            self._span("overload:rescale", 0.0, {"op": name, "to": new})
            g.rescale(name, new)
        except WindFlowError as e:
            self.last_error = f"scale rung: {e}"
            return False
        if auto is not None:
            # one surge, one reaction: the autoscaler must not stack its
            # own decision on the transient our rescale just caused
            auto.policy.note_action(time.monotonic())
        return True

    # -- rung 3: shed ------------------------------------------------------
    def _engage_shed(self) -> None:
        if self._gates:
            return
        replicas = list(self._source_replicas())
        if not replicas:
            raise WindFlowError("overload governor: no gateable sources")
        if self.admit_rate_tps > 0:
            # re-engage after a supervised restart/rescale (gates
            # pruned, rung still SHED): reuse the rate the AIMD loop
            # had converged to — the windowed counters rewound with the
            # replicas, so admitted_tps is zero/stale this tick and
            # deriving from it would collapse the admit rate to the
            # floor and over-shed until the slow probe recovers
            rate = max(self.policy.min_rate_tps, self.admit_rate_tps)
        else:
            # first engagement: admit rate = measured downstream
            # capacity (the admitted throughput while breached IS what
            # the graph absorbs), derated
            rate = max(self.policy.min_rate_tps,
                       self.admitted_tps * self.policy.shed_start_factor)
        self.admit_rate_tps = rate
        per = rate / len(replicas)
        for r in replicas:
            gate = AdmissionGate(
                r, self.policy.shed_policy, per,
                priority_fn=getattr(r.op, "priority_fn", None),
                shed_log=self.shed_log,
                buffer_cap=self.policy.shed_buffer,
                seed=0x5eed ^ r.idx)
            self._gates.append((r, gate))
            r._gate = gate
        self._span("shed:engage", 0.0,
                   {"rate_tps": round(rate, 1),
                    "policy": self.policy.shed_policy,
                    "sources": len(replicas)})

    def _aimd(self, factor: float) -> None:
        if not self._gates:
            return
        rate = max(self.policy.min_rate_tps, self.admit_rate_tps * factor)
        self.admit_rate_tps = rate
        per = rate / len(self._gates)
        for _, gate in self._gates:
            gate.bucket.set_rate(per)
        self._span("shed:rate", 0.0, {"rate_tps": round(rate, 1),
                                      "shed_tps": round(self.shed_tps, 1)})

    def _disengage_shed(self) -> None:
        # pass-through release: the SOURCE thread drains any buffered
        # records on its next push (or at end-of-stream) and clears the
        # gate itself — the governor never emits on a foreign thread
        for _, gate in self._gates:
            gate.released = True
        self._gates = []
        self.admit_rate_tps = 0.0
        self._span("shed:disengage")

    # -- introspection -----------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        shed_records = shed_bytes = 0
        for r in self._source_replicas():
            shed_records += r.stats.shed_records
            shed_bytes += r.stats.shed_bytes
        return {
            "Overload_state": self.policy.rung,
            "Overload_state_name": SLO_STATES[self.policy.rung],
            "Overload_slo_p99_usec": round(self.policy.slo_us, 1),
            "Overload_window_p99_usec": round(self.window_p99_us, 1),
            "Overload_escalations": self.escalations,
            "Overload_releases": self.releases,
            "Overload_shedding": self.shedding,
            "Overload_admit_rate_tps": round(self.admit_rate_tps, 1),
            "Overload_offered_tps": round(self.offered_tps, 1),
            "Overload_admitted_tps": round(self.admitted_tps, 1),
            "Overload_shed_tps": round(self.shed_tps, 1),
            "Overload_shed_records": shed_records,
            "Overload_shed_bytes": shed_bytes,
            "Overload_errors": self.errors,
            "Overload_last_error": self.last_error,
            "Overload_degraded_devices": self._degraded_devices(),
            "Overload_history": list(self.history),
        }
