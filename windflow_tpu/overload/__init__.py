"""Overload-protection plane: SLO-driven admission control, priority
load shedding, and graceful degradation past the autoscaler's MAX_PAR.

Blocking backpressure (bounded channels) and elastic scale-out
(``windflow_tpu.scaling``) bound latency only while parallelism headroom
exists: at ``MAX_PAR`` a static answer to offered load is unbounded
queueing delay. This package closes that gap with an
:class:`OverloadGovernor` control loop that consumes the signals the
observability plane already exports (queue backpressure gauges, sink-side
end-to-end latency histograms, autoscaler state) and walks an escalation
ladder when a user-declared SLO (``PipeGraph.with_slo(p99_ms)``) is
breached:

1. **TUNE** — shrink the device-ahead dispatch depth and source-side
   output batching (latency for throughput);
2. **SCALE** — delegate to the elastic plane (scale the bottleneck
   operator, bounded by MAX_PAR);
3. **SHED** — switch sources from blocking to admission-controlled
   ingestion: a token bucket rate-limits admits and a pluggable policy
   (``drop_newest`` / ``drop_oldest`` / ``probabilistic`` /
   ``key_priority``) picks what to shed — at SOURCE admission, before
   checkpoint barriers and the exactly-once plane, so delivery stays
   byte-identical over the admitted records;

then recovers with hysteresis and cooldown (AIMD on the admit rate, one
rung at a time back down the ladder). Every shed is accounted:
``Shed_records``/``Shed_bytes`` stats, ``windflow_shed_*`` and
``windflow_overload_*`` metric families, ``shed:*``/``overload:*``
flight-recorder spans, and an optional ``WF_SHED_DIR`` JSONL audit log
(the dead-letter writer's machinery).
"""

from .admission import (SHED_POLICIES, AdmissionGate, ShedLog, TokenBucket,
                        parse_shed_policy)
from .governor import SLO_STATES, GovernorPolicy, OverloadGovernor

__all__ = [
    "AdmissionGate", "TokenBucket", "ShedLog", "SHED_POLICIES",
    "parse_shed_policy", "GovernorPolicy", "OverloadGovernor",
    "SLO_STATES",
]
