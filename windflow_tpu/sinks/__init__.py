"""Sink-side delivery guarantees (no reference analog).

``windflow_tpu.sinks.transactional`` upgrades every sink family from
at-least-once to exactly-once via an epoch-fenced two-phase commit driven
by the aligned-barrier checkpoint plane (``windflow_tpu.checkpoint``):
sink output buffers/stages per epoch, pre-commits at barrier-snapshot
time, and becomes visible atomically only when the coordinator finalizes
the epoch.
"""

from .transactional import (EpochSegmentStore, EpochTxnDriver,
                            FencedWriteError, txn_dir_for)

__all__ = ["EpochSegmentStore", "EpochTxnDriver", "FencedWriteError",
           "txn_dir_for"]
