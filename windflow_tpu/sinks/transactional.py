"""Exactly-once sinks: epoch-fenced two-phase commit on checkpoint finalize.

PR 3's aligned-barrier checkpointing gives at-least-once delivery: on
recovery the sources replay the suffix after the barrier and every sink
re-emits it. This module closes the gap with a classic two-phase commit
whose coordinator is the existing ``CheckpointCoordinator``:

- between barriers a sink replica stages its output under the CURRENT
  epoch (an in-memory buffer for functor sinks, an open broker
  transaction for Kafka, an uncommitted sqlite transaction for P_Sink);
- at barrier-snapshot time (``Worker.checkpoint_now`` calls the replica's
  ``precommit_epoch(ckpt_id)`` hook) the epoch is **pre-committed**:
  made durable but not yet visible — a staged segment file published with
  tmp+atomic-rename, a prepared broker transaction, a committed sqlite
  image carrying the epoch marker;
- when the coordinator finalizes the epoch, a finalize listener flips a
  watermark and the sink's own thread **commits** every pre-committed
  epoch at or below it (rename ``.pending`` -> ``.seg``, broker
  transaction commit, sqlite finalized-epoch marker);
- on restore from checkpoint ``cid``, pre-committed epochs ``<= cid``
  roll FORWARD (their records are pre-barrier data the replay will not
  regenerate) and epochs ``> cid`` abort (the replayed suffix regenerates
  them) — so kill-anywhere / restore / compare yields byte-identical,
  duplicate-free sink output.

Epoch fencing: a replica instance acquires a monotonically increasing
fence token when it opens its transaction log (broker transactional id /
sqlite meta row). Rebuilding the runtime plane — a live ``rescale()``,
or a restore — bumps the fence, and any write or commit attempted by a
stale pre-rebuild replica raises ``FencedWriteError`` instead of
corrupting the committed stream (Kafka's zombie-producer fencing,
generalized to every sink family).
"""

from __future__ import annotations

import os
import pickle
import re
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..basic import WindFlowError


class FencedWriteError(WindFlowError):
    """A stale (zombie) sink replica attempted a transactional write
    after a newer replica generation took over its log."""


def txn_dir_for(op_name: str, replica_idx: int,
                base: Optional[str] = None) -> str:
    """Default staging root for one sink replica's transaction log:
    ``<WF_TXN_DIR or wf_txn_sinks>/<sanitized op>_r<idx>``."""
    root = base or os.environ.get("WF_TXN_DIR") or "wf_txn_sinks"
    safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in op_name)
    return os.path.join(root, f"{safe}_r{replica_idx}")


def _atomic_write(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


_SEG_RE = re.compile(r"^epoch_(\d{10})\.(pending|seg)$")


class EpochSegmentStore:
    """One sink replica's on-disk transaction log: one staged segment per
    epoch, crash-safe by construction (the same tmp+atomic-rename
    discipline as ``checkpoint/store.py``)::

        <root>/
          epoch_0000000003.pending   # pre-committed (durable, invisible)
          epoch_0000000002.seg       # committed (the sink's real output)

    ``precommit`` publishes the pending file atomically; ``commit`` is a
    single ``os.replace`` of ``.pending`` to ``.seg``; both are
    idempotent so a crash between the coordinator finalize and the
    sink-side rename is healed by roll-forward on restore. Orphaned
    ``.tmp`` debris from a crash mid-precommit is reaped on recovery.
    """

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, epoch: int, pending: bool) -> str:
        return os.path.join(
            self.root, f"epoch_{epoch:010d}.{'pending' if pending else 'seg'}")

    # -- the 2PC verbs -----------------------------------------------------
    def precommit(self, epoch: int, payload: bytes) -> str:
        path = self._path(epoch, pending=True)
        _atomic_write(path, payload)
        return path

    def commit(self, epoch: int) -> bool:
        """``.pending`` -> ``.seg``; True when this call performed the
        rename (False: already committed — the idempotent replay case)."""
        final = self._path(epoch, pending=False)
        if os.path.exists(final):
            return False
        pending = self._path(epoch, pending=True)
        os.replace(pending, final)  # missing pending = a real bug: raise
        return True

    def abort(self, epoch: int) -> bool:
        try:
            os.unlink(self._path(epoch, pending=True))
            return True
        except FileNotFoundError:
            return False

    # -- introspection / recovery ------------------------------------------
    def _scan(self) -> List[Tuple[int, str]]:
        out = []
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return []
        for name in names:
            m = _SEG_RE.match(name)
            if m:
                out.append((int(m.group(1)), m.group(2)))
        return sorted(out)

    def pending_epochs(self) -> List[int]:
        return [e for e, kind in self._scan() if kind == "pending"]

    def committed_epochs(self) -> List[int]:
        return [e for e, kind in self._scan() if kind == "seg"]

    def is_committed(self, epoch: int) -> bool:
        return os.path.exists(self._path(epoch, pending=False))

    def read(self, epoch: int, pending: bool = False) -> bytes:
        with open(self._path(epoch, pending), "rb") as f:
            return f.read()

    def reap_tmp(self) -> int:
        """Delete torn ``.tmp`` files a crash mid-precommit left behind."""
        n = 0
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return 0
        for name in names:
            if name.endswith(".tmp"):
                try:
                    os.unlink(os.path.join(self.root, name))
                    n += 1
                except OSError:
                    pass
        return n


def read_committed_records(root: str) -> List[Any]:
    """All committed records of one replica's segment store, concatenated
    in epoch order — the canonical 'what did this sink output' view the
    exactly-once differentials compare."""
    store = EpochSegmentStore(root)
    out: List[Any] = []
    for epoch in store.committed_epochs():
        out.extend(pickle.loads(store.read(epoch)))
    return out


class EpochTxnDriver:
    """Shared two-phase-commit state machine for one sink replica.

    The family-specific mechanics live in a small backend object with
    the verbs ``do_precommit(epoch, records)``, ``do_commit(epoch) ->
    Optional[records]`` (the returned records are handed to ``deliver``,
    the functor-delivery callback), ``do_abort(epoch)`` and
    ``do_recover(last_epoch) -> (rolled_forward, aborted)``. The driver
    owns epoch bookkeeping, the finalize watermark, commit-latency
    accounting, and the ``Sink_txn_*`` stats + ``txn:*`` flight spans.

    Threading: ``on_finalized`` runs on whichever worker thread acked
    last (the coordinator contract) and only stores an int watermark;
    every other method runs on the sink replica's own thread (or the
    main thread, for ``restore``/``complete_all`` — worker joined).
    """

    def __init__(self, backend: Any, stats: Any,
                 deliver: Optional[Callable[[Any], None]] = None) -> None:
        self.backend = backend
        self.stats = stats
        self.deliver = deliver
        self.buffer: List[Any] = []  # current-epoch records (file flavor)
        self._pending: Dict[int, float] = {}  # epoch -> precommit t
        self._commit_ready = 0  # finalize watermark (listener-written)
        self.last_epoch = 0
        # commit-latency accounting (precommit -> commit visible), for
        # microbench --txn and the PERF.md numbers
        self.commit_latency_last_us = 0.0
        self.commit_latency_total_us = 0.0
        self.commits = 0

    # -- wiring ------------------------------------------------------------
    def bind(self, coordinator: Any) -> None:
        self._commit_ready = coordinator.last_completed_id
        coordinator.add_finalize_listener(self.on_finalized)
        abort_bind = getattr(coordinator, "add_abort_listener", None)
        if abort_bind is not None:
            abort_bind(self.on_epoch_failed)

    def on_finalized(self, ckpt_id: int) -> None:
        # another worker's thread: publish the watermark only
        if ckpt_id > self._commit_ready:
            self._commit_ready = ckpt_id

    def on_epoch_failed(self, ckpt_id: int) -> None:
        """Coordinator abort path (epoch timeout / rescale teardown): the
        epoch will never finalize, but its pre-committed records are
        still pre-barrier data — they stay staged and ride the next
        committed epoch's watermark (or roll forward/abort on restore).
        Record the event so the abandonment is visible."""
        self._span("txn:epoch_failed", 0.0, {"epoch": ckpt_id})

    def _span(self, name: str, dur_us: float, arg: Any = None) -> None:
        rec = getattr(self.stats, "recorder", None)
        if rec is not None:
            try:
                rec.event(name, dur_us, arg)
            except Exception:
                pass  # telemetry must never fail a commit

    def _fenced(self, exc: BaseException) -> None:
        """Uniform accounting for a refused zombie write, whichever
        backend detected it."""
        self.stats.txn_fenced_writes += 1
        self._span("txn:fenced", 0.0, str(exc))

    # -- phase 1: pre-commit at the aligned barrier ------------------------
    def precommit_epoch(self, ckpt_id: int) -> None:
        """Worker hook at barrier-snapshot time: everything staged since
        the previous barrier belongs to epoch ``ckpt_id``. Commits any
        already-finalized older epoch first (keeps disk bounded), then
        durably prepares this one. An epoch that is ALREADY committed in
        the log (restore from an older checkpoint replayed it) is
        discarded instead — the sink-side duplicate filter."""
        self.poll()
        records, self.buffer = self.buffer, []
        self.last_epoch = max(self.last_epoch, ckpt_id)
        already = getattr(self.backend, "is_committed", None)
        if already is not None and already(ckpt_id):
            self.stats.txn_aborts += 1
            self._span("txn:discard_committed", 0.0,
                       {"epoch": ckpt_id, "records": len(records)})
            return
        t0 = time.perf_counter()
        try:
            self.backend.do_precommit(ckpt_id, records)
        except FencedWriteError as e:
            self._fenced(e)
            raise
        self._pending[ckpt_id] = time.perf_counter()
        self.stats.txn_precommits += 1
        self._span("txn:precommit", (time.perf_counter() - t0) * 1e6,
                   {"epoch": ckpt_id, "records": len(records)})

    # -- phase 2: commit on coordinator finalize ---------------------------
    def poll(self) -> bool:
        """Commit every pre-committed epoch at or below the finalize
        watermark (epoch order). Called from the sink's own thread: the
        message path, the worker idle tick, and the barrier hook."""
        ready = self._commit_ready
        did = False
        for epoch in sorted(e for e in self._pending if e <= ready):
            self._commit_one(epoch)
            did = True
        return did

    def _commit_one(self, epoch: int) -> None:
        t_pre = self._pending.pop(epoch)
        t0 = time.perf_counter()
        try:
            records = self.backend.do_commit(epoch)
        except FencedWriteError as e:
            self._pending[epoch] = t_pre  # still staged; not ours anymore
            self._fenced(e)
            raise
        now = time.perf_counter()
        lat_us = (now - t_pre) * 1e6
        self.commit_latency_last_us = lat_us
        self.commit_latency_total_us += lat_us
        self.commits += 1
        self.stats.txn_commits += 1
        self._span("txn:commit", (now - t0) * 1e6,
                   {"epoch": epoch, "latency_us": round(lat_us, 1)})
        if records is not None and self.deliver is not None:
            self.deliver(records)

    # -- termination -------------------------------------------------------
    def seal_tail(self) -> None:
        """EOS: stage the records after the last barrier as one final
        epoch (``last_epoch + 1``); it commits in ``complete_all`` once
        the graph is known to have finished cleanly. A crash before that
        aborts it on restore — the replay regenerates the tail."""
        self.poll()
        if not self.buffer and not hasattr(self.backend, "always_seal"):
            return
        self.precommit_epoch(self.last_epoch + 1)

    def complete_all(self) -> None:
        """Clean end of the run (``PipeGraph.wait_end``, every worker
        joined without error): the stream is complete and nothing will
        replay, so every still-pending epoch — finalized or merely
        superseded — commits now, in epoch order."""
        for epoch in sorted(self._pending):
            self._commit_one(epoch)

    # -- checkpoint snapshot / restore -------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        return {"txn_last_epoch": self.last_epoch}

    def restore(self, state: Dict[str, Any]) -> None:
        """Recovery: roll pre-committed epochs ``<= txn_last_epoch`` (the
        restored checkpoint's id — their data precedes the replay point)
        forward to committed; abort everything newer (the replayed
        suffix regenerates it)."""
        last = int(state.get("txn_last_epoch", 0))
        self.last_epoch = last
        self._commit_ready = max(self._commit_ready, last)
        t0 = time.perf_counter()
        rolled, aborted = self.backend.do_recover(last)
        for epoch, records in rolled:
            self.commits += 1
            self.stats.txn_commits += 1
            if records is not None and self.deliver is not None:
                self.deliver(records)
        self.stats.txn_aborts += len(aborted)
        if rolled or aborted:
            self._span("txn:recover", (time.perf_counter() - t0) * 1e6,
                       {"rolled_forward": [e for e, _ in rolled],
                        "aborted": list(aborted)})


class SegmentBackend:
    """File-flavor backend over :class:`EpochSegmentStore` — used by the
    row (``SinkReplica``) and columnar (``ColumnarSinkReplica``) sinks.
    Records are pickled per epoch; the committed ``.seg`` files are the
    sink's durable, exactly-once output stream.

    Fencing: a ``fence`` file in the segment root holds the current
    replica generation. Constructing a backend (a restore, a live
    rescale rebuilding the runtime plane) bumps it atomically; a stale
    pre-rebuild replica fails its next precommit/commit instead of
    racing the new owner's renames."""

    def __init__(self, root: str) -> None:
        self.store = EpochSegmentStore(root)
        self._records: Dict[int, List[Any]] = {}  # uncommitted, in-memory
        self._fence_path = os.path.join(root, "fence")
        self.fence = self._read_fence() + 1
        _atomic_write(self._fence_path, str(self.fence).encode())

    def _read_fence(self) -> int:
        try:
            with open(self._fence_path, "rb") as f:
                return int(f.read() or 0)
        except (FileNotFoundError, ValueError):
            return 0

    def check_fence(self) -> None:
        stored = self._read_fence()
        if stored != self.fence:
            raise FencedWriteError(
                f"segment store {self.store.root!r}: fence {self.fence} "
                f"is stale (current {stored}); a newer replica "
                "generation owns this transaction log")

    def is_committed(self, epoch: int) -> bool:
        return self.store.is_committed(epoch)

    def do_precommit(self, epoch: int, records: List[Any]) -> None:
        self.check_fence()
        self.store.precommit(epoch, pickle.dumps(
            records, protocol=pickle.HIGHEST_PROTOCOL))
        self._records[epoch] = records

    def do_commit(self, epoch: int) -> Optional[List[Any]]:
        self.check_fence()
        if not self.store.commit(epoch):
            self._records.pop(epoch, None)
            return None  # already committed: do not re-deliver
        return self._records.pop(epoch, None)

    def do_abort(self, epoch: int) -> None:
        self._records.pop(epoch, None)
        self.store.abort(epoch)

    def do_recover(self, last_epoch: int
                   ) -> Tuple[List[Tuple[int, Any]], List[int]]:
        self.store.reap_tmp()
        rolled: List[Tuple[int, Any]] = []
        aborted: List[int] = []
        for epoch in self.store.pending_epochs():
            if epoch <= last_epoch:
                payload = self.store.read(epoch, pending=True)
                if self.store.commit(epoch):
                    rolled.append((epoch, pickle.loads(payload)))
            else:
                self.store.abort(epoch)
                aborted.append(epoch)
        return rolled, aborted
