"""Source operator and Source_Shipper.

Parity: ``wf/source.hpp:55-163`` (user functor drives the shipper, then EOS)
and ``wf/source_shipper.hpp`` (``push`` for INGRESS_TIME at L171/210,
``pushWithTimestamp``/``setNextWatermark`` for EVENT_TIME at L248/289/328).
Timestamps are microseconds; in DEFAULT mode with ingress time the watermark
equals the tuple timestamp (monotone because "now" is monotone).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..basic import (ExecutionMode, OpType, RoutingMode, TimePolicy,
                     WindFlowError, current_time_usecs)
from .base import BasicOperator, BasicReplica, arity


class SourceShipper:
    """User-visible push API for Source functors."""

    def __init__(self, replica: "SourceReplica") -> None:
        self._r = replica
        self._next_wm = 0
        self._epoch = current_time_usecs()

    # -- INGRESS_TIME ------------------------------------------------------
    def push(self, payload: Any) -> None:
        if self._r.op.time_policy is not TimePolicy.INGRESS_TIME:
            raise WindFlowError("push() requires INGRESS_TIME; use "
                                "push_with_timestamp() under EVENT_TIME")
        ts = current_time_usecs() - self._epoch
        wm = ts if self._r.op.execution_mode is ExecutionMode.DEFAULT else 0
        self._r.ship(payload, ts, wm)

    # -- EVENT_TIME --------------------------------------------------------
    def push_with_timestamp(self, payload: Any, ts: int) -> None:
        if self._r.op.time_policy is not TimePolicy.EVENT_TIME:
            raise WindFlowError("push_with_timestamp() requires EVENT_TIME")
        self._r.ship(payload, int(ts), self._next_wm)

    def set_next_watermark(self, wm: int) -> None:
        if wm < self._next_wm:
            raise WindFlowError("watermarks must be non-decreasing")
        self._next_wm = int(wm)

    # -- columnar fast path ------------------------------------------------
    def push_columns(self, cols, ts=None) -> None:
        """Push a whole COLUMN BATCH (dict of equal-length 1-D numpy
        arrays) in one call. On a device edge this skips per-tuple Python
        entirely — the arrays are padded and shipped as one ``BatchTPU``
        (the reference's per-tuple shipper has no analog; this is the
        tpu-first staging surface). On a CPU edge rows materialize as
        dicts. INGRESS_TIME stamps every row "now"; EVENT_TIME requires
        ``ts`` (int64 array, same length)."""
        import numpy as np

        n = -1
        for v in cols.values():
            if n < 0:
                n = len(v)
            elif len(v) != n:
                raise WindFlowError("push_columns: ragged columns")
        if n <= 0:
            return
        if self._r.op.time_policy is TimePolicy.INGRESS_TIME:
            if ts is not None:
                raise WindFlowError("push_columns(ts=...) requires "
                                    "EVENT_TIME")
            now = current_time_usecs() - self._epoch
            ts_arr = np.full(n, now, dtype=np.int64)
            wm = (now if self._r.op.execution_mode is ExecutionMode.DEFAULT
                  else 0)
        else:
            if ts is None:
                raise WindFlowError("push_columns under EVENT_TIME needs a "
                                    "ts array")
            ts_arr = np.asarray(ts, dtype=np.int64)
            if len(ts_arr) != n:
                raise WindFlowError("push_columns: ts length mismatch")
            wm = self._next_wm
        self._r.ship_columns(cols, ts_arr, wm)

    # -- checkpointing -----------------------------------------------------
    def request_checkpoint(self) -> Optional[int]:
        """Force an aligned checkpoint NOW (at this tuple boundary) instead
        of waiting for the coordinator's interval — the deterministic
        trigger used by tests and drain-style shutdowns. Returns the new
        checkpoint id, or None when checkpointing is not enabled."""
        return self._r.request_checkpoint()

    # convenience used by generators/tests
    @property
    def current_watermark(self) -> int:
        return self._next_wm


class Source(BasicOperator):
    """Parallel replicas are independent generators; ``func(shipper[, ctx])``
    is called once per replica and runs its own loop."""

    op_type = OpType.SOURCE

    def __init__(self, func: Callable, name: str = "source",
                 parallelism: int = 1, output_batch_size: int = 0) -> None:
        super().__init__(name, parallelism, RoutingMode.NONE,
                         output_batch_size=output_batch_size)
        self.func = func
        self._riched = arity(func) >= 2

    def build_replicas(self) -> None:
        self.replicas = [SourceReplica(self, i) for i in range(self.parallelism)]


class SourceReplica(BasicReplica):
    def __init__(self, op, idx):
        super().__init__(op, idx)
        # sampled latency tracing (monitoring/tracing.py): every Nth
        # shipped tuple carries a wall-clock origin stamp. The gate is
        # a single integer AND against this mask — sample_every is a
        # power of two, and a mask of -1 (sampling off) can never make
        # ``inputs_received & mask`` zero, so the hot path costs the
        # same with tracing off or sampling 1/64
        self._trace_mask = self.stats.sample_every - 1
        # aligned checkpointing (windflow_tpu.checkpoint): the coordinator
        # bumps an epoch; we notice at the next tuple boundary, snapshot
        # our replay position and inject the barrier downstream
        self._coord = None
        self._inject_cb = None  # Worker.checkpoint_now (chain-wide)
        self._last_ckpt = 0
        self._restore_position = None
        # overload admission control (windflow_tpu.overload): the
        # governor installs an AdmissionGate here while shedding; the
        # default hot path pays one is-None check per push. Shedding
        # happens HERE — before the emitter, the barriers and the
        # exactly-once plane — so shed records never enter a channel,
        # a snapshot or a sink transaction.
        self._gate = None
        # records that were buffered in an admission gate at snapshot
        # time (restore_state stashes them; run_source re-emits before
        # the functor resumes — the cursor is already past them)
        self._restore_gate_pending = None

    def process(self, payload, ts, wm, tag):  # pragma: no cover
        raise WindFlowError("Source has no input")

    # -- checkpointing -----------------------------------------------------
    def bind_checkpoint(self, coordinator, inject_cb) -> None:
        """Wired by the source Worker when checkpointing is enabled."""
        self._coord = coordinator
        self._inject_cb = inject_cb
        self._last_ckpt = coordinator.requested_id

    def request_checkpoint(self):
        if self._coord is None:
            return None
        cid = self._coord.trigger(force=True)
        self._maybe_inject()
        return cid

    def _maybe_inject(self) -> None:
        from ..message import Barrier
        cid = self._coord.requested_id
        if cid > self._last_ckpt:
            self._last_ckpt = cid
            self._inject_cb(Barrier(cid))

    def final_checkpoint(self) -> None:
        """Called by the worker when the generation loop ends, before the
        EOS cascade: an epoch opened while we were finishing still gets
        this source's barrier + (final) position snapshot."""
        if self._coord is not None:
            self._maybe_inject()

    def snapshot_state(self) -> dict:
        """Base state + the functor's replay position when it speaks the
        replayable protocol: ``snapshot_position([ctx])`` returning any
        picklable cursor, and ``restore(position[, ctx])`` on restart.
        The position must describe exactly the tuples pushed so far —
        barriers inject at push boundaries, so a one-tuple-per-increment
        cursor gives exact resume; coarser cursors give at-least-once."""
        st = super().snapshot_state()
        st["shipped"] = self.stats.inputs_received
        # shed accounting rides the snapshot: a restore/rescale must not
        # zero counters for records that are gone for good
        st["shed_records"] = self.stats.shed_records
        st["shed_bytes"] = self.stats.shed_bytes
        snap = getattr(self.op.func, "snapshot_position", None)
        if snap is not None:
            st["position"] = (snap(self.context) if arity(snap) >= 1
                              else snap())
        gate = self._gate
        if gate is not None and gate.pending:
            # records accepted into the gate but still awaiting tokens:
            # the position above already covers them (the cursor
            # advanced when they were pushed), so they must ride the
            # snapshot — a restore that dropped them would lose records
            # that are neither admitted nor shed
            st["gate_pending"] = gate.snapshot_pending()
        return st

    def restore_state(self, state: dict) -> None:
        super().restore_state(state)
        self._restore_position = state.get("position")
        self._restore_gate_pending = state.get("gate_pending")
        self.stats.inputs_received = state.get("shipped", 0)
        self.stats.shed_records = state.get("shed_records", 0)
        self.stats.shed_bytes = state.get("shed_bytes", 0)

    def run_source(self) -> None:
        """Run the user generation loop to completion (then the worker
        triggers the EOS cascade, ``wf/source.hpp:114-129``)."""
        shipper = SourceShipper(self)
        if self._restore_position is not None:
            restore = getattr(self.op.func, "restore", None)
            if restore is None:
                raise WindFlowError(
                    f"{self.op.name}: checkpoint restore needs a replayable "
                    "source functor (snapshot_position()/restore(position)); "
                    "this one has no restore()")
            if arity(restore) >= 2:
                restore(self._restore_position, self.context)
            else:
                restore(self._restore_position)
        pend = self._restore_gate_pending
        if pend:
            # records the snapshot caught inside an admission gate's
            # buffer: the restored cursor is already past them, so the
            # functor will never regenerate them — re-emit (with their
            # accept-time watermarks) before the loop resumes, ahead of
            # everything the replay produces
            self._restore_gate_pending = None
            for p, t, w in pend:
                self._advance_wm(w)
                self._emit_admitted(p, t)
        if self.op._riched:
            self.op.func(shipper, self.context)
        else:
            self.op.func(shipper)
        gate = self._gate
        if gate is not None and gate.pending:
            # end-of-stream with records still buffered in the admission
            # gate: they were ACCEPTED (only awaiting tokens) — emit them
            # rather than silently dropping accepted data at EOS
            for p, t, w in gate.drain_pending():
                self._advance_wm(w)
                self._emit_admitted(p, t)

    def ship(self, payload: Any, ts: int, wm: int) -> None:
        # barrier BEFORE the tuple: the functor's cursor has not advanced
        # past the tuple being pushed (the natural ``v = pos; push(v);
        # pos += 1`` style), so the snapshot position covers exactly the
        # tuples already emitted and the in-flight one replays post-restore
        if self._coord is not None \
                and self._coord.requested_id != self._last_ckpt:
            self._maybe_inject()
        gate = self._gate
        if gate is not None:
            # the watermark rides each record through the gate: while
            # records wait in its buffer ``cur_wm`` must NOT advance
            # past them, or they would emit under a watermark newer
            # than their ts and downstream windows the gate chose to
            # ADMIT them into would already be closed
            for p, t, w in gate.offer(payload, ts, wm):
                self._advance_wm(w)
                self._emit_admitted(p, t)
            if gate.released and not gate.pending:
                self._gate = None  # recovery: back to the ungated path
            return
        if wm > self.cur_wm:
            self.cur_wm = wm
        self._emit_admitted(payload, ts)

    def _emit_admitted(self, payload: Any, ts: int) -> None:
        st = self.stats
        st.inputs_received += 1
        if not (st.inputs_received & self._trace_mask):
            self.emitter.trace_ts = current_time_usecs()
        self.emitter.emit(payload, ts, self.cur_wm)

    def ship_columns(self, cols, ts_arr, wm: int) -> None:
        if self._coord is not None \
                and self._coord.requested_id != self._last_ckpt:
            self._maybe_inject()  # before the push, like ship()
        gate = self._gate
        if gate is not None:
            if gate.pending:
                # row-path records accepted into the buffer precede
                # this batch: emit them (with their accept-time
                # watermarks) first — discarding them here would lose
                # accepted records, emitting them later would reorder
                for p, t, w in gate.drain_pending():
                    self._advance_wm(w)
                    self._emit_admitted(p, t)
            if gate.released:
                self._gate = None  # recovery: back to the ungated path
            else:
                cols, ts_arr, n = gate.offer_columns(cols, ts_arr)
                if n == 0:
                    return
        if wm > self.cur_wm:
            self.cur_wm = wm
        self.stats.inputs_received += len(ts_arr)
        if self.stats.sample_every:
            # columnar pushes sample at push granularity (one stamp per
            # call): per-row stamping would defeat the no-Python fast path
            self.emitter.trace_ts = current_time_usecs()
        self.emitter.emit_columns(cols, ts_arr, self.cur_wm)
