"""Source operator and Source_Shipper.

Parity: ``wf/source.hpp:55-163`` (user functor drives the shipper, then EOS)
and ``wf/source_shipper.hpp`` (``push`` for INGRESS_TIME at L171/210,
``pushWithTimestamp``/``setNextWatermark`` for EVENT_TIME at L248/289/328).
Timestamps are microseconds; in DEFAULT mode with ingress time the watermark
equals the tuple timestamp (monotone because "now" is monotone).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from ..basic import (ExecutionMode, OpType, RoutingMode, TimePolicy,
                     WindFlowError, current_time_usecs)
from .base import BasicOperator, BasicReplica, arity


class SourceShipper:
    """User-visible push API for Source functors."""

    def __init__(self, replica: "SourceReplica") -> None:
        self._r = replica
        self._next_wm = 0
        self._epoch = current_time_usecs()

    # -- INGRESS_TIME ------------------------------------------------------
    def push(self, payload: Any) -> None:
        if self._r.op.time_policy is not TimePolicy.INGRESS_TIME:
            raise WindFlowError("push() requires INGRESS_TIME; use "
                                "push_with_timestamp() under EVENT_TIME")
        ts = current_time_usecs() - self._epoch
        wm = ts if self._r.op.execution_mode is ExecutionMode.DEFAULT else 0
        self._r.ship(payload, ts, wm)

    # -- EVENT_TIME --------------------------------------------------------
    def push_with_timestamp(self, payload: Any, ts: int) -> None:
        if self._r.op.time_policy is not TimePolicy.EVENT_TIME:
            raise WindFlowError("push_with_timestamp() requires EVENT_TIME")
        ts = int(ts)
        st = self._r.stats
        if ts > st.wm_max_source_ts:  # event-time lag numerator
            st.wm_max_source_ts = ts
        self._r.ship(payload, ts, self._next_wm)

    def set_next_watermark(self, wm: int) -> None:
        if wm < self._next_wm:
            raise WindFlowError("watermarks must be non-decreasing")
        self._next_wm = int(wm)

    # -- columnar fast path ------------------------------------------------
    def push_columns(self, cols, ts=None) -> None:
        """Push a whole COLUMN BATCH (dict of equal-length 1-D numpy
        arrays) in one call. On a device edge this skips per-tuple Python
        entirely — the arrays are padded and shipped as one ``BatchTPU``
        (the reference's per-tuple shipper has no analog; this is the
        tpu-first staging surface). On a CPU edge rows materialize as
        dicts. INGRESS_TIME stamps every row "now"; EVENT_TIME requires
        ``ts`` (int64 array, same length)."""
        n = -1
        for v in cols.values():
            if n < 0:
                n = len(v)
            elif len(v) != n:
                raise WindFlowError("push_columns: ragged columns")
        if n <= 0:
            return
        if self._r.op.time_policy is TimePolicy.INGRESS_TIME:
            if ts is not None:
                raise WindFlowError("push_columns(ts=...) requires "
                                    "EVENT_TIME")
            now = current_time_usecs() - self._epoch
            ts_arr = np.full(n, now, dtype=np.int64)
            wm = (now if self._r.op.execution_mode is ExecutionMode.DEFAULT
                  else 0)
        else:
            if ts is None:
                raise WindFlowError("push_columns under EVENT_TIME needs a "
                                    "ts array")
            ts_arr = np.asarray(ts, dtype=np.int64)
            if len(ts_arr) != n:
                raise WindFlowError("push_columns: ts length mismatch")
            st = self._r.stats
            m = int(ts_arr.max())
            if m > st.wm_max_source_ts:  # event-time lag numerator
                st.wm_max_source_ts = m
            wm = self._next_wm
        self._r.ship_columns(cols, ts_arr, wm)

    # -- checkpointing -----------------------------------------------------
    def request_checkpoint(self) -> Optional[int]:
        """Force an aligned checkpoint NOW (at this tuple boundary) instead
        of waiting for the coordinator's interval — the deterministic
        trigger used by tests and drain-style shutdowns. Returns the new
        checkpoint id, or None when checkpointing is not enabled."""
        return self._r.request_checkpoint()

    # convenience used by generators/tests
    @property
    def current_watermark(self) -> int:
        return self._next_wm


class Source(BasicOperator):
    """Parallel replicas are independent generators; ``func(shipper[, ctx])``
    is called once per replica and runs its own loop."""

    op_type = OpType.SOURCE

    def __init__(self, func: Callable, name: str = "source",
                 parallelism: int = 1, output_batch_size: int = 0) -> None:
        super().__init__(name, parallelism, RoutingMode.NONE,
                         output_batch_size=output_batch_size)
        self.func = func
        self._riched = arity(func) >= 2

    def build_replicas(self) -> None:
        self.replicas = [SourceReplica(self, i) for i in range(self.parallelism)]


class SourceReplica(BasicReplica):
    def __init__(self, op, idx):
        super().__init__(op, idx)
        # sampled latency tracing (monitoring/tracing.py): every Nth
        # shipped tuple carries a wall-clock origin stamp. The gate is
        # a single integer AND against this mask — sample_every is a
        # power of two, and a mask of -1 (sampling off) can never make
        # ``inputs_received & mask`` zero, so the hot path costs the
        # same with tracing off or sampling 1/64
        self._trace_mask = self.stats.sample_every - 1
        # aligned checkpointing (windflow_tpu.checkpoint): the coordinator
        # bumps an epoch; we notice at the next tuple boundary, snapshot
        # our replay position and inject the barrier downstream
        self._coord = None
        self._inject_cb = None  # Worker.checkpoint_now (chain-wide)
        self._last_ckpt = 0
        # set while a multi-chunk column block is mid-flight: barriers
        # may only land at BLOCK boundaries (the functor's cursor moves
        # per block, so a mid-block barrier would replay already-emitted
        # chunks after a restore — see ColumnarSourceReplica._drive)
        self._inject_suppressed = False
        self._restore_position = None
        # overload admission control (windflow_tpu.overload): the
        # governor installs an AdmissionGate here while shedding; the
        # default hot path pays one is-None check per push. Shedding
        # happens HERE — before the emitter, the barriers and the
        # exactly-once plane — so shed records never enter a channel,
        # a snapshot or a sink transaction.
        self._gate = None
        # records that were buffered in an admission gate at snapshot
        # time (restore_state stashes them; run_source re-emits before
        # the functor resumes — the cursor is already past them)
        self._restore_gate_pending = None

    def process(self, payload, ts, wm, tag):  # pragma: no cover
        raise WindFlowError("Source has no input")

    # -- checkpointing -----------------------------------------------------
    def bind_checkpoint(self, coordinator, inject_cb) -> None:
        """Wired by the source Worker when checkpointing is enabled."""
        self._coord = coordinator
        self._inject_cb = inject_cb
        self._last_ckpt = coordinator.requested_id

    def request_checkpoint(self):
        if self._coord is None:
            return None
        cid = self._coord.trigger(force=True)
        self._maybe_inject()
        return cid

    def _maybe_inject(self) -> None:
        from ..message import Barrier
        cid = self._coord.requested_id
        if cid > self._last_ckpt:
            self._last_ckpt = cid
            self._inject_cb(Barrier(cid))

    def final_checkpoint(self) -> None:
        """Called by the worker when the generation loop ends, before the
        EOS cascade: an epoch opened while we were finishing still gets
        this source's barrier + (final) position snapshot."""
        if self._coord is not None:
            self._maybe_inject()

    def snapshot_state(self) -> dict:
        """Base state + the functor's replay position when it speaks the
        replayable protocol: ``snapshot_position([ctx])`` returning any
        picklable cursor, and ``restore(position[, ctx])`` on restart.
        The position must describe exactly the tuples pushed so far —
        barriers inject at push boundaries, so a one-tuple-per-increment
        cursor gives exact resume; coarser cursors give at-least-once."""
        st = super().snapshot_state()
        st["shipped"] = self.stats.inputs_received
        # shed accounting rides the snapshot: a restore/rescale must not
        # zero counters for records that are gone for good
        st["shed_records"] = self.stats.shed_records
        st["shed_bytes"] = self.stats.shed_bytes
        snap = getattr(self.op.func, "snapshot_position", None)
        if snap is not None:
            st["position"] = (snap(self.context) if arity(snap) >= 1
                              else snap())
        gate = self._gate
        if gate is not None and gate.pending:
            # records accepted into the gate but still awaiting tokens:
            # the position above already covers them (the cursor
            # advanced when they were pushed), so they must ride the
            # snapshot — a restore that dropped them would lose records
            # that are neither admitted nor shed
            st["gate_pending"] = gate.snapshot_pending()
        return st

    def restore_state(self, state: dict) -> None:
        super().restore_state(state)
        self._restore_position = state.get("position")
        self._restore_gate_pending = state.get("gate_pending")
        self.stats.inputs_received = state.get("shipped", 0)
        self.stats.shed_records = state.get("shed_records", 0)
        self.stats.shed_bytes = state.get("shed_bytes", 0)

    def run_source(self) -> None:
        """Run the user generation loop to completion (then the worker
        triggers the EOS cascade, ``wf/source.hpp:114-129``)."""
        shipper = SourceShipper(self)
        if self._restore_position is not None:
            restore = getattr(self.op.func, "restore", None)
            if restore is None:
                raise WindFlowError(
                    f"{self.op.name}: checkpoint restore needs a replayable "
                    "source functor (snapshot_position()/restore(position)); "
                    "this one has no restore()")
            if arity(restore) >= 2:
                restore(self._restore_position, self.context)
            else:
                restore(self._restore_position)
        pend = self._restore_gate_pending
        if pend:
            # records the snapshot caught inside an admission gate's
            # buffer: the restored cursor is already past them, so the
            # functor will never regenerate them — re-emit (with their
            # accept-time watermarks) before the loop resumes, ahead of
            # everything the replay produces
            self._restore_gate_pending = None
            for p, t, w in pend:
                self._advance_wm(w)
                self._emit_admitted(p, t)
        self._drive(shipper)
        gate = self._gate
        if gate is not None and gate.pending:
            # end-of-stream with records still buffered in the admission
            # gate: they were ACCEPTED (only awaiting tokens) — emit them
            # rather than silently dropping accepted data at EOS
            for p, t, w in gate.drain_pending():
                self._advance_wm(w)
                self._emit_admitted(p, t)

    def _drive(self, shipper: SourceShipper) -> None:
        """Run the user functor (the generation loop). Subclasses with a
        different functor contract (block sources) override this; the
        restore / gate-pending / EOS-drain bracket in ``run_source``
        stays shared."""
        if self.op._riched:
            self.op.func(shipper, self.context)
        else:
            self.op.func(shipper)

    def ship(self, payload: Any, ts: int, wm: int) -> None:
        # barrier BEFORE the tuple: the functor's cursor has not advanced
        # past the tuple being pushed (the natural ``v = pos; push(v);
        # pos += 1`` style), so the snapshot position covers exactly the
        # tuples already emitted and the in-flight one replays post-restore
        if self._coord is not None \
                and self._coord.requested_id != self._last_ckpt:
            self._maybe_inject()
        gate = self._gate
        if gate is not None:
            # the watermark rides each record through the gate: while
            # records wait in its buffer ``cur_wm`` must NOT advance
            # past them, or they would emit under a watermark newer
            # than their ts and downstream windows the gate chose to
            # ADMIT them into would already be closed
            for p, t, w in gate.offer(payload, ts, wm):
                self._advance_wm(w)
                self._emit_admitted(p, t)
            if gate.released and not gate.pending:
                self._gate = None  # recovery: back to the ungated path
            return
        if wm > self.cur_wm:
            self.cur_wm = wm
            st = self.stats
            st.wm_current = wm
            st.wm_advances += 1
        self._emit_admitted(payload, ts)

    def _emit_admitted(self, payload: Any, ts: int) -> None:
        st = self.stats
        st.inputs_received += 1
        if not (st.inputs_received & self._trace_mask):
            self.emitter.trace_ts = current_time_usecs()
        self.emitter.emit(payload, ts, self.cur_wm)

    def ship_columns(self, cols, ts_arr, wm: int) -> None:
        t0_ns = time.perf_counter_ns()
        if self._coord is not None and not self._inject_suppressed \
                and self._coord.requested_id != self._last_ckpt:
            self._maybe_inject()  # before the push, like ship()
        gate = self._gate
        if gate is not None:
            if gate.pending:
                # row-path records accepted into the buffer precede
                # this batch: emit them (with their accept-time
                # watermarks) first — discarding them here would lose
                # accepted records, emitting them later would reorder
                for p, t, w in gate.drain_pending():
                    self._advance_wm(w)
                    self._emit_admitted(p, t)
            if gate.released:
                self._gate = None  # recovery: back to the ungated path
            else:
                cols, ts_arr, n = gate.offer_columns(cols, ts_arr)
                if n == 0:
                    return
        if wm > self.cur_wm:
            self.cur_wm = wm
            self.stats.wm_current = wm
            self.stats.wm_advances += 1
        st = self.stats
        n = len(ts_arr)
        base = st.inputs_received
        st.inputs_received = base + n
        trace_rows = None
        se = st.sample_every
        if se:
            # vectorized mask gate: the traced cohort is exactly the rows
            # the row path would stamp — global positions base+1+i that
            # are multiples of sample_every — computed as one arange, all
            # sharing one wall-clock stamp (per-row clock reads would
            # defeat the no-Python fast path)
            first = (-(base + 1)) % se
            if first < n:
                trace_rows = np.arange(first, n, se)
                self.emitter.trace_ts = current_time_usecs()
        self.emitter.emit_columns(cols, ts_arr, self.cur_wm, trace_rows)
        st.note_ingest_block(n, time.perf_counter_ns() - t0_ns)


class Columnar_Source(Source):
    """Schema-declared BLOCK source: the functor is a generator of column
    blocks instead of a per-tuple push loop. Called as ``func([ctx])``,
    it yields ``cols`` (a dict of equal-length 1-D arrays; INGRESS_TIME),
    ``(cols, ts)`` (int64 microsecond timestamps; EVENT_TIME) or
    ``(cols, ts, wm)`` (also advances the watermark before the push).
    Blocks ride ``SourceReplica.ship_columns`` — barriers, the admission
    gate, trace stamps and watermark triples all operate on block
    boundaries, and on a device edge no per-tuple Python runs at all.

    ``block_size`` (builder: ``with_block_size``; env default
    ``WF_INGEST_BLOCK_ROWS``) re-chunks oversized yields; barriers still
    land only at FUNCTOR-YIELD boundaries so a replayable functor's
    block-granular cursor stays exact. ``schema`` (name -> numpy dtype)
    canonicalizes each declared column's dtype at the edge."""

    def __init__(self, func: Callable, name: str = "columnar_source",
                 parallelism: int = 1, output_batch_size: int = 0,
                 block_size: int = 0,
                 schema: Optional[Dict[str, Any]] = None) -> None:
        super().__init__(func, name, parallelism, output_batch_size)
        if block_size <= 0:
            try:
                block_size = int(os.environ.get("WF_INGEST_BLOCK_ROWS", "0"))
            except ValueError:
                block_size = 0
        self.block_size = max(0, block_size)
        self.block_schema = ({k: np.dtype(v) for k, v in schema.items()}
                             if schema else None)
        # the block functor takes (ctx) not (shipper[, ctx]): rich means
        # it wants the RuntimeContext
        self._riched = arity(func) >= 1

    def build_replicas(self) -> None:
        self.replicas = [ColumnarSourceReplica(self, i)
                         for i in range(self.parallelism)]


class ColumnarSourceReplica(SourceReplica):
    """Drives a block functor; everything else (restore, gate pending,
    EOS gate drain, snapshot semantics) is the row replica's."""

    def _drive(self, shipper: SourceShipper) -> None:
        op = self.op
        it = op.func(self.context) if op._riched else op.func()
        if it is None:
            return
        bs = op.block_size
        schema = op.block_schema
        for block in it:
            cols, ts, wm = _normalize_block(block)
            if schema is not None:
                # asarray is copy-free when the dtype already matches
                cols = {k: (np.asarray(v, dtype=schema[k])
                            if k in schema else v)
                        for k, v in cols.items()}
            if wm is not None:
                shipper.set_next_watermark(int(wm))
            n = 0
            for v in cols.values():
                n = len(v)
                break
            if bs and n > bs:
                # re-chunk to the declared block size; suppress barrier
                # injection between chunks — the functor's cursor covers
                # whole blocks, so a mid-block barrier would double-emit
                # the leading chunks after a restore
                off = 0
                try:
                    while off < n:
                        end = min(off + bs, n)
                        shipper.push_columns(
                            {k: v[off:end] for k, v in cols.items()},
                            ts[off:end] if ts is not None else None)
                        self._inject_suppressed = True
                        off = end
                finally:
                    self._inject_suppressed = False
            else:
                shipper.push_columns(cols, ts)


def _normalize_block(block):
    """(cols, ts_or_None, wm_or_None) from a block functor yield."""
    if isinstance(block, dict):
        return block, None, None
    if isinstance(block, tuple):
        if len(block) == 2:
            return block[0], block[1], None
        if len(block) == 3:
            return block
    raise WindFlowError(
        "Columnar_Source functor must yield cols dicts or "
        "(cols, ts[, wm]) tuples, got " + type(block).__name__)


class ArrayBlockSource:
    """Replayable block functor over in-memory numpy columns: yields
    ``block_size``-row slices. The cursor advances AFTER each yield, so
    a barrier injected during the push snapshots a position that covers
    exactly the blocks already shipped — the in-flight block replays
    post-restore (exactly-once with aligned checkpointing)."""

    def __init__(self, cols: Dict[str, Any], ts: Optional[Any] = None,
                 block_size: int = 8192) -> None:
        if block_size <= 0:
            raise WindFlowError("ArrayBlockSource: block_size must be > 0")
        self._cols = {k: np.asarray(v) for k, v in cols.items()}
        n = -1
        for v in self._cols.values():
            if n < 0:
                n = len(v)
            elif len(v) != n:
                raise WindFlowError("ArrayBlockSource: ragged columns")
        self._ts = None if ts is None else np.asarray(ts, dtype=np.int64)
        if self._ts is not None and len(self._ts) != max(n, 0):
            raise WindFlowError("ArrayBlockSource: ts length mismatch")
        self._n = max(n, 0)
        self._bs = block_size
        self._pos = 0

    def __call__(self):
        while self._pos < self._n:
            lo = self._pos
            hi = min(lo + self._bs, self._n)
            cols = {k: v[lo:hi] for k, v in self._cols.items()}
            if self._ts is None:
                yield cols
            else:
                yield cols, self._ts[lo:hi]
            self._pos = hi

    # replayable-source protocol (block-granular cursor)
    def snapshot_position(self) -> int:
        return self._pos

    def restore(self, position: int) -> None:
        self._pos = int(position)


def arrow_block_source(table, ts_column: Optional[str] = None,
                       block_size: int = 8192) -> ArrayBlockSource:
    """Block functor over a pyarrow Table / RecordBatch: columns convert
    to numpy once (zero-copy where the Arrow layout allows) and stream
    as ``ArrayBlockSource`` blocks. Gated on pyarrow being installed."""
    try:
        import pyarrow  # noqa: F401
    except Exception as exc:  # pragma: no cover - depends on environment
        raise WindFlowError(
            "arrow_block_source requires pyarrow, which is not "
            "available in this environment") from exc
    tbl = table.combine_chunks() if hasattr(table, "combine_chunks") else table
    cols = {}
    for name in tbl.schema.names:
        col = tbl.column(name) if hasattr(tbl, "column") else tbl[name]
        try:
            cols[name] = col.to_numpy(zero_copy_only=True)
        except Exception:
            cols[name] = col.to_numpy(zero_copy_only=False)
    ts = cols.pop(ts_column) if ts_column else None
    return ArrayBlockSource(cols, ts, block_size)
