"""FlatFAT: flat fixed-size aggregation tree over a circular buffer
(Tangwongsan et al., VLDB'15; reference ``wf/flatfat.hpp:54-348``).

O(log n) insert/evict of sliding-window elements with an associative —
not necessarily commutative — combine. The tree is an array of 2*capacity
slots (capacity = power of two): leaves in [capacity, 2*capacity), internal
nodes above; ``None`` is the identity. Range results combine left-to-right
in logical (insertion) order, so non-commutative combines are safe: the
query walks the standard iterative segment-tree decomposition keeping
separate left/right accumulators (the reference keeps prefix/suffix arrays
for the same purpose, ``flatfat.hpp:85-132``).

The TPU sibling (``windflow_tpu.tpu.flatfat_tpu``) keeps the same layout as
a batched device array, updating levels with vectorized gathers.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional


def next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


class FlatFAT:
    def __init__(self, capacity: int, combine: Callable[[Any, Any], Any]) -> None:
        self.capacity = next_pow2(max(2, capacity))
        self.combine = combine
        self.tree: List[Optional[Any]] = [None] * (2 * self.capacity)
        self.head = 0  # physical slot of the logical first element
        self.size = 0

    # -- updates -----------------------------------------------------------
    def _update_path(self, pos: int) -> None:
        i = (self.capacity + pos) >> 1
        while i >= 1:
            l, r = self.tree[2 * i], self.tree[2 * i + 1]
            if l is None:
                self.tree[i] = r
            elif r is None:
                self.tree[i] = l
            else:
                self.tree[i] = self.combine(l, r)
            i >>= 1

    def push(self, value: Any) -> None:
        """Append at the logical tail."""
        if self.size >= self.capacity:
            raise OverflowError("FlatFAT full")
        pos = (self.head + self.size) % self.capacity
        self.tree[self.capacity + pos] = value
        self.size += 1
        self._update_path(pos)

    def pop(self, k: int = 1) -> None:
        """Evict k elements from the logical head."""
        k = min(k, self.size)
        for _ in range(k):
            self.tree[self.capacity + self.head] = None
            self._update_path(self.head)
            self.head = (self.head + 1) % self.capacity
            self.size -= 1

    # -- queries -----------------------------------------------------------
    def _acc(self, a: Optional[Any], b: Optional[Any]) -> Optional[Any]:
        if a is None:
            return b
        if b is None:
            return a
        return self.combine(a, b)

    def _query_linear(self, lo: int, hi: int) -> Optional[Any]:
        """Ordered combine of physical leaf range [lo, hi)."""
        left: Optional[Any] = None
        right: Optional[Any] = None
        l = self.capacity + lo
        r = self.capacity + hi
        while l < r:
            if l & 1:
                left = self._acc(left, self.tree[l])
                l += 1
            if r & 1:
                r -= 1
                right = self._acc(self.tree[r], right)
            l >>= 1
            r >>= 1
        return self._acc(left, right)

    def query_logical(self, start: int, length: int) -> Optional[Any]:
        """Ordered combine of ``length`` elements beginning at logical offset
        ``start`` from the head (wrapping the circular buffer)."""
        if length <= 0 or self.size == 0:
            return None
        length = min(length, self.size - start)
        lo = (self.head + start) % self.capacity
        if lo + length <= self.capacity:
            return self._query_linear(lo, lo + length)
        first = self._query_linear(lo, self.capacity)
        second = self._query_linear(0, (lo + length) % self.capacity)
        return self._acc(first, second)

    def query_all(self) -> Optional[Any]:
        return self.query_logical(0, self.size)
