"""Window operators: Keyed_Windows, Parallel_Windows, Paned_Windows,
MapReduce_Windows.

Parity map:
- Keyed_Windows (``wf/keyed_windows.hpp``): KEYBY routing, each replica runs
  the engine in role SEQ over its key partition.
- Parallel_Windows (``wf/parallel_windows.hpp``): BROADCAST routing, windows
  round-robined across replicas by global window id; CB+SEQ is rejected in
  DEFAULT mode (arrival-order nondeterminism, ``parallel_windows.hpp:119-123``).
- Paned_Windows (``wf/paned_windows.hpp:140-141``): PLQ = Parallel_Windows
  over tumbling panes of gcd(win, slide); WLQ = count-based Parallel_Windows
  over pane results (win/gcd, slide/gcd), fed through an ID-sequencing
  collector. Requires win > slide.
- MapReduce_Windows (``wf/mapreduce_windows.hpp:140-141``): MAP =
  Parallel_Windows with unchanged win/slide where each replica folds its
  ``ts % p`` tuple partition of every window; REDUCE = count-based
  Parallel_Windows with win=slide=map_parallelism combining the partials.

Composite operators expose ``sub_operators``; MultiPipe.add expands them
into consecutive stages (the reference nests them inside one FastFlow
operator; the stage split is identical at runtime).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional

from ..basic import (ExecutionMode, OpType, RoutingMode, TimePolicy, WinRole,
                     WinType, WindFlowError)
from .base import BasicOperator, BasicReplica, arity
from .window_engine import WindowEngine, WinResult


class _WindowReplica(BasicReplica):
    """Hosts a WindowEngine; wires emission and punctuation-driven firing."""

    def __init__(self, op: "_WindowOperatorBase", idx: int) -> None:
        super().__init__(op, idx)
        self.engine = op._make_engine(idx, self.context)
        # unified late accounting: the engine classifies every tuple as
        # on-time / late-admitted / late-dropped against this record
        self.engine.stats = self.stats

    def _emit_cb(self, payload: Any, ts: int, wm: int,
                 msg_id: Optional[int]) -> None:
        self.emitter.emit(payload, ts, wm, msg_id)

    def process(self, payload, ts, wm, tag):
        if (self.engine.role in (WinRole.WLQ, WinRole.REDUCE)
                and self.op.execution_mode is ExecutionMode.DEFAULT):
            ts = wm  # reference window_replica.hpp:214-217
        self.engine.process(payload, ts, wm, self._emit_cb)

    def on_punctuation(self, wm: int) -> None:
        self.engine.on_watermark(self.cur_wm, self._emit_cb)
        super().on_punctuation(wm)

    def flush_on_termination(self) -> None:
        self.engine.flush(self._emit_cb)
        self.stats.inputs_ignored += self.engine.ignored_tuples

    # -- checkpointing -------------------------------------------------------
    def snapshot_state(self) -> dict:
        st = super().snapshot_state()
        st["engine"] = self.engine.snapshot_state()
        return st

    def restore_state(self, state: dict) -> None:
        super().restore_state(state)
        eng = state.get("engine")
        if eng is not None:
            self.engine.restore_state(eng)


class _WindowOperatorBase(BasicOperator):
    op_type = OpType.WIN

    def __init__(self, win_func: Callable, key_extractor: Callable,
                 win_len: int, slide_len: int, win_type: WinType,
                 lateness: int, incremental: bool, initial_value: Any,
                 name: str, parallelism: int, input_routing: RoutingMode,
                 output_batch_size: int, role: WinRole = WinRole.SEQ,
                 tb_origin=None) -> None:
        if win_len <= 0 or slide_len <= 0:
            raise WindFlowError(f"{name}: window length and slide must be > 0")
        super().__init__(name, parallelism, input_routing, key_extractor,
                         output_batch_size)
        self.win_func = win_func
        self.win_len = win_len
        self.slide_len = slide_len
        self.win_type = win_type
        self.lateness = lateness
        self.incremental = incremental
        self.initial_value = initial_value
        self.role = role
        # reference-compat TB numbering (wf/window_replica.hpp:253-283):
        # origin-anchored windows with identity-valued empty fires
        self.tb_origin = tb_origin
        n_args = arity(win_func)
        self._riched = n_args >= (3 if incremental else 2)

    @property
    def is_chainable(self) -> bool:
        return False

    def build_replicas(self) -> None:
        self.replicas = [_WindowReplica(self, i) for i in range(self.parallelism)]

    def _make_engine(self, idx: int, context) -> WindowEngine:
        raise NotImplementedError


class Keyed_Windows(_WindowOperatorBase):
    def __init__(self, win_func: Callable, key_extractor: Callable,
                 win_len: int, slide_len: int,
                 win_type: WinType = WinType.CB, lateness: int = 0,
                 incremental: bool = False, initial_value: Any = None,
                 name: str = "keyed_windows", parallelism: int = 1,
                 output_batch_size: int = 0, tb_origin=None) -> None:
        if key_extractor is None:
            raise WindFlowError("Keyed_Windows requires a key extractor")
        super().__init__(win_func, key_extractor, win_len, slide_len, win_type,
                         lateness, incremental, initial_value, name,
                         parallelism, RoutingMode.KEYBY, output_batch_size,
                         WinRole.SEQ, tb_origin)

    def _make_engine(self, idx: int, context) -> WindowEngine:
        return WindowEngine(self.win_type, self.win_len, self.slide_len,
                            self.lateness, self.key_extractor, self.win_func,
                            self.incremental, self.initial_value, WinRole.SEQ,
                            0, 1, 1, 0, self.execution_mode, self._riched,
                            context, tb_origin=self.tb_origin)


class Parallel_Windows(_WindowOperatorBase):
    def __init__(self, win_func: Callable, key_extractor: Callable,
                 win_len: int, slide_len: int,
                 win_type: WinType = WinType.TB, lateness: int = 0,
                 incremental: bool = False, initial_value: Any = None,
                 name: str = "parallel_windows", parallelism: int = 1,
                 output_batch_size: int = 0,
                 role: WinRole = WinRole.SEQ, tb_origin=None) -> None:
        super().__init__(win_func, key_extractor, win_len, slide_len, win_type,
                         lateness, incremental, initial_value, name,
                         parallelism, RoutingMode.BROADCAST, output_batch_size,
                         role, tb_origin)

    def configure(self, execution_mode, time_policy) -> None:
        super().configure(execution_mode, time_policy)
        # The reference only rejects role SEQ (parallel_windows.hpp:119-123),
        # but PLQ/MAP have the identical hazard: count-based assignment uses
        # each broadcast replica's own arrival order, which differs across
        # replicas in DEFAULT mode. We reject all three (stricter-than-
        # reference, silently-wrong-results otherwise); WLQ/REDUCE are safe
        # behind the ID-sequencing collector.
        if (self.win_type is WinType.CB
                and self.role in (WinRole.SEQ, WinRole.PLQ, WinRole.MAP)
                and execution_mode is ExecutionMode.DEFAULT):
            raise WindFlowError(
                f"{self.name}: count-based windows over BROADCAST "
                "distribution are nondeterministic in DEFAULT mode; use "
                "DETERMINISTIC mode or Keyed_Windows")

    def _make_engine(self, idx: int, context) -> WindowEngine:
        if self.role is WinRole.MAP:
            return WindowEngine(self.win_type, self.win_len, self.slide_len,
                                self.lateness, self.key_extractor,
                                self.win_func, self.incremental,
                                self.initial_value, WinRole.MAP, 0, 1,
                                self.parallelism, idx, self.execution_mode,
                                self._riched, context,
                                tb_origin=self.tb_origin)
        return WindowEngine(self.win_type, self.win_len, self.slide_len,
                            self.lateness, self.key_extractor, self.win_func,
                            self.incremental, self.initial_value, self.role,
                            idx, self.parallelism, 1, 0, self.execution_mode,
                            self._riched, context, tb_origin=self.tb_origin)


def _wrap_stage2_func(user_func: Callable, incremental: bool) -> Callable:
    """Second-stage (WLQ/REDUCE) functions consume the VALUES of first-stage
    WinResults (the reference feeds user result_t objects straight through).
    The wrapper's arity mirrors the user function's so riched (context-taking)
    variants are still detected downstream."""
    riched = arity(user_func) >= (3 if incremental else 2)
    if incremental:
        if riched:
            def wrapped(res, acc, ctx):
                return user_func(res.value, acc, ctx)
        else:
            def wrapped(res, acc):
                return user_func(res.value, acc)
    else:
        if riched:
            def wrapped(results, ctx):
                return user_func([r.value for r in results], ctx)
        else:
            def wrapped(results):
                return user_func([r.value for r in results])
    return wrapped


def _result_key(r: WinResult) -> Any:
    return r.key


class _CompositeWindows(BasicOperator):
    """Two internal Parallel_Windows stages expanded by MultiPipe.add."""

    op_type = OpType.WIN

    def __init__(self, name: str, stage1: Parallel_Windows,
                 stage2: Parallel_Windows) -> None:
        super().__init__(name, stage1.parallelism + stage2.parallelism,
                         RoutingMode.BROADCAST, stage1.key_extractor, 0)
        stage2.collector_override = "id"
        self.sub_operators = [stage1, stage2]

    def build_replicas(self) -> None:  # pragma: no cover - expanded before build
        raise WindFlowError(f"{self.name}: composite operator must be "
                            "expanded by MultiPipe.add")


class Paned_Windows(_CompositeWindows):
    """PLQ over gcd-panes + count-based WLQ over pane results
    (``wf/paned_windows.hpp:67-213``)."""

    def __init__(self, plq_func: Callable, wlq_func: Callable,
                 key_extractor: Callable, win_len: int, slide_len: int,
                 win_type: WinType = WinType.TB, lateness: int = 0,
                 plq_incremental: bool = False, plq_initial: Any = None,
                 wlq_incremental: bool = False, wlq_initial: Any = None,
                 name: str = "paned_windows", plq_parallelism: int = 1,
                 wlq_parallelism: int = 1, output_batch_size: int = 0,
                 tb_origin=None) -> None:
        if win_len <= slide_len:
            raise WindFlowError("Paned_Windows requires sliding windows "
                                "(win_len > slide_len)")
        pane = math.gcd(win_len, slide_len)
        plq = Parallel_Windows(plq_func, key_extractor, pane, pane, win_type,
                               lateness, plq_incremental, plq_initial,
                               name + "_plq", plq_parallelism, 0, WinRole.PLQ,
                               tb_origin)
        wlq = Parallel_Windows(_wrap_stage2_func(wlq_func, wlq_incremental),
                               _result_key, win_len // pane, slide_len // pane,
                               WinType.CB, 0, wlq_incremental, wlq_initial,
                               name + "_wlq", wlq_parallelism,
                               output_batch_size, WinRole.WLQ)
        super().__init__(name, plq, wlq)


class MapReduce_Windows(_CompositeWindows):
    """MAP partitions each window's tuples across replicas by ``ts % p``;
    REDUCE merges the p partials per window
    (``wf/mapreduce_windows.hpp:140-141``)."""

    def __init__(self, map_func: Callable, reduce_func: Callable,
                 key_extractor: Callable, win_len: int, slide_len: int,
                 win_type: WinType = WinType.TB, lateness: int = 0,
                 map_incremental: bool = False, map_initial: Any = None,
                 reduce_incremental: bool = False, reduce_initial: Any = None,
                 name: str = "mapreduce_windows", map_parallelism: int = 1,
                 reduce_parallelism: int = 1,
                 output_batch_size: int = 0, tb_origin=None) -> None:
        map_stage = Parallel_Windows(map_func, key_extractor, win_len,
                                     slide_len, win_type, lateness,
                                     map_incremental, map_initial,
                                     name + "_map", map_parallelism, 0,
                                     WinRole.MAP, tb_origin)
        reduce_stage = Parallel_Windows(
            _wrap_stage2_func(reduce_func, reduce_incremental), _result_key,
            map_parallelism, map_parallelism, WinType.CB, 0,
            reduce_incremental, reduce_initial, name + "_reduce",
            reduce_parallelism, output_batch_size, WinRole.REDUCE)
        super().__init__(name, map_stage, reduce_stage)
