"""Map / Filter / FlatMap / Reduce / Sink — the stateless/keyed CPU operators.

Parity (all per-tuple semantics, functor variants by arity):
- Map: ``wf/map.hpp:57-385``. A functor returning ``None`` is treated as
  in-place (mutated payload re-emitted); returning a value emits that value.
  ``copy_on_write`` shields broadcast-shared payloads (``wf/map.hpp:348``).
- Filter: ``wf/filter.hpp`` — predicate; dropped tuples counted.
- FlatMap: ``wf/flatmap.hpp`` + ``wf/shipper.hpp:58-182`` — user pushes 0..N
  results through a Shipper bound to the current (ts, wm).
- Reduce: ``wf/reduce.hpp:57-334`` — keyed running state (KEYBY mandatory);
  the updated state is copied and emitted after every update.
- Sink: ``wf/sink.hpp`` — consumes tuples; receives ``None`` once at EOS.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Optional

from ..basic import OpType, RoutingMode, WindFlowError
from .base import BasicOperator, BasicReplica, arity


# --------------------------------------------------------------------------
# Map
# --------------------------------------------------------------------------
class Map(BasicOperator):
    def __init__(self, func: Callable, name: str = "map", parallelism: int = 1,
                 input_routing: RoutingMode = RoutingMode.FORWARD,
                 key_extractor: Optional[Callable] = None,
                 output_batch_size: int = 0) -> None:
        super().__init__(name, parallelism, input_routing, key_extractor,
                         output_batch_size)
        self.func = func
        self._riched = arity(func) >= 2

    def build_replicas(self) -> None:
        self.replicas = [MapReplica(self, i) for i in range(self.parallelism)]


class MapReplica(BasicReplica):
    def process(self, payload, ts, wm, tag):
        if self.copy_on_write:
            payload = copy.copy(payload)
        out = (self.op.func(payload, self.context) if self.op._riched
               else self.op.func(payload))
        if out is None:  # in-place variant
            out = payload
        self.emitter.emit(out, ts, wm)


# --------------------------------------------------------------------------
# Filter
# --------------------------------------------------------------------------
class Filter(BasicOperator):
    def __init__(self, predicate: Callable, name: str = "filter",
                 parallelism: int = 1,
                 input_routing: RoutingMode = RoutingMode.FORWARD,
                 key_extractor: Optional[Callable] = None,
                 output_batch_size: int = 0) -> None:
        super().__init__(name, parallelism, input_routing, key_extractor,
                         output_batch_size)
        self.predicate = predicate
        self._riched = arity(predicate) >= 2

    def build_replicas(self) -> None:
        self.replicas = [FilterReplica(self, i) for i in range(self.parallelism)]


class FilterReplica(BasicReplica):
    def process(self, payload, ts, wm, tag):
        keep = (self.op.predicate(payload, self.context) if self.op._riched
                else self.op.predicate(payload))
        if keep:
            self.emitter.emit(payload, ts, wm)
        else:
            self.stats.inputs_ignored += 1


# --------------------------------------------------------------------------
# FlatMap
# --------------------------------------------------------------------------
class Shipper:
    """Bound to the in-flight tuple's (ts, wm); user pushes 0..N outputs."""

    __slots__ = ("_replica", "_ts", "_wm")

    def __init__(self, replica: "FlatMapReplica") -> None:
        self._replica = replica
        self._ts = 0
        self._wm = 0

    def push(self, payload: Any) -> None:
        self._replica.emitter.emit(payload, self._ts, self._wm)


class FlatMap(BasicOperator):
    def __init__(self, func: Callable, name: str = "flatmap",
                 parallelism: int = 1,
                 input_routing: RoutingMode = RoutingMode.FORWARD,
                 key_extractor: Optional[Callable] = None,
                 output_batch_size: int = 0) -> None:
        super().__init__(name, parallelism, input_routing, key_extractor,
                         output_batch_size)
        self.func = func
        self._riched = arity(func) >= 3

    def build_replicas(self) -> None:
        self.replicas = [FlatMapReplica(self, i) for i in range(self.parallelism)]


class FlatMapReplica(BasicReplica):
    def __init__(self, op, idx):
        super().__init__(op, idx)
        self.shipper = Shipper(self)

    def process(self, payload, ts, wm, tag):
        self.shipper._ts = ts
        self.shipper._wm = wm
        if self.op._riched:
            self.op.func(payload, self.shipper, self.context)
        else:
            self.op.func(payload, self.shipper)


# --------------------------------------------------------------------------
# Reduce
# --------------------------------------------------------------------------
class Reduce(BasicOperator):
    """``func(tuple, state) -> state`` (or mutate state and return None);
    requires KEYBY routing; not chainable (``wf/multipipe.hpp:1058-1060``)."""

    def __init__(self, func: Callable, key_extractor: Callable,
                 initial_state: Any = None, name: str = "reduce",
                 parallelism: int = 1, output_batch_size: int = 0) -> None:
        if key_extractor is None:
            raise WindFlowError("Reduce requires a key extractor (KEYBY)")
        super().__init__(name, parallelism, RoutingMode.KEYBY, key_extractor,
                         output_batch_size)
        self.func = func
        self.initial_state = initial_state
        self._riched = arity(func) >= 3

    @property
    def is_chainable(self) -> bool:
        return False

    def build_replicas(self) -> None:
        self.replicas = [ReduceReplica(self, i) for i in range(self.parallelism)]


class ReduceReplica(BasicReplica):
    def __init__(self, op, idx):
        super().__init__(op, idx)
        self.key_state = {}

    def process(self, payload, ts, wm, tag):
        key = self.op.key_extractor(payload)
        state = self.key_state.get(key)
        if state is None:
            state = copy.deepcopy(self.op.initial_state)
        out = (self.op.func(payload, state, self.context) if self.op._riched
               else self.op.func(payload, state))
        if out is not None:
            state = out
        self.key_state[key] = state
        self.emitter.emit(copy.copy(state), ts, wm)

    # -- checkpointing -------------------------------------------------------
    def snapshot_state(self) -> dict:
        st = super().snapshot_state()
        st["key_state"] = self.key_state
        return st

    def restore_state(self, state: dict) -> None:
        super().restore_state(state)
        self.key_state = dict(state.get("key_state", {}))


# --------------------------------------------------------------------------
# Sink
# --------------------------------------------------------------------------
class Sink(BasicOperator):
    op_type = OpType.SINK
    # exactly-once mode (windflow_tpu.sinks.transactional): output
    # buffers per checkpoint epoch, pre-commits at the barrier as a
    # staged segment file and becomes visible (tmp+atomic-rename) only
    # when the coordinator finalizes the epoch
    supports_exactly_once = True

    def __init__(self, func: Callable, name: str = "sink", parallelism: int = 1,
                 input_routing: RoutingMode = RoutingMode.FORWARD,
                 key_extractor: Optional[Callable] = None,
                 accepts_columns: bool = False) -> None:
        super().__init__(name, parallelism, input_routing, key_extractor, 0)
        self.func = func
        # columnar consumer (the exit-side dual of push_columns): the
        # functor takes whole COLUMN batches, ``func(cols, ts)`` with
        # cols a dict of host numpy arrays — device-plane exits then
        # skip per-row boxing entirely (the reference exit iterates
        # pinned memory without materializing objects,
        # ``wf/batch_gpu_t.hpp:154-179``)
        self.accepts_columns = accepts_columns
        self._riched = arity(func) >= (3 if accepts_columns else 2)
        self.exactly_once = False
        self.txn_dir: Optional[str] = None

    def build_replicas(self) -> None:
        if self.exactly_once:
            cls = (TxnColumnarSinkReplica if self.accepts_columns
                   else TxnSinkReplica)
        else:
            cls = ColumnarSinkReplica if self.accepts_columns else SinkReplica
        self.replicas = [cls(self, i) for i in range(self.parallelism)]


class SinkReplica(BasicReplica):
    def __init__(self, op, idx):
        super().__init__(op, idx)
        # sinks record end-to-end latency of traced tuples (None when
        # sampling is off — the generic handle_msg hook stays dormant)
        self._e2e = self.stats.hist_e2e

    def process(self, payload, ts, wm, tag):
        if self.op._riched:
            self.op.func(payload, self.context)
        else:
            self.op.func(payload)

    def flush_on_termination(self) -> None:
        # EOS marker: reference passes an empty optional (wf/sink.hpp)
        if self.op._riched:
            self.op.func(None, self.context)
        else:
            self.op.func(None)


class ColumnarSinkReplica(BasicReplica):
    """Consumes whole device batches as host COLUMN dicts — one functor
    call per batch, no per-row Python objects on the exit path."""

    def __init__(self, op, idx):
        super().__init__(op, idx)
        self._e2e = self.stats.hist_e2e

    def handle_msg(self, ch: int, msg: Any) -> None:
        self.stats.start_svc()
        n = 1
        if msg.is_punct:
            self.stats.punct_received += 1
            self._advance_wm(msg.wm)
            self.on_punctuation(msg.wm)
        else:
            from ..tpu.batch import BatchTPU
            if not isinstance(msg, BatchTPU):
                raise WindFlowError(
                    f"{self.op.name}: with_columns sink received a row "
                    f"message ({type(msg).__name__}); columnar sinks "
                    "consume device batches — drop with_columns or move "
                    "the producer to the device plane")
            import numpy as np
            n = msg.size
            self.stats.inputs_received += n
            self._advance_wm(msg.wm)
            if self.stats.sample_every:  # per batch, not per tuple
                self.stats._svc_rec = True
            if self._e2e is not None and msg.trace_min:
                from ..basic import current_time_usecs
                now = current_time_usecs()
                self._e2e.record(now - msg.trace_max)
                if msg.trace_max != msg.trace_min:
                    self._e2e.record(now - msg.trace_min)
            cols = {name: np.asarray(col)[:n]
                    for name, col in msg.fields.items()}
            ts = msg.ts_host[:n]
            self.context._set_meta(int(ts[-1]) if n else 0, self.cur_wm)
            self._consume(cols, ts)
        self.stats.end_svc(n)

    def _consume(self, cols, ts) -> None:
        """One host column batch -> the user functor (the exactly-once
        subclass buffers it into the current epoch instead)."""
        if self.op._riched:
            self.op.func(cols, ts, self.context)
        else:
            self.op.func(cols, ts)

    def flush_on_termination(self) -> None:
        if self.op._riched:
            self.op.func(None, None, self.context)
        else:
            self.op.func(None, None)


# --------------------------------------------------------------------------
# Exactly-once sinks (windflow_tpu.sinks.transactional): two-phase commit
# driven by the checkpoint coordinator. Separate subclasses so the default
# at-least-once hot path is byte-identical to before — the exactly-once
# machinery costs nothing unless with_exactly_once() selected it.
# --------------------------------------------------------------------------
class _TxnSinkMixin:
    """Chain-node hooks shared by the row and columnar transactional
    sinks; the 2PC state machine lives in ``EpochTxnDriver``."""

    def _init_txn(self) -> None:
        from ..sinks.transactional import (EpochTxnDriver, SegmentBackend,
                                           txn_dir_for)
        self.txn_root = txn_dir_for(self.op.name, self.idx, self.op.txn_dir)
        self._txn = EpochTxnDriver(SegmentBackend(self.txn_root), self.stats,
                                   deliver=self._deliver)
        # instance attribute so the worker's idle tick drives commits
        # (plain sinks have no on_idle and stay off the idle-tick path)
        self.on_idle = self._txn.poll

    # -- worker / coordinator hooks (runtime/worker.py) --------------------
    def bind_txn_coordinator(self, coordinator) -> None:
        self._txn.bind(coordinator)

    def precommit_epoch(self, ckpt_id: int) -> None:
        self._txn.precommit_epoch(ckpt_id)

    def handle_msg(self, ch: int, msg: Any) -> None:
        # commit finalized epochs from our OWN thread before the next
        # message (the finalize listener only flips a watermark); the
        # fast path inside poll() is one int compare per message
        t = self._txn
        if t._pending and min(t._pending) <= t._commit_ready:
            t.poll()
        super().handle_msg(ch, msg)

    def flush_on_termination(self) -> None:
        # EOS in exactly-once mode: commit what is finalized, stage the
        # post-barrier tail as one last pending epoch. Functor delivery
        # of still-pending epochs (and the EOS None marker) happens in
        # txn_complete once the whole graph finished cleanly.
        self._txn.seal_tail()

    def txn_complete(self) -> None:
        """Called by ``PipeGraph.wait_end`` on a clean finish (worker
        joined, no errors): commit every remaining epoch in order, then
        hand the functor its EOS marker."""
        self._txn.complete_all()
        self._eos_marker()

    # -- checkpoint snapshot / restore -------------------------------------
    def snapshot_state(self) -> dict:
        st = super().snapshot_state()
        st.update(self._txn.snapshot())
        return st

    def restore_state(self, state: dict) -> None:
        super().restore_state(state)
        self._txn.restore(state)


class TxnSinkReplica(_TxnSinkMixin, SinkReplica):
    """Row sink in exactly-once mode: tuples buffer per epoch; the
    committed ``epoch_*.seg`` files under ``txn_root`` are the durable
    output stream, and the functor sees each record exactly once, at
    commit time (epoch order)."""

    def __init__(self, op, idx):
        super().__init__(op, idx)
        self._init_txn()

    def process(self, payload, ts, wm, tag):
        self._txn.buffer.append((payload, ts))

    def _deliver(self, records) -> None:
        for payload, ts in records:
            self.context._set_meta(ts, self.cur_wm)
            if self.op._riched:
                self.op.func(payload, self.context)
            else:
                self.op.func(payload)

    def _eos_marker(self) -> None:
        SinkReplica.flush_on_termination(self)


class TxnColumnarSinkReplica(_TxnSinkMixin, ColumnarSinkReplica):
    """Columnar sink in exactly-once mode: whole host column batches
    buffer per epoch (the arrays are already host copies at this point),
    one functor call per batch at commit time."""

    def __init__(self, op, idx):
        super().__init__(op, idx)
        self._init_txn()

    def _consume(self, cols, ts) -> None:
        self._txn.buffer.append((cols, ts))

    def _deliver(self, records) -> None:
        for cols, ts in records:
            if self.op._riched:
                self.op.func(cols, ts, self.context)
            else:
                self.op.func(cols, ts)

    def _eos_marker(self) -> None:
        ColumnarSinkReplica.flush_on_termination(self)
