"""Operator and replica base classes.

Parity: ``wf/basic_operator.hpp`` — an operator is metadata plus a vector of
replicas; each replica is the unit of execution (one FastFlow node there, one
chain-node here) with the ``svc()`` hot loop, emitter wiring, punctuation
handling and stats. Functor-variant dispatch (riched vs non-riched, in-place
vs non-in-place) is done once at construction by arity inspection — the
Python analog of the reference's ``if constexpr`` over invocability
predicates (``wf/map.hpp:65-71``).
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, List, Optional

from ..basic import (ExecutionMode, OpType, RoutingMode, TimePolicy,
                     WindFlowError, as_key_fn, current_time_usecs,
                     key_field_name, key_fields_names)
from ..context import RuntimeContext
from ..message import Batch, Single
from ..monitoring.stats import StatsRecord
from ..monitoring.tracing import resolve_sample_every
from ..runtime.emitters import BasicEmitter


def arity(fn: Callable) -> int:
    """Number of REQUIRED positional parameters of a user functor; drives
    the riched/non-riched variant choice (``wf/meta.hpp`` overload sets).
    Parameters with defaults don't count: ``lambda t, _m=x: ...`` is the
    common closure idiom and must not be mistaken for a riched variant."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return -1
    n = 0
    for p in sig.parameters.values():
        if p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                      inspect.Parameter.POSITIONAL_OR_KEYWORD):
            if p.default is inspect.Parameter.empty:
                n += 1
        elif p.kind == inspect.Parameter.VAR_POSITIONAL:
            return -1  # *args: caller decides
    return n


class BasicOperator:
    """Metadata + replicas. Subclasses create their replica list in
    ``build_replicas`` (called by the topology layer at add-time)."""

    op_type: OpType = OpType.BASIC

    def __init__(self, name: str, parallelism: int,
                 input_routing: RoutingMode = RoutingMode.FORWARD,
                 key_extractor: Optional[Callable[[Any], Any]] = None,
                 output_batch_size: int = 0) -> None:
        if parallelism < 1:
            raise WindFlowError(f"operator {name}: parallelism must be >= 1")
        self.name = name
        self.parallelism = parallelism
        self.input_routing = input_routing
        # a string names a tuple field (device-column-friendly); normalize
        # to a callable once here, remembering the field name for the
        # device plane
        self.key_field = key_field_name(key_extractor)
        self.key_fields = key_fields_names(key_extractor)
        self.key_extractor = as_key_fn(key_extractor)
        self.output_batch_size = output_batch_size
        self.closing_func: Optional[Callable] = None
        self.replicas: List["BasicReplica"] = []
        self.execution_mode = ExecutionMode.DEFAULT
        self.time_policy = TimePolicy.INGRESS_TIME
        # latency-tracing sample interval override (with_latency_tracing);
        # None falls back to WF_LATENCY_SAMPLE (monitoring/tracing.py)
        self.latency_sample: Optional[int] = None
        # flight-recorder ring capacity override (with_flight_recorder);
        # None falls back to the graph-level setting, then
        # WF_FLIGHTREC_EVENTS (monitoring/flightrec.py; 0 = off)
        self.flightrec_events: Optional[int] = None
        # per-record error policy (windflow_tpu.supervision.errors):
        # None/FAIL = the pre-existing fail-fast behavior, zero new cost;
        # SKIP / RETRY / DEAD_LETTER wrap functor invocation per record
        self.error_policy = None
        self._used = False  # operators are copied into the pipe; guard reuse

    # hooks -----------------------------------------------------------------
    def build_replicas(self) -> None:
        raise NotImplementedError

    def configure(self, execution_mode: ExecutionMode, time_policy: TimePolicy) -> None:
        """Called by the topology layer before build_replicas."""
        self.execution_mode = execution_mode
        self.time_policy = time_policy

    @property
    def is_chainable(self) -> bool:
        """Reference: Reduce and window operators are not chainable
        (``wf/multipipe.hpp:1058-1060``); anything KEYBY/BROADCAST-routed
        needs a shuffle stage anyway."""
        return self.input_routing in (RoutingMode.FORWARD, RoutingMode.NONE)

    def describe(self) -> str:
        return (f"{type(self).__name__}(name={self.name!r}, "
                f"parallelism={self.parallelism})")


class BasicReplica:
    """One execution unit. Implements the chain-node protocol:
    ``handle_msg(ch, msg)`` / ``terminate()``."""

    def __init__(self, op: BasicOperator, idx: int) -> None:
        self.op = op
        self.idx = idx
        self.context = RuntimeContext(op.parallelism, idx)
        self.stats = StatsRecord(op.name, idx,
                                 sample_every=resolve_sample_every(op))
        self.emitter: Optional[BasicEmitter] = None
        self.copy_on_write = False  # set when fed by a broadcast emitter
        self.terminated = False
        self.cur_wm = 0
        # end-to-end recording hook: SINK replicas bind this to their
        # stats histogram when sampling is on; None keeps the per-message
        # tracing check to one attribute load
        self._e2e = None
        # per-record error policy: a non-FAIL policy shadows process with
        # a guarded wrapper (instance attribute); the FAIL default leaves
        # the class method untouched — zero cost on the hot path
        pol = op.error_policy
        if pol is not None and not pol.is_fail:
            from ..supervision.errors import make_guarded_process
            self.process = make_guarded_process(self, pol)

    # -- wiring --------------------------------------------------------------
    def set_emitter(self, emitter: BasicEmitter) -> None:
        self.emitter = emitter
        emitter.set_stats(self.stats)

    # -- message dispatch ----------------------------------------------------
    def handle_msg(self, ch: int, msg: Any) -> None:
        self.stats.start_svc()
        n = 1
        if msg.is_punct:
            st = self.stats
            st.punct_received += 1
            self._advance_wm(msg.wm)
            # wm:advance spans ride punctuations only (bounded rate) —
            # per-tuple advances would flood the ring
            if st.recorder is not None and msg.wm >= self.cur_wm:
                st.recorder.event("wm:advance", 0.0, self.cur_wm)
            self.on_punctuation(msg.wm)
        elif isinstance(msg, Batch):
            n = msg.size
            self.stats.inputs_received += n
            self._advance_wm(msg.wm)
            tag = msg.stream_tag
            t0 = msg.trace_min
            if t0:  # traced batch: forward the stamp / record at sinks
                self.stats._svc_rec = True
                if self._e2e is not None:
                    now = current_time_usecs()
                    self._e2e.record(now - msg.trace_max)
                    if msg.trace_max != t0:
                        self._e2e.record(now - t0)
                em = self.emitter
                if em is not None:
                    em.trace_ts = t0
            for payload, ts in msg.rows:
                self.context._set_meta(ts, self.cur_wm)
                self.process(payload, ts, self.cur_wm, tag)
            if t0:
                em = self.emitter
                if em is not None:
                    em.trace_ts = 0
        else:
            self.stats.inputs_received += 1
            self._advance_wm(msg.wm)
            t0 = msg.trace_ts
            if t0:  # traced tuple: forward the stamp / record at sinks
                self.stats._svc_rec = True
                if self._e2e is not None:
                    self._e2e.record(current_time_usecs() - t0)
                em = self.emitter
                if em is not None:
                    em.trace_ts = t0
            self.context._set_meta(msg.ts, self.cur_wm)
            self.process(msg.payload, msg.ts, self.cur_wm, msg.stream_tag)
            if t0:
                em = self.emitter
                if em is not None:
                    em.trace_ts = 0  # a dropped tuple must not stamp later ones
        self.stats.end_svc(n)

    def _advance_wm(self, wm: int) -> None:
        if wm > self.cur_wm:
            self.cur_wm = wm
            # event-time health gauges: two stores on ADVANCE only; lag,
            # idle and stall detection derive at poll time (stats.py)
            st = self.stats
            st.wm_current = wm
            st.wm_advances += 1

    # -- hooks ---------------------------------------------------------------
    def process(self, payload: Any, ts: int, wm: int, tag: int) -> None:
        raise NotImplementedError

    def on_punctuation(self, wm: int) -> None:
        """Default: use the watermark for progress, then forward it
        downstream (the replica owns punctuation propagation,
        ``wf/basic_operator.hpp:180-189``)."""
        if self.emitter is not None:
            self.emitter.propagate_punctuation(self.cur_wm)

    def flush_on_termination(self) -> None:
        """Emit pending state at EOS (window operators override)."""

    # -- checkpointing (aligned snapshots, windflow_tpu.checkpoint) ----------
    def snapshot_state(self) -> dict:
        """Return this replica's complete processing state as a picklable
        dict. Called on the replica's own worker thread at an aligned
        barrier (no tuple in flight; device dispatch queues drained, so
        subclasses may ``jax.device_get`` their device state directly).
        Stateful subclasses extend the base dict via ``super()``."""
        return {"cur_wm": self.cur_wm}

    def restore_state(self, state: dict) -> None:
        """Inverse of ``snapshot_state``; called after ``build_replicas``
        (emitter/collector wiring done) and before any worker starts."""
        self.cur_wm = state.get("cur_wm", 0)
        self.stats.wm_current = self.cur_wm

    def terminate(self) -> None:
        if self.terminated:
            return
        self.terminated = True
        self.flush_on_termination()
        if self.op.closing_func is not None:
            if arity(self.op.closing_func) >= 1:
                self.op.closing_func(self.context)
            else:
                self.op.closing_func()
        if self.emitter is not None:
            self.emitter.flush()
        self.stats.is_terminated = True
