"""General windowing engine: triggerers, per-key descriptors, window
assignment, firing, lateness, EOS flush.

Parity map (semantics reproduced exactly; the encoding is Python/columnar
rather than templates):
- Triggerer_CB / Triggerer_TB: ``wf/window_structure.hpp:49-116`` — window
  ``lwid`` covers index range ``[initial + lwid*slide_local,
  initial + lwid*slide_local + win)`` where the index is the per-key arrival
  counter (CB) or the timestamp (TB).
- Window distribution: replica ``id_inner`` of ``num_inner`` owns global
  window ids ``gwid ≡ (id_inner - hash(key)) mod num_inner``; its local
  slide is ``slide * num_inner`` and its first window starts at
  ``first_gwid_key * slide`` (``wf/window_replica.hpp:253-283``,
  ``wf/parallel_windows.hpp`` replica construction: ``slide_len *
  parallelism`` for non-MAP roles).
- MAP role: every replica evaluates EVERY window but only folds tuples with
  ``ts % map_parallelism == replica_index`` (``window_replica.hpp:286``);
  result ids step by ``map_parallelism`` starting at the replica index so the
  REDUCE stage's count-based windows (win=slide=map_parallelism) gather the
  partials of one window (``window_replica.hpp:333-336``).
- PLQ role: pane results are emitted with their global pane id
  (``window_replica.hpp:337-341``) for the WLQ's ID-sequencing collector.
- Firing: CB windows fire by count; TB windows in DEFAULT mode fire when
  ``watermark > window_end + lateness`` (``window_replica.hpp:304-311``);
  fired results carry ts=watermark in DEFAULT mode, ts=trigger ts otherwise
  (``window_replica.hpp:330-332``).
- Late tuples older than the last fired window boundary are dropped and
  counted (``window_replica.hpp:258-268``).
- EOS flushes every open window with partial content
  (``window_replica.hpp:356-408``).
"""

from __future__ import annotations

import bisect
import copy
import math
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..basic import ExecutionMode, WinRole, WinType


@dataclass
class WinResult:
    """Result of one window evaluation (the reference constructs the user's
    result type with (key, gwid) via ``create_win_result_t``,
    ``wf/basic.hpp:331-342``)."""

    key: Any
    wid: int
    value: Any
    ts: int = 0


@dataclass
class _OpenWindow:
    lwid: int
    gwid: int
    start: int  # first index (count or ts) covered
    end: int  # one past the last index covered
    acc: Any = None  # incremental accumulator
    n_tuples: int = 0


@dataclass
class _KeyDesc:
    next_input_id: int = 0  # per-key arrival counter (CB index)
    next_lwid: int = 0
    last_fired_lwid: int = -1
    next_res_id: int = 0
    wins: List[_OpenWindow] = field(default_factory=list)
    # archive for non-incremental queries: parallel sorted lists
    arch_idx: List[int] = field(default_factory=list)
    arch_payload: List[Any] = field(default_factory=list)


class WindowEngine:
    """Per-replica window machinery; usable in roles SEQ/PLQ/WLQ/MAP/REDUCE.

    The host replica supplies ``emit(result_payload, ts, wm, msg_id)`` and a
    key extractor; the engine owns assignment, accumulation and firing.
    """

    def __init__(self,
                 win_type: WinType,
                 win_len: int,
                 slide_len: int,
                 lateness: int,
                 key_extractor: Callable[[Any], Any],
                 win_func: Callable,
                 incremental: bool,
                 initial_value: Any,
                 role: WinRole,
                 id_inner: int,
                 num_inner: int,
                 map_parallelism: int = 1,
                 map_index: int = 0,
                 execution_mode: ExecutionMode = ExecutionMode.DEFAULT,
                 riched: bool = False,
                 context: Any = None,
                 tb_origin: Optional[int] = None) -> None:
        assert win_len > 0 and slide_len > 0
        self.win_type = win_type
        self.win_len = win_len
        # non-MAP distributed roles stretch the local slide by num_inner
        self.slide_local = slide_len * num_inner
        self.slide_global = slide_len
        self.lateness = lateness
        self.key_extractor = key_extractor
        self.win_func = win_func
        self.incremental = incremental
        self.initial_value = initial_value
        self.role = role
        self.id_inner = id_inner
        self.num_inner = num_inner
        self.map_parallelism = map_parallelism
        self.map_index = map_index
        self.execution_mode = execution_mode
        self.riched = riched
        self.context = context
        self.key_map: Dict[Any, _KeyDesc] = {}
        self.ignored_tuples = 0
        self.cur_wm = 0
        # unified late accounting (event-time health plane): the owning
        # replica wires its StatsRecord here; None (bare engine in unit
        # tests) keeps the engine standalone
        self.stats = None
        # Reference-compat TB numbering (wf/window_replica.hpp:253-283):
        # when set, a key's windows are anchored at this time origin (not
        # its first tuple), and every window between the origin and the
        # first tuple is created and fired with the identity/empty value.
        # None (default) keeps the first-tuple anchoring documented in
        # PARITY.md §2.3 (epoch-scale timestamps would otherwise create
        # ~ts/slide empty windows — the origin bounds that blowup).
        self.tb_origin = tb_origin if win_type is WinType.TB else None

    # ------------------------------------------------------------------
    def _first_gwid(self, key: Any) -> int:
        h = hash(key) % self.num_inner
        return (self.id_inner - h + self.num_inner) % self.num_inner

    def _new_acc(self, key: Any, gwid: int) -> Any:
        if callable(self.initial_value):
            return self.initial_value(key, gwid)
        return copy.deepcopy(self.initial_value)

    # ------------------------------------------------------------------
    def process(self, payload: Any, ts: int, wm: int,
                emit: Callable[[Any, int, int, Optional[int]], None]) -> None:
        if wm > self.cur_wm:
            self.cur_wm = wm
        key = self.key_extractor(payload)
        kd = self.key_map.get(key)
        if kd is None:
            kd = self.key_map[key] = _KeyDesc(
                next_res_id=(self.map_index if self.role is WinRole.MAP else 0))
        is_new_key = kd.next_input_id == 0
        ident = kd.next_input_id
        kd.next_input_id += 1
        index = ident if self.win_type is WinType.CB else ts
        first_gwid = self._first_gwid(key)
        initial = first_gwid * (self.slide_local // self.num_inner)
        if self.tb_origin is not None:
            # reference-compat numbering: anchor every key's windows at
            # the configured time origin; windows between the origin and
            # the key's first tuple open below and fire empty (identity
            # value) as the watermark passes them
            initial += self.tb_origin
        elif is_new_key and self.win_type is WinType.TB:
            # a key first seen at a large timestamp starts at the first
            # window that can contain it — creating (and empty-firing) every
            # window since the time origin would blow up with epoch-scale
            # timestamps. Global window ids stay aligned.
            rel = index - initial
            kd.next_lwid = max(0, (rel - self.win_len) // self.slide_local + 1)
        # late-tuple guard: before the first still-open window => ignored
        min_boundary = (self.win_len + kd.last_fired_lwid * self.slide_local
                        if kd.last_fired_lwid >= 0 else 0)
        if index < initial + min_boundary:
            # count real drops: fired-past tuples, and (origin mode) tuples
            # before the configured origin — NOT pre-`initial` tuples that
            # simply belong to another replica's windows (broadcast roles)
            if kd.last_fired_lwid >= 0 or (self.tb_origin is not None
                                           and index < self.tb_origin):
                self.ignored_tuples += 1
                st = self.stats
                if st is not None:
                    st.note_late(1, 1, float(wm - ts)
                                 if self.win_type is WinType.TB and wm > ts
                                 else None)
            return
        # admitted-late: a TB tuple behind the watermark that still lands
        # in an open window (within the allowed lateness). Dropped late
        # tuples returned above, so the two sites classify disjointly and
        # inputs == on_time + late_admitted + late_dropped holds exactly
        st = self.stats
        if st is not None and self.win_type is WinType.TB and ts < wm:
            st.note_late(1, 0, float(wm - ts))
        # open every window whose range has been reached
        if self.win_len >= self.slide_local:  # sliding / tumbling
            last_w = math.ceil((index + 1 - initial) / self.slide_local) - 1
        else:  # hopping (gaps between windows)
            last_w = (index - initial) // self.slide_local
        for lwid in range(kd.next_lwid, last_w + 1):
            gwid = first_gwid + lwid * self.num_inner
            start = initial + lwid * self.slide_local
            w = _OpenWindow(lwid, gwid, start, start + self.win_len)
            if self.incremental:
                w.acc = self._new_acc(key, gwid)
            kd.wins.append(w)
            kd.next_lwid = lwid + 1
        # MAP role: fold only this replica's tuple partition
        if (self.role is WinRole.MAP
                and ts % self.map_parallelism != self.map_index):
            return
        if not self.incremental:
            pos = bisect.bisect_right(kd.arch_idx, index)
            kd.arch_idx.insert(pos, index)
            kd.arch_payload.insert(pos, payload)
        cnt_fired = 0
        for w in kd.wins:
            if index < w.start:
                continue  # OLD for this window
            if index < w.end:  # IN
                if self.incremental:
                    out = (self.win_func(payload, w.acc, self.context)
                           if self.riched else self.win_func(payload, w.acc))
                    if out is not None:
                        w.acc = out
                w.n_tuples += 1
            else:  # FIRED by index
                if (self.win_type is WinType.CB
                        or self.execution_mode is not ExecutionMode.DEFAULT
                        or w.end - 1 + self.lateness < wm):
                    self._fire(key, kd, w, ts, wm, emit)
                    cnt_fired += 1
        if cnt_fired:
            del kd.wins[:cnt_fired]

    # ------------------------------------------------------------------
    def on_watermark(self, wm: int,
                     emit: Callable[[Any, int, int, Optional[int]], None]) -> None:
        """Fire TB windows whose end passed the watermark. The reference only
        fires lazily on the next tuple/EOS; firing on punctuations too is a
        liveness improvement with identical results."""
        if wm > self.cur_wm:
            self.cur_wm = wm
        if self.win_type is not WinType.TB \
                or self.execution_mode is not ExecutionMode.DEFAULT:
            return
        for key, kd in self.key_map.items():
            cnt = 0
            for w in kd.wins:
                if w.end - 1 + self.lateness < wm:
                    self._fire(key, kd, w, wm, wm, emit)
                    cnt += 1
                else:
                    break
            if cnt:
                del kd.wins[:cnt]

    # ------------------------------------------------------------------
    def _window_content(self, kd: _KeyDesc, w: _OpenWindow) -> List[Any]:
        lo = bisect.bisect_left(kd.arch_idx, w.start)
        hi = bisect.bisect_left(kd.arch_idx, w.end)
        return kd.arch_payload[lo:hi]

    def _purge_archive(self, kd: _KeyDesc, upto_index: int) -> None:
        lo = bisect.bisect_left(kd.arch_idx, upto_index)
        if lo:
            del kd.arch_idx[:lo]
            del kd.arch_payload[:lo]

    def _fire(self, key: Any, kd: _KeyDesc, w: _OpenWindow, ts: int, wm: int,
              emit: Callable[[Any, int, int, Optional[int]], None]) -> None:
        if self.incremental:
            value = w.acc
        else:
            content = self._window_content(kd, w)
            value = (self.win_func(content, self.context) if self.riched
                     else self.win_func(content))
            # later windows never need anything before the NEXT window's start
            self._purge_archive(kd, w.start + self.slide_local)
        kd.last_fired_lwid = w.lwid
        used_ts = wm if self.execution_mode is ExecutionMode.DEFAULT else ts
        used_wm = wm if self.execution_mode is ExecutionMode.DEFAULT else 0
        result = WinResult(key, w.gwid, value, used_ts)
        if self.role is WinRole.MAP:
            msg_id = kd.next_res_id
            kd.next_res_id += self.map_parallelism
        elif self.role is WinRole.PLQ:
            msg_id = self._first_gwid(key) + kd.next_res_id * self.num_inner
            kd.next_res_id += 1
        else:
            msg_id = None
        emit(result, used_ts, used_wm, msg_id)

    # ------------------------------------------------------------------
    def flush(self, emit: Callable[[Any, int, int, Optional[int]], None]) -> None:
        """EOS: fire all open windows with partial content
        (``window_replica.hpp:356-408``)."""
        for key, kd in self.key_map.items():
            for w in kd.wins:
                self._fire(key, kd, w, self.cur_wm, self.cur_wm, emit)
            kd.wins.clear()

    # ------------------------------------------------------------------
    # checkpointing: the engine's state is pure data (_KeyDesc trees of
    # open windows, archives, counters) — functors/context stay out of
    # the blob and come from the rebuilt operator on restore
    def snapshot_state(self) -> dict:
        return {"key_map": dict(self.key_map.items()),
                "ignored_tuples": self.ignored_tuples,
                "cur_wm": self.cur_wm}

    def restore_state(self, state: dict) -> None:
        km = state.get("key_map", {})
        if isinstance(self.key_map, dict):
            self.key_map = dict(km)
        else:  # cache-backed store (P_Keyed_Windows): write through
            for k, v in km.items():
                self.key_map[k] = v
        self.ignored_tuples = state.get("ignored_tuples", 0)
        self.cur_wm = state.get("cur_wm", 0)
