"""Interval_Join: stream join on key with a time interval predicate.

Parity: ``wf/interval_join.hpp:60-558``. Streams A and B (tagged by the
collector from input channel ranges) join on key where
``ts_b ∈ [ts_a - lower, ts_a + upper]``; the user function produces the
output tuple (None drops the pair). Two parallelism modes
(``wf/builders.hpp:1480-1538`` withKPMode/withDPMode):

- KP (key parallelism): KEYBY routing — a key's whole archive lives on one
  replica;
- DP (data parallelism): BROADCAST routing — every replica sees every
  tuple, but STORES only every p-th tuple per stream (round-robin by a
  shared deterministic arrival order, ``interval_join.hpp:317-319``), while
  probing its own store with every arrival. Each matched pair is emitted by
  exactly the replica storing the earlier tuple. DEFAULT mode puts a
  watermark-driven ordering collector (the reference's Join_Collector) in
  front so every replica observes the identical sequence.

Archives are ts-sorted per (key, stream); watermark progress purges
entries no future opposite tuple can match: A when ``ts_a < wm - upper``,
B when ``ts_b < wm - lower`` (``interval_join.hpp:155-165``).

Emitted results carry ``ts = max(ts_a, ts_b)``.
"""

from __future__ import annotations

import bisect
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..basic import JoinMode, OpType, RoutingMode, WindFlowError
from .base import BasicOperator, BasicReplica, arity


class Interval_Join(BasicOperator):
    op_type = OpType.JOIN

    def __init__(self, join_func: Callable, key_extractor: Callable,
                 lower_bound: int, upper_bound: int,
                 join_mode: JoinMode = JoinMode.KP,
                 name: str = "interval_join", parallelism: int = 1,
                 output_batch_size: int = 0) -> None:
        if key_extractor is None:
            raise WindFlowError(f"{name}: requires a key extractor")
        if join_mode not in (JoinMode.KP, JoinMode.DP):
            raise WindFlowError(f"{name}: join mode must be KP or DP")
        routing = (RoutingMode.KEYBY if join_mode is JoinMode.KP
                   else RoutingMode.BROADCAST)
        super().__init__(name, parallelism, routing, key_extractor,
                         output_batch_size)
        self.join_func = join_func
        self.lower_bound = int(lower_bound)
        self.upper_bound = int(upper_bound)
        self.join_mode = join_mode
        self._riched = arity(join_func) >= 3

    @property
    def is_chainable(self) -> bool:
        return False

    def configure(self, execution_mode, time_policy) -> None:
        from ..basic import ExecutionMode
        if (self.join_mode is JoinMode.DP
                and execution_mode is ExecutionMode.PROBABILISTIC):
            # K-slack reordering is arrival-dependent per replica, so
            # broadcast DP replicas would disagree on storage assignment
            raise WindFlowError(
                f"{self.name}: DP-mode Interval_Join is not supported in "
                "PROBABILISTIC mode (per-replica K-slack ordering diverges);"
                " use KP mode")
        super().configure(execution_mode, time_policy)

    def build_replicas(self) -> None:
        self.replicas = [IntervalJoinReplica(self, i)
                         for i in range(self.parallelism)]


class _KeyArchives:
    """Per-key ts-sorted archives for both streams + DP storage counters."""

    __slots__ = ("ts", "rows", "counters")

    def __init__(self) -> None:
        self.ts: Tuple[List[int], List[int]] = ([], [])
        self.rows: Tuple[List[Any], List[Any]] = ([], [])
        self.counters = [0, 0]  # DP round-robin per stream


class IntervalJoinReplica(BasicReplica):
    def __init__(self, op: Interval_Join, idx: int) -> None:
        super().__init__(op, idx)
        self.keys: Dict[Any, _KeyArchives] = {}

    def process(self, payload, ts, wm, tag):
        op = self.op
        if ts < wm:
            # admitted-late: the join never drops, but a KP-mode tuple
            # behind the watermark probes archives the purge frontier may
            # already have trimmed — matches can be missed; account it
            self.stats.note_late(1, 0, float(wm - ts))
        key = op.key_extractor(payload)
        ka = self.keys.get(key)
        if ka is None:
            ka = self.keys[key] = _KeyArchives()
        side = 1 if tag else 0
        other = 1 - side
        # probe the opposite archive: for an A arrival the matching B range
        # is [ts - lower, ts + upper]; for a B arrival it is the mirrored
        # [ts - upper, ts + lower]
        if side == 0:
            lo, hi = ts - op.lower_bound, ts + op.upper_bound
        else:
            lo, hi = ts - op.upper_bound, ts + op.lower_bound
        ots, orows = ka.ts[other], ka.rows[other]
        i = bisect.bisect_left(ots, lo)
        j = bisect.bisect_right(ots, hi)
        for p in range(i, j):
            stored = orows[p]
            a, b = (payload, stored) if side == 0 else (stored, payload)
            out = (op.join_func(a, b, self.context) if op._riched
                   else op.join_func(a, b))
            if out is not None:
                self.emitter.emit(out, max(ts, ots[p]), wm)
        # store (DP: only this replica's share of the shared sequence)
        store = True
        if op.join_mode is JoinMode.DP:
            store = (ka.counters[side] % op.parallelism) == self.idx
            ka.counters[side] += 1
        if store:
            pos = bisect.bisect_right(ka.ts[side], ts)
            ka.ts[side].insert(pos, ts)
            ka.rows[side].insert(pos, payload)
        # purge frontier: DP inputs are delivered in ts order by their
        # collector, so the current ts bounds every future arrival — the
        # watermark may run AHEAD of still-queued deliveries and must not
        # drive the purge. KP purges by watermark (reference
        # interval_join.hpp:155-165; late tuples may miss matches).
        frontier = ts if op.join_mode is JoinMode.DP else wm
        self._purge(ka, frontier)

    def _purge(self, ka: _KeyArchives, wm: int) -> None:
        for side, bound in ((0, self.op.upper_bound),
                            (1, self.op.lower_bound)):
            cutoff = wm - bound
            ts_list = ka.ts[side]
            k = bisect.bisect_left(ts_list, cutoff)
            if k:
                del ts_list[:k]
                del ka.rows[side][:k]

    # -- checkpointing -----------------------------------------------------
    def snapshot_state(self) -> dict:
        st = super().snapshot_state()
        st["keys"] = {
            key: {"ts": (list(ka.ts[0]), list(ka.ts[1])),
                  "rows": (list(ka.rows[0]), list(ka.rows[1])),
                  "counters": list(ka.counters)}
            for key, ka in self.keys.items()}
        return st

    def restore_state(self, state: dict) -> None:
        super().restore_state(state)
        self.keys = {}
        for key, d in state.get("keys", {}).items():
            ka = _KeyArchives()
            ka.ts = (list(d["ts"][0]), list(d["ts"][1]))
            ka.rows = (list(d["rows"][0]), list(d["rows"][1]))
            ka.counters = list(d["counters"])
            self.keys[key] = ka
