"""Ffat_Windows: sliding-window aggregation with lift+combine over a
FlatFAT tree (reference ``wf/ffat_windows.hpp`` + ``wf/ffat_replica.hpp``).

Semantics: the user supplies ``lift(tuple) -> value`` and an associative
``combine(value, value) -> value``; each fired window emits the ordered
combine of the lifted values it covers.

- CB: per key, a FlatFAT ring holds the current window's lifted values;
  window ``g`` fires when its last tuple (count ``g*slide + win``) arrives,
  then ``slide`` oldest values are evicted.
- TB: pane decomposition exactly like the reference GPU path
  (``wf/ffat_replica_gpu.hpp:638-642``): pane length = gcd(win, slide);
  tuples fold into per-pane partials; watermark progress completes panes
  (``first incomplete pane = (wm - lateness) / pane_len``,
  ``ffat_replica_gpu.hpp:875-881``), completed panes are pushed into the
  FlatFAT (missing panes as identity placeholders so positions align), and
  window ``g`` fires once ``win/pane`` panes are present, evicting
  ``slide/pane``.

Late tuples behind the consumed-pane frontier are counted as ignored.

Empty-window contract: a window containing no tuples fires with ``value
None`` (the combine identity) — unlike the engine-based window operators,
which apply the user's window function to an empty collection. This mirrors
the reference split (GPU FFAT yields identity-valued results, CPU windows
call the functor on an empty Iterable); switching operators may require
handling ``None``.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

from ..basic import (ExecutionMode, OpType, RoutingMode, WinType,
                     WindFlowError)
from .base import BasicOperator, BasicReplica
from .flatfat import FlatFAT
from .window_engine import WinResult


class _FfatKeyState:
    __slots__ = ("fat", "count", "next_gwid", "pending_panes",
                 "next_pane_to_push")

    def __init__(self) -> None:
        self.fat = None  # lazily built (needs combine fn)
        self.count = 0  # CB arrival counter
        self.next_gwid = 0
        self.pending_panes: Dict[int, Any] = {}
        self.next_pane_to_push = 0


class Ffat_Windows(BasicOperator):
    op_type = OpType.WIN

    def __init__(self, lift_func: Callable, combine_func: Callable,
                 key_extractor: Callable, win_len: int, slide_len: int,
                 win_type: WinType = WinType.CB, lateness: int = 0,
                 name: str = "ffat_windows", parallelism: int = 1,
                 output_batch_size: int = 0) -> None:
        if key_extractor is None:
            raise WindFlowError("Ffat_Windows requires a key extractor")
        if win_len <= 0 or slide_len <= 0:
            raise WindFlowError("Ffat_Windows: win/slide must be > 0")
        super().__init__(name, parallelism, RoutingMode.KEYBY, key_extractor,
                         output_batch_size)
        self.lift = lift_func
        self.combine = combine_func
        self.win_len = win_len
        self.slide_len = slide_len
        self.win_type = win_type
        self.lateness = lateness
        self.pane_len = math.gcd(win_len, slide_len)

    @property
    def is_chainable(self) -> bool:
        return False

    def build_replicas(self) -> None:
        self.replicas = [FfatReplica(self, i) for i in range(self.parallelism)]


class FfatReplica(BasicReplica):
    def __init__(self, op: Ffat_Windows, idx: int) -> None:
        super().__init__(op, idx)
        self.keys: Dict[Any, _FfatKeyState] = {}
        if op.win_type is WinType.CB:
            self._fat_cap = op.win_len
            self._win_units = op.win_len
            self._slide_units = op.slide_len
        else:
            self._win_units = op.win_len // op.pane_len
            self._slide_units = op.slide_len // op.pane_len
            self._fat_cap = self._win_units
        self.ignored = 0

    def _key_state(self, key: Any) -> _FfatKeyState:
        ks = self.keys.get(key)
        if ks is None:
            ks = self.keys[key] = _FfatKeyState()
            ks.fat = FlatFAT(self._fat_cap, self.op.combine)
        return ks

    # ------------------------------------------------------------------
    def process(self, payload, ts, wm, tag):
        op = self.op
        key = op.key_extractor(payload)
        ks = self._key_state(key)
        value = op.lift(payload)
        if op.win_type is WinType.CB:
            i = ks.count
            ks.count += 1
            if op.slide_len > op.win_len and (i % op.slide_len) >= op.win_len:
                return  # hopping windows: tuple falls in an inter-window gap
            ks.fat.push(value)
            if ks.fat.size >= op.win_len:
                self._fire(key, ks, wm, ts)
        else:
            pane_id = ts // op.pane_len
            if ks.count == 0:
                # first tuple of this key: align the ring to the first
                # window that can contain it (epoch-scale ts safety)
                w0 = max(0, (pane_id - self._win_units) // self._slide_units + 1)
                ks.next_pane_to_push = w0 * self._slide_units
                ks.next_gwid = w0
            ks.count += 1
            if pane_id < ks.next_pane_to_push:
                self.ignored += 1  # behind the consumed-pane frontier
                self.stats.note_late(1, 1,
                                     float(wm - ts) if wm > ts else None)
                return
            if ts < wm:
                # admitted-late: behind the watermark but ahead of the
                # consumed-pane frontier (within the allowed lateness)
                self.stats.note_late(1, 0, float(wm - ts))
            cur = ks.pending_panes.get(pane_id)
            ks.pending_panes[pane_id] = (value if cur is None
                                         else op.combine(cur, value))
            self._advance_tb(key, ks, ts, wm)

    def _effective_bound(self, ts: int, wm: int) -> int:
        """First incomplete pane. DEFAULT: watermark-driven; other modes:
        inputs arrive in ts order, so ts itself is the frontier."""
        if self.op.execution_mode is ExecutionMode.DEFAULT:
            return max(0, (wm - self.op.lateness)) // self.op.pane_len
        return ts // self.op.pane_len

    def _advance_tb(self, key, ks: _FfatKeyState, ts: int, wm: int) -> None:
        bound = self._effective_bound(ts, wm)
        while ks.next_pane_to_push < bound:
            if ks.fat.size >= self._win_units:
                # FlatFAT full => the oldest window is complete; fire it
                self._fire(key, ks, wm, ts)
            pane_id = ks.next_pane_to_push
            ks.next_pane_to_push += 1
            if self._slide_units > self._win_units \
                    and (pane_id % self._slide_units) >= self._win_units:
                ks.pending_panes.pop(pane_id, None)
                continue  # hopping windows: pane in an inter-window gap
            partial = ks.pending_panes.pop(pane_id, None)
            ks.fat.push(partial)  # None = identity placeholder (empty pane)
        while ks.fat.size >= self._win_units:
            self._fire(key, ks, wm, ts)

    def _fire(self, key, ks: _FfatKeyState, wm: int, ts: int,
              partial_len: Optional[int] = None) -> None:
        length = partial_len if partial_len is not None else self._win_units
        value = ks.fat.query_logical(0, length)
        used_ts = wm if self.op.execution_mode is ExecutionMode.DEFAULT else ts
        res = WinResult(key, ks.next_gwid, value, used_ts)
        ks.next_gwid += 1
        self.emitter.emit(res, used_ts,
                          wm if self.op.execution_mode is ExecutionMode.DEFAULT else 0)
        ks.fat.pop(self._slide_units)

    # ------------------------------------------------------------------
    def on_punctuation(self, wm: int) -> None:
        if self.op.win_type is WinType.TB \
                and self.op.execution_mode is ExecutionMode.DEFAULT:
            for key, ks in self.keys.items():
                self._advance_tb(key, ks, 0, self.cur_wm)
        super().on_punctuation(wm)

    # -- checkpointing -----------------------------------------------------
    # The FlatFAT ring holds the user's combine callable, which must stay
    # out of the pickle: snapshot the pure data (tree slots, head, size)
    # and re-attach the operator's combine on restore.
    def snapshot_state(self) -> dict:
        st = super().snapshot_state()
        st["ignored"] = self.ignored
        st["keys"] = {
            key: {"count": ks.count, "next_gwid": ks.next_gwid,
                  "pending_panes": dict(ks.pending_panes),
                  "next_pane_to_push": ks.next_pane_to_push,
                  "fat": (ks.fat.capacity, ks.fat.head, ks.fat.size,
                          list(ks.fat.tree))}
            for key, ks in self.keys.items()}
        return st

    def restore_state(self, state: dict) -> None:
        super().restore_state(state)
        self.ignored = state.get("ignored", 0)
        self.keys = {}
        for key, d in state.get("keys", {}).items():
            ks = _FfatKeyState()
            cap, head, size, tree = d["fat"]
            fat = FlatFAT(cap, self.op.combine)
            fat.tree = list(tree)
            fat.head = head
            fat.size = size
            ks.fat = fat
            ks.count = d["count"]
            ks.next_gwid = d["next_gwid"]
            ks.pending_panes = dict(d["pending_panes"])
            ks.next_pane_to_push = d["next_pane_to_push"]
            self.keys[key] = ks

    def flush_on_termination(self) -> None:
        op = self.op
        for key, ks in self.keys.items():
            if op.win_type is WinType.TB and ks.pending_panes:
                # push every remaining pane in order
                last = max(ks.pending_panes)
                while ks.next_pane_to_push <= last:
                    if ks.fat.size >= self._win_units:
                        self._fire(key, ks, self.cur_wm, self.cur_wm)
                    pane_id = ks.next_pane_to_push
                    ks.next_pane_to_push += 1
                    if self._slide_units > self._win_units \
                            and (pane_id % self._slide_units) >= self._win_units:
                        ks.pending_panes.pop(pane_id, None)
                        continue
                    partial = ks.pending_panes.pop(pane_id, None)
                    ks.fat.push(partial)
            # fire remaining (possibly partial) windows
            while ks.fat.size > 0:
                self._fire(key, ks, self.cur_wm, self.cur_wm,
                           partial_len=min(self._win_units, ks.fat.size))
        self.stats.inputs_ignored += self.ignored
