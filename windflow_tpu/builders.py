"""Fluent builders — the user-facing API.

Parity: ``wf/builders.hpp`` (1,691 LoC of CRTP builders). The reference
encodes key types in the builder's template parameters (``withKeyBy`` returns
a new builder type, L217-245); in Python the same validations happen at
``build()`` time. Accepted functor signatures follow the reference's ``API``
catalog, with "riched" variants detected by arity (a trailing
``RuntimeContext`` parameter).

Builder surface (CPU):
  Source_Builder, Map_Builder, Filter_Builder, FlatMap_Builder,
  Reduce_Builder, Sink_Builder                                (this module)
  Keyed/Parallel/Paned/MapReduce/Ffat windows, Interval_Join  (M2+)
TPU builders (``.with_tpu()``-style siblings of builders_gpu.hpp) live in
``windflow_tpu.tpu.builders``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from .basic import RoutingMode, WindFlowError
from .operators.basic_ops import Filter, FlatMap, Map, Reduce, Sink
from .operators.source import Columnar_Source, Source


class BasicBuilder:
    """withName / withParallelism / withOutputBatchSize / withClosingFunction
    (``wf/builders.hpp:79-124``)."""

    _default_name = "op"

    def __init__(self, func: Callable) -> None:
        self._func = func
        self._name = self._default_name
        self._parallelism = 1
        self._output_batch_size = 0
        self._closing: Optional[Callable] = None
        self._latency_sample: Optional[int] = None
        self._flightrec_events: Optional[int] = None
        self._error_policy = None

    def with_name(self, name: str) -> "BasicBuilder":
        self._name = name
        return self

    def with_parallelism(self, parallelism: int) -> "BasicBuilder":
        if parallelism < 1:
            raise WindFlowError("parallelism must be >= 1")
        self._parallelism = parallelism
        return self

    def with_output_batch_size(self, size: int) -> "BasicBuilder":
        if size < 0:
            raise WindFlowError("output batch size must be >= 0")
        self._output_batch_size = size
        return self

    def with_closing_function(self, fn: Callable) -> "BasicBuilder":
        self._closing = fn
        return self

    def with_latency_tracing(self, rate=1) -> "BasicBuilder":
        """Per-operator latency-tracing sample rate, overriding the
        ``WF_LATENCY_SAMPLE`` env knob for this operator: ``1`` samples
        every tuple, ``"1/64"`` (or ``0.015625``) every 64th, ``0``
        disables. Sources stamp sampled tuples, sinks record end-to-end
        latency, every replica records sampled service/dispatch
        latencies into its histograms (monitoring/tracing.py)."""
        from .monitoring.tracing import parse_sample_rate
        self._latency_sample = parse_sample_rate(rate)
        return self

    def with_flight_recorder(self, events: int = 0) -> "BasicBuilder":
        """Enable the flight recorder for this operator's workers with a
        ring of ``events`` span events (0 picks ``WF_FLIGHTREC_EVENTS``
        or the 4096 default). A chained stage uses the largest override
        among its operators. See ``PipeGraph.with_flight_recorder`` for
        the graph-wide switch and ``PipeGraph.dump_trace`` /
        ``GET /trace`` for the export paths."""
        from .monitoring.flightrec import DEFAULT_EVENTS, env_flightrec_events
        if events < 0:
            raise WindFlowError("with_flight_recorder: events must be >= 0")
        self._flightrec_events = (int(events) if events > 0
                                  else env_flightrec_events()
                                  or DEFAULT_EVENTS)
        return self

    def with_error_policy(self, policy) -> "BasicBuilder":
        """Per-record failure containment
        (``windflow_tpu.supervision.errors``): ``policy`` is an
        ``ErrorPolicy`` — ``FAIL`` (default: a functor exception kills
        the worker, the pre-existing behavior), ``SKIP`` (drop + count),
        ``RETRY(n, backoff_s=...)`` (re-invoke with exponential backoff,
        then the ``on_exhausted`` fallback), or ``DEAD_LETTER``
        (quarantine record + exception metadata into the graph's
        dead-letter queue, surfaced as ``Dlq_*`` stats /
        ``windflow_dlq_records_total``). On device operators a failing
        batch is bisected until the poison record is isolated. A string
        is parsed like the ``WF_ERROR_POLICY`` env knob
        (``"skip"`` / ``"dead_letter"`` / ``"retry:3"``)."""
        from .supervision.errors import ErrorPolicy
        if isinstance(policy, str):
            policy = ErrorPolicy.parse(policy)
        if not isinstance(policy, ErrorPolicy):
            raise WindFlowError(
                f"with_error_policy: expected an ErrorPolicy (or a spec "
                f"string), got {type(policy).__name__}")
        self._error_policy = policy
        return self

    def _finish(self, op):
        op.closing_func = self._closing
        if self._latency_sample is not None:
            op.latency_sample = self._latency_sample
        if self._flightrec_events is not None:
            op.flightrec_events = self._flightrec_events
        if self._error_policy is not None:
            op.error_policy = self._error_policy
        return op


class _RoutableBuilder(BasicBuilder):
    """Adds withKeyBy / withRebalancing (``wf/builders.hpp:217-245``)."""

    def __init__(self, func: Callable) -> None:
        super().__init__(func)
        self._routing = RoutingMode.FORWARD
        self._key_extractor: Optional[Callable] = None

    def with_key_by(self, key_extractor: Callable[[Any], Any]) -> "_RoutableBuilder":
        self._routing = RoutingMode.KEYBY
        self._key_extractor = key_extractor
        return self

    def with_rebalancing(self) -> "_RoutableBuilder":
        if self._routing is RoutingMode.KEYBY:
            raise WindFlowError("withRebalancing is incompatible with withKeyBy")
        self._routing = RoutingMode.REBALANCING
        return self

    def with_broadcast(self) -> "_RoutableBuilder":
        if self._routing is RoutingMode.KEYBY:
            raise WindFlowError("withBroadcast is incompatible with withKeyBy")
        self._routing = RoutingMode.BROADCAST
        return self


class _SourceOverloadMixin:
    """``with_slo`` / ``with_priority`` for source builders — the
    overload-protection surface (``windflow_tpu.overload``). Shared with
    the Kafka source builder."""

    _slo_p99_ms: Optional[float] = None
    _priority_fn: Optional[Callable] = None

    def with_slo(self, p99_ms: float):
        """Declare this source's end-to-end p99 latency budget
        (milliseconds). Attaches the overload governor to the graph at
        ``start()``; with several declared budgets (graph-level
        ``PipeGraph.with_slo`` and/or other sources) the TIGHTEST one
        governs. Env twin (graph-wide): ``WF_SLO_P99_MS``."""
        if p99_ms <= 0:
            raise WindFlowError("with_slo: p99_ms must be > 0")
        self._slo_p99_ms = float(p99_ms)
        return self

    def with_priority(self, fn: Callable[[Any], Any]):
        """Record-priority extractor (higher = more important) for the
        ``key_priority`` shed policy: when the governor's admission gate
        must evict, the LOWEST-priority buffered record sheds — so (for
        a Zipf workload) head keys survive an overload that drops the
        tail. Ignored by the other shed policies."""
        if not callable(fn):
            raise WindFlowError("with_priority: fn must be callable")
        self._priority_fn = fn
        return self

    def _finish_overload(self, op):
        op.slo_p99_ms = self._slo_p99_ms
        op.priority_fn = self._priority_fn
        return op


class Source_Builder(_SourceOverloadMixin, BasicBuilder):
    _default_name = "source"

    def build(self) -> Source:
        return self._finish_overload(self._finish(
            Source(self._func, self._name, self._parallelism,
                   self._output_batch_size)))


class Columnar_Source_Builder(_SourceOverloadMixin, BasicBuilder):
    """Builder for schema-declared BLOCK sources: the functor yields
    ``(cols, ts)`` column blocks instead of pushing per-tuple (see
    ``Columnar_Source``). ``with_block_size`` re-chunks oversized yields;
    ``with_schema`` declares column dtypes canonicalized at the edge."""

    _default_name = "columnar_source"

    def __init__(self, func: Callable) -> None:
        super().__init__(func)
        self._block_size = 0
        self._block_schema: Optional[dict] = None

    def with_block_size(self, n: int) -> "Columnar_Source_Builder":
        if n <= 0:
            raise WindFlowError("with_block_size: block size must be >= 1")
        self._block_size = int(n)
        return self

    def with_schema(self, schema: dict) -> "Columnar_Source_Builder":
        if not isinstance(schema, dict) or not schema:
            raise WindFlowError(
                "with_schema: expected a non-empty {field: dtype} dict")
        self._block_schema = dict(schema)
        return self

    def build(self) -> Columnar_Source:
        return self._finish_overload(self._finish(
            Columnar_Source(self._func, self._name, self._parallelism,
                            self._output_batch_size, self._block_size,
                            self._block_schema)))


class Map_Builder(_RoutableBuilder):
    _default_name = "map"

    def build(self) -> Map:
        return self._finish(Map(self._func, self._name, self._parallelism,
                                self._routing, self._key_extractor,
                                self._output_batch_size))


class Filter_Builder(_RoutableBuilder):
    _default_name = "filter"

    def build(self) -> Filter:
        return self._finish(Filter(self._func, self._name, self._parallelism,
                                   self._routing, self._key_extractor,
                                   self._output_batch_size))


class FlatMap_Builder(_RoutableBuilder):
    _default_name = "flatmap"

    def build(self) -> FlatMap:
        return self._finish(FlatMap(self._func, self._name, self._parallelism,
                                    self._routing, self._key_extractor,
                                    self._output_batch_size))


class Reduce_Builder(_RoutableBuilder):
    """``withKeyBy`` is mandatory; ``withInitialState`` mirrors
    ``wf/builders.hpp:627``."""

    _default_name = "reduce"

    def __init__(self, func: Callable) -> None:
        super().__init__(func)
        self._initial_state: Any = None

    def with_initial_state(self, state: Any) -> "Reduce_Builder":
        self._initial_state = state
        return self

    def build(self) -> Reduce:
        if self._key_extractor is None:
            raise WindFlowError("Reduce_Builder: withKeyBy(...) is mandatory")
        return self._finish(Reduce(self._func, self._key_extractor,
                                   self._initial_state, self._name,
                                   self._parallelism, self._output_batch_size))


class Sink_Builder(_RoutableBuilder):
    _default_name = "sink"

    def __init__(self, func: Callable) -> None:
        super().__init__(func)
        self._columns = False
        self._exactly_once = False
        self._txn_dir: Optional[str] = None

    def with_exactly_once(self, staging_dir: Optional[str] = None
                          ) -> "Sink_Builder":
        """Exactly-once delivery (windflow_tpu.sinks.transactional):
        output buffers per checkpoint epoch, pre-commits at the aligned
        barrier as a staged segment file under ``staging_dir`` (default
        ``$WF_TXN_DIR`` / ``wf_txn_sinks``) and becomes visible —
        one atomic rename, then the functor call — only when the
        coordinator finalizes the epoch. Requires
        ``PipeGraph.with_checkpointing``; the graph refuses loudly
        otherwise. Env twin for the whole graph: ``WF_EXACTLY_ONCE=1`` /
        ``PipeGraph.with_exactly_once()``."""
        self._exactly_once = True
        if staging_dir is not None:
            self._txn_dir = staging_dir
        return self

    def with_columns(self) -> "Sink_Builder":
        """Columnar consumer (the exit-side dual of ``push_columns``):
        the functor becomes ``sink(cols, ts)`` — ``cols`` a dict of host
        numpy arrays, ``ts`` the int64 timestamp array; riched variants
        add the context; EOS delivers ``sink(None, None)``. Requires a
        device-plane producer: the exit then ships whole column batches
        with NO per-row boxing (reference exit semantics,
        ``wf/batch_gpu_t.hpp:154-179``)."""
        self._columns = True
        return self

    def build(self) -> Sink:
        op = self._finish(Sink(self._func, self._name, self._parallelism,
                               self._routing, self._key_extractor,
                               accepts_columns=self._columns))
        op.exactly_once = self._exactly_once
        op.txn_dir = self._txn_dir
        return op


# ---------------------------------------------------------------------------
# Window builders (reference wf/builders.hpp:743-782 add withCBWindows /
# withTBWindows / withLateness on top of the basic surface)
# ---------------------------------------------------------------------------
from .basic import WinType  # noqa: E402
from .operators.ffat import Ffat_Windows  # noqa: E402
from .operators.windows import (Keyed_Windows, MapReduce_Windows,  # noqa: E402
                                Paned_Windows, Parallel_Windows)


class _WindowedBuilder(BasicBuilder):
    def __init__(self, func):
        super().__init__(func)
        self._key_extractor = None
        self._win_len = 0
        self._slide_len = 0
        self._win_type = None
        self._lateness = 0
        self._incremental = False
        self._initial = None
        self._tb_origin = None

    def with_key_by(self, key_extractor):
        self._key_extractor = key_extractor
        return self

    def with_cb_windows(self, win_len: int, slide_len: int):
        self._win_type = WinType.CB
        self._win_len, self._slide_len = win_len, slide_len
        return self

    def with_tb_windows(self, win_usec: int, slide_usec: int):
        self._win_type = WinType.TB
        self._win_len, self._slide_len = win_usec, slide_usec
        return self

    def with_lateness(self, lateness_usec: int):
        self._lateness = lateness_usec
        return self

    def with_tb_origin(self, origin_usec: int = 0):
        """Reference-compat TB window numbering
        (``wf/window_replica.hpp:253-283``): anchor every key's windows at
        this time origin and fire identity-valued EMPTY windows between
        the origin and the key's first tuple as the watermark passes them.
        Default (not called): a key's first window aligns to its first
        tuple (PARITY.md §2.3) — epoch-scale timestamps would otherwise
        create ~ts/slide empty windows, which this origin bounds."""
        self._tb_origin = origin_usec
        return self

    def incremental(self, initial_value=None):
        """Switch the window function to incremental form
        ``func(tuple, acc) -> acc``; ``initial_value`` may be a value
        (deep-copied per window) or a factory ``(key, gwid) -> acc``."""
        self._incremental = True
        self._initial = initial_value
        return self

    def _check_windows(self, what: str) -> None:
        if self._win_type is None:
            raise WindFlowError(f"{what}: call with_cb_windows() or "
                                "with_tb_windows() first")
        if self._tb_origin is not None and self._win_type is not WinType.TB:
            raise WindFlowError(f"{what}: with_tb_origin applies to "
                                "time-based windows only (the origin is a "
                                "timestamp; CB windows count arrivals)")


class Keyed_Windows_Builder(_WindowedBuilder):
    _default_name = "keyed_windows"

    def build(self) -> Keyed_Windows:
        self._check_windows("Keyed_Windows_Builder")
        if self._key_extractor is None:
            raise WindFlowError("Keyed_Windows_Builder: withKeyBy mandatory")
        return self._finish(Keyed_Windows(
            self._func, self._key_extractor, self._win_len, self._slide_len,
            self._win_type, self._lateness, self._incremental, self._initial,
            self._name, self._parallelism, self._output_batch_size,
            tb_origin=self._tb_origin))


class Parallel_Windows_Builder(_WindowedBuilder):
    _default_name = "parallel_windows"

    def build(self) -> Parallel_Windows:
        self._check_windows("Parallel_Windows_Builder")
        if self._key_extractor is None:
            raise WindFlowError("Parallel_Windows_Builder: withKeyBy mandatory")
        return self._finish(Parallel_Windows(
            self._func, self._key_extractor, self._win_len, self._slide_len,
            self._win_type, self._lateness, self._incremental, self._initial,
            self._name, self._parallelism, self._output_batch_size,
            tb_origin=self._tb_origin))


class _TwoStageWindowedBuilder(_WindowedBuilder):
    def __init__(self, func1, func2):
        super().__init__(func1)
        self._func2 = func2
        self._incremental2 = False
        self._initial2 = None
        self._parallelism2 = 1

    def incremental_stage2(self, initial_value=None):
        self._incremental2 = True
        self._initial2 = initial_value
        return self

    def with_parallelism(self, p1: int, p2: int = None):  # type: ignore[override]
        super().with_parallelism(p1)
        self._parallelism2 = p2 if p2 is not None else p1
        return self


class Paned_Windows_Builder(_TwoStageWindowedBuilder):
    _default_name = "paned_windows"

    def build(self) -> Paned_Windows:
        self._check_windows("Paned_Windows_Builder")
        if self._key_extractor is None:
            raise WindFlowError("Paned_Windows_Builder: withKeyBy mandatory")
        return self._finish(Paned_Windows(
            self._func, self._func2, self._key_extractor, self._win_len,
            self._slide_len, self._win_type, self._lateness,
            self._incremental, self._initial, self._incremental2,
            self._initial2, self._name, self._parallelism,
            self._parallelism2, self._output_batch_size,
            tb_origin=self._tb_origin))


class MapReduce_Windows_Builder(_TwoStageWindowedBuilder):
    _default_name = "mapreduce_windows"

    def build(self) -> MapReduce_Windows:
        self._check_windows("MapReduce_Windows_Builder")
        if self._key_extractor is None:
            raise WindFlowError("MapReduce_Windows_Builder: withKeyBy mandatory")
        return self._finish(MapReduce_Windows(
            self._func, self._func2, self._key_extractor, self._win_len,
            self._slide_len, self._win_type, self._lateness,
            self._incremental, self._initial, self._incremental2,
            self._initial2, self._name, self._parallelism,
            self._parallelism2, self._output_batch_size,
            tb_origin=self._tb_origin))


class Ffat_Windows_Builder(_WindowedBuilder):
    """lift+combine FlatFAT aggregator (``wf/builders.hpp`` FFAT_Builder)."""

    _default_name = "ffat_windows"

    def __init__(self, lift_func, combine_func):
        super().__init__(lift_func)
        self._combine = combine_func

    def incremental(self, initial_value=None):
        raise WindFlowError(
            "Ffat_Windows is inherently incremental via lift+combine; "
            "incremental() does not apply (use Keyed_Windows_Builder for "
            "seeded accumulators)")

    def build(self) -> Ffat_Windows:
        self._check_windows("Ffat_Windows_Builder")
        if self._key_extractor is None:
            raise WindFlowError("Ffat_Windows_Builder: withKeyBy mandatory")
        if self._tb_origin is not None:
            raise WindFlowError(
                "Ffat_Windows_Builder: with_tb_origin applies to the "
                "window-engine operators (Keyed/Parallel/Paned/MapReduce "
                "windows); the FFAT planes keep first-tuple anchoring")
        return self._finish(Ffat_Windows(
            self._func, self._combine, self._key_extractor, self._win_len,
            self._slide_len, self._win_type, self._lateness, self._name,
            self._parallelism, self._output_batch_size))


# ---------------------------------------------------------------------------
# Interval_Join builder (wf/builders.hpp:1480-1538: withBoundaries,
# withKPMode, withDPMode)
# ---------------------------------------------------------------------------
from .basic import JoinMode  # noqa: E402
from .operators.join import Interval_Join  # noqa: E402


class Interval_Join_Builder(BasicBuilder):
    _default_name = "interval_join"

    def __init__(self, join_func):
        super().__init__(join_func)
        self._key_extractor = None
        self._lower = None
        self._upper = None
        self._mode = JoinMode.KP

    def with_key_by(self, key_extractor):
        self._key_extractor = key_extractor
        return self

    def with_boundaries(self, lower_usec: int, upper_usec: int):
        self._lower, self._upper = lower_usec, upper_usec
        return self

    def with_kp_mode(self):
        self._mode = JoinMode.KP
        return self

    def with_dp_mode(self):
        self._mode = JoinMode.DP
        return self

    def build(self) -> Interval_Join:
        if self._key_extractor is None:
            raise WindFlowError("Interval_Join_Builder: withKeyBy mandatory")
        if self._lower is None:
            raise WindFlowError("Interval_Join_Builder: withBoundaries "
                                "mandatory")
        return self._finish(Interval_Join(
            self._func, self._key_extractor, self._lower, self._upper,
            self._mode, self._name, self._parallelism,
            self._output_batch_size))
