"""Fluent builders — the user-facing API.

Parity: ``wf/builders.hpp`` (1,691 LoC of CRTP builders). The reference
encodes key types in the builder's template parameters (``withKeyBy`` returns
a new builder type, L217-245); in Python the same validations happen at
``build()`` time. Accepted functor signatures follow the reference's ``API``
catalog, with "riched" variants detected by arity (a trailing
``RuntimeContext`` parameter).

Builder surface (CPU):
  Source_Builder, Map_Builder, Filter_Builder, FlatMap_Builder,
  Reduce_Builder, Sink_Builder                                (this module)
  Keyed/Parallel/Paned/MapReduce/Ffat windows, Interval_Join  (M2+)
TPU builders (``.with_tpu()``-style siblings of builders_gpu.hpp) live in
``windflow_tpu.tpu.builders``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from .basic import RoutingMode, WindFlowError
from .operators.basic_ops import Filter, FlatMap, Map, Reduce, Sink
from .operators.source import Source


class BasicBuilder:
    """withName / withParallelism / withOutputBatchSize / withClosingFunction
    (``wf/builders.hpp:79-124``)."""

    _default_name = "op"

    def __init__(self, func: Callable) -> None:
        self._func = func
        self._name = self._default_name
        self._parallelism = 1
        self._output_batch_size = 0
        self._closing: Optional[Callable] = None

    def with_name(self, name: str) -> "BasicBuilder":
        self._name = name
        return self

    def with_parallelism(self, parallelism: int) -> "BasicBuilder":
        if parallelism < 1:
            raise WindFlowError("parallelism must be >= 1")
        self._parallelism = parallelism
        return self

    def with_output_batch_size(self, size: int) -> "BasicBuilder":
        if size < 0:
            raise WindFlowError("output batch size must be >= 0")
        self._output_batch_size = size
        return self

    def with_closing_function(self, fn: Callable) -> "BasicBuilder":
        self._closing = fn
        return self

    def _finish(self, op):
        op.closing_func = self._closing
        return op


class _RoutableBuilder(BasicBuilder):
    """Adds withKeyBy / withRebalancing (``wf/builders.hpp:217-245``)."""

    def __init__(self, func: Callable) -> None:
        super().__init__(func)
        self._routing = RoutingMode.FORWARD
        self._key_extractor: Optional[Callable] = None

    def with_key_by(self, key_extractor: Callable[[Any], Any]) -> "_RoutableBuilder":
        self._routing = RoutingMode.KEYBY
        self._key_extractor = key_extractor
        return self

    def with_rebalancing(self) -> "_RoutableBuilder":
        if self._routing is RoutingMode.KEYBY:
            raise WindFlowError("withRebalancing is incompatible with withKeyBy")
        self._routing = RoutingMode.REBALANCING
        return self

    def with_broadcast(self) -> "_RoutableBuilder":
        if self._routing is RoutingMode.KEYBY:
            raise WindFlowError("withBroadcast is incompatible with withKeyBy")
        self._routing = RoutingMode.BROADCAST
        return self


class Source_Builder(BasicBuilder):
    _default_name = "source"

    def build(self) -> Source:
        return self._finish(Source(self._func, self._name, self._parallelism,
                                   self._output_batch_size))


class Map_Builder(_RoutableBuilder):
    _default_name = "map"

    def build(self) -> Map:
        return self._finish(Map(self._func, self._name, self._parallelism,
                                self._routing, self._key_extractor,
                                self._output_batch_size))


class Filter_Builder(_RoutableBuilder):
    _default_name = "filter"

    def build(self) -> Filter:
        return self._finish(Filter(self._func, self._name, self._parallelism,
                                   self._routing, self._key_extractor,
                                   self._output_batch_size))


class FlatMap_Builder(_RoutableBuilder):
    _default_name = "flatmap"

    def build(self) -> FlatMap:
        return self._finish(FlatMap(self._func, self._name, self._parallelism,
                                    self._routing, self._key_extractor,
                                    self._output_batch_size))


class Reduce_Builder(_RoutableBuilder):
    """``withKeyBy`` is mandatory; ``withInitialState`` mirrors
    ``wf/builders.hpp:627``."""

    _default_name = "reduce"

    def __init__(self, func: Callable) -> None:
        super().__init__(func)
        self._initial_state: Any = None

    def with_initial_state(self, state: Any) -> "Reduce_Builder":
        self._initial_state = state
        return self

    def build(self) -> Reduce:
        if self._key_extractor is None:
            raise WindFlowError("Reduce_Builder: withKeyBy(...) is mandatory")
        return self._finish(Reduce(self._func, self._key_extractor,
                                   self._initial_state, self._name,
                                   self._parallelism, self._output_batch_size))


class Sink_Builder(_RoutableBuilder):
    _default_name = "sink"

    def build(self) -> Sink:
        return self._finish(Sink(self._func, self._name, self._parallelism,
                                 self._routing, self._key_extractor))
