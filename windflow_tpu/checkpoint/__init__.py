"""Aligned-barrier checkpointing and crash recovery.

Flink-style asynchronous aligned snapshots over the WindFlow dataflow
graph (the reference has no fault tolerance at all — SURVEY.md §5):

- a ``CheckpointCoordinator`` (owned by the ``PipeGraph``) periodically
  bumps a checkpoint epoch; source replicas notice at the next tuple
  boundary, snapshot their replay position, and inject a ``Barrier``
  message (``message.py``) downstream on every edge;
- each worker aligns barriers across its input channels (buffering
  post-barrier input from already-barriered channels — no post-barrier
  tuple can leak into a pre-barrier snapshot), drains its device dispatch
  pipeline, flushes partial output batches, forwards the barrier, and
  snapshots every fused replica's state (keyed tables, window panes, FFAT
  forests via ``jax.device_get``, persistent DB contents, collector
  buffers) into the ``CheckpointStore``;
- when every worker has acknowledged, the coordinator atomically commits
  the checkpoint (manifest + rename) and notifies listeners (the Kafka
  source commits consumer offsets only then — at-least-once end to end);
- ``PipeGraph.run(restore_from=...)`` rebuilds the topology, restores
  every replica from the manifest's blobs, and resumes sources from the
  recorded positions.

DrJAX's observation (PAPERS.md) that MapReduce-style state movement is
cheap when state lives in arrays is what keeps device snapshots small
here: a grid-scan table or FFAT forest is a handful of ``device_get``
calls per replica, not a per-operator serializer.
"""

from . import delta
from .coordinator import CheckpointCoordinator
from .store import CheckpointStore, CorruptCheckpointError

__all__ = ["CheckpointCoordinator", "CheckpointStore",
           "CorruptCheckpointError", "delta"]
