"""Incremental-checkpoint plane: delta markers, snapshot context, apply.

Three cooperating pieces make checkpoint cost scale with CHANGE RATE
instead of state size (opt-in via ``WF_CKPT_DELTA``):

1. **Content-addressed blob refs** (``store.py``): a blob whose payload
   digest matches the previous committed epoch's is *referenced* in the
   manifest (``refs``), never rewritten. Pure storage-side dedup — it
   needs nothing from this module beyond the env knobs.
2. **State deltas** (this module): an engine that tracks its touched
   slot rows emits a *delta node* instead of the full state dict — the
   dirty rows plus small replaced fields, plus the epoch id of the FULL
   snapshot they patch (``base``). The store records the dependency in
   the manifest (``deps``) and ``load_states`` materializes the full
   state transparently, so the supervisor ladder, the repartitioner and
   ``restore_from=`` never see a delta.
3. **Snapshot context**: ``Worker._capture_blobs`` wraps the capture in
   ``capturing(ckpt_id, store)``; engines consult ``snapshot_ctx()`` /
   ``delta_eligible`` to decide full vs delta. No context (retirement
   snapshots, direct ``snapshot_state`` calls) always means FULL — the
   conservative default keeps every non-checkpoint path byte-identical
   to the pre-delta behavior.

Chain-length discipline: an engine's delta base is always its LAST FULL
snapshot (never a previous delta), so a delta chain is one hop deep at
the state level and ``WF_CKPT_FULL_EVERY`` (default 8) bounds how long
a base must be retained. A base epoch that failed to commit simply
fails ``delta_eligible`` at the next capture and the engine re-emits a
full snapshot — self-healing, no coordination.
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Set

import numpy as np

DELTA_KEY = "__state_delta__"


# -- env knobs ---------------------------------------------------------------
def env_ckpt_delta() -> bool:
    """``WF_CKPT_DELTA``: opt-in incremental checkpointing (blob refs +
    state deltas). Off by default — the on-disk layout stays exactly the
    pre-delta format until the operator asks for deltas."""
    v = os.environ.get("WF_CKPT_DELTA", "0").strip().lower()
    return v not in ("0", "false", "off", "no", "")


def env_ckpt_async() -> bool:
    """``WF_CKPT_ASYNC``: opt-in asynchronous snapshot upload — the
    barrier fences only the state CUT (device/host copy); serialization
    and the blob writes run on a background uploader and the epoch
    commits when every upload lands. Off by default."""
    v = os.environ.get("WF_CKPT_ASYNC", "0").strip().lower()
    return v not in ("0", "false", "off", "no", "")


def env_ckpt_full_every() -> int:
    """``WF_CKPT_FULL_EVERY``: emit a FULL state snapshot at least every
    N captures (bounds delta-chain length and how far back a base epoch
    must be retained). Default 8, minimum 1 (1 = always full)."""
    try:
        return max(1, int(os.environ.get("WF_CKPT_FULL_EVERY", "8")))
    except ValueError:
        return 8


# -- snapshot context --------------------------------------------------------
class SnapshotContext:
    """What the engines need to know about the capture in progress: the
    epoch id being snapshotted and whether a candidate base epoch is
    committed on disk (cached — one directory listing per capture)."""

    __slots__ = ("ckpt_id", "store", "_committed")

    def __init__(self, ckpt_id: int, store) -> None:
        self.ckpt_id = int(ckpt_id)
        self.store = store
        self._committed: Optional[Set[int]] = None

    def is_committed(self, cid: int) -> bool:
        if self._committed is None:
            try:
                self._committed = set(self.store.completed_ids())
            except Exception:
                self._committed = set()
        return cid in self._committed


_tls = threading.local()


@contextmanager
def capturing(ckpt_id: Optional[int], store) -> Any:
    """Install the snapshot context for the duration of one blob
    capture (``Worker._capture_blobs``). Nested/absent-safe."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = (SnapshotContext(ckpt_id, store)
                if ckpt_id is not None and store is not None else None)
    try:
        yield _tls.ctx
    finally:
        _tls.ctx = prev


def snapshot_ctx() -> Optional[SnapshotContext]:
    return getattr(_tls, "ctx", None)


def delta_eligible(base_ckpt: Optional[int], snaps_since_full: int,
                   ctx: Optional[SnapshotContext] = None) -> bool:
    """May the current capture emit a delta against ``base_ckpt``?
    Requires: a capture context, the knob on, a known base, the
    full-snapshot cadence not yet due, and the base COMMITTED on disk
    (an uncommitted base means the full snapshot it rode never became
    restorable — re-emit full)."""
    if ctx is None:
        ctx = snapshot_ctx()
    if ctx is None or base_ckpt is None or not env_ckpt_delta():
        return False
    if snaps_since_full + 1 >= env_ckpt_full_every():
        return False
    return ctx.is_committed(int(base_ckpt))


# -- delta nodes -------------------------------------------------------------
def make_delta(base_ckpt: int, rows: Optional[Dict[str, Any]] = None,
               shards: Optional[Dict[str, Any]] = None,
               replace: Optional[Dict[str, Any]] = None,
               carry: Optional[List[str]] = None) -> dict:
    """Build a delta node patching the same-path subtree of the base
    epoch's blob:

    - ``rows``: ``{state_key: {"slots": int_array, "leaves": [row_arrays]}}``
      — slot-row patches along each leaf's leading axis, leaves in
      ``tree_flatten`` order of the base value;
    - ``shards``: ``{state_key: [per-shard rows-patch or None]}`` — the
      mesh twin's block-sharded tables (base value is a LIST of shard
      pytrees, patched per shard);
    - ``replace``: small fields stored whole (may themselves contain
      nested delta nodes, e.g. a tier WAL delta);
    - ``carry``: field names copied VERBATIM from the base subtree —
      zero bytes in the delta. The key directory rides here when no key
      registered since the base, so delta size cannot regrow with the
      number of keys.
    """
    node: Dict[str, Any] = {DELTA_KEY: 1, "base": int(base_ckpt)}
    if rows:
        node["rows"] = rows
    if shards:
        node["shards"] = shards
    if replace:
        node["replace"] = replace
    if carry:
        node["carry"] = list(carry)
    return node


def make_tier_delta(base_ckpt: int, wal_puts: List, wal_dels: List,
                    replace: Dict[str, Any]) -> dict:
    """A tiered-store sub-blob delta: the cold tier as a WAL (puts/dels
    since the base's full cold image) plus the replaced bookkeeping
    fields. Applied by ``state.tiered.apply_tier_delta``."""
    return {DELTA_KEY: 1, "base": int(base_ckpt), "kind": "tier",
            "wal_puts": list(wal_puts), "wal_dels": list(wal_dels),
            "replace": dict(replace)}


def is_delta(node: Any) -> bool:
    return isinstance(node, dict) and DELTA_KEY in node


def delta_bases(state: Any, _out: Optional[Set[int]] = None) -> Set[int]:
    """Every base epoch id referenced by delta nodes anywhere in a
    state tree (structure walk only — array leaves are not entered)."""
    out: Set[int] = set() if _out is None else _out
    if isinstance(state, dict):
        if DELTA_KEY in state:
            out.add(int(state["base"]))
        for v in state.values():
            delta_bases(v, out)
    elif isinstance(state, (list, tuple)):
        for v in state:
            delta_bases(v, out)
    return out


# -- application -------------------------------------------------------------
def _apply_rows(base_val: Any, patch: Dict[str, Any]) -> Any:
    """Patch dirty slot rows into a copy of ``base_val`` (any pytree of
    arrays sharing a leading slot axis)."""
    import jax

    slots = np.asarray(patch["slots"])
    leaves, treedef = jax.tree_util.tree_flatten(base_val)
    rows = patch["leaves"]
    if len(rows) != len(leaves):
        raise ValueError(
            f"state-delta row patch holds {len(rows)} leaves, base value "
            f"has {len(leaves)} — base/delta structure mismatch")
    out = []
    for b, r in zip(leaves, rows):
        arr = np.array(np.asarray(b), copy=True)
        if len(slots):
            arr[slots] = r
        out.append(arr)
    return jax.tree_util.tree_unflatten(treedef, out)


def _descend(bases: Dict[int, Any], key: Any) -> Dict[int, Any]:
    out = {}
    for cid, bs in bases.items():
        if isinstance(bs, dict):
            out[cid] = bs.get(key)
        elif isinstance(bs, (list, tuple)) and isinstance(key, int) \
                and 0 <= key < len(bs):
            out[cid] = bs[key]
        else:
            out[cid] = None
    return out


def _apply_node(node: dict, bases: Dict[int, Any]) -> Any:
    base = bases.get(int(node["base"]))
    if node.get("kind") == "tier":
        from ..state.tiered import apply_tier_delta
        if base is None:
            raise ValueError(
                "tier WAL delta has no base tier sub-blob to patch")
        return apply_tier_delta(base, node)
    if base is None:
        raise ValueError(
            "state delta has no corresponding base subtree to patch "
            f"(base epoch {node['base']})")
    out: Dict[str, Any] = {}
    for k in node.get("carry") or ():
        out[k] = base[k]
    for k, v in (node.get("replace") or {}).items():
        out[k] = resolve(v, _descend({int(node["base"]): base}, k))
    for k, patch in (node.get("rows") or {}).items():
        out[k] = _apply_rows(base[k], patch)
    for k, shard_patches in (node.get("shards") or {}).items():
        base_shards = base[k]
        patched = []
        for i, p in enumerate(shard_patches):
            if p is None:
                patched.append(base_shards[i])
            else:
                patched.append(_apply_rows(base_shards[i], p))
        out[k] = patched
    return out


def resolve(state: Any, bases: Dict[int, Any]) -> Any:
    """Materialize a (possibly delta-bearing) state tree against the
    base states, recursively: delta nodes apply against the same-path
    subtree of their base epoch's blob, plain containers recurse, array
    leaves pass through untouched."""
    if isinstance(state, dict):
        if DELTA_KEY in state:
            return _apply_node(state, bases)
        return {k: resolve(v, _descend(bases, k))
                for k, v in state.items()}
    if isinstance(state, list):
        return [resolve(v, _descend(bases, i))
                for i, v in enumerate(state)]
    return state


def materialize(state: Any, base_states: Dict[int, Any]) -> Any:
    """Entry point for the store: reconstruct the FULL state of one blob
    from its delta-bearing form plus the (already materialized) states
    of every base epoch it references, keyed by epoch id."""
    if not delta_bases(state):
        return state
    return resolve(state, dict(base_states))
